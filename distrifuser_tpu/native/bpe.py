"""Native CLIP tokenizer: snapshot vocab.json/merges.txt -> input_ids [B, 77].

The reference reaches tokenization through HuggingFace's tokenizer stack
(diffusers from_pretrained, /root/reference/distrifuser/pipelines.py:30-42).
Here the hot per-word BPE merge loop runs in C++ (native/clip_bpe.cc) while
this wrapper owns exact parity with `CLIPTokenizerFast` — the tokenizer
diffusers actually loads for the reference pipelines:

* normalization: unicode NFC, collapse runs of whitespace, lowercase
  (the fast tokenizer's Normalizer sequence; no ftfy/html-unescape — those
  belong to the slow tokenizer's pre-processing only);
* the CLIP pre-tokenization regex (via the `regex` package, \\p classes);
* GPT-2 byte->unicode mapping, "</w>" end-of-word marker;
* framing: <|startoftext|> + tokens[:75] + <|endoftext|>, padded with the
  eos token to model_max_length (CLIP's pad token is eos).

Construction raises if the native engine or the vocab files are unavailable
— callers (pipelines._tokenizer_or_fallback) then fall back to transformers.
tests/test_native_tokenizer.py asserts id-level parity against transformers
on the same vocab files.
"""

from __future__ import annotations

import ctypes
import json
import os
import unicodedata
from functools import lru_cache
from typing import List

import numpy as np


@lru_cache()
def _bytes_to_unicode():
    """GPT-2/CLIP byte -> printable-unicode-char table (stable, reversible)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _normalize(text: str) -> str:
    """CLIPTokenizerFast's normalizer sequence: NFC, \\s+ -> ' ', lowercase."""
    import regex

    return regex.sub(r"\s+", " ", unicodedata.normalize("NFC", text)).lower()


class NativeCLIPTokenizer:
    """Drop-in for the transformers call surface pipelines._tokenize uses:
    ``tok(texts, padding="max_length", max_length=tok.model_max_length,
    truncation=True, return_tensors="np")["input_ids"]``."""

    model_max_length = 77

    def __init__(self, tokenizer_dir: str):
        import regex

        from . import _build_bpe

        vocab_path = os.path.join(tokenizer_dir, "vocab.json")
        merges_path = os.path.join(tokenizer_dir, "merges.txt")
        with open(vocab_path, encoding="utf-8") as f:
            vocab = json.load(f)
        merges: List[tuple] = []
        with open(merges_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                parts = line.split()
                if len(parts) == 2:
                    merges.append((parts[0], parts[1]))

        lib = _build_bpe()
        if lib is None:
            raise RuntimeError("native BPE engine unavailable (no compiler?)")
        self._lib = lib
        self._h = lib.bpe_new()
        self.bos_token_id = vocab["<|startoftext|>"]
        self.eos_token_id = vocab["<|endoftext|>"]
        # Pad token from the snapshot, NOT assumed: SD's tokenizer/ pads with
        # eos, but SDXL's tokenizer_2/ declares pad_token "!" (id 0) in
        # special_tokens_map.json — pad ids feed unmasked cross-attention, so
        # getting this wrong shifts every generated image.
        self.pad_token_id = self.eos_token_id
        pad_str = self._read_pad_token(tokenizer_dir)
        # Special/added tokens are split out of the text BEFORE BPE and map
        # to their single id with no </w> (tokenizers' added-token splitter);
        # a pad token like SDXL tokenizer_2's "!" joins the set.
        self._special = {
            "<|startoftext|>": self.bos_token_id,
            "<|endoftext|>": self.eos_token_id,
        }
        if pad_str is not None and pad_str in vocab:
            self.pad_token_id = vocab[pad_str]
            self._special[pad_str] = self.pad_token_id
        lib.bpe_set_unk(self._h, self.eos_token_id)  # CLIP unk == eos
        for sym, idx in vocab.items():
            b = sym.encode("utf-8")
            lib.bpe_add_token(self._h, b, len(b), int(idx))
        for rank, (l, r) in enumerate(merges):
            lb, rb = l.encode("utf-8"), r.encode("utf-8")
            lib.bpe_add_merge(self._h, lb, len(lb), rb, len(rb), rank)

        self._byte_map = _bytes_to_unicode()
        self._pat = regex.compile(
            r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"
            r"|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+",
            regex.IGNORECASE,
        )
        self._added_re = regex.compile(
            "|".join(
                regex.escape(s)
                for s in sorted(self._special, key=len, reverse=True)
            )
        )
        self._out = (ctypes.c_int32 * 4096)()

    @staticmethod
    def _read_pad_token(tokenizer_dir: str):
        """Pad token string from special_tokens_map.json / tokenizer_config
        (either plain string or AddedToken dict form)."""
        for fname in ("special_tokens_map.json", "tokenizer_config.json"):
            path = os.path.join(tokenizer_dir, fname)
            if not os.path.exists(path):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    entry = json.load(f).get("pad_token")
            except (OSError, ValueError):
                continue
            if isinstance(entry, dict):
                entry = entry.get("content")
            if isinstance(entry, str):
                return entry
        return None

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.bpe_free(h)

    def _encode_word(self, word: str) -> List[int]:
        mapped = "".join(self._byte_map[b] for b in word.encode("utf-8"))
        # initial symbols: one per mapped char, last carries the </w> marker
        syms = list(mapped[:-1]) + [mapped[-1] + "</w>"]
        payload = "\x00".join(syms).encode("utf-8")
        n = self._lib.bpe_encode_word(
            self._h, payload, len(payload), self._out, len(self._out)
        )
        if n < 0:  # absurdly long word: ids would overflow the buffer
            return [self.eos_token_id]
        return list(self._out[:n])

    def encode(self, text: str) -> List[int]:
        """Raw BPE ids (no bos/eos framing) of one prompt."""
        text = _normalize(text)
        ids: List[int] = []
        pos = 0
        # added-token splitter: special-token literals come out whole, the
        # text between them goes through regex pre-tokenization + BPE
        for m in self._added_re.finditer(text):
            for word in self._pat.findall(text[pos : m.start()]):
                ids.extend(self._encode_word(word))
            ids.append(self._special[m.group(0)])
            pos = m.end()
        for word in self._pat.findall(text[pos:]):
            ids.extend(self._encode_word(word))
        return ids

    def __call__(self, texts, padding="max_length", max_length=None,
                 truncation=True, return_tensors="np"):
        max_length = max_length or self.model_max_length
        rows = []
        for t in texts:
            ids = self.encode(t)
            if truncation:
                ids = ids[: max_length - 2]
            row = [self.bos_token_id] + ids + [self.eos_token_id]
            row += [self.pad_token_id] * (max_length - len(row))
            rows.append(row[:max_length])
        return {"input_ids": np.asarray(rows, np.int64)}

// Zero-copy safetensors reader: the native IO layer of the weight loader.
//
// The reference reaches its native weight loading through torch/safetensors
// C++ (diffusers from_pretrained, /root/reference/distrifuser/pipelines.py:
// 26-28).  This module is the TPU build's equivalent data-loader runtime
// piece: it mmaps a checkpoint shard and fans out a thread pool that touches
// every page (madvise WILLNEED + striped reads), so a cold 5-10 GB SDXL
// shard pages in at full disk bandwidth instead of serially during the
// Python-side tensor conversion.  Tensor views are served zero-copy: Python
// wraps the mapping with numpy.frombuffer and slices per the JSON header.
//
// Plain C ABI (loaded via ctypes; no Python.h dependency):
//   st_open(path, out_size)  -> mmap base address (NULL on error)
//   st_prefetch(addr, size, n_threads) -> bytes touched
//   st_close(addr, size)
//
// Build: distrifuser_tpu/native/__init__.py compiles this with g++ on first
// use and caches the .so next to the source.

#include <cstddef>
#include <cstdint>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

void* st_open(const char* path, uint64_t* out_size) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* addr = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);  // mapping keeps the file alive
  if (addr == MAP_FAILED) return nullptr;
  madvise(addr, st.st_size, MADV_WILLNEED);
  *out_size = static_cast<uint64_t>(st.st_size);
  return addr;
}

uint64_t st_prefetch(void* addr, uint64_t size, int n_threads) {
  if (addr == nullptr || size == 0) return 0;
  if (n_threads < 1) n_threads = 1;
  const size_t page = 4096;
  const uint64_t stripe = (size + n_threads - 1) / n_threads;
  std::vector<std::thread> workers;
  std::vector<uint64_t> touched(n_threads, 0);
  for (int t = 0; t < n_threads; ++t) {
    workers.emplace_back([&, t]() {
      const uint64_t begin = t * stripe;
      const uint64_t end = begin + stripe < size ? begin + stripe : size;
      volatile uint8_t sink = 0;
      const uint8_t* base = static_cast<const uint8_t*>(addr);
      for (uint64_t off = begin; off < end; off += page) {
        sink ^= base[off];
        touched[t] += page;
      }
      (void)sink;
    });
  }
  for (auto& w : workers) w.join();
  uint64_t total = 0;
  for (auto v : touched) total += v;
  return total < size ? total : size;
}

void st_close(void* addr, uint64_t size) {
  if (addr != nullptr && size > 0) munmap(addr, size);
}

}  // extern "C"

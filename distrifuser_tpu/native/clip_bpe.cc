// Native CLIP byte-pair-encoding engine.
//
// The reference tokenizes through HuggingFace's tokenizer stack (Rust/BPE,
// pulled in by diffusers' from_pretrained — /root/reference/distrifuser/
// pipelines.py:30-42).  This is the TPU build's native equivalent: the hot
// per-word merge loop (rank lookups + pair folding, O(n^2) per word) runs in
// C++, while Python owns the unicode-aware pre-tokenization (regex split,
// byte->unicode mapping) and the 77-token framing.  See native/bpe.py.
//
// Interface (ctypes, see native/__init__.py):
//   bpe_new()                        -> engine handle
//   bpe_add_token(h, sym, len, id)   vocab entry: symbol bytes -> id
//   bpe_add_merge(h, l, ll, r, rl, rank)
//   bpe_encode_word(h, word, len, out, cap) -> n ids (or -1 on overflow)
//     `word` is the mapped word as UTF-8 with '\x00' between the initial
//     symbols (codepoint granularity, last symbol carrying "</w>").
//     Unknown residual symbols fall back to `unk` (set via bpe_set_unk).
//   bpe_free(h)
//
// Encoded words are memoized per engine (prompts repeat words heavily).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Engine {
  std::unordered_map<std::string, int32_t> vocab;
  std::unordered_map<std::string, int32_t> merge_rank;  // "l\x01r" -> rank
  std::unordered_map<std::string, std::vector<int32_t>> cache;
  int32_t unk = -1;
};

std::string pair_key(const std::string& l, const std::string& r) {
  std::string k;
  k.reserve(l.size() + r.size() + 1);
  k += l;
  k += '\x01';
  k += r;
  return k;
}

}  // namespace

extern "C" {

void* bpe_new() { return new Engine(); }

void bpe_free(void* h) { delete static_cast<Engine*>(h); }

void bpe_set_unk(void* h, int32_t id) { static_cast<Engine*>(h)->unk = id; }

void bpe_add_token(void* h, const char* sym, uint32_t len, int32_t id) {
  static_cast<Engine*>(h)->vocab.emplace(std::string(sym, len), id);
}

void bpe_add_merge(void* h, const char* l, uint32_t ll, const char* r,
                   uint32_t rl, int32_t rank) {
  static_cast<Engine*>(h)->merge_rank.emplace(
      pair_key(std::string(l, ll), std::string(r, rl)), rank);
}

int32_t bpe_encode_word(void* h, const char* word, uint32_t len, int32_t* out,
                        int32_t cap) {
  Engine& e = *static_cast<Engine*>(h);
  std::string key(word, len);
  auto hit = e.cache.find(key);
  if (hit == e.cache.end()) {
    // split on the '\x00' separators Python placed between initial symbols
    std::vector<std::string> syms;
    {
      size_t start = 0;
      for (size_t i = 0; i <= key.size(); ++i) {
        if (i == key.size() || key[i] == '\0') {
          if (i > start) syms.emplace_back(key.substr(start, i - start));
          start = i + 1;
        }
      }
    }
    // iterative lowest-rank pair folding
    while (syms.size() > 1) {
      int32_t best_rank = INT32_MAX;
      size_t best_i = 0;
      for (size_t i = 0; i + 1 < syms.size(); ++i) {
        auto it = e.merge_rank.find(pair_key(syms[i], syms[i + 1]));
        if (it != e.merge_rank.end() && it->second < best_rank) {
          best_rank = it->second;
          best_i = i;
        }
      }
      if (best_rank == INT32_MAX) break;
      // fold every occurrence of the winning pair left-to-right
      const std::string l = syms[best_i];
      const std::string r = syms[best_i + 1];
      std::vector<std::string> merged;
      merged.reserve(syms.size());
      for (size_t i = 0; i < syms.size();) {
        if (i + 1 < syms.size() && syms[i] == l && syms[i + 1] == r) {
          merged.emplace_back(l + r);
          i += 2;
        } else {
          merged.emplace_back(syms[i]);
          i += 1;
        }
      }
      syms.swap(merged);
    }
    std::vector<int32_t> ids;
    ids.reserve(syms.size());
    for (const auto& s : syms) {
      auto it = e.vocab.find(s);
      ids.push_back(it != e.vocab.end() ? it->second : e.unk);
    }
    hit = e.cache.emplace(std::move(key), std::move(ids)).first;
  }
  const std::vector<int32_t>& ids = hit->second;
  if (static_cast<int32_t>(ids.size()) > cap) return -1;
  std::memcpy(out, ids.data(), ids.size() * sizeof(int32_t));
  return static_cast<int32_t>(ids.size());
}

}  // extern "C"

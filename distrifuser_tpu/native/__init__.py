"""Native (C++) runtime pieces, loaded via ctypes.

`load_safetensors_fast(path)` is the preferred checkpoint-shard reader used
by models/weights.py: it mmaps the file through fast_safetensors.cc (zero
copy; threaded page-in for cold multi-GB SDXL shards) and serves numpy views
sliced per the safetensors JSON header.  Any failure — no compiler, odd
platform, unexpected dtype, corrupt header — falls back to the pure-Python
safetensors package, so the native path is an accelerator, never a
requirement.  Call `release_mappings()` once the returned arrays have been
copied (the weight converters produce fresh jax arrays) to unmap the shards.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
from typing import Dict, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fast_safetensors.cc")
_SO = os.path.join(os.path.dirname(__file__), "_fast_safetensors.so")

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "BF16": None,  # no numpy bf16: served via ml_dtypes (or rejected)
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U64": np.uint64, "U32": np.uint32, "U16": np.uint16, "U8": np.uint8,
    "BOOL": np.bool_,
}

_lib: Optional[ctypes.CDLL] = None
_mappings = []  # (addr, size) for mappings whose views may still be alive


def _compile_and_load(src: str, so: str, *flags: str) -> Optional[ctypes.CDLL]:
    """Rebuild-if-stale then dlopen; None on any failure (callers fall back
    to their pure-Python paths — native code is an accelerator here, never a
    requirement)."""
    try:
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", *flags, "-o", so, src],
                check=True, capture_output=True,
            )
        return ctypes.CDLL(so)
    except Exception:
        return None


def _build() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    lib = _compile_and_load(_SRC, _SO, "-pthread")
    try:
        # a stale/foreign .so may load but lack a symbol: fall back, not crash
        if lib is not None:
            lib.st_open.restype = ctypes.c_void_p
            lib.st_open.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
            lib.st_prefetch.restype = ctypes.c_uint64
            lib.st_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
            lib.st_close.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    except AttributeError:
        lib = None
    _lib = lib
    return _lib


def available() -> bool:
    return _build() is not None


_BPE_SRC = os.path.join(os.path.dirname(__file__), "clip_bpe.cc")
_BPE_SO = os.path.join(os.path.dirname(__file__), "_clip_bpe.so")
_bpe_lib: Optional[ctypes.CDLL] = None


def _build_bpe() -> Optional[ctypes.CDLL]:
    """Compile/load the native CLIP BPE engine (native/bpe.py wraps it)."""
    global _bpe_lib
    if _bpe_lib is not None:
        return _bpe_lib
    lib = _compile_and_load(_BPE_SRC, _BPE_SO)
    try:
        if lib is None:
            raise AttributeError  # no engine; cache the None below
        lib.bpe_new.restype = ctypes.c_void_p
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_set_unk.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.bpe_add_token.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int32,
        ]
        lib.bpe_add_merge.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int32,
        ]
        lib.bpe_encode_word.restype = ctypes.c_int32
        lib.bpe_encode_word.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
    except AttributeError:
        lib = None
    _bpe_lib = lib
    return _bpe_lib


def release_mappings() -> int:
    """Unmap every shard opened by the fast loader.

    Only safe once no numpy views into the mappings are live — the weight
    converters copy everything into jax arrays, so pipelines call this after
    conversion.  Returns the number of mappings released.
    """
    lib = _build()
    n = 0
    if lib is not None:
        while _mappings:
            addr, size = _mappings.pop()
            lib.st_close(addr, size)
            n += 1
    else:
        _mappings.clear()
    return n


def load_safetensors_fast(
    path: str, prefetch_threads: int = 8
) -> Optional[Dict[str, np.ndarray]]:
    """Zero-copy load; returns None whenever the Python loader should be used."""
    lib = _build()
    if lib is None:
        return None
    size = ctypes.c_uint64()
    addr = lib.st_open(path.encode(), ctypes.byref(size))
    if not addr:
        return None
    if prefetch_threads > 0:
        # threaded page-in: touch every page with a striped thread pool so a
        # cold multi-GB shard reads at full disk bandwidth up front instead of
        # serially faulting during per-tensor conversion
        lib.st_prefetch(addr, size.value, prefetch_threads)
    try:
        buf = (ctypes.c_ubyte * size.value).from_address(addr)
        raw = np.frombuffer(buf, dtype=np.uint8)
        (header_len,) = struct.unpack("<Q", raw[:8].tobytes())
        header = json.loads(raw[8 : 8 + header_len].tobytes())
        data = raw[8 + header_len :]

        out: Dict[str, np.ndarray] = {}
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            dt = meta["dtype"]
            begin, end = meta["data_offsets"]
            flat = data[begin:end]
            if dt == "BF16":
                import ml_dtypes  # raises -> python fallback

                arr = flat.view(np.uint16).reshape(meta["shape"]).view(ml_dtypes.bfloat16)
            else:
                arr = flat.view(_DTYPES[dt]).reshape(meta["shape"])
            # views alias a PROT_READ mapping: a write would SIGSEGV, so make
            # the numpy contract say so
            arr.flags.writeable = False
            out[name] = arr
    except Exception:
        lib.st_close(addr, size.value)
        return None
    _mappings.append((addr, size.value))
    return out

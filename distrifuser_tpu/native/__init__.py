"""Native (C++) runtime pieces, loaded via ctypes.

`load_safetensors_fast(path)` is the preferred checkpoint-shard reader used
by models/weights.py: it mmaps the file through fast_safetensors.cc (zero
copy; threaded page-in for cold multi-GB SDXL shards) and serves numpy views
sliced per the safetensors JSON header.  Any failure — no compiler, odd
platform — falls back to the pure-Python safetensors package, so the native
path is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
from typing import Dict, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fast_safetensors.cc")
_SO = os.path.join(os.path.dirname(__file__), "_fast_safetensors.so")

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "BF16": None,  # no numpy bf16: served as uint16 and bitcast by jax
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}

_lib: Optional[ctypes.CDLL] = None
_mappings = []  # keep (addr, size) alive for the process lifetime


def _build() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-o", _SO, _SRC],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
        lib.st_open.restype = ctypes.c_void_p
        lib.st_open.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.st_prefetch.restype = ctypes.c_uint64
        lib.st_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
        lib.st_close.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return _build() is not None


def load_safetensors_fast(
    path: str, prefetch_threads: int = 8
) -> Optional[Dict[str, np.ndarray]]:
    """Zero-copy load; returns None if the native path is unavailable."""
    lib = _build()
    if lib is None:
        return None
    size = ctypes.c_uint64()
    addr = lib.st_open(path.encode(), ctypes.byref(size))
    if not addr:
        return None
    _mappings.append((addr, size.value))
    if prefetch_threads > 0:
        lib.st_prefetch(addr, size.value, prefetch_threads)

    buf = (ctypes.c_ubyte * size.value).from_address(addr)
    raw = np.frombuffer(buf, dtype=np.uint8)
    (header_len,) = struct.unpack("<Q", raw[:8].tobytes())
    header = json.loads(raw[8 : 8 + header_len].tobytes())
    data = raw[8 + header_len :]

    out: Dict[str, np.ndarray] = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        dt = meta["dtype"]
        begin, end = meta["data_offsets"]
        flat = data[begin:end]
        if dt == "BF16":
            # serve raw uint16 code points; models/weights.py bitcasts via
            # jax (ml_dtypes) when casting to the target dtype
            arr = flat.view(np.uint16).reshape(meta["shape"])
            try:
                import ml_dtypes

                arr = arr.view(ml_dtypes.bfloat16)
            except ImportError:
                pass
        else:
            arr = flat.view(_DTYPES[dt]).reshape(meta["shape"])
        out[name] = arr
    return out

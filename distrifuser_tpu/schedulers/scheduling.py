"""Functional diffusion schedulers: DDIM, Euler (discrete), DPM-Solver++ (2M).

The reference delegates scheduling to diffusers and runs it replicated on
every rank (SURVEY.md §1: "the denoising loop, schedulers ... are NOT
reimplemented"); its CLI exposes exactly these three
(/root/reference/scripts/run_sdxl.py:33-36 `--scheduler {ddim,euler,
dpm-solver}`).  A TPU build needs them *functional* so the whole denoise loop
can live inside one `lax.scan` under a single jit: every per-step coefficient
is precomputed into fixed tables at `set_timesteps` time, and `step()` is a
pure function of (sample, model_output, step_index, carry-state) — no data-
dependent Python, no dynamic shapes.

Numerics follow diffusers==0.24.0 (the reference's pin) with the SD/SDXL
defaults: scaled_linear betas in [0.00085, 0.012], 1000 train steps, epsilon
prediction, "leading" timestep spacing, steps_offset=1.

Multistep history (DPM-Solver 2M) is explicit carry state (`init_state`),
exactly like the displaced-patch activation state — it threads through the
scan.

``step_index`` may be a scalar (the scan/stepwise path) or a ``[B]``
vector (the packed cohort step, serve/executors.py `step_run`): every
table lookup broadcasts per batch row through `_per_row`, which is a
no-op on scalars — the scalar path traces the exact program it always
did, and the vector path applies row ``j``'s coefficients to row ``j``
only (elementwise, so bitwise identical per row to the scalar run).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np


def _per_row(coef, ref):
    """Shape a per-row coefficient against a batch-major sample: a
    scalar passes through untouched (the scalar path's program is
    byte-for-byte what it was); a ``[B]`` vector reshapes to
    ``[B, 1, ..., 1]`` so it broadcasts along ``ref``'s batch axis."""
    coef = jnp.asarray(coef)
    if coef.ndim == 0:
        return coef
    return coef.reshape(coef.shape + (1,) * (jnp.ndim(ref) - 1))


def _make_alphas_cumprod(
    num_train_timesteps: int, beta_start: float, beta_end: float, beta_schedule: str
) -> np.ndarray:
    if beta_schedule == "scaled_linear":
        betas = (
            np.linspace(beta_start**0.5, beta_end**0.5, num_train_timesteps) ** 2
        )
    elif beta_schedule == "linear":
        betas = np.linspace(beta_start, beta_end, num_train_timesteps)
    else:
        raise ValueError(f"unsupported beta_schedule {beta_schedule!r}")
    return np.cumprod(1.0 - betas, axis=0)


def _leading_timesteps(num_train_timesteps: int, n: int, steps_offset: int) -> np.ndarray:
    step_ratio = num_train_timesteps // n
    ts = (np.arange(n) * step_ratio).round()[::-1].astype(np.int64) + steps_offset
    return ts


@dataclasses.dataclass
class BaseScheduler:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    beta_schedule: str = "scaled_linear"
    steps_offset: int = 1
    prediction_type: str = "epsilon"

    def __post_init__(self):
        if self.prediction_type not in ("epsilon", "v_prediction"):
            raise NotImplementedError(
                "prediction_type must be 'epsilon' or 'v_prediction'"
            )
        self._alphas_cumprod = _make_alphas_cumprod(
            self.num_train_timesteps, self.beta_start, self.beta_end, self.beta_schedule
        )
        self.num_inference_steps = None

    def _to_epsilon(self, sample, model_output, alpha_cumprod_t):
        """Convert the model output to an epsilon prediction.

        SD 2.x checkpoints are v-prediction (v = alpha*eps - sigma*x0), which
        the reference inherits from diffusers' scheduler configs; normalizing
        to epsilon keeps one update rule per sampler.
        """
        if self.prediction_type == "epsilon":
            return model_output
        a = jnp.sqrt(alpha_cumprod_t)
        s = jnp.sqrt(1.0 - alpha_cumprod_t)
        return a * model_output + s * sample.astype(jnp.float32)

    # ---- shared API -------------------------------------------------------
    @property
    def init_noise_sigma(self) -> float:
        return 1.0

    def scale_model_input(self, sample, step_index):
        return sample

    def init_state(self, latent_shape, dtype=jnp.float32) -> Dict[str, Any]:
        """Carry state threaded through the scan (empty for single-step methods)."""
        return {}

    def timesteps(self) -> jnp.ndarray:
        assert self.num_inference_steps is not None, "call set_timesteps first"
        return self._timesteps

    def add_noise(self, original, noise, step_index):
        """Noise a clean latent to the schedule point ``step_index`` — the
        img2img entry (diffusers add_noise parity): x_t = sqrt(ac_t) x0 +
        sqrt(1 - ac_t) eps at t = timesteps()[step_index]."""
        t = self.timesteps()[step_index]
        ac = _per_row(jnp.asarray(self._alphas_cumprod, jnp.float32)[t],
                      original)
        x0 = original.astype(jnp.float32)
        out = jnp.sqrt(ac) * x0 + jnp.sqrt(1.0 - ac) * noise.astype(jnp.float32)
        return out.astype(original.dtype)

    def step(self, sample, model_output, step_index, state):
        raise NotImplementedError


class DDIMScheduler(BaseScheduler):
    """Deterministic DDIM (eta=0), diffusers DDIMScheduler parity
    (set_alpha_to_one=False for SD/SDXL)."""

    def set_timesteps(self, n: int):
        self.num_inference_steps = n
        ts = _leading_timesteps(self.num_train_timesteps, n, self.steps_offset)
        prev_ts = ts - self.num_train_timesteps // n
        ac = self._alphas_cumprod
        final_alpha = ac[0]  # set_alpha_to_one=False
        alpha_t = ac[ts]
        alpha_prev = np.where(prev_ts >= 0, ac[np.clip(prev_ts, 0, None)], final_alpha)
        self._timesteps = jnp.asarray(ts)
        self._alpha_t = jnp.asarray(alpha_t, jnp.float32)
        self._alpha_prev = jnp.asarray(alpha_prev, jnp.float32)
        return self

    def step(self, sample, model_output, step_index, state):
        a_t = _per_row(self._alpha_t[step_index], sample)
        a_prev = _per_row(self._alpha_prev[step_index], sample)
        x = sample.astype(jnp.float32)
        eps = self._to_epsilon(sample, model_output.astype(jnp.float32), a_t)
        x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        x_prev = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps
        return x_prev.astype(sample.dtype), state


class EulerDiscreteScheduler(BaseScheduler):
    """diffusers EulerDiscreteScheduler parity (no churn/noise: s_churn=0)."""

    def set_timesteps(self, n: int):
        self.num_inference_steps = n
        ts = _leading_timesteps(self.num_train_timesteps, n, self.steps_offset)
        ac = self._alphas_cumprod
        sigmas_full = ((1.0 - ac) / ac) ** 0.5
        sigmas = sigmas_full[ts]
        self._timesteps = jnp.asarray(ts)
        self._sigmas = jnp.asarray(np.append(sigmas, 0.0), jnp.float32)
        self._init_noise_sigma = float((sigmas.max() ** 2 + 1) ** 0.5)
        return self

    @property
    def init_noise_sigma(self) -> float:
        return self._init_noise_sigma

    def scale_model_input(self, sample, step_index):
        sigma = _per_row(self._sigmas[step_index], sample)
        return (sample / jnp.sqrt(sigma**2 + 1.0)).astype(sample.dtype)

    def add_noise(self, original, noise, step_index):
        """Euler carries the sigma-space latent x = x0 + sigma * eps
        (diffusers EulerDiscreteScheduler.add_noise)."""
        sigma = _per_row(self._sigmas[step_index], original)
        out = original.astype(jnp.float32) + sigma * noise.astype(jnp.float32)
        return out.astype(original.dtype)

    def step(self, sample, model_output, step_index, state):
        # Euler works in the sigma-space parameterization x = x0 + sigma * n;
        # `sample` here is that scaled latent (init noise multiplied by
        # init_noise_sigma), `model_output` is epsilon (or v) at the descaled
        # input.
        sigma = _per_row(self._sigmas[step_index], sample)
        sigma_next = _per_row(self._sigmas[step_index + 1], sample)
        x = sample.astype(jnp.float32)
        ac_t = 1.0 / (sigma**2 + 1.0)  # alpha_cumprod of this sigma
        eps = self._to_epsilon(x * jnp.sqrt(ac_t), model_output.astype(jnp.float32), ac_t)
        # x0-from-epsilon in this parameterization: x0 = x - sigma * eps
        x_next = x + (sigma_next - sigma) * eps
        return x_next.astype(sample.dtype), state


class DPMSolverMultistepScheduler(BaseScheduler):
    """DPM-Solver++ 2M, diffusers algorithm_type='dpmsolver++' solver_order=2.

    Second-order multistep: carries the previous step's predicted x0 and
    lambda as explicit scan state.
    """

    solver_order: int = 2

    def set_timesteps(self, n: int):
        self.num_inference_steps = n
        ts = _leading_timesteps(self.num_train_timesteps, n, self.steps_offset)
        ac = self._alphas_cumprod
        alpha = np.sqrt(ac[ts])
        sigma = np.sqrt(1.0 - ac[ts])
        lam = np.log(alpha) - np.log(sigma)
        # final boundary: sigma->0, lambda->+inf; use the conventional
        # diffusers tail where the last step returns x0.
        self._timesteps = jnp.asarray(ts)
        self._alpha = jnp.asarray(np.append(alpha, 1.0), jnp.float32)
        self._sigma = jnp.asarray(np.append(sigma, 0.0), jnp.float32)
        self._lambda = jnp.asarray(np.append(lam, np.inf), jnp.float32)
        return self

    def init_state(self, latent_shape, dtype=jnp.float32):
        return {
            "x0_prev": jnp.zeros(latent_shape, jnp.float32),
            "lambda_prev": jnp.asarray(0.0, jnp.float32),
            "have_prev": jnp.asarray(False),
        }

    def step(self, sample, model_output, step_index, state):
        lam_t_raw = self._lambda[step_index]
        a_t = _per_row(self._alpha[step_index], sample)
        s_t = _per_row(self._sigma[step_index], sample)
        lam_t = _per_row(lam_t_raw, sample)
        a_n = _per_row(self._alpha[step_index + 1], sample)
        s_n = _per_row(self._sigma[step_index + 1], sample)
        lam_n = _per_row(self._lambda[step_index + 1], sample)

        x = sample.astype(jnp.float32)
        eps = self._to_epsilon(sample, model_output.astype(jnp.float32), a_t**2)
        x0 = (x - s_t * eps) / a_t

        h = lam_n - lam_t
        # 2M correction using the previous x0.  First step has no history and
        # the final step uses the first-order update (diffusers
        # lower_order_final=True: the 2M ratio h_prev/h degenerates as
        # sigma -> 0), both falling back to D = x0.
        h_prev = lam_t - _per_row(state["lambda_prev"], sample)
        r = h_prev / jnp.maximum(h, 1e-12)
        d_corr = (1.0 + 1.0 / (2.0 * jnp.maximum(r, 1e-12))) * x0 - (
            1.0 / (2.0 * jnp.maximum(r, 1e-12))
        ) * state["x0_prev"]
        use_corr = state["have_prev"] & (step_index < self.num_inference_steps - 1)
        d = jnp.where(_per_row(use_corr, x0), d_corr, x0)

        # dpmsolver++ update: x_next = (s_n/s_t) x - a_n (e^{-h} - 1) D;
        # at the final step sigma_next == 0 and h == inf, so this reduces to
        # x_next = a_n * D = x0 with no special-casing.
        ratio = jnp.where(s_t > 0, s_n / jnp.maximum(s_t, 1e-12), 0.0)
        em1 = jnp.expm1(-h)
        x_next = ratio * x - a_n * em1 * d

        # the carried scalars keep the shape they arrived with: scalar on
        # the scan/stepwise path, [B] on the packed cohort path
        new_state = {
            "x0_prev": x0,
            "lambda_prev": lam_t_raw,
            "have_prev": (jnp.asarray(True)
                          if jnp.ndim(state["have_prev"]) == 0
                          else jnp.ones_like(state["have_prev"])),
        }
        return x_next.astype(sample.dtype), new_state


@dataclasses.dataclass
class FlowMatchEulerScheduler(BaseScheduler):
    """Euler sampler for rectified-flow models (SD3-class MMDiT).

    Rectified flow parameterizes x_t = (1 - sigma) x0 + sigma * noise with
    sigma in [0, 1]; the model predicts the (straight-path) velocity
    v = noise - x0, and sampling integrates dx = v dsigma from 1 to 0.
    SD3 shifts the sigma grid toward the noisy end for high resolution:
    sigma' = shift * s / (1 + (shift - 1) * s) (Esser et al. 2024, eq. 23
    timestep shifting; shift=3 is the SD3-medium default).  The "timestep"
    fed to the model is sigma * num_train_timesteps.

    The reference pins diffusers 0.24, which predates flow matching
    entirely — this scheduler exists for the MMDiT family extension, not
    for reference parity.  Same functional contract as the others: fixed
    tables at set_timesteps, pure step(), empty carry state.
    """

    shift: float = 3.0

    def __post_init__(self):
        # no beta/alpha tables: flow sigmas are their own schedule.  The
        # inherited dataclass __init__ defaults prediction_type="epsilon";
        # a flow sampler has exactly one prediction convention, so pin it.
        self.prediction_type = "flow"
        self.num_inference_steps = None

    def set_timesteps(self, n: int):
        self.num_inference_steps = n
        lin = np.linspace(1.0, 1.0 / n, n)
        sig = self.shift * lin / (1.0 + (self.shift - 1.0) * lin)
        self._sigmas = jnp.asarray(np.append(sig, 0.0), jnp.float32)
        self._timesteps = jnp.asarray(
            sig * self.num_train_timesteps, jnp.float32
        )
        return self

    def add_noise(self, original, noise, step_index):
        """Flow interpolant x_t = (1 - sigma) x0 + sigma noise (the img2img
        entry; diffusers calls this scale_noise for flow-match schedulers)."""
        s = _per_row(self._sigmas[step_index], original)
        out = (1.0 - s) * original.astype(jnp.float32) + s * noise.astype(
            jnp.float32
        )
        return out.astype(original.dtype)

    def step(self, sample, model_output, step_index, state):
        s = _per_row(self._sigmas[step_index], sample)
        s_next = _per_row(self._sigmas[step_index + 1], sample)
        x = sample.astype(jnp.float32) + (s_next - s) * model_output.astype(
            jnp.float32
        )
        return x.astype(sample.dtype), state


SCHEDULERS = {
    "ddim": DDIMScheduler,
    "euler": EulerDiscreteScheduler,
    "dpm-solver": DPMSolverMultistepScheduler,
    "flow-euler": FlowMatchEulerScheduler,
}


def get_scheduler(name: str, **kwargs) -> BaseScheduler:
    """CLI-name factory, matching the reference's choices (run_sdxl.py:33-36)."""
    if name not in SCHEDULERS:
        raise ValueError(f"scheduler must be one of {sorted(SCHEDULERS)}, got {name!r}")
    return SCHEDULERS[name](**kwargs)

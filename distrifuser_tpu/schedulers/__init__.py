from .scheduling import (
    SCHEDULERS,
    BaseScheduler,
    DDIMScheduler,
    DPMSolverMultistepScheduler,
    EulerDiscreteScheduler,
    get_scheduler,
)

from .scheduling import (
    SCHEDULERS,
    BaseScheduler,
    DDIMScheduler,
    DPMSolverMultistepScheduler,
    EulerDiscreteScheduler,
    FlowMatchEulerScheduler,
    get_scheduler,
)

"""Trace-time comm/compute overlap classification (jaxpr, not HLO).

`utils/overlap.py` proves the displaced-patch overlap contract — every
stale-exchange collective's value reaches ONLY the loop carry, through
data movement (plus, under comm_compress, the cheap elementwise dequant
chain) — from **compiled HLO**.  That check is exact but expensive: the
fake-8-device CPU compile of even the tiny config takes minutes, so the
HLO tests are `slow`-marked and never run on the 2-core tier-1 runner.

This module proves the same structural property one stage earlier, from
the **jaxpr**: tracing is seconds where compiling is minutes, because no
XLA optimization runs.  The classification is necessarily a conservative
mirror of the HLO one — XLA only ever *moves collectives earlier* (its
latency-hiding scheduler) and never introduces a same-iteration consumer
that the jaxpr didn't have — so:

* a collective classified **deferred** here (carry-only through data
  movement) is guaranteed overlappable in the compiled program;
* **deferred_compute** = carry-only but through `_EW_PRIMS` elementwise
  arithmetic — where the compressed-refresh dequantize chains land
  (parallel/compress.py), matching `LoopReport.deferred_compute`;
* **inline** = some transitive consumer does real work this iteration
  (attention matmuls on sync KV, the CFG combine) — these serialize.

`lax.fori_loop` with static bounds and `lax.scan` both trace to `scan`
primitives; unrolled `while` bodies are analyzed the same way with every
output treated as carry.  Call-like primitives (pjit, shard_map, remat,
custom_jvp/vjp) are inlined into one flat dataflow graph; nested control
flow stays opaque (a collective consumed by a nested loop counts inline
— conservative) and is analyzed as its own loop.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: collective primitives whose placement the overlap contract governs
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "all_gather", "psum", "all_to_all", "psum_scatter",
    "reduce_scatter", "pmin", "pmax", "pgather",
})
#: pure data movement: consuming a value through these does not compute
#: with it (jaxpr analog of overlap._DM_OPS)
_DM_PRIMS = frozenset({
    "convert_element_type", "bitcast_convert_type", "reshape", "transpose",
    "concatenate", "pad", "slice", "dynamic_slice", "dynamic_update_slice",
    "broadcast_in_dim", "squeeze", "expand_dims", "rev", "copy", "gather",
    "split", "stop_gradient", "device_put", "optimization_barrier",
})
#: cheap elementwise arithmetic a carry-only chain may traverse and still
#: count latency-hidden (the dequant convert/scale-multiply/residual-add
#: chains) — jaxpr analog of overlap._EW_OPS.  Deliberately excludes
#: dot_general/conv/reduce_* and every collective: traversing those means
#: real compute (or another exchange) consumed the value this iteration.
_EW_PRIMS = frozenset({
    "add", "sub", "mul", "div", "neg", "abs", "sign", "max", "min",
    "clamp", "select_n", "eq", "ne", "ge", "gt", "le", "lt",
    "round", "floor", "ceil", "and", "or", "not", "xor", "rem",
    "integer_pow",
})
#: call-like primitives inlined transparently into the dataflow graph
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "remat", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "shard_map", "custom_partitioning",
})
_LOOP_PRIMS = frozenset({"scan", "while"})


def _jaxpr_types():
    from jax.core import ClosedJaxpr, Jaxpr, Literal

    return Jaxpr, ClosedJaxpr, Literal


def _sub_jaxprs(eqn) -> List[Any]:
    Jaxpr, ClosedJaxpr, _ = _jaxpr_types()
    out = []
    for v in eqn.params.values():
        if isinstance(v, (Jaxpr, ClosedJaxpr)):
            out.append(v)
        elif isinstance(v, (tuple, list)):
            out.extend(x for x in v if isinstance(x, (Jaxpr, ClosedJaxpr)))
    return out


def _open(jx):
    _, ClosedJaxpr, _ = _jaxpr_types()
    return jx.jaxpr if isinstance(jx, ClosedJaxpr) else jx


@dataclasses.dataclass
class JaxprLoopReport:
    """Per-loop classification, same buckets as overlap.LoopReport."""

    kind: str  # "scan" | "while"
    deferred: Dict[str, str]  # instruction label -> primitive name
    inline: Dict[str, str]
    deferred_compute: Dict[str, str]

    @property
    def n_deferred(self) -> int:
        return len(self.deferred)

    @property
    def n_inline(self) -> int:
        return len(self.inline)

    @property
    def n_deferred_compute(self) -> int:
        return len(self.deferred_compute)

    @property
    def n_collectives(self) -> int:
        return self.n_deferred + self.n_inline + self.n_deferred_compute


class _FlatGraph:
    """The loop body flattened across call-like primitives into one SSA
    graph: nodes are integers, `alias` maps each scope's Vars onto them
    (Vars are unique objects per jaxpr, so ``id()`` keys are sound for
    the lifetime of the traced object we hold a reference to)."""

    def __init__(self):
        self.eqns: List[Tuple[str, List[int], List[int]]] = []
        self._alias: Dict[int, int] = {}
        self._n = 0
        self._keepalive: List[Any] = []  # pin Vars so id() stays unique

    def node_for(self, var) -> Optional[int]:
        _, _, Literal = _jaxpr_types()
        if isinstance(var, Literal):
            return None
        key = id(var)
        if key not in self._alias:
            self._alias[key] = self._n
            self._keepalive.append(var)
            self._n += 1
        return self._alias[key]

    def alias(self, var, node: int) -> None:
        self._alias[id(var)] = node
        self._keepalive.append(var)

    def add(self, jx) -> None:
        jaxpr = _open(jx)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            subs = _sub_jaxprs(eqn)
            if name in _CALL_PRIMS and len(subs) == 1:
                sub = _open(subs[0])
                # call invars align with the tail of eqn.invars (leading
                # entries, when present, are closed-over consts)
                n_in = len(sub.invars)
                evs = (eqn.invars[-n_in:] if len(eqn.invars) >= n_in
                       else eqn.invars)
                for sv, ev in zip(sub.invars, evs):
                    node = self.node_for(ev)
                    if node is not None:
                        self.alias(sv, node)
                self.add(subs[0])
                for ov, sv in zip(eqn.outvars, sub.outvars):
                    node = self.node_for(sv)
                    if node is not None:
                        self.alias(ov, node)
                continue
            ins = [n for n in (self.node_for(v) for v in eqn.invars)
                   if n is not None]
            outs = [self.node_for(v) for v in eqn.outvars]
            self.eqns.append((name, ins, [o for o in outs if o is not None]))


def analyze_loop_body(body, num_carry: Optional[int],
                      kind: str) -> Optional[JaxprLoopReport]:
    """Classify every collective in one loop body.  ``num_carry=None``
    treats every outvar as carry (while loops)."""
    jaxpr = _open(body)
    graph = _FlatGraph()
    graph.add(body)
    # only the NON-carry outvars (stacked per-iteration ys) matter to
    # classification: reaching one means same-iteration consumption
    n_carry = len(jaxpr.outvars) if num_carry is None else num_carry
    ys_nodes = set()
    for i, ov in enumerate(jaxpr.outvars[n_carry:]):
        node = graph.node_for(ov)
        if node is not None:
            ys_nodes.add(node)

    consumers: Dict[int, List[int]] = {}
    for idx, (_, ins, _outs) in enumerate(graph.eqns):
        for n in ins:
            consumers.setdefault(n, []).append(idx)

    def classify(out_nodes: Sequence[int]) -> str:
        seen = set()
        frontier = list(out_nodes)
        ew_used = False
        while frontier:
            node = frontier.pop()
            if node in seen:
                continue
            seen.add(node)
            if node in ys_nodes:
                # stacked per-iteration output: consumed outside the
                # carry contract — same-iteration work in disguise
                return "inline"
            for cdx in consumers.get(node, []):
                cname, _cins, couts = graph.eqns[cdx]
                if cname in _DM_PRIMS:
                    frontier.extend(couts)
                elif cname in _EW_PRIMS:
                    ew_used = True
                    frontier.extend(couts)
                else:
                    return "inline"
        return "deferred_compute" if ew_used else "deferred"

    deferred: Dict[str, str] = {}
    inline: Dict[str, str] = {}
    deferred_compute: Dict[str, str] = {}
    count = 0
    for name, _ins, outs in graph.eqns:
        if name not in COLLECTIVE_PRIMS:
            continue
        label = f"{name}#{count}"
        count += 1
        bucket = classify(outs)
        {"deferred": deferred, "inline": inline,
         "deferred_compute": deferred_compute}[bucket][label] = name
    if count == 0:
        return None
    return JaxprLoopReport(kind=kind, deferred=deferred, inline=inline,
                           deferred_compute=deferred_compute)


def find_loops(closed_jaxpr) -> List[Any]:
    """Every scan/while eqn anywhere in the jaxpr tree (call-likes and
    loop bodies are both descended, so nested loops are found too)."""
    loops = []

    def walk(jx):
        jaxpr = _open(jx)
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in _LOOP_PRIMS:
                loops.append(eqn)
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(closed_jaxpr)
    return loops


def analyze_jaxpr_collectives(closed_jaxpr) -> List[JaxprLoopReport]:
    """Classify every loop-body collective of a traced program —
    the jaxpr counterpart of `overlap.analyze_loop_collectives`."""
    reports = []
    for eqn in find_loops(closed_jaxpr):
        if eqn.primitive.name == "scan":
            report = analyze_loop_body(eqn.params["jaxpr"],
                                       eqn.params["num_carry"], "scan")
        else:
            report = analyze_loop_body(eqn.params["body_jaxpr"], None,
                                       "while")
        if report is not None:
            reports.append(report)
    return reports


def format_reports(reports: Sequence[JaxprLoopReport]) -> str:
    from collections import Counter

    out = []
    for r in reports:
        out.append(f"{r.kind} body: {r.n_deferred} deferred / "
                   f"{r.n_deferred_compute} deferred-compute / "
                   f"{r.n_inline} inline")
        for label, bucket in (("deferred", r.deferred),
                              ("deferred-compute", r.deferred_compute),
                              ("inline", r.inline)):
            if bucket:
                out.append(f"  {label}: {dict(Counter(bucket.values()))}")
    return "\n".join(out) if out else "no loop collectives found"

"""distrilint: repo-native static analysis for the invariants PRs re-prove.

The system's correctness under load rests on cross-cutting contracts that
no single module owns — every trace-affecting serve knob mirrored into
`ExecKey` (serve/cache.py), every collective routed through the
WIRE_REGISTRY-accounted helpers so the comm_plan/StepTimeline exact
reconciliation stays exhaustive (parallel/context.py), serve-layer
mutations respecting the scheduler-thread/lock ownership rules
(serve/resilience.py), typed outcomes on every serve failure path
(serve/errors.py), and the stale-exchange collectives staying deferred to
the carry (utils/overlap.py; the PipeFusion/FastUSP overlap contracts).
Until now these were enforced by comments, reviewer memory, and
`slow`-marked 8-device HLO tests that never run on the 2-core tier-1
runner.  This package machine-checks them:

* each **checker** (analysis/checkers/) emits structured `Finding`s with
  ``file:line``, severity, and a stable fingerprint;
* **suppressions** live in a checked-in baseline (analysis/baseline.txt)
  where every entry requires a ``# provenance:`` reason line — the same
  contract the measured routing tables enforce on their data
  (scripts/lint_route_tables.py, itself folded in as a checker);
* ``python -m distrifuser_tpu.analysis --strict`` is the one entry point,
  wired into tier-1 CI as a hard gate before pytest.

See docs/ANALYSIS.md for the checker catalog and the baseline workflow.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    Baseline,
    BaselineError,
    CheckContext,
    Finding,
    apply_baseline,
    render_baseline,
)
from .registry import all_checkers, get_checker, run_checkers  # noqa: F401

"""Checker API: findings, fingerprints, and the provenance'd baseline.

Design contract (mirrors the routing-table lint this generalizes):

* a `Finding` is one violated invariant at one place, with a
  **fingerprint** that is stable across unrelated edits — it hashes the
  checker name, the repo-relative path, and an *identity* string the
  checker chooses (enclosing qualname + violation kind + occurrence
  index, never a line number), so inserting code above a suppressed
  finding does not orphan its baseline entry;
* the **baseline** is reviewable suppressions-as-data: each entry MUST be
  preceded by a ``# provenance:`` line explaining why the violation is
  deliberate.  An entry whose reason is missing (or still the
  ``UNREVIEWED`` placeholder ``--write-baseline`` emits) fails the run —
  a suppression nobody justified is debt pretending to be policy;
* **stale** entries (fingerprint no longer emitted by any checker) fail
  strict runs too: the baseline must shrink when the tree heals, or its
  size stops meaning anything (scripts/analysis_report.py trends it).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")

#: marker ``--write-baseline`` stamps on machine-generated entries; the
#: baseline validator rejects it so every suppression gets a human reason.
UNREVIEWED = "UNREVIEWED"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violated invariant at one location.

    ``identity`` is the fingerprint material (checker-chosen, stable
    across unrelated edits — no line numbers); it defaults to ``message``
    for checkers whose messages are already stable.
    """

    checker: str
    path: str  # repo-relative, posix separators
    line: int  # 1-based; 0 = module/whole-file finding
    message: str
    severity: str = "error"
    identity: str = ""

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}")

    @property
    def fingerprint(self) -> str:
        material = self.identity or self.message
        digest = hashlib.sha256(
            f"{self.checker}|{self.path}|{material}".encode()
        ).hexdigest()
        return digest[:12]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return (f"{loc}: [{self.checker}/{self.severity}] {self.message} "
                f"[{self.fingerprint}]")

    def to_json(self) -> Dict[str, object]:
        return {
            "checker": self.checker,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class BaselineError(ValueError):
    """The baseline file itself violates its format contract (entry
    without a provenance reason, unparseable line, UNREVIEWED reason)."""


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    fingerprint: str
    checker: str
    path: str
    note: str
    reason: str
    line: int  # line number in the baseline file (diagnostics only)


@dataclasses.dataclass
class Baseline:
    """Parsed suppression file.  ``parse`` raises `BaselineError` on
    format violations — a malformed baseline must fail the gate, not
    silently suppress nothing (or everything)."""

    entries: Tuple[BaselineEntry, ...] = ()
    path: Optional[str] = None

    @property
    def fingerprints(self) -> Dict[str, BaselineEntry]:
        return {e.fingerprint: e for e in self.entries}

    @classmethod
    def parse(cls, text: str, path: Optional[str] = None) -> "Baseline":
        entries: List[BaselineEntry] = []
        reason: Optional[str] = None
        for lineno, raw in enumerate(text.splitlines(), start=1):
            line = raw.strip()
            if not line:
                reason = None  # a blank line detaches a dangling reason
                continue
            if line.startswith("#"):
                body = line.lstrip("#").strip()
                if body.lower().startswith("provenance:"):
                    reason = body[len("provenance:"):].strip()
                continue
            parts = line.split(None, 3)
            if len(parts) < 3:
                raise BaselineError(
                    f"{path or 'baseline'}:{lineno}: unparseable entry "
                    f"{line!r} (want: <fingerprint> <checker> <path> "
                    "[note])")
            fp, checker, relpath = parts[0], parts[1], parts[2]
            note = parts[3] if len(parts) == 4 else ""
            if not (len(fp) == 12 and all(c in "0123456789abcdef"
                                          for c in fp)):
                raise BaselineError(
                    f"{path or 'baseline'}:{lineno}: malformed "
                    f"fingerprint {fp!r}")
            if reason is None:
                raise BaselineError(
                    f"{path or 'baseline'}:{lineno}: entry {fp} has no "
                    "'# provenance:' reason line — every suppression "
                    "must say why the violation is deliberate")
            if UNREVIEWED in reason:
                raise BaselineError(
                    f"{path or 'baseline'}:{lineno}: entry {fp} still "
                    f"carries the {UNREVIEWED} placeholder — replace it "
                    "with a real reason or fix the finding")
            entries.append(BaselineEntry(fp, checker, relpath, note,
                                         reason, lineno))
            reason = None
        return cls(entries=tuple(entries), path=path)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(entries=(), path=path)
        with open(path) as f:
            return cls.parse(f.read(), path=path)


@dataclasses.dataclass
class BaselineResult:
    new: List[Finding]
    suppressed: List[Tuple[Finding, BaselineEntry]]
    stale: List[BaselineEntry]


def apply_baseline(findings: Sequence[Finding], baseline: Baseline,
                   active_checkers: Optional[Sequence[str]] = None
                   ) -> BaselineResult:
    """Partition findings into (new, suppressed) and surface stale
    baseline entries whose fingerprint nothing emitted this run.
    ``active_checkers`` limits staleness to entries owned by checkers
    that actually ran — a ``--checker`` subset must not misreport the
    other checkers' suppressions as healed."""
    by_fp = baseline.fingerprints
    new: List[Finding] = []
    suppressed: List[Tuple[Finding, BaselineEntry]] = []
    seen_fps = set()
    for f in findings:
        entry = by_fp.get(f.fingerprint)
        if entry is not None:
            suppressed.append((f, entry))
            seen_fps.add(f.fingerprint)
        else:
            new.append(f)
    active = set(active_checkers) if active_checkers is not None else None
    stale = [e for e in baseline.entries
             if e.fingerprint not in seen_fps
             and (active is None or e.checker in active)]
    return BaselineResult(new=new, suppressed=suppressed, stale=stale)


def render_baseline(findings: Sequence[Finding],
                    previous: Optional[Baseline] = None,
                    header: str = "") -> str:
    """Baseline text covering ``findings``: entries already justified in
    ``previous`` keep their reason; new ones get the UNREVIEWED
    placeholder the validator rejects (forcing a human-written reason
    before the suppression counts)."""
    prev = previous.fingerprints if previous is not None else {}
    out = [header.rstrip()] if header else []
    for f in sorted(findings, key=lambda f: (f.path, f.checker,
                                             f.fingerprint)):
        old = prev.get(f.fingerprint)
        reason = old.reason if old is not None else (
            f"{UNREVIEWED} — justify this suppression or fix the finding")
        out.append(f"# provenance: {reason}")
        out.append(f"{f.fingerprint} {f.checker} {f.path} {f.message}")
        out.append("")
    return "\n".join(out).rstrip() + "\n"


class CheckContext:
    """What checkers get: the repo root, cached ASTs, and file listing.

    Tests point this at fixture trees; the CLI points it at the real
    repo (the directory containing the ``distrifuser_tpu`` package).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._ast_cache: Dict[str, ast.Module] = {}
        self._src_cache: Dict[str, str] = {}

    def abspath(self, relpath: str) -> str:
        return os.path.join(self.root, relpath.replace("/", os.sep))

    def exists(self, relpath: str) -> bool:
        return os.path.exists(self.abspath(relpath))

    def source(self, relpath: str) -> str:
        if relpath not in self._src_cache:
            with open(self.abspath(relpath)) as f:
                self._src_cache[relpath] = f.read()
        return self._src_cache[relpath]

    def tree(self, relpath: str) -> ast.Module:
        if relpath not in self._ast_cache:
            self._ast_cache[relpath] = ast.parse(
                self.source(relpath), filename=relpath)
        return self._ast_cache[relpath]

    def iter_py(self, subdir: str = "") -> Iterable[str]:
        """Repo-relative paths of every .py file under ``subdir``
        (sorted, posix separators), skipping this package itself —
        checker fixtures embedded in docstrings must not self-flag."""
        base = os.path.join(self.root, subdir.replace("/", os.sep))
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames.sort()
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith("distrifuser_tpu/analysis/"):
                    continue
                yield rel


def enclosing_qualname(stack: Sequence[ast.AST]) -> str:
    """Dotted name of the enclosing class/function scope, for stable
    finding identities (``UNet.forward`` survives line-number churn)."""
    names = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    return ".".join(names) if names else "<module>"

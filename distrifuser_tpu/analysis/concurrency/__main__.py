"""``python -m distrifuser_tpu.analysis.concurrency`` — the distrisched
gate: explore N seeded schedules per serve scenario, report race /
deadlock / registry-drift findings through the distrilint baseline, and
fail on scenario invariant violations (which replay bit-identically
from the printed seed).

Exit codes mirror the static gate:
  0  clean (or only baselined findings; non-strict tolerates stale)
  1  non-baselined findings, scenario failures, stale entries (--strict),
     or a malformed baseline
  2  usage errors
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distrifuser_tpu.analysis.concurrency",
        description="distrisched: deterministic schedule exploration "
                    "with happens-before race and deadlock detection "
                    "(docs/ANALYSIS.md)")
    parser.add_argument("--schedules", type=int, default=50,
                        help="seeded schedules per scenario (seeds "
                        "0..N-1; default 50 — the CI gate passes 85 for "
                        "680 total across the eight scenarios)")
    parser.add_argument("--seed", type=int, default=None,
                        help="replay exactly ONE seed per scenario "
                        "(failure reproduction) instead of the range")
    parser.add_argument("--scenario", action="append", default=None,
                        metavar="NAME",
                        help="run only this scenario (repeatable)")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios and exit")
    parser.add_argument("--strict", action="store_true",
                        help="fail on stale baseline entries too (the "
                        "CI gate mode; run the full default scenario x "
                        "seed set or staleness is meaningless)")
    parser.add_argument("--json", metavar="PATH",
                        help="write the findings/exploration report")
    parser.add_argument("--baseline", metavar="PATH", default=None,
                        help="baseline file (default: the shared "
                        "distrifuser_tpu/analysis/baseline.txt)")
    parser.add_argument("--trace-dir", metavar="DIR", default=None,
                        help="write failing schedules' traces here "
                        "(one file per failure, named scenario_seed)")
    parser.add_argument("--print-trace", action="store_true",
                        help="dump each failing schedule trace to "
                        "stderr as well")
    parser.add_argument("--max-steps", type=int, default=60000)
    args = parser.parse_args(argv)

    from ..core import Baseline, BaselineError, apply_baseline
    from ..__main__ import _repo_root, default_baseline_path
    from . import CHECKER_NAMES, SCENARIOS, explore

    if args.list:
        for name, fn in SCENARIOS.items():
            doc = (fn.__doc__ or "").strip().splitlines()
            print(f"{name:28s} {doc[0] if doc else ''}")
        return 0

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s) {unknown}; have {list(SCENARIOS)}",
              file=sys.stderr)
        return 2
    scenarios = {n: SCENARIOS[n] for n in names}
    seeds = ([args.seed] if args.seed is not None
             else list(range(args.schedules)))

    result = explore(scenarios, seeds, max_steps=args.max_steps)

    baseline_path = args.baseline or default_baseline_path(_repo_root())
    try:
        baseline = Baseline.load(baseline_path)
    except BaselineError as exc:
        print(f"BASELINE INVALID: {exc}", file=sys.stderr)
        return 1
    applied = apply_baseline(result.findings, baseline,
                             active_checkers=list(CHECKER_NAMES))

    for f in sorted(applied.new, key=lambda f: (f.checker, f.path)):
        print(f.render(), file=sys.stderr)
    for e in applied.stale:
        print(f"STALE BASELINE ENTRY {e.fingerprint} ({e.checker} "
              f"{e.path}): no explored schedule emits this fingerprint "
              f"any more — remove it from {baseline_path}",
              file=sys.stderr)
    for fail in result.failures:
        print(f"SCENARIO FAILURE {fail.scenario} --seed {fail.seed}: "
              f"{fail.error}", file=sys.stderr)
        if args.trace_dir:
            os.makedirs(args.trace_dir, exist_ok=True)
            path = os.path.join(args.trace_dir,
                                f"{fail.scenario}_{fail.seed}.trace")
            with open(path, "w") as fh:
                fh.write(fail.trace)
            print(f"  schedule trace: {path}", file=sys.stderr)
        if args.print_trace:
            print(fail.trace, file=sys.stderr)

    counts = result.counts()
    summary = {
        "schema": 1,
        "schedules_explored": result.schedules_explored,
        "per_scenario": result.per_scenario,
        "races": counts["concurrency-race"],
        "deadlocks": counts["concurrency-deadlock"],
        "guard_registry_drift": counts["guard-registry-drift"],
        "new": len(applied.new),
        "suppressed": len(applied.suppressed),
        "stale_baseline": len(applied.stale),
        "failures": len(result.failures),
    }
    if args.json:
        report = dict(summary)
        report["findings"] = [f.to_json() for f in applied.new]
        report["suppressed_findings"] = [
            {**f.to_json(), "provenance": e.reason}
            for f, e in applied.suppressed
        ]
        report["failure_list"] = [
            {"scenario": f.scenario, "seed": f.seed, "error": f.error}
            for f in result.failures
        ]
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)

    failed = bool(applied.new) or bool(result.failures) or (
        args.strict and bool(applied.stale))
    status = "FAIL" if failed else "ok"
    print(f"distrisched {status}: {result.schedules_explored} schedules "
          f"across {len(result.per_scenario)} scenarios — "
          f"{counts['concurrency-race']} races, "
          f"{counts['concurrency-deadlock']} deadlocks, "
          f"{counts['guard-registry-drift']} drift "
          f"({len(applied.new)} new, {len(applied.suppressed)} "
          f"suppressed, {len(applied.stale)} stale), "
          f"{len(result.failures)} scenario failures")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

"""distrisched: deterministic schedule exploration for the serve plane.

The dynamic half of the correctness tooling distrilint started (PR 13):
serve scenarios run on seeded virtual schedules (sched.py), a
vector-clock happens-before detector and a lock-order graph watch every
sync point and instrumented attribute write (races.py, harness.py), and
what they find flows through the same Finding/fingerprint/baseline
pipeline as the static checkers.  ``python -m
distrifuser_tpu.analysis.concurrency`` is the gate; docs/ANALYSIS.md
"Concurrency analysis" is the walkthrough.
"""

from .harness import (  # noqa: F401
    CHECKER_NAMES,
    DEADLOCK,
    DRIFT,
    RACE,
    ExplorationResult,
    Failure,
    ScenarioContext,
    ScheduleResult,
    explore,
    run_schedule,
    synthesize_findings,
)
from .races import (  # noqa: F401
    LockOrderGraph,
    RaceDetector,
    RaceReport,
    WriteOriginRecorder,
)
from .sched import (  # noqa: F401
    DeterministicRuntime,
    ScheduleAbort,
    SchedulerError,
)
from .scenarios import SCENARIOS  # noqa: F401

"""The serve-plane scenario suite distrisched explores.

Each scenario drives REAL serve classes (server, fleet, replica, staged
pipeline — the same objects production runs) with the deterministic
fakes from serve/testing.py, under the seeded scheduler.  Scenarios
encode the cross-thread invariants the race-pinning tests
(test_fleet.py stop-during-failover, test_staging.py cache-pin races)
each hand-construct ONE interleaving of — here N seeds explore N
interleavings of the same story, and the invariants are asserted at the
end of every one:

* ``submit_stop_race``   — submit() from clients racing stop(): every
  admitted future resolves; nothing leaks.
* ``failover_exactly_once`` — a replica killed mid-dispatch: the fleet
  fails over, the shared execution ledger proves no request completed
  twice, and every future resolves.
* ``drain_completes_inflight`` — drain() racing live traffic: admitted
  work finishes (never dropped), the replica reaches drained, resume
  serves again.
* ``kill_restart_generation`` — kill then concurrent restarts: exactly
  one restart wins, the generation advances once, the fresh generation
  serves.
* ``staging_stop_midpipeline`` — stop() against the three-stage
  pipeline with batches in flight: every future resolves, the stage
  workers exit.
* ``stepbatch_join_while_stepping`` — clients submitting into the
  step-granular slot pool while it is mid-denoise: every admitted
  future resolves to the request's own deterministic image (joins
  around a request never touch its numerics).
* ``stepbatch_preempt_cancel_race`` — a tight-deadline arrival forcing
  preemption of the occupied slot while a client concurrently cancels
  the victim's future: no wedge, the preemptor completes, the victim
  resolves or stays cancelled — never hangs.
* ``stepbatch_stop_midpreview`` — stop() against the slot pool while
  previews are streaming: every future resolves, the scheduler drains
  occupied AND parked carries deterministically.
* ``stepbatch_kill_during_carry_export`` — a replica killed mid-denoise
  under step batching: every resident carry exports exactly once
  (``CarryExportedError`` with a decodable snapshot), queued work fails
  typed, and the exported carry resumes to completion on a SECOND
  replica, bit-identical.
* ``stepbatch_migrate_vs_cancel`` — a client cancel racing stop()'s
  carry export of the same request: the future settles exactly once
  (cancelled, exported, or completed) under every interleaving, and no
  carry leaks in the pool.
* ``stepbatch_preempt_vs_pack_race`` — a tight-deadline preemption and
  a client cancel landing while the pool is packing same-signature
  slots into fused dispatches: every future settles, every surviving
  image is the request's own deterministic bytes, the pool drains, and
  the pack accounting stays coherent (rows >= dispatches, never
  negative fill).
* ``autoscale_down_vs_carry_export`` — an autoscaler-initiated
  scale-down drain racing the victim's mid-denoise carry export, a
  concurrent late admission, and the survivor's adoption: every future
  resolves, the shared step ledger proves zero re-executed steps, the
  active count never falls below ``min_replicas``, and no carry leaks.
* ``gateway_stop_midstream`` — gateway stop() while SSE consumers are
  mid-stream and requests are mid-denoise: every open stream resolves
  (readers terminate), every admitted future settles, nothing wedges.
* ``gateway_cancel_final_race`` — HTTP cancel racing the scheduler's
  completion of the same request: exactly ONE terminal event lands,
  and the polled status agrees with it under every interleaving.

Gateway scenarios drive the SOCKET-FREE core (`handle_generate` /
`next_events` / `handle_cancel` / `stop`) — the HTTP listener is a thin
translation over it, and a real socket would block the virtual clock.

Keep scenarios clock-clean: every serve object takes ``ctx.clock``, no
real sleeps, tick threads off (tick()/housekeeping driven explicitly) —
the schedule trace must be a pure function of the seed.
"""

from __future__ import annotations

from typing import Dict

from .harness import ScenarioContext


def _serve_config(**overrides):
    from ...utils.config import ObservabilityConfig, ResilienceConfig, \
        ServeConfig

    kw = dict(
        max_queue_depth=16,
        max_batch_size=4,
        batch_window_s=0.002,
        buckets=((64, 64),),
        warmup_buckets=(),
        default_steps=2,
        default_ttl_s=300.0,
        cache_capacity=4,
        resilience=ResilienceConfig(
            max_retries=1,
            backoff_base_s=0.0,
            backoff_multiplier=1.0,
            backoff_max_s=0.0,
            backoff_jitter=0.0,
            watchdog_timeout_s=0.0,  # inline dispatch: hangs are not
            # under test here, interleavings are
            breaker_failure_threshold=3,
            breaker_cooldown_s=0.1,
        ),
        observability=ObservabilityConfig(trace=False),
    )
    kw.update(overrides)
    return ServeConfig(**kw)


def submit_stop_race(ctx: ScenarioContext) -> None:
    """submit() racing stop(): every admitted future resolves."""
    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import FakeExecutorFactory

    server = InferenceServer(FakeExecutorFactory(batch_size=4),
                             _serve_config(), clock=ctx.clock)
    server.start(warmup=False)
    futures = []

    def client(i: int) -> None:
        try:
            futures.append(server.submit(f"prompt-{i}", height=64,
                                         width=64, seed=i))
        except ServeError:
            pass  # admission raced the stop: a typed reject is correct

    clients = [ctx.spawn(f"client{i}", client, i) for i in range(3)]
    stopper = ctx.spawn("stopper", lambda: server.stop(timeout=60.0))
    for t in clients:
        t.join()
    stopper.join()
    server.stop(timeout=60.0)  # idempotent
    for f in futures:
        # ADMITTED futures must resolve — to a result or a typed error,
        # never hang (the invariant stop() documents)
        ctx.result(f, tolerate=(ServeError,))


def failover_exactly_once(ctx: ScenarioContext) -> None:
    """replica killed mid-dispatch: failover succeeds, the ledger
    proves no request executed to completion twice."""
    from ...serve.errors import ServeError
    from ...serve.faults import FaultPlan, FaultRule
    from ...serve.fleet import build_fleet
    from ...serve.testing import ExecutionLedger, LedgerFakeExecutorFactory
    from ...utils.config import FleetConfig

    ledger = ExecutionLedger()
    plan = FaultPlan([FaultRule(site="replica", kind="kill",
                                key_substr="r0", at_calls=(0,))], seed=0)
    fleet = build_fleet(
        lambda name: LedgerFakeExecutorFactory(ledger, name, batch_size=4),
        _serve_config(),
        FleetConfig(tick_s=0.0, auto_restart=False, max_failovers=3,
                    probe_cooldown_s=0.05),
        replicas=(("r0", 1.0), ("r1", 1.0)),
        clock=ctx.clock,
        fault_plan=plan,
    )
    fleet.start()
    futs = [fleet.submit(f"prompt-{i}", height=64, width=64, seed=i)
            for i in range(2)]

    def pump() -> None:
        # housekeeping runs explicitly (tick thread off): re-dispatch
        # parked failovers until everything resolves
        while not all(f.done() for f in futs):
            fleet.tick()
            ctx.rt.yield_point("pump")

    pumper = ctx.spawn("pumper", pump)
    for f in futs:
        r = ctx.result(f, tolerate=(ServeError,))
        assert not isinstance(r, Exception), (
            f"failover should recover onto r1, got {r!r}")
    pumper.join()
    fleet.stop(timeout=60.0)
    assert ledger.max_count() <= 1, (
        f"a request executed to completion twice: {ledger.snapshot()}")


def drain_completes_inflight(ctx: ScenarioContext) -> None:
    """drain() racing traffic: admitted work finishes, drained is
    reached, resume serves again."""
    from ...serve.errors import ServeError, ServerClosedError
    from ...serve.replica import REPLICA_DRAINING, Replica
    from ...serve.testing import FakeExecutorFactory

    rep = Replica("r0", FakeExecutorFactory(batch_size=4),
                  _serve_config(), clock=ctx.clock)
    rep.start()
    futs = [rep.submit(f"prompt-{i}", height=64, width=64, seed=i)
            for i in range(3)]
    drainer = ctx.spawn("drainer", rep.drain)
    for f in futs:
        r = ctx.result(f, tolerate=(ServeError,))
        assert not isinstance(r, Exception), (
            f"drain must let admitted work FINISH, got {r!r}")
    drainer.join()
    assert rep.state == REPLICA_DRAINING, rep.state
    ctx.wait_until(lambda: rep.drained, "replica drained")
    try:
        rep.submit("late", height=64, width=64, seed=9)
        raise AssertionError("a draining replica admitted a request")
    except ServerClosedError:
        pass
    rep.resume()
    r = ctx.result(rep.submit("after-resume", height=64, width=64,
                              seed=10))
    assert r.output is not None
    rep.stop(timeout=60.0)


def kill_restart_generation(ctx: ScenarioContext) -> None:
    """kill then racing restarts: one wins, the generation advances,
    the fresh generation serves."""
    from ...serve.errors import LifecycleError
    from ...serve.faults import FaultPlan, FaultRule
    from ...serve.replica import REPLICA_SERVING, REPLICA_STOPPED, Replica
    from ...serve.testing import FakeExecutorFactory

    plan = FaultPlan([FaultRule(site="replica", kind="kill",
                                key_substr="r0", at_calls=(0,))], seed=0)
    rep = Replica("r0", FakeExecutorFactory(batch_size=4),
                  _serve_config(), clock=ctx.clock, fault_plan=plan)
    rep.start()
    gen = rep.generation
    f = rep.submit("doomed", height=64, width=64, seed=0)
    # the injected kill surfaces as InjectedReplicaKilled (deliberately
    # outside the ServeError hierarchy), so tolerate any exception and
    # assert the dispatch failed
    r = ctx.result(f, tolerate=(Exception,))
    assert isinstance(r, Exception), "the killed dispatch cannot succeed"
    ctx.wait_until(lambda: rep.state == REPLICA_STOPPED, "kill lands")

    outcomes = []

    def restart() -> None:
        try:
            rep.restart(timeout=60.0)
            outcomes.append("ok")
        except LifecycleError:
            outcomes.append("lost-race")  # the documented loser outcome

    r1 = ctx.spawn("restart1", restart)
    r2 = ctx.spawn("restart2", restart)
    r1.join()
    r2.join()
    assert "ok" in outcomes, outcomes
    assert rep.state == REPLICA_SERVING, rep.state
    assert rep.generation >= gen + 1, (rep.generation, gen)
    out = ctx.result(rep.submit("reborn", height=64, width=64, seed=1))
    assert out.output is not None
    rep.stop(timeout=60.0)


def staging_stop_midpipeline(ctx: ScenarioContext) -> None:
    """stop() against the stage pipeline mid-flight: every future
    resolves, the stage workers exit."""
    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import StagedFakeExecutorFactory

    server = InferenceServer(
        StagedFakeExecutorFactory(batch_size=4),
        _serve_config(pipeline_stages=True, max_inflight_batches=2),
        clock=ctx.clock)
    server.start(warmup=False)
    futures = []

    def client(i: int) -> None:
        try:
            futures.append(server.submit(f"prompt-{i}", height=64,
                                         width=64, seed=i))
        except ServeError:
            pass

    clients = [ctx.spawn(f"client{i}", client, i) for i in range(4)]
    stopper = ctx.spawn("stopper", lambda: server.stop(timeout=60.0))
    for t in clients:
        t.join()
    stopper.join()
    server.stop(timeout=60.0)
    for f in futures:
        ctx.result(f, tolerate=(ServeError,))


def _step_config(_serve_overrides=None, **step_kw):
    from ...utils.config import StepBatchConfig

    step_kw.setdefault("enabled", True)
    step_kw.setdefault("slots", 2)
    step_kw.setdefault("step_service_prior_s", 0.01)
    return _serve_config(step_batching=StepBatchConfig(**step_kw),
                         **(_serve_overrides or {}))


def stepbatch_join_while_stepping(ctx: ScenarioContext) -> None:
    """clients joining the in-flight slot pool between steps: every
    admitted future resolves to ITS OWN deterministic image — who
    joined or left around a request never touches its numerics."""
    import numpy as np

    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory, fake_image

    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
        _step_config(), clock=ctx.clock)
    server.start(warmup=False)
    futures = {}

    def client(i: int) -> None:
        try:
            futures[i] = server.submit(f"prompt-{i}", height=64, width=64,
                                       seed=i)
        except ServeError:
            pass  # admission raced the stop: a typed reject is correct

    clients = [ctx.spawn(f"client{i}", client, i) for i in range(4)]
    for t in clients:
        t.join()
    results = {i: ctx.result(f, tolerate=(ServeError,))
               for i, f in futures.items()}
    server.stop(timeout=60.0)
    key = server._exec_key_for(64, 64, 2, cfg=True)
    for i, r in results.items():
        if isinstance(r, Exception):
            continue
        assert np.array_equal(r.output, fake_image(f"prompt-{i}", i, key)), (
            f"request {i} got someone else's image under interleaving")


def stepbatch_preempt_cancel_race(ctx: ScenarioContext) -> None:
    """a tight-deadline arrival preempting the only slot while the
    victim's client concurrently cancels: no wedge, the preemptor
    completes, the victim resolves or stays cancelled."""
    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory

    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.05),
        _step_config(slots=1, step_service_prior_s=0.05),
        clock=ctx.clock)
    server.start(warmup=False)
    victim = server.submit("victim", height=64, width=64, seed=0,
                           num_inference_steps=4, ttl_s=300.0)
    ctx.wait_until(lambda: server.stepbatch.occupied(), "victim admitted")
    # needs 4 x 0.05 = 0.2s; ttl 0.3 => waiting out the victim's ~0.2s
    # remaining would miss, admitted-now makes it: the preemption shape
    tight = server.submit("tight", height=64, width=64, seed=1,
                          num_inference_steps=4, ttl_s=0.3)
    canceller = ctx.spawn("canceller", victim.cancel)
    # the preemptor must COMPLETE (ctx.result waiting out a hang is the
    # step budget's job); a typed reject is also legal under some
    # interleavings — what is not legal is an unresolved future
    ctx.result(tight, tolerate=(ServeError,))
    canceller.join()
    # the victim must SETTLE (result, typed error, or cancelled) — a
    # preempted-then-cancelled slot must never hang its future
    ctx.wait_until(victim.done, "victim future settles")
    server.stop(timeout=60.0)
    sb = server.stepbatch
    assert not sb.occupied() and not sb.parked, "slots leaked at stop"


def stepbatch_stop_midpreview(ctx: ScenarioContext) -> None:
    """stop() against the slot pool mid-preview-stream: every future
    resolves; occupied and parked carries drain deterministically."""
    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory

    previews = []
    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
        _step_config(preview_interval=1), clock=ctx.clock)
    server.start(warmup=False)
    futures = []

    def client(i: int) -> None:
        try:
            futures.append(server.submit(
                f"prompt-{i}", height=64, width=64, seed=i,
                num_inference_steps=4,
                on_progress=lambda s, t, img: previews.append((s, t))))
        except ServeError:
            pass

    clients = [ctx.spawn(f"client{i}", client, i) for i in range(3)]
    stopper = ctx.spawn("stopper", lambda: server.stop(timeout=60.0))
    for t in clients:
        t.join()
    stopper.join()
    server.stop(timeout=60.0)
    for f in futures:
        ctx.result(f, tolerate=(ServeError,))
    sb = server.stepbatch
    assert not sb.occupied() and not sb.parked, "carries leaked at stop"


def stepbatch_kill_during_carry_export(ctx: ScenarioContext) -> None:
    """a replica killed mid-denoise: resident carries export exactly
    once, and the exported carry resumes bit-identically elsewhere."""
    import numpy as np

    from ...serve.errors import CarryExportedError, ServeError
    from ...serve.faults import FaultPlan, FaultRule
    from ...serve.migration import decode_snapshot
    from ...serve.replica import REPLICA_STOPPED, Replica
    from ...serve.testing import StepFakeExecutorFactory, fake_image

    plan = FaultPlan([FaultRule(site="replica", kind="kill",
                                key_substr="r0", p=1.0, after_calls=2,
                                max_fires=1)], seed=0)
    rep = Replica("r0",
                  StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
                  _step_config(), clock=ctx.clock, fault_plan=plan)
    rep.start()
    futs = {}

    def client(i: int) -> None:
        try:
            futs[i] = rep.submit(f"prompt-{i}", height=64, width=64,
                                 seed=i, num_inference_steps=4)
        except ServeError:
            pass  # admission raced the kill: a typed reject is correct

    clients = [ctx.spawn(f"client{i}", client, i) for i in range(3)]
    for t in clients:
        t.join()
    exported = {}
    for i, f in futs.items():
        # killed dispatches fail TYPED — CarryExportedError for resident
        # carries, ServerClosedError for queued work — never hang (the
        # injected kill itself must not leak to a request future)
        r = ctx.result(f, tolerate=(ServeError,))
        assert isinstance(r, Exception), (
            "a 4-step request cannot outrun the round-2 kill")
        if isinstance(r, CarryExportedError) and r.snapshot is not None:
            snap = decode_snapshot(r.snapshot)  # corrupt would raise
            assert 0 < snap.step < snap.steps_total, snap.step
            assert snap.step == r.steps_done, (snap.step, r.steps_done)
            exported[i] = r.snapshot
    assert exported, "a kill after 2 cohort rounds must export a carry"
    ctx.wait_until(lambda: rep.state == REPLICA_STOPPED, "kill lands")
    rep.stop(timeout=60.0)
    server = rep.server
    if server is not None and server.stepbatch is not None:
        sb = server.stepbatch
        assert not sb.occupied() and not sb.parked, "carries leaked"
    # the exported carry must RESUME on a fresh replica, bit-identical
    # to the request's own deterministic image — the migration story
    i, data = sorted(exported.items())[0]
    rep2 = Replica("r1",
                   StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
                   _step_config(), clock=ctx.clock)
    rep2.start()
    out = ctx.result(rep2.submit(f"prompt-{i}", height=64, width=64,
                                 seed=i, num_inference_steps=4,
                                 carry_snapshot=data))
    assert out.migrations == 1 and out.steps_salvaged > 0, (
        out.migrations, out.steps_salvaged)
    key = rep2.server._exec_key_for(64, 64, 4, cfg=True)
    assert np.array_equal(out.output, fake_image(f"prompt-{i}", i, key)), (
        f"migrated request {i} resumed to a different image")
    rep2.stop(timeout=60.0)


def stepbatch_migrate_vs_cancel(ctx: ScenarioContext) -> None:
    """a client cancel racing stop()'s carry export of the same
    request: the future settles exactly once — cancelled, exported
    (CarryExportedError), or completed — never hangs, no carry leaks."""
    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory

    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
        _step_config(), clock=ctx.clock)
    server.start(warmup=False)
    fut = server.submit("contested", height=64, width=64, seed=0,
                        num_inference_steps=6)
    ctx.wait_until(lambda: server.stepbatch.occupied(), "carry resident")
    canceller = ctx.spawn("canceller", fut.cancel)
    stopper = ctx.spawn("stopper", lambda: server.stop(timeout=60.0))
    canceller.join()
    stopper.join()
    server.stop(timeout=60.0)
    # the contested future must SETTLE exactly once under every
    # interleaving: cancel winning leaves it cancelled (the export's
    # set_exception loses silently), export winning resolves it with
    # CarryExportedError carrying the snapshot, and a full-speed run
    # may simply complete — what is never legal is an unresolved future
    ctx.wait_until(fut.done, "contested future settles")
    if not fut.cancelled():
        ctx.result(fut, tolerate=(ServeError,))
    # stop() may return on its bounded scheduler join (stop_join_timeouts
    # is a real, explored path) while the drain is still removing the
    # cancelled carry — the invariant is EVENTUAL emptiness, not
    # emptiness at the instant stop() returns
    sb = server.stepbatch
    ctx.wait_until(lambda: not sb.occupied() and not sb.parked,
                   "pool drains (no carry leaked)")


def stepbatch_preempt_vs_pack_race(ctx: ScenarioContext) -> None:
    """preemption and cancel landing while the pool packs
    same-signature slots into fused dispatches (step_width truncation +
    pack_align on): the park must extract the victim OUT of the shared
    packed carry mid-round, the survivors keep packing, and every
    surviving image is the request's own deterministic bytes.  The
    pack accounting (stepbatch_dispatches / stepbatch_packed_rows /
    pack_aligned) must stay coherent under every interleaving."""
    import numpy as np

    from ...serve.errors import ServeError
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory, fake_image

    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.02),
        _step_config(slots=3, step_width=2, step_service_prior_s=0.02),
        clock=ctx.clock)
    server.start(warmup=False)
    futures = {}

    def client(i: int, steps: int, ttl: float) -> None:
        try:
            futures[i] = server.submit(
                f"prompt-{i}", height=64, width=64, seed=i,
                num_inference_steps=steps, ttl_s=ttl)
        except ServeError:
            pass  # admission raced the stop: a typed reject is correct

    # three packable residents (same signature: same step count) fill
    # the slots; the width-2 cohort packs two of them per round
    residents = [ctx.spawn(f"client{i}", client, i, 6, 300.0)
                 for i in range(3)]
    for t in residents:
        t.join()
    ctx.wait_until(lambda: len(server.stepbatch.occupied()) > 0,
                   "a resident admitted")
    # a tight-deadline arrival forces preemption of the slackest
    # resident (parking a member of the active pack) while a client
    # concurrently cancels another resident
    tight = ctx.spawn("tight", client, 9, 4, 0.25)
    canceller = ctx.spawn("canceller",
                          lambda: 0 in futures and futures[0].cancel())
    tight.join()
    canceller.join()
    results = {i: ctx.result(f, tolerate=(ServeError,))
               for i, f in futures.items() if not f.cancelled()}
    server.stop(timeout=60.0)
    sb = server.stepbatch
    ctx.wait_until(lambda: not sb.occupied() and not sb.parked,
                   "pool drains (no carry leaked)")
    # bit-identity under preempt-vs-pack: every completed request got
    # ITS OWN image regardless of who it was packed with or parked over
    for i, r in results.items():
        if isinstance(r, Exception):
            continue
        steps = 4 if i == 9 else 6
        key = server._exec_key_for(64, 64, steps, cfg=True)
        assert np.array_equal(r.output, fake_image(f"prompt-{i}", i, key)), (
            f"request {i} got someone else's image under preempt-vs-pack")
    # pack accounting coherence: rows cover at least one request-step
    # per dispatch and never exceed capacity
    snap = server.metrics_snapshot()
    reqs = snap["requests"]
    nd = reqs.get("stepbatch_dispatches", 0)
    nr = reqs.get("stepbatch_packed_rows", 0)
    assert nr >= nd >= 0, (nd, nr)
    assert nr == reqs.get("steps_executed", 0), (nr, reqs)
    assert snap["step_batching"]["pack_aligned"] >= 0


def autoscale_down_vs_carry_export(ctx: ScenarioContext) -> None:
    """an autoscaler scale-down drain racing the victim's mid-denoise
    carry export, a late admission, and the survivor's adoption: every
    future settles, the step ledger proves zero re-executed steps, the
    floor holds, and no carry leaks."""
    import numpy as np

    from ...serve.autoscale import Autoscaler
    from ...serve.errors import ServeError
    from ...serve.fleet import FleetRouter
    from ...serve.replica import REPLICA_STOPPED, Replica
    from ...serve.testing import ExecutionLedger, \
        StepLedgerFakeExecutorFactory, fake_image
    from ...utils.config import AutoscaleConfig, FleetConfig

    ledger = ExecutionLedger()
    cfg = _step_config(slots=4)
    reps = [Replica(n,
                    StepLedgerFakeExecutorFactory(ledger, replica=n,
                                                  batch_size=4,
                                                  step_time_s=0.01),
                    cfg, clock=ctx.clock)
            for n in ("r0", "r1")]
    router = FleetRouter(reps, FleetConfig(tick_s=0.0, auto_restart=False),
                         clock=ctx.clock)
    router.start()
    # attached AFTER start so BOTH replicas serve — the interleavings
    # under exploration are drain-vs-export-vs-adoption, not the
    # dormant-start path (tests/test_autoscale.py owns that).  The high
    # watermark is parked out of reach: a transient adoption spike must
    # not re-warm the victim mid-story.
    a = Autoscaler(router, AutoscaleConfig(
        enabled=True, min_replicas=1, pressure_high=10.0,
        pressure_low=0.5, up_sustain_s=0.0, down_sustain_s=0.0,
        cooldown_s=0.0, drain_deadline_s=0.02))
    router.autoscaler = a
    # submit SEQUENTIALLY so least-pending routing spreads the two
    # residents across the replicas — but tolerate the schedules where
    # both land on one replica or a request finishes early (the drain
    # then has less to export; the invariants below hold regardless)
    futs = {0: router.submit("prompt-0", height=64, width=64, seed=0,
                             num_inference_steps=6)}
    ctx.wait_until(
        lambda: futs[0].done()
        or any(r.server.stepbatch.occupied() for r in reps),
        "first carry resident")
    futs[1] = router.submit("prompt-1", height=64, width=64, seed=1,
                            num_inference_steps=6)
    ctx.wait_until(
        lambda: any(f.done() for f in futs.values())
        or all(r.server.stepbatch.occupied() for r in reps),
        "a carry resident per replica (or an early finisher)")
    # <= 2 occupied / 8 slots = 0.25 <= low with active 2 > min 1: the
    # policy MUST fire; the 0.02s deadline lands mid-denoise (6 steps
    # x 0.01s), so the victim's resident exports under most schedules
    fired = a.tick()
    assert fired == "down", fired

    def late_client() -> None:
        # admission racing the background drain: must route around the
        # draining victim or reject typed — never wedge, never land work
        # that the drain then drops
        try:
            futs[9] = router.submit("late", height=64, width=64, seed=9,
                                    num_inference_steps=2)
        except ServeError:
            pass

    late = ctx.spawn("late-client", late_client)

    def pump() -> None:
        # housekeeping runs explicitly (tick thread off): parked
        # adoptions re-dispatch until everything resolves; the
        # autoscaler ticks ride along and must hold the min floor
        while not all(f.done() for f in futs.values()):
            router.tick()
            ctx.rt.yield_point("pump")

    pumper = ctx.spawn("pumper", pump)
    late.join()
    ctx.wait_until(lambda: any(r.state == REPLICA_STOPPED for r in reps),
                   "victim released")
    victim = next(r for r in reps if r.state == REPLICA_STOPPED)
    survivor = next(r for r in reps if r is not victim)
    outs = {i: ctx.result(f, tolerate=(ServeError,))
            for i, f in futs.items()}
    pumper.join()
    ctx.wait_until(lambda: not a.snapshot()["op_inflight"],
                   "drain op finishes")
    assert a.active_count() >= 1, "drained below min_replicas"
    # the two residents were ADMITTED before the drain: a scale-down
    # salvages them (complete in place or migrate), never drops them
    for i in range(2):
        out = outs[i]
        assert not isinstance(out, Exception), (
            f"scale-down dropped admitted request {i}: {out!r}")
        if out.migrations:
            assert out.replica == survivor.name, (out.replica, victim.name)
            assert out.steps_salvaged > 0, out.steps_salvaged
        key = survivor.server._exec_key_for(64, 64, 6, cfg=True)
        assert np.array_equal(out.output,
                              fake_image(f"prompt-{i}", i, key)), (
            f"request {i} resumed to a different image after the drain")
    router.stop(timeout=60.0)
    assert ledger.max_step_count() <= 1, (
        f"a denoise step executed twice: {ledger.steps_snapshot()}")
    snap = router.metrics_snapshot()["fleet"]["requests"]
    assert snap.get("fleet_steps_reexecuted", 0) == 0, snap
    for r in reps:
        server = r.server
        if server is not None and server.stepbatch is not None:
            sb = server.stepbatch
            ctx.wait_until(lambda sb=sb: not sb.occupied() and not sb.parked,
                           "pool drains (no carry leaked)")


def gateway_stop_midstream(ctx: ScenarioContext) -> None:
    """gateway stop() while SSE consumers are mid-stream: every open
    stream resolves (no reader left waiting), every admitted future
    settles, and the draining gateway rejects new work with a typed
    503 — never a hang."""
    from ...serve.errors import ServeError, ServerClosedError
    from ...serve.gateway import Gateway
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory
    from ...utils.config import GatewayConfig, TenantConfig

    gw_cfg = GatewayConfig(tenants=(TenantConfig(name="a", weight=2.0),
                                    TenantConfig(name="b", weight=1.0)))
    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
        _step_config({"gateway": gw_cfg}, preview_interval=1),
        clock=ctx.clock)
    server.start(warmup=False)
    gateway = Gateway(server, config=gw_cfg, clock=ctx.clock)
    subs = []

    def client(i: int) -> None:
        status, body = gateway.handle_generate({
            "prompt": f"prompt-{i}", "height": 64, "width": 64,
            "steps": 4, "seed": i, "tenant": "a" if i % 2 else "b"})
        if status == 202:
            subs.append(body["id"])
        else:
            # admission raced the drain: typed rejection is correct
            assert status in (429, 503), (status, body)

    streams = {}

    def reader(i: int) -> None:
        # waits out client i's submission, then consumes its stream to
        # resolution — exactly what the HTTP SSE handler loop does
        ctx.wait_until(lambda: len(subs) > i or gateway._stopping,
                       f"stream {i} has a request id")
        if len(subs) <= i:
            return
        rid, cursor, names = subs[i], -1, []
        while True:
            evs, resolved = gateway.next_events(rid, cursor, timeout=0.05)
            for seq, name, _ in evs:
                cursor, _ = seq, names.append(name)
            if resolved and not evs:
                break
        streams[i] = names

    clients = [ctx.spawn(f"client{i}", client, i) for i in range(3)]
    readers = [ctx.spawn(f"reader{i}", reader, i) for i in range(3)]
    stopper = ctx.spawn("stopper", gateway.stop)
    for t in clients:
        t.join()
    stopper.join()
    for t in readers:
        t.join()  # the invariant: NO reader is left waiting after stop
    # a draining gateway turns new work away with the typed 503
    status, body = gateway.handle_generate({"prompt": "late"})
    assert status == 503 and body["error"] == "ServerClosedError"
    server.stop(timeout=60.0)
    for rid in subs:
        # every admitted future settles (result, typed error, cancel)
        gr = gateway._get(rid)
        ctx.result(gr.future, tolerate=(ServeError, ServerClosedError))
    for names in streams.values():
        # a consumed stream always starts at queued; at most one
        # terminal event ever lands, whatever the stop interleaving
        assert not names or names[0] == "queued", names
        terminals = [n for n in names
                     if n in ("final", "error", "cancelled")]
        assert len(terminals) <= 1, names


def gateway_cancel_final_race(ctx: ScenarioContext) -> None:
    """cancel racing the scheduler's own completion: exactly one
    terminal event, and handle_status agrees with it."""
    from ...serve.gateway import Gateway
    from ...serve.server import InferenceServer
    from ...serve.testing import StepFakeExecutorFactory

    server = InferenceServer(
        StepFakeExecutorFactory(batch_size=4, step_time_s=0.01),
        _step_config(preview_interval=1), clock=ctx.clock)
    server.start(warmup=False)
    gateway = Gateway(server, clock=ctx.clock)
    status, sub = gateway.handle_generate({
        "prompt": "contested", "height": 64, "width": 64, "steps": 2})
    assert status == 202
    rid = sub["id"]
    canceller = ctx.spawn(
        "canceller", lambda: gateway.handle_cancel(rid))
    canceller.join()
    gr = gateway._get(rid)
    ctx.wait_until(gr.future.done, "contested future settles")
    ctx.wait_until(lambda: gr.done, "terminal event lands")
    server.stop(timeout=60.0)
    evs, resolved = gateway.next_events(rid, -1, timeout=0)
    assert resolved
    names = [n for _, n, _ in evs]
    terminals = [n for n in names if n in ("final", "error", "cancelled")]
    assert len(terminals) == 1, names      # exactly one winner
    _, st = gateway.handle_status(rid)
    # the polled status is the event stream's terminal, never a mix
    assert (terminals[0], st["status"]) in (
        ("final", "completed"), ("error", "error"),
        ("cancelled", "cancelled")), (terminals, st)
    gateway.stop()


SCENARIOS: Dict[str, object] = {
    "submit_stop_race": submit_stop_race,
    "failover_exactly_once": failover_exactly_once,
    "drain_completes_inflight": drain_completes_inflight,
    "kill_restart_generation": kill_restart_generation,
    "staging_stop_midpipeline": staging_stop_midpipeline,
    "stepbatch_join_while_stepping": stepbatch_join_while_stepping,
    "stepbatch_preempt_cancel_race": stepbatch_preempt_cancel_race,
    "stepbatch_stop_midpreview": stepbatch_stop_midpreview,
    "stepbatch_kill_during_carry_export": stepbatch_kill_during_carry_export,
    "stepbatch_migrate_vs_cancel": stepbatch_migrate_vs_cancel,
    "stepbatch_preempt_vs_pack_race": stepbatch_preempt_vs_pack_race,
    "autoscale_down_vs_carry_export": autoscale_down_vs_carry_export,
    "gateway_stop_midstream": gateway_stop_midstream,
    "gateway_cancel_final_race": gateway_cancel_final_race,
}

"""distrisched's deterministic scheduler: serve code on virtual threads.

The serve plane runs unmodified — real Python threads, real control flow
— but every synchronization primitive it constructs (via utils/sync.py)
is a *virtual* one owned by this runtime, and exactly ONE managed thread
holds the run token at any instant.  At every sync point (lock
acquire/release, condition wait/notify, event set/wait, semaphore ops,
queue ops, thread start/join/exit, patched time.sleep, Future waits) the
running thread yields to the scheduler, which picks the next thread from
a seeded RNG — so a schedule is a pure function of its seed, any failure
replays bit-identically from the printed seed, and N seeds explore N
distinct interleavings of the same scenario.

Blocking is modeled, never real: a thread that would block parks on the
runtime (its real thread waits on a private baton event) until the
resource wakes it — or, for finite-timeout waits, until the scheduler
*chooses* to deliver the timeout, which is how timeout-dependent paths
(watchdog fires, join gives up, linger window closes) get explored
without wall-clock time.  Virtual time advances a fixed quantum per
step, so deadline arithmetic stays deterministic.

Detection rides the same hooks: vector clocks flow through every
release/acquire pair (races.py), the lock-order graph accumulates
held-while-acquiring edges, and a state where no thread is runnable nor
timeout-wakeable is a concrete deadlock — reported with its wait-for
cycle and the replay seed, then unwound by aborting every thread with
`ScheduleAbort` (a BaseException, so serve-layer ``except Exception``
guards cannot swallow the teardown).
"""

from __future__ import annotations

import queue as _queue_mod
import random
import threading as _threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .races import LockOrderGraph, RaceDetector, WriteOriginRecorder, merge

RUNNABLE = "runnable"
BLOCKED = "blocked"
FINISHED = "finished"
NEW = "new"


class ScheduleAbort(BaseException):
    """Raised inside managed threads to unwind an aborted schedule
    (deadlock found / step budget exhausted).  BaseException on purpose:
    the serve layer's broad ``except Exception`` guards must not swallow
    the teardown and keep a dead schedule's threads running."""


class SchedulerError(RuntimeError):
    """Harness misuse (unmanaged thread touched a virtual primitive,
    nested runtimes, ...) — a bug in the scenario or the harness, never
    a finding about the code under test."""


class VThread:
    """Bookkeeping for one managed thread."""

    __slots__ = ("tid", "name", "state", "baton", "vc", "wake_reason",
                 "waiting_on", "wait_kind", "timeout_ok", "waiters",
                 "held", "real", "target", "args", "kwargs", "exc",
                 "started", "last_op")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.state = NEW
        self.baton = _threading.Event()  # real: the run token hand-off
        self.vc: Dict[int, int] = {tid: 1}
        self.wake_reason: Optional[str] = None
        self.waiting_on: Any = None
        self.wait_kind = ""
        self.timeout_ok = False
        self.waiters: List["VThread"] = []  # joiners
        self.held: List[Any] = []  # virtual locks currently held
        self.real: Optional[_threading.Thread] = None
        self.target: Optional[Callable] = None
        self.args: tuple = ()
        self.kwargs: dict = {}
        self.exc: Optional[BaseException] = None
        self.started = False
        self.last_op = ""


class DeadlockInfo:
    """One concrete wedged state: who waits on what, plus the lock-owner
    wait-for cycle when one exists."""

    def __init__(self, waits: List[Tuple[str, str, str]],
                 cycle: Tuple[str, ...], seed: int, step: int):
        self.waits = waits  # (thread, kind, label)
        self.cycle = cycle  # thread names, possibly empty
        self.seed = seed
        self.step = step

    def describe(self) -> str:
        waits = "; ".join(f"{t} waits[{k}] {l}" for t, k, l in self.waits)
        cyc = (" cycle: " + " -> ".join(self.cycle)) if self.cycle else ""
        return f"step {self.step}: {waits}{cyc}"


class DeterministicRuntime:
    """One seeded schedule over one scenario run (module docstring)."""

    CLOCK_QUANTUM = 0.0005  # virtual seconds per scheduling step

    def __init__(self, seed: int, max_steps: int = 60000,
                 check_reads: bool = False):
        self.seed = seed
        self.rng = random.Random(seed)
        self.max_steps = max_steps
        self.threads: List[VThread] = []
        self._by_ident: Dict[int, VThread] = {}
        self._now = 0.0
        self._steps = 0
        self._prim_seq = 0
        self._obj_seq: Dict[int, int] = {}  # id(obj) -> stable seq
        # pin every observed object: id() values recycle after GC, and a
        # recycled id would alias two objects' access histories
        self._obj_refs: List[Any] = []
        self._aborted = False
        self.budget_exhausted = False
        self.trace: List[str] = []
        self.detector = RaceDetector(check_reads=check_reads)
        self.lock_graph = LockOrderGraph()
        self.writes = WriteOriginRecorder()
        self.deadlocks: List[DeadlockInfo] = []
        # cross-channel (Future) hand-off clocks, keyed by id(channel)
        self._channel_vc: Dict[int, Dict[int, int]] = {}
        self._names: Dict[int, str] = {}
        self._lock_labels_seen: List[str] = []

    # -- registration -------------------------------------------------------

    def register_main(self) -> VThread:
        vt = VThread(0, "0:main")
        vt.state = RUNNABLE
        vt.started = True
        self.threads.append(vt)
        self._names[0] = vt.name
        self._by_ident[_threading.get_ident()] = vt
        return vt

    def current(self) -> VThread:
        vt = self._by_ident.get(_threading.get_ident())
        if vt is None:
            raise SchedulerError(
                "a virtual primitive was touched from a thread the "
                "deterministic runtime does not manage — scenarios must "
                "create every thread through utils.sync.Thread")
        return vt

    def clock(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += max(0.0, float(dt))

    def obj_seq(self, obj) -> int:
        key = id(obj)
        seq = self._obj_seq.get(key)
        if seq is None:
            seq = len(self._obj_seq)
            self._obj_seq[key] = seq
            self._obj_refs.append(obj)
        return seq

    # -- the scheduling core ------------------------------------------------

    def _check_abort(self) -> None:
        if self._aborted:
            raise ScheduleAbort()

    def yield_point(self, op: str) -> None:
        """One scheduling decision: trace the op, advance virtual time,
        and maybe hand the token to another thread."""
        self._check_abort()
        cur = self.current()
        self._step(cur, op)
        self._check_abort()
        nxt = self._choose()
        if nxt is None or nxt is cur:
            return
        self._handoff(cur, nxt)
        self._check_abort()

    def _step(self, cur: VThread, op: str) -> None:
        cur.last_op = op  # context for race reports
        self.trace.append(f"{self._steps:05d} {cur.name} {op}")
        self._steps += 1
        self._now += self.CLOCK_QUANTUM
        if self._steps > self.max_steps:
            self.budget_exhausted = True
            self._abort_all(cur)

    def _candidates(self) -> List[VThread]:
        return [t for t in self.threads
                if t.state == RUNNABLE
                or (t.state == BLOCKED and t.timeout_ok)]

    def _choose(self) -> Optional[VThread]:
        cands = self._candidates()
        if not cands:
            return None
        return self.rng.choice(cands)

    def _wake(self, vt: VThread, reason: str) -> None:
        """Move a blocked thread back to RUNNABLE (does not hand off)."""
        if vt.state != BLOCKED:
            return
        w = vt.waiting_on
        if w is not None:
            waiters = getattr(w, "waiters", None)
            if waiters is not None and vt in waiters:
                waiters.remove(vt)
        vt.waiting_on = None
        vt.timeout_ok = False
        vt.state = RUNNABLE
        vt.wake_reason = reason

    def _handoff(self, cur: Optional[VThread], nxt: VThread) -> None:
        if nxt.state == BLOCKED:
            # chosen for timeout delivery
            self._wake(nxt, "timeout")
        nxt.baton.set()
        if cur is not None:
            cur.baton.wait()
            cur.baton.clear()

    def block(self, waitable, kind: str, timeout=None) -> str:
        """Park the current thread on ``waitable`` until woken; returns
        the wake reason ("notify" / "retry" / "timeout")."""
        self._check_abort()
        cur = self.current()
        label = getattr(waitable, "label", getattr(waitable, "name", "?"))
        self._step(cur, f"block[{kind}] {label}")
        self._check_abort()
        cur.state = BLOCKED
        cur.waiting_on = waitable
        cur.wait_kind = kind
        cur.timeout_ok = timeout is not None and timeout >= 0
        waitable.waiters.append(cur)
        nxt = self._choose()
        if nxt is None:
            self._deadlock(cur)
            raise ScheduleAbort()
        self._handoff(cur, nxt)
        self._check_abort()
        reason = cur.wake_reason or "retry"
        cur.wake_reason = None
        if reason == "timeout":
            # a timeout wait consumed (at least) its budgeted wall time —
            # advance past it so deadline loops computing `remaining`
            # from the virtual clock converge instead of spinning
            self._now += max(float(timeout or 0.0), self.CLOCK_QUANTUM)
        return reason

    # -- deadlock / abort ---------------------------------------------------

    def _wait_cycle(self) -> Tuple[str, ...]:
        """Thread-name cycle through lock owners, when one exists."""
        for start in self.threads:
            seen: List[VThread] = []
            t: Optional[VThread] = start
            while (t is not None and t.state == BLOCKED
                   and t.wait_kind in ("lock", "rlock")):
                if t in seen:
                    i = seen.index(t)
                    return tuple(x.name for x in seen[i:]) + (t.name,)
                seen.append(t)
                t = getattr(t.waiting_on, "owner", None)
        return ()

    def _deadlock(self, cur: VThread) -> None:
        waits = [(t.name, t.wait_kind,
                  str(getattr(t.waiting_on, "label",
                              getattr(t.waiting_on, "name", "?"))))
                 for t in self.threads if t.state == BLOCKED]
        info = DeadlockInfo(sorted(waits), self._wait_cycle(), self.seed,
                            self._steps)
        self.deadlocks.append(info)
        self.trace.append(f"{self._steps:05d} DEADLOCK {info.describe()}")
        self._abort_all(cur)

    def _abort_all(self, cur: Optional[VThread]) -> None:
        """Unwind the schedule: every parked thread wakes into
        `ScheduleAbort`; serialization is abandoned (the threads only
        run their unwind paths from here)."""
        if self._aborted:
            return
        self._aborted = True
        for t in self.threads:
            if t is cur:
                continue
            if t.state == BLOCKED:
                self._wake(t, "abort")
            t.baton.set()

    # -- thread management --------------------------------------------------

    def new_vthread(self, name: Optional[str]) -> VThread:
        tid = len(self.threads)
        vt = VThread(tid, f"{tid}:{name or 'thread'}")
        self.threads.append(vt)
        self._names[tid] = vt.name
        return vt

    def start_vthread(self, vt: VThread) -> None:
        cur = self.current()
        self.yield_point(f"thread-start {vt.name}")
        # fork: the child begins with (and after) everything the parent
        # did so far
        vt.vc = dict(cur.vc)
        vt.vc[vt.tid] = vt.vc.get(vt.tid, 0) + 1
        cur.vc[cur.tid] = cur.vc.get(cur.tid, 0) + 1
        vt.started = True
        vt.state = RUNNABLE
        real = _threading.Thread(target=self._thread_body, args=(vt,),
                                 name=vt.name, daemon=True)
        vt.real = real
        real.start()

    def _thread_body(self, vt: VThread) -> None:
        self._by_ident[_threading.get_ident()] = vt
        vt.baton.wait()
        vt.baton.clear()
        try:
            if not self._aborted:
                vt.target(*vt.args, **vt.kwargs)
        except ScheduleAbort:
            pass
        except BaseException as exc:  # noqa: BLE001 — surfaced by harness
            vt.exc = exc
        finally:
            self._finish_thread(vt)

    def _finish_thread(self, vt: VThread) -> None:
        vt.state = FINISHED
        if self._aborted:
            return
        self.trace.append(f"{self._steps:05d} {vt.name} exit")
        self._steps += 1
        for w in list(vt.waiters):
            self._wake(w, "notify")
        nxt = self._choose()
        if nxt is not None:
            self._handoff(None, nxt)
        elif any(t.state == BLOCKED for t in self.threads):
            self._deadlock(None)

    def join_vthread(self, vt: VThread, timeout=None) -> None:
        if not vt.started:
            # stdlib semantics, faithfully: a schedule that reaches a
            # join-before-start must surface the production crash, not
            # silently no-op past it
            raise RuntimeError("cannot join thread before it is started")
        cur = self.current()
        self.yield_point(f"join {vt.name}")
        while vt.state != FINISHED:
            if self.block(vt, "join", timeout) == "timeout":
                return
        merge(cur.vc, vt.vc)

    def drain(self) -> None:
        """Run every remaining managed thread to completion (the harness
        epilogue; the scenario must have initiated all shutdowns)."""
        cur = self.current()
        while any(t is not cur and t.started and t.state != FINISHED
                  for t in self.threads):
            self.yield_point("drain")
        for t in self.threads:
            if t.real is not None:
                t.real.join(timeout=10.0)

    # -- clocks + channels --------------------------------------------------

    def release_clock(self, store: Dict[int, int]) -> None:
        """release-style op: publish the current thread's clock into a
        primitive's stored clock, then tick."""
        cur = self.current()
        merge(store, cur.vc)
        cur.vc[cur.tid] = cur.vc.get(cur.tid, 0) + 1

    def acquire_clock(self, store: Dict[int, int]) -> None:
        merge(self.current().vc, store)

    def channel_store(self, channel) -> None:
        """Hand-off edge through a non-virtual channel (Future resolve)."""
        if self._by_ident.get(_threading.get_ident()) is None:
            return
        store = self._channel_vc.setdefault(id(channel), {})
        self.release_clock(store)

    def channel_load(self, channel) -> None:
        if self._by_ident.get(_threading.get_ident()) is None:
            return
        store = self._channel_vc.get(id(channel))
        if store:
            self.acquire_clock(store)

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)
        self.yield_point(f"sleep {float(seconds):.4g}")

    # -- instrumentation hooks ---------------------------------------------

    def record_write(self, obj, attr: str, value, op: str = "") -> None:
        vt = self._by_ident.get(_threading.get_ident())
        if vt is None or self._aborted:
            return
        if isinstance(value, _VBase) and value.auto_label:
            value.label = f"{type(obj).__name__}.{attr}#{value.idx}"
            value.auto_label = False
        cls = type(obj).__name__
        seq = self.obj_seq(obj)
        self.writes.note(seq, cls, attr, vt.tid)
        self.detector.write((seq, attr), (cls, attr), vt.tid, vt.name,
                            vt.vc, op or f"after {vt.last_op}",
                            self._names)

    def record_read(self, obj, attr: str, op: str = "") -> None:
        vt = self._by_ident.get(_threading.get_ident())
        if vt is None or self._aborted:
            return
        cls = type(obj).__name__
        seq = self.obj_seq(obj)
        self.detector.read((seq, attr), (cls, attr), vt.tid, vt.name,
                           vt.vc, op or f"after {vt.last_op}",
                           self._names)

    # -- factory surface consumed by utils.sync -----------------------------

    def _next_prim(self) -> int:
        self._prim_seq += 1
        return self._prim_seq

    def create_lock(self):
        return VLock(self)

    def create_rlock(self):
        return VRLock(self)

    def create_condition(self, lock=None):
        return VCondition(self, lock)

    def create_event(self):
        return VEvent(self)

    def create_semaphore(self, value: int = 1):
        return VSemaphore(self, value)

    def create_queue(self, maxsize: int = 0):
        return VQueue(self, maxsize)

    def create_thread(self, target=None, args=(), kwargs=None, name=None):
        return VThreadHandle(self, target, args, kwargs or {}, name)

    def trace_text(self) -> str:
        return "\n".join(self.trace) + "\n"


# -- virtual primitives ------------------------------------------------------


class _VBase:
    def __init__(self, rt: DeterministicRuntime, kind: str):
        self.rt = rt
        self.idx = rt._next_prim()
        self.label = f"{kind}#{self.idx}"
        self.auto_label = True
        self.waiters: List[VThread] = []
        self.clock: Dict[int, int] = {}

    def _wake_all(self, reason: str = "retry") -> None:
        for w in list(self.waiters):
            self.rt._wake(w, reason)


class VLock(_VBase):
    REENTRANT = False

    def __init__(self, rt: DeterministicRuntime, kind: str = "Lock"):
        super().__init__(rt, kind)
        self.owner: Optional[VThread] = None
        self.count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        rt = self.rt
        rt.yield_point(f"acquire {self.label}")
        cur = rt.current()
        to = None if (timeout is None or timeout < 0) else timeout
        while True:
            if self.owner is None:
                self.owner = cur
                self.count = 1
                self._on_acquired(cur)
                return True
            if self.REENTRANT and self.owner is cur:
                self.count += 1
                return True
            if not blocking:
                return False
            if rt.block(self, "lock", to) == "timeout":
                return False

    def release(self):
        rt = self.rt
        cur = rt.current()
        if self.owner is not cur:
            raise RuntimeError(f"release of un-owned {self.label}")
        rt.yield_point(f"release {self.label}")
        self.count -= 1
        if self.count == 0:
            self._on_released(cur)

    def _on_acquired(self, cur: VThread) -> None:
        rt = self.rt
        rt.acquire_clock(self.clock)
        for held in cur.held:
            rt.lock_graph.edge(held.label, self.label)
        cur.held.append(self)
        if self.label not in rt._lock_labels_seen:
            rt._lock_labels_seen.append(self.label)

    def _on_released(self, cur: VThread) -> None:
        self.owner = None
        if self in cur.held:
            cur.held.remove(self)
        self.rt.release_clock(self.clock)
        self._wake_all("retry")

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class VRLock(VLock):
    REENTRANT = True

    def __init__(self, rt: DeterministicRuntime):
        super().__init__(rt, "RLock")


class VCondition(_VBase):
    def __init__(self, rt: DeterministicRuntime, lock=None):
        super().__init__(rt, "Condition")
        self.lock = lock if lock is not None else VLock(rt)

    # delegate the lock interface (``with cond:`` and explicit acquire)
    def acquire(self, *a, **k):
        return self.lock.acquire(*a, **k)

    def release(self):
        return self.lock.release()

    def __enter__(self):
        self.lock.acquire()
        return self

    def __exit__(self, *exc):
        self.lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        rt = self.rt
        cur = rt.current()
        if self.lock.owner is not cur:
            raise RuntimeError("cond.wait without holding its lock")
        rt.yield_point(f"cond-wait {self.label}")
        saved = self.lock.count
        self.lock.count = 0
        self.lock._on_released(cur)
        reason = rt.block(self, "cond", timeout)
        # reacquire unconditionally (stdlib semantics), then restore the
        # recursion depth the waiter entered with
        while True:
            if self.lock.owner is None:
                self.lock.owner = cur
                self.lock.count = saved
                self.lock._on_acquired(cur)
                break
            rt.block(self.lock, "lock", None)
        if reason == "notify":
            rt.acquire_clock(self.clock)
            return True
        return False

    def _notify(self, n: Optional[int]) -> None:
        rt = self.rt
        if self.lock.owner is not rt.current():
            raise RuntimeError("cond.notify without holding its lock")
        rt.yield_point(f"notify {self.label}")
        rt.release_clock(self.clock)
        targets = list(self.waiters) if n is None else list(self.waiters)[:n]
        for w in targets:
            rt._wake(w, "notify")

    def notify(self, n: int = 1) -> None:
        self._notify(n)

    def notify_all(self) -> None:
        self._notify(None)


class VEvent(_VBase):
    def __init__(self, rt: DeterministicRuntime):
        super().__init__(rt, "Event")
        self.flag = False

    def is_set(self) -> bool:
        return self.flag

    def set(self) -> None:
        rt = self.rt
        rt.yield_point(f"set {self.label}")
        self.flag = True
        rt.release_clock(self.clock)
        self._wake_all("notify")

    def clear(self) -> None:
        self.rt.yield_point(f"clear {self.label}")
        self.flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        rt = self.rt
        rt.yield_point(f"event-wait {self.label}")
        while True:
            if self.flag:
                rt.acquire_clock(self.clock)
                return True
            if rt.block(self, "event", timeout) == "timeout":
                return False


class VSemaphore(_VBase):
    def __init__(self, rt: DeterministicRuntime, value: int):
        super().__init__(rt, "Semaphore")
        self.value = int(value)

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        rt = self.rt
        rt.yield_point(f"sem-acquire {self.label}")
        while True:
            if self.value > 0:
                self.value -= 1
                rt.acquire_clock(self.clock)
                return True
            if not blocking:
                return False
            if rt.block(self, "semaphore", timeout) == "timeout":
                return False

    def release(self) -> None:
        rt = self.rt
        rt.yield_point(f"sem-release {self.label}")
        self.value += 1
        rt.release_clock(self.clock)
        self._wake_all("retry")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class VQueue(_VBase):
    """FIFO with the stdlib queue exception surface (raises the real
    ``queue.Empty``/``queue.Full`` so existing except clauses match).
    ``maxsize`` is honored — a bounded queue's producer-blocked-on-full
    states must be explorable, not silently unbounded.  Clocks travel
    per item: a get happens-after exactly its put."""

    def __init__(self, rt: DeterministicRuntime, maxsize: int = 0):
        super().__init__(rt, "Queue")
        self.maxsize = int(maxsize)
        self.items: List[Tuple[Any, Dict[int, int]]] = []

    def _full(self) -> bool:
        return 0 < self.maxsize <= len(self.items)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        rt = self.rt
        rt.yield_point(f"put {self.label}")
        while self._full():
            if not block:
                raise _queue_mod.Full()
            if rt.block(self, "queue-full", timeout) == "timeout":
                raise _queue_mod.Full()
        cur = rt.current()
        vc = dict(cur.vc)
        cur.vc[cur.tid] = cur.vc.get(cur.tid, 0) + 1
        self.items.append((item, vc))
        self._wake_all("retry")

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        rt = self.rt
        rt.yield_point(f"get {self.label}")
        while True:
            if self.items:
                item, vc = self.items.pop(0)
                rt.acquire_clock(vc)
                self._wake_all("retry")  # a slot opened for blocked puts
                return item
            if not block:
                raise _queue_mod.Empty()
            if rt.block(self, "queue", timeout) == "timeout":
                raise _queue_mod.Empty()

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return len(self.items)

    def empty(self) -> bool:
        return not self.items


class VThreadHandle:
    """What utils.sync.Thread returns under the runtime: the stdlib
    Thread surface (start/join/is_alive/name/daemon) over a VThread."""

    def __init__(self, rt: DeterministicRuntime, target, args, kwargs,
                 name):
        self.rt = rt
        self.daemon = True
        self.vt = rt.new_vthread(name)
        self.vt.target = target if target is not None else (lambda: None)
        self.vt.args = tuple(args)
        self.vt.kwargs = dict(kwargs)

    @property
    def name(self) -> str:
        return self.vt.name

    def start(self) -> None:
        if self.vt.started:
            raise RuntimeError("threads can only be started once")
        self.rt.start_vthread(self.vt)

    def join(self, timeout: Optional[float] = None) -> None:
        self.rt.join_vthread(self.vt, timeout)

    def is_alive(self) -> bool:
        return self.vt.started and self.vt.state != FINISHED

"""Pure detector math for distrisched: vector clocks, the
happens-before race check, the lock-order graph, and the write-origin
recorder behind the guard-registry drift cross-check.

Everything here is schedule-fed and deterministic: the scheduler
(sched.py) calls in at sync points and instrumented attribute accesses,
and the outputs (`RaceReport`s, cycles, multi-writer attrs) are plain
data the harness turns into distrilint `Finding`s.  No threads, no
globals — unit-testable without running a schedule.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

# -- vector clocks -----------------------------------------------------------
#
# A clock is a plain {thread_id: int} dict.  Threads tick their own
# component on release-style operations; acquire-style operations join
# the releasing side's stored clock.  "a happened-before b" holds iff
# a's epoch (its writer's own component at access time) is <= b's view
# of that writer — the standard vector-clock order, evaluated lazily per
# access pair (FastTrack-style epochs, without the adaptive read
# representation: the serve scenarios touch few enough variables that
# full per-thread maps are cheap).


def merge(into: Dict[int, int], other: Dict[int, int]) -> None:
    """into := join(into, other), in place."""
    for tid, c in other.items():
        if c > into.get(tid, 0):
            into[tid] = c


@dataclasses.dataclass(frozen=True)
class RaceReport:
    """One unordered access pair on one attribute (object-level; the
    harness aggregates to class-level findings)."""

    class_name: str
    attr: str
    kind: str  # "write-write" | "read-write" | "write-read"
    thread_a: str
    thread_b: str
    op_a: str
    op_b: str


class _VarState:
    __slots__ = ("writes", "reads", "write_ops", "read_ops")

    def __init__(self):
        # per-thread last-access epochs (tid -> that thread's own clock
        # component at access time) and the op label active at the access
        self.writes: Dict[int, int] = {}
        self.reads: Dict[int, int] = {}
        self.write_ops: Dict[int, str] = {}
        self.read_ops: Dict[int, str] = {}


class RaceDetector:
    """Happens-before race detection over instrumented attribute
    accesses.

    ``check_reads`` gates read/write pair reporting: the serve layer's
    documented thread model deliberately blesses unlocked snapshot-style
    reads (GIL dict-copy semantics — serve/resilience.py snapshot docs,
    mirrored by the static lock-discipline checker, which also skips
    reads), so the shipped-tree gate runs writes-only and the fixture
    tests prove the read machinery works.
    """

    def __init__(self, check_reads: bool = False):
        self.check_reads = check_reads
        self._vars: Dict[Tuple[int, str], _VarState] = {}
        self.reports: List[RaceReport] = []
        self._seen: Set[Tuple[str, str, str]] = set()

    def _report(self, meta, kind: str, tid_a: int, op_a: str,
                name_a: str, tid_b: int, op_b: str, name_b: str) -> None:
        key = (meta[0], meta[1], kind)
        if key in self._seen:
            return
        self._seen.add(key)
        self.reports.append(RaceReport(
            class_name=meta[0], attr=meta[1], kind=kind,
            thread_a=name_a, thread_b=name_b, op_a=op_a, op_b=op_b))

    def write(self, var: Tuple[int, str], meta: Tuple[str, str],
              tid: int, tname: str, vc: Dict[int, int], op: str,
              names: Dict[int, str]) -> None:
        st = self._vars.setdefault(var, _VarState())
        for u, e in st.writes.items():
            if u != tid and e > vc.get(u, 0):
                self._report(meta, "write-write", u, st.write_ops.get(u, ""),
                             names.get(u, str(u)), tid, op, tname)
        if self.check_reads:
            for u, e in st.reads.items():
                if u != tid and e > vc.get(u, 0):
                    self._report(meta, "read-write", u,
                                 st.read_ops.get(u, ""),
                                 names.get(u, str(u)), tid, op, tname)
        st.writes[tid] = vc.get(tid, 0)
        st.write_ops[tid] = op

    def read(self, var: Tuple[int, str], meta: Tuple[str, str],
             tid: int, tname: str, vc: Dict[int, int], op: str,
             names: Dict[int, str]) -> None:
        if not self.check_reads:
            return
        st = self._vars.setdefault(var, _VarState())
        for u, e in st.writes.items():
            if u != tid and e > vc.get(u, 0):
                self._report(meta, "write-read", u, st.write_ops.get(u, ""),
                             names.get(u, str(u)), tid, op, tname)
        st.reads[tid] = vc.get(tid, 0)
        st.read_ops[tid] = op


# -- lock-order graph --------------------------------------------------------


class LockOrderGraph:
    """Directed acquisition-order graph over lock *instances*.

    An edge A -> B is recorded when a thread acquires B while holding A.
    A cycle across every explored schedule is a potential deadlock even
    if no single schedule wedged — the AB/BA pattern needs the unlucky
    interleaving, and the graph union sees it from the lucky ones.
    Instance labels (``Class.attr#n``) keep two same-named locks on
    different objects distinct; cycle findings collapse to the
    class-attr names, which survive unrelated edits.
    """

    def __init__(self):
        self.edges: Dict[str, Set[str]] = {}
        # representative context per edge, for the finding message
        self.context: Dict[Tuple[str, str], str] = {}

    def edge(self, held: str, acquired: str, where: str = "") -> None:
        if held == acquired:
            return
        self.edges.setdefault(held, set()).add(acquired)
        self.context.setdefault((held, acquired), where)

    def absorb(self, other: "LockOrderGraph") -> None:
        for a, bs in other.edges.items():
            for b in bs:
                self.edge(a, b, other.context.get((a, b), ""))

    def cycles(self) -> List[Tuple[str, ...]]:
        """Every elementary cycle's node set, deduplicated by its sorted
        membership (one finding per distinct lock set, not one per
        rotation)."""
        out: Dict[Tuple[str, ...], Tuple[str, ...]] = {}
        for start in sorted(self.edges):
            stack = [(start, (start,))]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(self.edges.get(node, ())):
                    if nxt == start and len(path) > 1:
                        key = tuple(sorted(path))
                        out.setdefault(key, path)
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + (nxt,)))
        return [out[k] for k in sorted(out)]


# -- guard-registry drift ----------------------------------------------------


def strip_instance(label: str) -> str:
    """``Class.attr#7`` -> ``Class.attr`` (the edit-stable identity)."""
    return label.split("#", 1)[0]


class WriteOriginRecorder:
    """Which threads wrote which attribute of which object.

    Feeds the registry-drift cross-check: an attribute of one object
    observed written from >= 2 distinct threads is cross-thread shared
    state, and if its class/attr is absent from the static checker's
    GUARDED_REGISTRY the static pass is blind to it — dynamic evidence
    of exactly the blind spot ISSUE 14 names.
    """

    def __init__(self):
        # (obj_seq, attr) -> set of thread ids; obj_seq -> class name
        self._writers: Dict[Tuple[int, str], Set[int]] = {}
        self._cls: Dict[int, str] = {}

    def note(self, obj_seq: int, class_name: str, attr: str,
             tid: int) -> None:
        self._cls[obj_seq] = class_name
        self._writers.setdefault((obj_seq, attr), set()).add(tid)

    def multi_writer_attrs(self) -> List[Tuple[str, str]]:
        """Sorted (class, attr) pairs where some single object saw
        writes from >= 2 threads."""
        out = set()
        for (oid, attr), tids in self._writers.items():
            if len(tids) >= 2:
                out.add((self._cls[oid], attr))
        return sorted(out)

    def absorb(self, other: "WriteOriginRecorder", offset: int) -> None:
        """Merge another schedule's recorder; ``offset`` keeps object
        sequence numbers from colliding across schedules."""
        for oid, cls in other._cls.items():
            self._cls[oid + offset] = cls
        for (oid, attr), tids in other._writers.items():
            self._writers.setdefault((oid + offset, attr), set()).update(
                tids)

"""distrisched harness: run serve scenarios under the deterministic
scheduler and turn what the detectors saw into distrilint findings.

`run_schedule(scenario, seed)` is the unit of exploration: it installs
the seeded runtime into utils.sync, patches ``time.monotonic``/
``time.sleep`` to virtual time and `concurrent.futures.Future` so
resolve->callback hand-offs carry vector clocks, instruments every
serve/utils class's ``__setattr__`` so cross-thread attribute writes
feed the race detector and the drift recorder, runs the scenario, and
drains every thread it spawned.  Everything is restored in ``finally``
— a harness run leaves the process exactly as it found it.

`explore(...)` fans one scenario across N seeds (or several scenarios
across a seed range), merges the per-schedule evidence, and emits three
checkers' worth of `Finding`s through the ordinary baseline pipeline:

* ``concurrency-race`` — unordered write/write (and, in fixture mode,
  read/write) access pairs on one attribute, per vector-clock
  happens-before;
* ``concurrency-deadlock`` — a concretely wedged schedule (with its
  wait-for cycle and replay seed), or a lock-order cycle accumulated
  across schedules (AB/BA seen from the lucky interleavings);
* ``guard-registry-drift`` — attributes observed written from >= 2
  threads on one object whose class/attr is absent from the static
  checker's GUARDED_REGISTRY: dynamic evidence of the static pass's
  blind spot.

Scenario invariant violations (assertion failures, unexpected thread
exceptions, step-budget exhaustion) are NOT findings — they are
failures, reported with the seed that reproduces them bit-identically.
"""

from __future__ import annotations

import dataclasses
import importlib
import os
import time as _time_mod
from concurrent import futures as _futures_mod
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ...utils import sync
from ..core import Finding
from .races import LockOrderGraph, WriteOriginRecorder, strip_instance
from .sched import DeterministicRuntime, ScheduleAbort

#: modules whose classes get write instrumentation during a harness run
#: (every class defined in them; exceptions excluded).  This is the
#: serve control plane plus the utils classes it shares across threads.
OBSERVED_MODULES = (
    "distrifuser_tpu.serve.queue",
    "distrifuser_tpu.serve.server",
    "distrifuser_tpu.serve.gateway",
    "distrifuser_tpu.serve.tenancy",
    "distrifuser_tpu.serve.fleet",
    "distrifuser_tpu.serve.replica",
    "distrifuser_tpu.serve.staging",
    "distrifuser_tpu.serve.stepbatch",
    "distrifuser_tpu.serve.resilience",
    "distrifuser_tpu.serve.cache",
    "distrifuser_tpu.serve.controller",
    "distrifuser_tpu.serve.promptcache",
    "distrifuser_tpu.serve.batcher",
    "distrifuser_tpu.serve.faults",
    "distrifuser_tpu.serve.testing",
    "distrifuser_tpu.utils.metrics",
    "distrifuser_tpu.utils.trace",
)

RACE = "concurrency-race"
DEADLOCK = "concurrency-deadlock"
DRIFT = "guard-registry-drift"
CHECKER_NAMES = (RACE, DEADLOCK, DRIFT)


def _repo_relpath(cls) -> str:
    """Repo-relative posix path of the module defining ``cls`` (falls
    back to the dotted module name for non-file classes)."""
    import sys

    mod = sys.modules.get(cls.__module__)
    path = getattr(mod, "__file__", None)
    if not path:
        return cls.__module__
    path = os.path.abspath(path)
    marker = os.sep + "distrifuser_tpu" + os.sep
    i = path.find(marker)
    if i < 0:
        return os.path.basename(path)
    return path[i + 1:].replace(os.sep, "/")


def observed_classes(extra: Sequence[type] = ()) -> List[type]:
    out: List[type] = []
    for modname in OBSERVED_MODULES:
        mod = importlib.import_module(modname)
        for obj in vars(mod).values():
            if (isinstance(obj, type) and obj.__module__ == modname
                    and not issubclass(obj, BaseException)):
                out.append(obj)
    out.extend(extra)
    return out


# -- patch plumbing ----------------------------------------------------------


class _Patcher:
    """Reversible monkey-patch set (class attrs + module attrs)."""

    def __init__(self):
        self._undo: List[Callable[[], None]] = []

    def set(self, owner, name: str, value) -> None:
        old = getattr(owner, name)
        setattr(owner, name, value)
        self._undo.append(lambda: setattr(owner, name, old))

    def set_class_attr(self, cls: type, name: str, value) -> None:
        """Like set(), but restore-exact for class dicts: an attribute
        the class merely INHERITED is removed again on restore, never
        written back as an own attribute (writing back would freeze the
        base class's patched wrapper into every subclass forever)."""
        had_own = name in vars(cls)
        old = vars(cls).get(name)
        setattr(cls, name, value)
        if had_own:
            self._undo.append(lambda: setattr(cls, name, old))
        else:
            self._undo.append(lambda: delattr(cls, name))

    def restore(self) -> None:
        while self._undo:
            self._undo.pop()()


def _covered_by_patched_base(cls: type, classes,
                             dunder: str) -> bool:
    """True when ``cls`` inherits ``dunder`` from another observed class
    — patching it again would stack a second wrapper (double-recording
    every write)."""
    return (dunder not in vars(cls)
            and any(b in classes for b in cls.__mro__[1:]))


def _instrument_writes(patcher: _Patcher, classes: Sequence[type]) -> None:
    cset = set(classes)
    for cls in classes:
        if _covered_by_patched_base(cls, cset, "__setattr__"):
            continue
        orig = cls.__setattr__

        def _setattr(self, name, value, _orig=orig):
            rt = sync.active_runtime()
            if rt is not None:
                rt.record_write(self, name, value)
            _orig(self, name, value)

        patcher.set_class_attr(cls, "__setattr__", _setattr)


def _instrument_reads(patcher: _Patcher, classes: Sequence[type]) -> None:
    cset = set(classes)
    for cls in classes:
        if _covered_by_patched_base(cls, cset, "__getattribute__"):
            continue
        orig = cls.__getattribute__

        def _getattribute(self, name, _orig=orig):
            value = _orig(self, name)
            if not name.startswith("__"):
                rt = sync.active_runtime()
                if rt is not None:
                    try:
                        d = _orig(self, "__dict__")
                    except AttributeError:
                        d = None
                    if d is not None and name in d:
                        rt.record_read(self, name)
            return value

        patcher.set_class_attr(cls, "__getattribute__", _getattribute)


def _patch_time(patcher: _Patcher, rt: DeterministicRuntime) -> None:
    patcher.set(_time_mod, "monotonic", rt.clock)
    patcher.set(_time_mod, "perf_counter", rt.clock)
    patcher.set(_time_mod, "sleep", rt.sleep)


def _patch_futures(patcher: _Patcher, rt: DeterministicRuntime) -> None:
    """Vector-clock edges through Future resolution: set_result /
    set_exception publish the resolver's clock; done-callbacks (how the
    fleet consumes replica outcomes) join it on entry."""
    fut = _futures_mod.Future
    orig_set_result = fut.set_result
    orig_set_exception = fut.set_exception
    orig_add_cb = fut.add_done_callback

    def set_result(self, result):
        rt.channel_store(self)
        orig_set_result(self, result)

    def set_exception(self, exception):
        rt.channel_store(self)
        orig_set_exception(self, exception)

    def add_done_callback(self, fn):
        def wrapped(f, _fn=fn):
            rt.channel_load(f)
            _fn(f)

        orig_add_cb(self, wrapped)

    patcher.set(fut, "set_result", set_result)
    patcher.set(fut, "set_exception", set_exception)
    patcher.set(fut, "add_done_callback", add_done_callback)


# -- scenario context --------------------------------------------------------


class ScenarioContext:
    """What a scenario gets: the runtime clock, managed-thread spawning,
    and schedule-aware waiting (never block the token on a real wait)."""

    def __init__(self, rt: DeterministicRuntime):
        self.rt = rt
        self.clock = rt.clock

    def spawn(self, name: str, fn: Callable, *args):
        t = sync.Thread(target=fn, args=args, name=name)
        t.start()
        return t

    def wait_until(self, pred: Callable[[], bool], what: str) -> None:
        """Yield until ``pred()`` holds; the step budget bounds a pred
        that can never hold (reported as a failure with the seed)."""
        while not pred():
            self.rt.yield_point(f"wait-until {what}")

    def result(self, future, tolerate: Tuple[type, ...] = ()):
        """Schedule-aware Future.result: spin-yield until resolved, then
        return the result (or the tolerated exception instance)."""
        self.wait_until(future.done, "future")
        exc = future.exception()
        if exc is None:
            return future.result()
        if tolerate and isinstance(exc, tolerate):
            return exc
        raise exc


# -- one schedule ------------------------------------------------------------


@dataclasses.dataclass
class ScheduleResult:
    scenario: str
    seed: int
    steps: int
    trace: str
    deadlocks: list
    race_reports: list
    lock_graph: LockOrderGraph
    writes: WriteOriginRecorder
    obj_count: int
    error: Optional[str] = None  # scenario failure (assertion, stray exc)


def run_schedule(scenario: Callable[[ScenarioContext], None], seed: int,
                 *, name: str = "", check_reads: bool = False,
                 max_steps: int = 60000,
                 extra_classes: Sequence[type] = ()) -> ScheduleResult:
    rt = DeterministicRuntime(seed, max_steps=max_steps,
                              check_reads=check_reads)
    patcher = _Patcher()
    classes = observed_classes(extra_classes)
    error: Optional[str] = None
    try:
        _instrument_writes(patcher, classes)
        if check_reads:
            _instrument_reads(patcher, classes)
        _patch_time(patcher, rt)
        _patch_futures(patcher, rt)
        sync.install_runtime(rt)
        rt.register_main()
        try:
            scenario(ScenarioContext(rt))
            rt.drain()
        except ScheduleAbort:
            pass
        except AssertionError as exc:
            error = f"invariant violated: {exc}"
        except Exception as exc:  # noqa: BLE001 — reported with the seed
            error = f"{type(exc).__name__}: {exc}"
        # let every thread unwind even on failure, so the patch restore
        # below cannot race a still-running managed thread
        rt._abort_all(None)
        for t in rt.threads:
            if t.real is not None:
                t.real.join(timeout=10.0)
    finally:
        sync.uninstall_runtime()
        patcher.restore()
    if error is None and rt.budget_exhausted:
        error = (f"step budget ({max_steps}) exhausted — livelock or a "
                 "scenario that never quiesces")
    if error is None:
        stray = [f"{t.name}: {type(t.exc).__name__}: {t.exc}"
                 for t in rt.threads if t.exc is not None]
        if stray:
            error = "thread exception: " + "; ".join(stray)
    return ScheduleResult(
        scenario=name or getattr(scenario, "__name__", "scenario"),
        seed=seed, steps=rt._steps, trace=rt.trace_text(),
        deadlocks=list(rt.deadlocks),
        race_reports=list(rt.detector.reports),
        lock_graph=rt.lock_graph, writes=rt.writes,
        obj_count=len(rt._obj_seq), error=error)


# -- exploration + findings --------------------------------------------------


@dataclasses.dataclass
class Failure:
    scenario: str
    seed: int
    error: str
    trace: str


@dataclasses.dataclass
class ExplorationResult:
    schedules_explored: int
    per_scenario: Dict[str, int]
    findings: List[Finding]
    failures: List[Failure]

    def counts(self) -> Dict[str, int]:
        out = {name: 0 for name in CHECKER_NAMES}
        for f in self.findings:
            out[f.checker] = out.get(f.checker, 0) + 1
        return out


def _registry_coverage() -> Dict[Tuple[str, str], Set[str]]:
    """(module path, class name) -> guarded attrs, from the static
    checker's registry (including the ``via=`` cross-object entries the
    dynamic pass validates).  Keyed with the module path deliberately:
    a same-named class in another module must NOT inherit coverage —
    that would blind both passes at once."""
    from ..checkers.lock_discipline import GUARDED_REGISTRY

    covered: Dict[Tuple[str, str], Set[str]] = {}
    for path, classes in GUARDED_REGISTRY.items():
        for cname, g in classes.items():
            covered.setdefault((path, cname), set()).update(g.attrs)
    return covered


def _class_paths(extra_classes: Sequence[type] = ()) -> Dict[str, str]:
    return {cls.__name__: _repo_relpath(cls)
            for cls in observed_classes(extra_classes)}


def synthesize_findings(results: Sequence[ScheduleResult],
                        extra_classes: Sequence[type] = ()
                        ) -> List[Finding]:
    """Merge per-schedule evidence into deduplicated, fingerprint-stable
    findings (identities carry class/attr/lock names, never seeds, line
    numbers, or thread names)."""
    paths = _class_paths(extra_classes)
    covered = _registry_coverage()
    findings: Dict[str, Finding] = {}

    def add(f: Finding) -> None:
        findings.setdefault(f.fingerprint, f)

    union = LockOrderGraph()
    instance_cycles: List[Tuple[str, ...]] = []
    writes = WriteOriginRecorder()
    offset = 0
    for r in results:
        # instance-level cycle detection runs PER SCHEDULE: labels carry
        # schedule-local creation indices, so unioning them across seeds
        # could alias two physical locks under one label and fabricate a
        # cycle.  The cross-schedule union below is class-attr-level
        # (stable names) — conservative by design, and same-name pairs
        # are dropped there (two instances of one lock class ordering
        # against each other is the instance pass's job).
        instance_cycles.extend(r.lock_graph.cycles())
        for a, bs in r.lock_graph.edges.items():
            for b in bs:
                union.edge(strip_instance(a), strip_instance(b))
        writes.absorb(r.writes, offset)
        offset += r.obj_count
        for rep in r.race_reports:
            path = paths.get(rep.class_name, "distrifuser_tpu")
            add(Finding(
                checker=RACE, path=path, line=0,
                message=(
                    f"{rep.kind} race on {rep.class_name}.{rep.attr}: "
                    f"{rep.thread_a} [{rep.op_a}] and {rep.thread_b} "
                    f"[{rep.op_b}] are unordered by happens-before "
                    f"(scenario {r.scenario}, replay --seed {r.seed}) — "
                    "take the documented lock, or baseline with the "
                    "reason the unsynchronized access is safe"),
                identity=f"{rep.class_name}.{rep.attr}:{rep.kind}",
            ))
        for dl in r.deadlocks:
            labels = sorted({strip_instance(l) for _, _, l in dl.waits})
            add(Finding(
                checker=DEADLOCK, path="distrifuser_tpu/serve", line=0,
                message=(
                    f"schedule wedged in scenario {r.scenario} "
                    f"(replay --seed {dl.seed}): {dl.describe()}"),
                identity=f"wedge:{r.scenario}:{':'.join(labels)}",
            ))
    for cycle in instance_cycles + union.cycles():
        names = sorted({strip_instance(l) for l in cycle})
        first_cls = names[0].split(".", 1)[0]
        add(Finding(
            checker=DEADLOCK, path=paths.get(first_cls, "distrifuser_tpu"),
            line=0,
            message=(
                "lock-order cycle over explored schedules: "
                + " -> ".join(cycle)
                + " — two threads taking these locks in opposite order "
                "deadlock; impose one order or baseline with the reason "
                "the orders can never overlap"),
            identity="cycle:" + ":".join(names),
        ))
    for cls, attr in writes.multi_writer_attrs():
        path = paths.get(cls, "distrifuser_tpu")
        if attr in covered.get((path, cls), set()):
            continue
        if not path.startswith("distrifuser_tpu/"):
            continue  # fixture classes prove the machinery, not the tree
        add(Finding(
            checker=DRIFT, path=path, line=0,
            message=(
                f"{cls}.{attr} observed written from >= 2 threads but is "
                "absent from lock_discipline.GUARDED_REGISTRY — the "
                "static pass is blind to it; register it (use via= for "
                "an owner-lock guard) or baseline with the reason it "
                "needs no guard"),
            identity=f"{cls}.{attr}",
        ))
    return sorted(findings.values(),
                  key=lambda f: (f.checker, f.path, f.identity))


def explore(scenarios: Dict[str, Callable], seeds: Sequence[int], *,
            check_reads: bool = False, max_steps: int = 60000,
            extra_classes: Sequence[type] = (),
            keep_traces: bool = False,
            on_schedule: Optional[Callable[[ScheduleResult], None]] = None,
            ) -> ExplorationResult:
    results: List[ScheduleResult] = []
    failures: List[Failure] = []
    per_scenario: Dict[str, int] = {}
    for sname, fn in scenarios.items():
        for seed in seeds:
            r = run_schedule(fn, seed, name=sname,
                             check_reads=check_reads, max_steps=max_steps,
                             extra_classes=extra_classes)
            per_scenario[sname] = per_scenario.get(sname, 0) + 1
            if r.error is not None:
                failures.append(Failure(sname, seed, r.error, r.trace))
            if not keep_traces:
                r.trace = "" if r.error is None else r.trace
            results.append(r)
            if on_schedule is not None:
                on_schedule(r)
    return ExplorationResult(
        schedules_explored=len(results),
        per_scenario=per_scenario,
        findings=synthesize_findings(results, extra_classes),
        failures=failures)

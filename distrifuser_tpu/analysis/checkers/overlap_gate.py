"""Jaxpr overlap gate: the stale-exchange deferral contract, on CPU, fast.

The displaced-patch design's latency claim — stale-refresh collectives
are consumed only by the NEXT step, so XLA overlaps them with compute
(the role of the reference's async NCCL gathers; the PipeFusion /
FastUSP overlap contracts, PAPERS.md arXiv 2405.14430 / 2602.10940) — is
verified today by `slow`-marked 8-device HLO tests (tests/test_overlap.py,
test_stepcache.py) that compile for minutes and never run on the 2-core
tier-1 runner.  A regression that turns a refresh collective inline
(e.g. an accidental same-step consumer added to a context emit path)
would land invisible to tier-1 and surface as a silent throughput cliff
on real chips.

This checker runs the same structural assertion at TRACE time
(analysis/jaxpr_overlap.py) on the tiny config — seconds, CPU-only,
tier-1-runnable:

* **stale scan** (corrected_async_gn): the steady-state body's ppermute
  halo refreshes and all_gather KV refreshes must all classify
  deferred/deferred_compute; inline is allowed ONLY for all_gather (the
  per-step CFG/output combine, synchronous in the reference too) and at
  most 2 of them — the exact envelope the HLO test pins;
* **compressed stale scan** (comm_compress=int8): the quantized refresh
  pairs land in deferred/deferred_compute (the elementwise dequant
  carve-out), same inline envelope;
* **negative control** (full_sync): the sync body must classify inline
  collectives — proving the analyzer still discriminates, so the gate
  cannot rot into a vacuous pass.
"""

from __future__ import annotations

from typing import List

from ..core import CheckContext, Finding

NAME = "jaxpr-overlap"
DESCRIPTION = ("stale-exchange collectives classify deferred at trace "
               "time on the tiny config (CPU-fast mirror of the slow "
               "HLO tests)")

RUNNER_PATH = "distrifuser_tpu/parallel/runner.py"

#: the HLO test's envelope (tests/test_overlap.py): at most this many
#: inline collectives in the stale scan, all of them gathers
MAX_INLINE = 2
MIN_DEFERRED = 10


def _finding(rule: str, message: str) -> Finding:
    return Finding(checker=NAME, path=RUNNER_PATH, line=0,
                   message=message, identity=rule)


def _trace_tiny(mode: str, steps: int, comm_compress: str = "none"):
    """Trace (never compile) the tiny-config fused loop; returns the
    ClosedJaxpr.  Mirrors tests/test_overlap.py::_compiled_hlo minus
    ``.compile()``."""
    import jax
    import jax.numpy as jnp

    from ...models import unet as unet_mod
    from ...parallel.runner import DenoiseRunner
    from ...schedulers import get_scheduler
    from ...utils.config import DistriConfig

    devices = jax.devices()[:8]
    ucfg = unet_mod.tiny_config(sdxl=False)
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    depth = len(ucfg.block_out_channels) - 1
    cfg = DistriConfig(
        devices=devices, height=8 * 8 * (1 << depth) * 2, width=128,
        warmup_steps=1, parallelism="patch", mode=mode,
        comm_compress=comm_compress,
    )
    runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    lat = jnp.zeros((1, cfg.latent_height, cfg.latent_width,
                     ucfg.in_channels))
    enc = jnp.zeros((2, 1, 7, ucfg.cross_attention_dim))
    fn = runner._build(steps)
    try:
        return fn.trace(params, lat, enc, None, 5.0).jaxpr
    except AttributeError:  # older jax.stages without .trace
        import jax as _jax

        return _jax.make_jaxpr(
            lambda p, l, e, g: fn(p, l, e, None, g)
        )(params, lat, enc, 5.0)


def _gate_stale(reports, tag: str) -> List[Finding]:
    from ..jaxpr_overlap import JaxprLoopReport  # noqa: F401

    findings: List[Finding] = []
    if not reports:
        return [_finding(f"{tag}:no-loops",
                         f"[{tag}] no loop collectives found in the "
                         "traced patch program — the analyzer lost the "
                         "scan, or the loop structure changed")]
    stale = max(reports, key=lambda r: r.n_deferred + r.n_deferred_compute)
    hidden = {**stale.deferred, **stale.deferred_compute}
    if stale.n_inline > MAX_INLINE:
        findings.append(_finding(
            f"{tag}:inline-count",
            f"[{tag}] stale scan has {stale.n_inline} inline "
            f"collectives (> {MAX_INLINE}): {stale.inline} — a "
            "stale-exchange collective gained a same-step consumer and "
            "now serializes against compute"))
    bad = [p for p in stale.inline.values() if p != "all_gather"]
    if bad:
        findings.append(_finding(
            f"{tag}:inline-kind",
            f"[{tag}] only the per-step output/CFG all_gather may be "
            f"inline in the stale scan; got {stale.inline} — ppermute/"
            "psum serializing means a refresh path broke its deferral"))
    if "ppermute" not in hidden.values():
        findings.append(_finding(
            f"{tag}:halo-missing",
            f"[{tag}] no deferred ppermute in the stale scan — the halo "
            "refresh exchanges are missing from the carry"))
    if "all_gather" not in hidden.values():
        findings.append(_finding(
            f"{tag}:kv-missing",
            f"[{tag}] no deferred all_gather in the stale scan — the KV "
            "refresh gathers are missing from the carry"))
    if len(hidden) < MIN_DEFERRED:
        findings.append(_finding(
            f"{tag}:deferred-count",
            f"[{tag}] only {len(hidden)} collectives classify "
            f"deferred/deferred-compute (< {MIN_DEFERRED}) — the "
            "refresh set shrank or the classifier regressed"))
    return findings


def run(ctx: CheckContext) -> List[Finding]:
    try:
        import jax
    except Exception as exc:  # pragma: no cover - env without jax
        return [_finding("no-jax",
                         f"jax unavailable, overlap gate cannot run: "
                         f"{exc}")]
    if len(jax.devices()) < 8:
        return [_finding(
            "no-devices",
            "overlap gate needs the fake 8-device CPU mesh — set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 (and "
            "JAX_PLATFORMS=cpu) before jax is first imported; the CLI "
            "entry point does this automatically")]

    from ..jaxpr_overlap import analyze_jaxpr_collectives

    findings: List[Finding] = []
    findings.extend(_gate_stale(
        analyze_jaxpr_collectives(_trace_tiny("corrected_async_gn", 4)),
        "stale"))
    findings.extend(_gate_stale(
        analyze_jaxpr_collectives(
            _trace_tiny("corrected_async_gn", 4, comm_compress="int8")),
        "stale-int8"))
    # negative control: the analyzer must still see sync gathers as
    # inline, or every assertion above passes vacuously
    sync_reports = analyze_jaxpr_collectives(_trace_tiny("full_sync", 5))
    if not any(r.n_inline > 0 for r in sync_reports):
        findings.append(_finding(
            "sync-control",
            "negative control failed: full_sync collectives did not "
            "classify inline — the jaxpr analyzer lost discrimination "
            "and the deferral gate is vacuous"))
    return findings

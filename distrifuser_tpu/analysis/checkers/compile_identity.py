"""Compile-identity completeness: no half-wired ExecKey knob can land.

The invariant (serve/cache.py ExecKey docstring, re-proved by hand in
every one of PRs 2/4/6/7/9/12): **every trace-affecting serve knob is a
compile-identity field**.  A `ServeConfig` knob that changes the traced
program but is missing from `ExecKey` makes two different XLA programs
alias one cache entry — a stale executor silently serves wrong numerics
to the whole fleet.  The wiring has four stations, and a new knob must
reach all of them:

1. a same-named `ExecKey` dataclass field (`serve/cache.py`);
2. `ExecKey.short()` must render it — short() keys the per-executor
   ledgers (weight_bytes, circuits, degradations), so an unrendered
   field lets two resident keys collide to one tag;
3. `executors.apply_key_policy` must consider it — degraded keys built
   by ladder/controller rewrites reach builders that predate the knob;
4. `InferenceServer._exec_key_for` must thread the ServeConfig value
   into the `ExecKey(...)` construction — or per-bucket routing forgets
   the knob entirely.

ServeConfig fields that deliberately do NOT trace live in
`SERVE_RUNTIME_ALLOWLIST` with a reason each (the explicit
trace-invariant allowlist); ExecKey fields no station needs are listed
the same way.  Removing any single ExecKey field — or its short()/
apply_key_policy handling — makes this checker fail (asserted field by
field in tests/test_analysis.py).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Tuple

from ..core import CheckContext, Finding

NAME = "compile-identity"
DESCRIPTION = ("ServeConfig knobs mirrored into ExecKey; short()/"
               "apply_key_policy/_exec_key_for cover every field")

CACHE_PATH = "distrifuser_tpu/serve/cache.py"
EXECUTORS_PATH = "distrifuser_tpu/serve/executors.py"
SERVER_PATH = "distrifuser_tpu/serve/server.py"

#: ServeConfig fields that never change the traced program — each with
#: the reason it is trace-invariant.  A new ServeConfig field must either
#: gain a same-named ExecKey field or an entry here; there is no third
#: option the gate accepts.
SERVE_RUNTIME_ALLOWLIST: Dict[str, str] = {
    "max_queue_depth": "admission bound — host-side queue shape",
    "default_ttl_s": "deadline bookkeeping on the host clock",
    "max_batch_size": "batcher coalescing bound; batch dim is padded "
                      "inside one program",
    "batch_window_s": "batcher linger timing, host-side",
    "buckets": "per-request: snapped resolutions enter keys as "
               "ExecKey.height/width",
    "cache_capacity": "LRU bound on the executor map itself",
    "warmup_buckets": "startup prefetch list; each bucket keys normally",
    "warmup_cfg": "warmup-only: enters keys via _exec_key_for(cfg=...)",
    "default_steps": "per-request default: enters ExecKey.steps",
    "bucket_parallelism": "routing map: resolves per bucket into "
                          "ExecKey.parallelism in _exec_key_for",
    "pipeline_stages": "staged vs monolithic dispatch of the SAME "
                       "compiled stage programs (bit-identical, "
                       "tests/test_staging.py)",
    "max_inflight_batches": "staging HBM cap, host-side semaphore",
    "prompt_cache_capacity": "host-side embedding LRU bound",
    "controller": "sub-config: tier walks rewrite keys via apply_tier",
    "step_batching": "sub-config: enabled resolves into ExecKey."
                     "exec_mode='step' in _exec_key_for (compile-"
                     "distinct); slots/preview/preempt knobs are "
                     "host-side scheduling policy",
    "resilience": "sub-config: ladder rungs rewrite keys via "
                  "DegradationLadder.apply",
    "observability": "host-side tracing/metrics plane",
    "gateway": "sub-config: HTTP/SSE transport + tenant fairness "
               "policy — pure host-side admission/scheduling, never "
               "touches what compiles or executes",
    "aot_cache": "sub-config: WHERE compiled programs persist, never "
                 "WHAT compiles — an entry only loads when its full "
                 "fingerprint (ExecKey scope + jax/jaxlib/backend + "
                 "mesh + layout) matches, and a mismatch falls back to "
                 "the normal compile path (bit-identity pinned by "
                 "tests/test_aotcache.py)",
}

#: ExecKey fields _exec_key_for does not thread from ServeConfig —
#: set only by degradation machinery downstream of key construction.
#: (exec_mode left this list when step-level continuous batching made
#: the server thread it: ServeConfig.step_batching.enabled keys every
#: bucket at exec_mode="step", so _exec_key_for passes it and the
#: key-for station checks it like any other field; the stepwise ladder
#: rung still rewrites it downstream.)
LADDER_ONLY_ALLOWLIST: Dict[str, str] = {}

#: ExecKey fields apply_key_policy leaves to build_pipeline: the builder
#: constructs its DistriConfig/weights from these, and no degradation
#: rung ever rewrites them post-construction except through a fresh key.
STRUCTURAL_FIELDS: Dict[str, str] = {
    "model_id": "selects the builder's weights — never forced post-build",
    "scheduler": "pipeline constructor argument",
    "height": "bucket geometry: the builder's DistriConfig shape",
    "width": "bucket geometry: the builder's DistriConfig shape",
    "steps": "prepare(key.steps) in pipeline_executor_factory",
    "cfg": "guidance branch topology, fixed at construction",
    "mesh_plan": "mesh layout, fixed at construction",
}


@dataclasses.dataclass(frozen=True)
class IdentityModel:
    """Everything the pure check needs, extracted from the tree.  Tests
    mutate copies of this to seed violations (missing field, dropped
    short() tag, unthreaded kwarg) without editing the repo."""

    exec_key_fields: Tuple[str, ...]
    serve_config_fields: Tuple[str, ...]
    short_attrs: FrozenSet[str]       # self.X reads inside ExecKey.short
    policy_attrs: FrozenSet[str]      # every attr name in apply_key_policy
    policy_key_attrs: FrozenSet[str]  # key.X reads in apply_key_policy
    key_call_kwargs: FrozenSet[str]   # ExecKey(...) kwargs in _exec_key_for
    lines: Dict[str, int] = dataclasses.field(default_factory=dict)

    def line(self, station: str) -> int:
        return self.lines.get(station, 0)


def _attr_reads(node: ast.AST, base: str) -> FrozenSet[str]:
    return frozenset(
        n.attr for n in ast.walk(node)
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name)
        and n.value.id == base
    )


def _all_attr_names(node: ast.AST) -> FrozenSet[str]:
    return frozenset(n.attr for n in ast.walk(node)
                     if isinstance(n, ast.Attribute))


def _find_def(tree: ast.Module, name: str, cls: str = None) -> ast.AST:
    for node in ast.walk(tree):
        if cls is not None:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                for sub in node.body:
                    if (isinstance(sub, ast.FunctionDef)
                            and sub.name == name):
                        return sub
        elif isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    raise LookupError(f"{name!r} not found" + (f" in class {cls}" if cls
                                               else ""))


def build_model(ctx: CheckContext) -> IdentityModel:
    """Extract the four stations from the real tree: ExecKey/ServeConfig
    fields by import (dataclass truth, inheritance-proof), the handling
    functions by AST (what the source actually references)."""
    from ...serve.cache import ExecKey
    from ...utils.config import ServeConfig

    short_def = _find_def(ctx.tree(CACHE_PATH), "short", cls="ExecKey")
    policy_def = _find_def(ctx.tree(EXECUTORS_PATH), "apply_key_policy")
    keyfor_def = _find_def(ctx.tree(SERVER_PATH), "_exec_key_for")
    key_call = None
    for node in ast.walk(keyfor_def):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "ExecKey"):
            key_call = node
            break
    kwargs = frozenset(kw.arg for kw in key_call.keywords
                       if kw.arg is not None) if key_call else frozenset()
    return IdentityModel(
        exec_key_fields=tuple(f.name for f in dataclasses.fields(ExecKey)),
        serve_config_fields=tuple(
            f.name for f in dataclasses.fields(ServeConfig)),
        short_attrs=_attr_reads(short_def, "self"),
        policy_attrs=_all_attr_names(policy_def),
        policy_key_attrs=_attr_reads(policy_def, "key"),
        key_call_kwargs=kwargs,
        lines={
            "short": short_def.lineno,
            "policy": policy_def.lineno,
            "key_for": keyfor_def.lineno,
        },
    )


def check_model(model: IdentityModel) -> List[Finding]:
    """The pure gate over an extracted (or test-seeded) model."""
    findings: List[Finding] = []
    key_fields = set(model.exec_key_fields)

    def finding(path, line, rule, field, message):
        findings.append(Finding(
            checker=NAME, path=path, line=line, message=message,
            identity=f"{rule}:{field}"))

    # station 1: every ServeConfig knob is mirrored or allowlisted
    for f in model.serve_config_fields:
        if f not in key_fields and f not in SERVE_RUNTIME_ALLOWLIST:
            finding("distrifuser_tpu/utils/config.py", 0, "mirror", f,
                    f"ServeConfig.{f} is neither an ExecKey field nor in "
                    "the trace-invariant allowlist — a trace-affecting "
                    "knob missing from the compile identity lets a stale "
                    "executor serve wrong numerics (add the ExecKey "
                    "field or allowlist it with a reason in "
                    "analysis/checkers/compile_identity.py)")
    # allowlist hygiene: entries must be live and must not shadow fields
    for f, _why in SERVE_RUNTIME_ALLOWLIST.items():
        if f not in model.serve_config_fields:
            finding("distrifuser_tpu/utils/config.py", 0,
                    "allowlist-stale", f,
                    f"trace-invariant allowlist names {f!r} which is no "
                    "longer a ServeConfig field — remove the entry")
        if f in key_fields:
            finding(CACHE_PATH, 0, "allowlist-shadow", f,
                    f"{f!r} is both an ExecKey field and allowlisted as "
                    "trace-invariant — one of the two is lying")

    # station 2: short() renders every field, and only real fields
    for f in model.exec_key_fields:
        if f not in model.short_attrs:
            finding(CACHE_PATH, model.line("short"), "short", f,
                    f"ExecKey.short() never reads self.{f} — the tag "
                    "keys per-executor ledgers, so two resident keys "
                    "differing only in this field would collide")
    for a in model.short_attrs - key_fields:
        finding(CACHE_PATH, model.line("short"), "short-dangling", a,
                f"ExecKey.short() reads self.{a} which is not an ExecKey "
                "field — dangling handling for a removed field")

    # station 3: apply_key_policy considers every non-structural field
    for f in model.exec_key_fields:
        if f in STRUCTURAL_FIELDS:
            continue
        if f not in model.policy_attrs:
            finding(EXECUTORS_PATH, model.line("policy"), "policy", f,
                    f"apply_key_policy never references {f!r} — degraded "
                    "keys carrying it would reach builders unchecked "
                    "(force it, validate it, or raise "
                    "DegradationInapplicableError)")
    for a in model.policy_key_attrs - key_fields:
        finding(EXECUTORS_PATH, model.line("policy"), "policy-dangling", a,
                f"apply_key_policy reads key.{a} which is not an ExecKey "
                "field — dangling handling for a removed field")

    # station 4: _exec_key_for threads every constructor-visible field
    for f in model.exec_key_fields:
        if f in LADDER_ONLY_ALLOWLIST:
            continue
        if f not in model.key_call_kwargs:
            finding(SERVER_PATH, model.line("key_for"), "key-for", f,
                    f"_exec_key_for's ExecKey(...) call never passes "
                    f"{f!r} — the ServeConfig knob would silently key "
                    "every bucket at the dataclass default")
    for a in model.key_call_kwargs - key_fields:
        finding(SERVER_PATH, model.line("key_for"), "key-for-dangling", a,
                f"_exec_key_for passes ExecKey kwarg {a!r} which is not "
                "a field — dangling construction for a removed field")
    for f, _why in LADDER_ONLY_ALLOWLIST.items():
        if f not in key_fields:
            finding(CACHE_PATH, 0, "ladder-allowlist-stale", f,
                    f"ladder-only allowlist names {f!r} which is not an "
                    "ExecKey field — remove the entry")
    return findings


def run(ctx: CheckContext) -> List[Finding]:
    return check_model(build_model(ctx))

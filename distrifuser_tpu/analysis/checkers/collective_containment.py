"""Collective containment: comm bytes only move where accounting sees.

The byte model became a *checked invariant* in PR 8: the live
StepTimeline counters reconcile EXACTLY against the closed-form
`comm_plan`, which prices what `context.WIRE_REGISTRY` registered.  That
reconciliation is only exhaustive while every collective flows through
the registered helpers — a raw `lax.all_gather` dropped into a model
file moves real wire bytes the plan never prices, and the exact test
keeps passing while lying.

This checker confines raw `lax.<collective>` call sites to the blessed
accounting layer:

* `parallel/collectives.py` — the named-axis helper surface itself;
* `parallel/context.py` — PatchContext's emit/refresh paths, which
  register every exchange in WIRE_REGISTRY as they trace it;
* `parallel/compress.py` — the quantized-wire variants, ditto.

Everything else must call the helpers (ops/, models/, parallel runners)
or carry a baseline entry whose provenance line names the accounting
that covers it (e.g. PipeFusion's ring hops are priced by its own
closed-form `comm_report`, reconciled in tests/test_pipefusion.py).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Tuple

from ..core import CheckContext, Finding, enclosing_qualname

NAME = "collective-containment"
DESCRIPTION = ("raw lax.<collective> calls confined to the "
               "WIRE_REGISTRY-accounted helper modules")

#: raw spellings this checker hunts (jax.lax surface)
COLLECTIVE_NAMES = frozenset({
    "ppermute", "all_gather", "psum", "pmean", "psum_scatter",
    "all_to_all", "pmin", "pmax", "pgather", "pshuffle", "pswapaxes",
})

#: modules where raw collectives ARE the accounting layer
BLESSED_MODULES = frozenset({
    "distrifuser_tpu/parallel/collectives.py",
    "distrifuser_tpu/parallel/context.py",
    "distrifuser_tpu/parallel/compress.py",
})


def _lax_bases(tree: ast.Module) -> frozenset:
    """Local names that refer to jax.lax in this module (``lax`` via
    ``from jax import lax`` / ``import jax.lax as lax``), plus direct
    names bound by ``from jax.lax import ppermute``."""
    bases, direct = set(), {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "lax":
                        bases.add(a.asname or "lax")
            elif node.module == "jax.lax":
                for a in node.names:
                    if a.name in COLLECTIVE_NAMES:
                        direct[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.lax":
                    # `import jax.lax as L` binds L; plain `import
                    # jax.lax` binds `jax`, and calls read jax.lax.x
                    bases.add(a.asname if a.asname else "jax.lax")
                elif a.name == "jax":
                    bases.add((a.asname or "jax") + ".lax")  # jax.lax.x
    return frozenset(bases), dict(direct)


def scan_module(tree: ast.Module, relpath: str,
                blessed: Sequence[str] = ()) -> List[Finding]:
    """Findings for raw collective calls in one module (pure core —
    tests feed fixture sources here directly)."""
    blessed = set(blessed) | BLESSED_MODULES
    if relpath in blessed:
        return []
    bases, direct = _lax_bases(tree)
    findings: List[Finding] = []
    counts: Dict[Tuple[str, str], int] = {}
    stack: List[ast.AST] = []

    def visit(node: ast.AST):
        is_scope = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))
        if is_scope:
            stack.append(node)
        if isinstance(node, ast.Call):
            name = None
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVE_NAMES:
                base = None
                if isinstance(fn.value, ast.Name):
                    base = fn.value.id
                elif (isinstance(fn.value, ast.Attribute)
                      and isinstance(fn.value.value, ast.Name)):
                    base = f"{fn.value.value.id}.{fn.value.attr}"
                if base in bases:
                    name = fn.attr
            elif isinstance(fn, ast.Name) and fn.id in direct:
                name = direct[fn.id]  # canonical name, not the alias
            if name is not None:
                scope = enclosing_qualname(stack)
                idx = counts.get((scope, name), 0)
                counts[(scope, name)] = idx + 1
                findings.append(Finding(
                    checker=NAME, path=relpath, line=node.lineno,
                    message=(
                        f"raw lax.{name} in {scope} — collectives must "
                        "flow through the WIRE_REGISTRY-accounted "
                        "helpers (parallel/collectives.py) or the "
                        "PatchContext emit paths, or the comm_plan/"
                        "StepTimeline exact reconciliation stops being "
                        "exhaustive; wrap it, or baseline it naming the "
                        "accounting that covers it"),
                    identity=f"{scope}:{name}:{idx}",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            stack.pop()

    visit(tree)
    return findings


def run(ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel in ctx.iter_py("distrifuser_tpu"):
        findings.extend(scan_module(ctx.tree(rel), rel))
    return findings

"""Checker implementations.  Each module exposes ``NAME``,
``DESCRIPTION``, and ``run(ctx) -> List[Finding]``; checkers keep a
pure core (operating on an extracted model of the tree) separate from
the extraction, so tests can seed violations without editing the repo.
Registration lives in analysis/registry.py."""

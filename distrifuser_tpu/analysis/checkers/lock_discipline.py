"""Serve thread/lock discipline: guarded attributes mutate under their
lock.

The serve layer's thread model is deliberate and documented, not
incidental: breaker/ladder VALUES are scheduler-thread-owned while map
MEMBERSHIP is lock-guarded (serve/resilience.py `_keys_lock` comment),
the executor cache's map and pin tables mutate only under `_lock` with a
``*_locked`` caller-holds-lock suffix convention (serve/cache.py), the
queue's items/closed/seq move under one lock shared with its condition,
and snapshot()-class readers rely on mutations being serialized to get
GIL-consistent copies.  A mutation that slips outside its lock corrupts
exactly the state the health/metrics planes read from other threads —
and reviews catch it only when someone remembers the rule.

This checker encodes the rule as data: `GUARDED_REGISTRY` maps each
audited class to its lock attribute and the attributes that lock guards
(derived from the in-code docs).  The AST pass then asserts every
mutation of a guarded attribute happens (a) lexically inside
``with self.<lock>:``, (b) in ``__init__``/``__post_init__`` (the object
is not yet shared), (c) in a method whose name ends ``_locked`` (the
documented caller-holds-lock convention), or (d) in a per-class
``owner_methods`` allowlist entry for scheduler-owned paths.

Reads are deliberately NOT checked: the serve metrics contract
explicitly blesses unlocked dict-copy reads (GIL snapshot semantics,
resilience.py snapshot docs).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Sequence, Tuple

from ..core import CheckContext, Finding

NAME = "lock-discipline"
DESCRIPTION = ("guarded serve-layer attributes mutate only under their "
               "documented lock (registry-driven AST pass)")

#: method names that mutate their receiver in place
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "discard",
    "pop", "popitem", "popleft", "clear", "update", "setdefault", "add",
    "move_to_end", "sort", "reverse",
})


@dataclasses.dataclass(frozen=True)
class Guard:
    """One audited class: which lock guards which attributes.

    ``via`` (non-empty) marks a CROSS-OBJECT guard: the attrs are
    protected by the named owner's lock or hand-off protocol, which this
    lexical pass cannot verify (the mutations are ``slot.x = ...`` in
    the owner's methods, not ``self.x``).  Such entries are skipped by
    the static scan and validated DYNAMICALLY instead: distrisched's
    happens-before race detector (analysis/concurrency/) checks the
    actual ordering on explored schedules, and its registry-drift
    cross-check treats the attrs as covered.  The entry is still the
    single machine-readable statement of the thread model.
    """

    lock: str
    attrs: FrozenSet[str]
    #: methods allowed to mutate without the lock (single-owner paths,
    #: each with the in-code doc that blesses it)
    owner_methods: FrozenSet[str] = frozenset()
    #: non-empty = guarded by this owner lock / hand-off protocol;
    #: statically unscannable, dynamically validated (see docstring)
    via: str = ""


def guard(lock: str, attrs: Sequence[str],
          owner_methods: Sequence[str] = (), via: str = "") -> Guard:
    return Guard(lock=lock, attrs=frozenset(attrs),
                 owner_methods=frozenset(owner_methods), via=via)


#: (module relpath -> class name -> Guard), derived from the thread-model
#: docs each class carries.  Growing the serve layer?  Register the new
#: class here — an unregistered lock is an unchecked invariant.
GUARDED_REGISTRY: Dict[str, Dict[str, Guard]] = {
    "distrifuser_tpu/serve/cache.py": {
        # "a lock still guards the map so stats reads ... are consistent"
        # (module docstring); *_locked = caller-holds-lock convention
        "ExecutorCache": guard(
            "_lock",
            ["_entries", "_pins", "_pin_refs", "_deferred", "hits",
             "misses", "evictions", "deferred_evictions",
             "build_seconds"],
        ),
    },
    "distrifuser_tpu/serve/resilience.py": {
        # "_keys_lock guards MAP membership only" (resilience.py §engine)
        "ResilienceEngine": guard("_keys_lock", ["_keys"]),
        # token-bucket state; _refill_locked is the caller-holds-lock
        # convention
        "RetryBudget": guard("_lock", ["_tokens", "_last"]),
    },
    "distrifuser_tpu/serve/queue.py": {
        "RequestQueue": guard("_lock", ["_items", "_closed", "_seq"]),
        # request lifecycle fields stamped by the batcher AFTER the
        # submitting thread hands the object over through queue._lock —
        # single-owner at every instant, ordered by the queue's lock
        # (distrisched validates the hand-off happens-before)
        "Request": guard(
            "_lock", ["bucket", "dequeue_ts", "trace"],
            via="RequestQueue._lock hand-off (submit -> scheduler)"),
    },
    "distrifuser_tpu/serve/controller.py": {
        # observe_batch/observe_step are documented any-thread; _classes
        # and both service rings move under _lock so snapshot() copies
        # are consistent
        "SLOController": guard(
            "_lock", ["_classes", "_service", "_service_sum",
                      "_step_service", "_step_service_sum"]),
    },
    "distrifuser_tpu/serve/promptcache.py": {
        "PromptCache": guard("_lock", ["_entries", "_hits", "_misses"]),
    },
    "distrifuser_tpu/serve/fleet.py": {
        # the parked list is mutated by submit failover, the housekeeping
        # tick, and stop() — all under the router RLock
        "FleetRouter": guard("_lock", ["_parked"]),
        # per-replica routing state: mutated only in FleetRouter methods
        # under the router RLock (submit path, done-callbacks, tick)
        "_ReplicaSlot": guard(
            "_lock",
            ["faulted", "manual", "drained_at", "probe_inflight",
             "restarting", "consecutive_failures", "last_score",
             "score_at", "dispatched", "completed", "failed"],
            via="FleetRouter._lock (all mutation sites are router "
                "methods holding it)"),
        # failover trail: exactly one owner at a time — the submitting
        # thread until dispatch, then whichever replica thread resolves
        # the inner future (the router re-dispatches only AFTER the
        # prior outcome is terminal); ordering rides Future resolution
        "_FleetRequest": guard(
            "_lock", ["attempts", "tried", "last_replica", "last_error",
                      "salvaged_steps"],
            via="single-owner failover hand-off (Future resolution "
                "happens-before the next dispatch)"),
    },
    "distrifuser_tpu/serve/aotcache.py": {
        # "file I/O runs outside _lock; the index and every counter
        # mutate only under it" (module docstring) — the store is shared
        # by parallel replica warmups through the thread-local
        # activation, so a slipped counter corrupts the hit/reject
        # accounting the warm-start bench gates
        "AotExecutableCache": guard(
            "_lock",
            ["_index", "_tick", "hits", "misses", "rejects", "saves",
             "save_skips", "evictions", "unserializable", "bytes_loaded",
             "bytes_saved", "deserialize_seconds", "serialize_seconds"],
        ),
    },
    "distrifuser_tpu/serve/autoscale.py": {
        # policy state shared by the fleet tick thread and the scale
        # operations' background threads (class docstring)
        "Autoscaler": guard(
            "_lock",
            ["_above_since", "_below_since", "_last_action_at",
             "_op_inflight", "_last_pressure"],
        ),
    },
    "distrifuser_tpu/serve/server.py": {
        # lifecycle cells mutated by concurrent stop()/start() callers
        # (stop is documented idempotent-from-any-thread); reads stay
        # unlocked under the blessed snapshot-read policy.  The pack-
        # fill accumulators feed the serve_stepbatch_pack_fill gauge:
        # written only by _step_advance on the scheduler thread
        # (init-time zeroing aside); the gauge reads ride the snapshot
        # policy like every other serve metric
        "InferenceServer": guard(
            "_lifecycle_lock", ["_started", "_thread",
                                "_pack_rows_total",
                                "_pack_capacity_total"],
            owner_methods=["_step_advance"]),
    },
    "distrifuser_tpu/serve/replica.py": {
        # the lifecycle state machine: every transition and handle swap
        # happens under the replica RLock (module docstring)
        "Replica": guard(
            "_lock",
            ["_state", "_history", "server", "killed", "generation",
             "_bg_stop", "_warm_nonce", "last_warmup_s",
             "last_warmup_compile_s", "last_warmup_deserialize_s"]),
    },
    "distrifuser_tpu/serve/staging.py": {
        # residency/outcome counters shared by the scheduler thread
        # (submit) and the three stage workers
        "StagePipeline": guard(
            "_lock",
            ["_inflight", "peak_inflight", "submitted", "completed",
             "failed"]),
    },
    "distrifuser_tpu/serve/stepbatch.py": {
        # the ENTIRE slot pool is scheduler-thread-owned (module
        # docstring): InferenceServer._loop drives every mutation from
        # its single step-round loop; gauges/snapshots read under the
        # blessed snapshot policy.  No lock exists to scan — distrisched
        # validates the single-owner claim dynamically (the three
        # stepbatch scenarios run at 85 seeds each in tier-1).
        # pack_aligned is the fused-dispatch grouping counter: cohort()
        # bumps it on the scheduler thread when pack_align reshapes a
        # width-truncated selection (the executor-side pack state —
        # step_pack_stats, the axes cache — is likewise touched only by
        # step_run on the same thread).
        "StepBatcher": guard(
            "_lock",
            ["_slots", "_parked", "_ewma", "_round_s_total",
             "_rounds_timed", "joins", "leaves", "preempt_count",
             "resumes", "rounds", "pack_aligned"],
            via="scheduler-thread single owner (InferenceServer._loop "
                "step rounds; reads are snapshot-blessed)"),
        "SlotState": guard(
            "_lock",
            ["work", "steps_done", "slot", "parked", "preempts",
             "previews", "first_preview_s", "migrations",
             "steps_salvaged"],
            via="scheduler-thread single owner (mutated only inside "
                "_step_round paths)"),
    },
    "distrifuser_tpu/serve/migration.py": {
        # the decoded snapshot is a frozen dataclass: immutable after
        # construction, shared READ-ONLY across the export/import
        # hand-off (dying scheduler thread -> fleet failover -> adopting
        # replica's submit path).  Nothing to lock — the entry records
        # the claim and keeps the registry-drift cross-check honest.
        "CarrySnapshot": guard(
            "_lock", ["meta", "leaves"],
            via="frozen dataclass — immutable after construction; "
                "crosses threads by value through Future resolution"),
    },
    "distrifuser_tpu/serve/gateway.py": {
        # connection table + drain flag: mutated by HTTP handler threads
        # (register, stop) under the gateway lock
        "Gateway": guard("_lock", ["_requests", "_stopping"]),
        # per-request event buffer + terminal state: every mutation is
        # inside this entry's own locked methods (push/finish/close);
        # `future` is written exactly once by handle_generate before the
        # entry is shared through Gateway._lock (the registration
        # hand-off) — distrisched's gateway scenarios validate both
        "_GatewayRequest": guard(
            "_lock",
            ["_events", "_next_seq", "dropped", "done", "closed",
             "outcome", "result", "error", "future"],
            via="entry-local locked methods; `future` set-once before "
                "the Gateway._lock registration hand-off"),
    },
    "distrifuser_tpu/serve/tenancy.py": {
        # the tenancy policy owns NO lock: every call (admit from
        # producer threads via put(), select/charge from the scheduler
        # via peek_best/remove) happens under RequestQueue._lock — the
        # queue IS the policy's lock.  distrisched validates via the
        # gateway scenarios (tenanted submits racing stop).
        "TenancyPolicy": guard(
            "_lock", ["_state", "_order", "_cursor", "_pending"],
            via="RequestQueue._lock (policy invoked only by queue "
                "methods holding it)"),
        "_TenantState": guard(
            "_lock", ["deficit", "admitted", "rejected_quota",
                      "dequeued"],
            via="RequestQueue._lock (policy invoked only by queue "
                "methods holding it)"),
        "TokenBucket": guard(
            "_lock", ["tokens", "last_refill"],
            via="RequestQueue._lock (refill/take only inside "
                "policy.admit under the queue lock)"),
    },
    # utils/ classes the serve plane shares across threads (brought under
    # the registry by ISSUE 14's sync_containment migration)
    "distrifuser_tpu/utils/metrics.py": {
        "Counter": guard("_lock", ["_c"]),
        "LatencyHistogram": guard(
            "_lock", ["_counts", "count", "sum", "min", "max"]),
        "GapTracker": guard(
            "_lock", ["_t0", "first_start", "last_end", "busy_s",
                      "intervals"]),
        "RingLog": guard("_lock", ["_items", "_seq"]),
        "Gauge": guard("_lock", ["_value"]),
        "RollingQuantile": guard("_lock", ["_buf", "_ts", "_n"]),
        "MetricsRegistry": guard("_lock", ["_families"]),
    },
    "distrifuser_tpu/utils/trace.py": {
        "Tracer": guard(
            "_lock",
            ["_records", "_open", "_next_trace", "_next_span",
             "_next_seq", "_next_flow", "dropped"]),
        "StepTimeline": guard(
            "_lock",
            ["runs", "_cur", "_phase_of", "_bytes_per_step", "_t_last"]),
    },
}


def _self_attr(node: ast.AST) -> str:
    """'X' when node is ``self.X``, else ''."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


def _is_lock_ctx(item: ast.withitem, lock: str) -> bool:
    return _self_attr(item.context_expr) == lock


def scan_class(cls: ast.ClassDef, spec: Guard, relpath: str,
               class_name: str = None) -> List[Finding]:
    """Findings for unguarded mutations in one class (pure core)."""
    class_name = class_name or cls.name
    findings: List[Finding] = []
    counts: Dict[Tuple[str, str], int] = {}

    def report(method: str, attr: str, line: int, how: str):
        idx = counts.get((method, attr), 0)
        counts[(method, attr)] = idx + 1
        findings.append(Finding(
            checker=NAME, path=relpath, line=line,
            message=(
                f"{class_name}.{method} mutates self.{attr} ({how}) "
                f"outside `with self.{spec.lock}:` — the thread-model "
                f"docs guard it with {spec.lock}; take the lock, rename "
                "the method *_locked if the caller holds it, or "
                "register it as scheduler-owned with a doc pointer"),
            identity=f"{class_name}.{method}:{attr}:{idx}",
        ))

    def walk(node: ast.AST, method: str, locked: bool):
        # track lock scope lexically
        if isinstance(node, ast.With):
            now_locked = locked or any(
                _is_lock_ctx(i, spec.lock) for i in node.items)
            for child in ast.iter_child_nodes(node):
                walk(child, method, now_locked)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (worker closures) run on other threads: they
            # start unlocked regardless of the enclosing with-block
            for child in node.body:
                walk(child, node.name, False)
            return
        if not locked:
            exempt = (method in ("__init__", "__post_init__")
                      or method.endswith("_locked")
                      or method in spec.owner_methods)
            if not exempt:
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        attr = _self_attr(t)
                        if attr in spec.attrs:
                            report(method, attr, node.lineno, "assign")
                        if (isinstance(t, (ast.Subscript, ast.Starred))
                                and _self_attr(getattr(t, "value", None))
                                in spec.attrs):
                            report(method,
                                   _self_attr(t.value), node.lineno,
                                   "item assign")
                        if isinstance(t, ast.Tuple):
                            for el in t.elts:
                                attr = _self_attr(el)
                                if attr in spec.attrs:
                                    report(method, attr, node.lineno,
                                           "tuple assign")
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        base = (t.value if isinstance(t, ast.Subscript)
                                else t)
                        attr = _self_attr(base)
                        if attr in spec.attrs:
                            report(method, attr, node.lineno, "del")
                elif isinstance(node, ast.Call):
                    fn = node.func
                    if (isinstance(fn, ast.Attribute)
                            and fn.attr in MUTATOR_METHODS):
                        attr = _self_attr(fn.value)
                        if attr in spec.attrs:
                            report(method, attr, node.lineno,
                                   f".{fn.attr}()")
        for child in ast.iter_child_nodes(node):
            walk(child, method, locked)

    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in item.body:
                walk(child, item.name, False)
    return findings


def run(ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    for relpath, classes in sorted(GUARDED_REGISTRY.items()):
        if not ctx.exists(relpath):
            findings.append(Finding(
                checker=NAME, path=relpath, line=0,
                message=(f"lock registry names {relpath} which no longer "
                         "exists — move or drop the registry entry"),
                identity=f"registry-missing:{relpath}"))
            continue
        tree = ctx.tree(relpath)
        found = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name in classes:
                found.add(node.name)
                spec = classes[node.name]
                if spec.via:
                    # cross-object guard: lexically unscannable by
                    # design — distrisched validates it dynamically
                    # (Guard docstring); the existence checks above
                    # still keep the entry honest
                    continue
                findings.extend(scan_class(node, spec, relpath))
        for missing in set(classes) - found:
            findings.append(Finding(
                checker=NAME, path=relpath, line=0,
                message=(f"lock registry names class {missing} which no "
                         f"longer exists in {relpath} — update the "
                         "registry"),
                identity=f"registry-missing:{relpath}:{missing}"))
    return findings

"""Sync containment: primitives only come from the instrumentable layer.

distrisched (analysis/concurrency/) can only explore interleavings it
can SEE: its deterministic scheduler interposes at the sync points of
primitives constructed through utils/sync.py.  A raw
``threading.Lock()`` (or ``queue.Queue()``) dropped into a serve module
is invisible to the harness — its waits neither yield to the seeded
scheduler nor carry vector clocks, so schedules silently stop covering
the code around it and the race/deadlock gate keeps passing while
blind.  This is the dynamic-analysis analog of collective-containment's
"bytes only move where accounting sees".

This checker confines raw constructor calls for
``threading.{Lock,RLock,Condition,Event,Semaphore,BoundedSemaphore,
Barrier,Thread,Timer}`` and ``queue.{Queue,LifoQueue,PriorityQueue,
SimpleQueue}`` to ``utils/sync.py`` (the passthrough layer itself).
Everything else under ``distrifuser_tpu/`` calls the sync factories, or
carries a baseline entry whose provenance names why harness coverage is
not needed there (same workflow as collective-containment).  Aliased
imports (``import threading as t``, ``from threading import Lock``) are
resolved, not pattern-matched.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Tuple

from ..core import CheckContext, Finding, enclosing_qualname

NAME = "sync-containment"
DESCRIPTION = ("raw threading/queue primitive constructors confined to "
               "utils/sync.py so distrisched's scheduler sees every "
               "sync point")

#: constructor names hunted, per module
SYNC_CTORS = {
    "threading": frozenset({
        "Lock", "RLock", "Condition", "Event", "Semaphore",
        "BoundedSemaphore", "Barrier", "Thread", "Timer",
    }),
    "queue": frozenset({
        "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    }),
}

#: the passthrough layer itself — raw constructors ARE its job
BLESSED_MODULES = frozenset({
    "distrifuser_tpu/utils/sync.py",
})


def _ctor_bindings(tree: ast.Module) -> Tuple[Dict[str, str],
                                              Dict[str, Tuple[str, str]]]:
    """(module-alias -> module, direct-name -> (module, ctor)) for the
    hunted modules, resolving ``import x as y`` and ``from x import C``."""
    mod_alias: Dict[str, str] = {}
    direct: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in SYNC_CTORS:
                    mod_alias[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module in SYNC_CTORS:
                for a in node.names:
                    if a.name in SYNC_CTORS[node.module]:
                        direct[a.asname or a.name] = (node.module, a.name)
    return mod_alias, direct


def scan_module(tree: ast.Module, relpath: str,
                blessed: Sequence[str] = ()) -> List[Finding]:
    """Findings for raw sync constructors in one module (pure core —
    tests feed fixture sources directly)."""
    blessed = set(blessed) | BLESSED_MODULES
    if relpath in blessed:
        return []
    mod_alias, direct = _ctor_bindings(tree)
    if not mod_alias and not direct:
        return []
    findings: List[Finding] = []
    counts: Dict[Tuple[str, str], int] = {}
    stack: List[ast.AST] = []

    def visit(node: ast.AST):
        is_scope = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))
        if is_scope:
            stack.append(node)
        if isinstance(node, ast.Call):
            hit = None  # (module, ctor)
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in mod_alias):
                module = mod_alias[fn.value.id]
                if fn.attr in SYNC_CTORS[module]:
                    hit = (module, fn.attr)
            elif isinstance(fn, ast.Name) and fn.id in direct:
                hit = direct[fn.id]
            if hit is not None:
                module, ctor = hit
                scope = enclosing_qualname(stack)
                idx = counts.get((scope, ctor), 0)
                counts[(scope, ctor)] = idx + 1
                findings.append(Finding(
                    checker=NAME, path=relpath, line=node.lineno,
                    message=(
                        f"raw {module}.{ctor}() in {scope} — construct "
                        "it via utils/sync.py so distrisched's "
                        "deterministic scheduler sees its sync points "
                        "(a raw primitive is a blind spot in the "
                        "race/deadlock gate); or baseline it naming why "
                        "harness coverage is not needed"),
                    identity=f"{scope}:{module}.{ctor}:{idx}",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            stack.pop()

    visit(tree)
    return findings


def run(ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel in ctx.iter_py("distrifuser_tpu"):
        findings.extend(scan_module(ctx.tree(rel), rel))
    return findings

"""Typed-error discipline: the serve layer never raises bare
RuntimeError/Exception.

Every failure a request can see is routed by TYPE (serve/errors.py):
`RetryableError` drives the retry loop and breaker, `FatalError` fails
the request terminally, `DegradationInapplicableError` retracts a ladder
rung.  A bare ``raise RuntimeError(...)`` in a serve hot path is
invisible to all of that — the breaker can't count it, the ladder can't
react, and callers are reduced to string matching (exactly what the
typed hierarchy exists to kill).

Rule: inside ``distrifuser_tpu/serve/``, ``raise`` of a *generic*
exception (`RuntimeError`, `Exception`, `BaseException`, `StandardError`)
is a finding.  Validation raises (`ValueError`/`TypeError`/`KeyError`/
`AssertionError`/`NotImplementedError`) stay legal everywhere — config
`__post_init__` and argument checking are not dispatch-relevant — and
typed subclasses are by definition not flagged (the AST sees the
subclass name at the raise site).  Deliberate escapes (e.g. a contract
violation that must BYPASS the typed retry routing) get their own named
subclass instead: `errors.ExecutorContractError` exists for exactly
that, staying outside the ServeError hierarchy on purpose.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..core import CheckContext, Finding, enclosing_qualname

NAME = "typed-raises"
DESCRIPTION = ("no bare RuntimeError/Exception raises in serve/* — the "
               "breaker/ladder must see typed outcomes")

GENERIC_EXCEPTIONS = frozenset({
    "RuntimeError", "Exception", "BaseException", "StandardError",
})

SERVE_PREFIX = "distrifuser_tpu/serve/"

#: modules whose ENTIRE raise surface must be one named type: every
#: rejection path in the AOT store must raise `AotCacheRejectedError`
#: (typed, never bare) so the load path's fallback-to-compile contract
#: — catch the one type, count a reject, delete the entry — can never
#: miss a rejection some other exception class would smuggle past it.
#: Bare re-raises (``raise`` with no expression) stay legal.
SINGLE_TYPE_MODULES: Dict[str, str] = {
    "distrifuser_tpu/serve/aotcache.py": "AotCacheRejectedError",
}


def scan_module(tree: ast.Module, relpath: str) -> List[Finding]:
    findings: List[Finding] = []
    counts: Dict[Tuple[str, str], int] = {}
    stack: List[ast.AST] = []
    required = SINGLE_TYPE_MODULES.get(relpath)

    def visit(node: ast.AST):
        is_scope = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))
        if is_scope:
            stack.append(node)
        if isinstance(node, ast.Raise) and node.exc is not None:
            name = None
            exc = node.exc
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if required is not None and name != required:
                scope = enclosing_qualname(stack)
                idx = counts.get((scope, name or "?"), 0)
                counts[(scope, name or "?")] = idx + 1
                findings.append(Finding(
                    checker=NAME, path=relpath, line=node.lineno,
                    message=(
                        f"`raise {name or '<expr>'}` in {scope} — every "
                        f"rejection path in {relpath} must raise "
                        f"{required} so the fallback-to-compile wrapper "
                        "(catch one type, count, delete the entry) can "
                        "never miss it"),
                    identity=f"single-type:{scope}:{name}:{idx}",
                ))
            elif name in GENERIC_EXCEPTIONS:
                scope = enclosing_qualname(stack)
                idx = counts.get((scope, name), 0)
                counts[(scope, name)] = idx + 1
                findings.append(Finding(
                    checker=NAME, path=relpath, line=node.lineno,
                    message=(
                        f"bare `raise {name}` in {scope} — serve "
                        "failures must be typed (serve/errors.py) so the "
                        "breaker/ladder/fleet routing sees them; raise a "
                        "ServeError subclass, or a named subclass like "
                        "ExecutorContractError when the point is to "
                        "bypass typed routing"),
                    identity=f"{scope}:{name}:{idx}",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_scope:
            stack.pop()

    visit(tree)
    return findings


def run(ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel in ctx.iter_py(SERVE_PREFIX.rstrip("/")):
        findings.extend(scan_module(ctx.tree(rel), rel))
    return findings

"""``python -m distrifuser_tpu.analysis`` — the one lint entry point.

Exit codes:
  0  clean (or only suppressed findings; non-strict tolerates stale
     baseline entries with a warning)
  1  non-baselined findings, stale baseline entries (--strict), or a
     malformed baseline
  2  usage errors

The jaxpr overlap gate needs the fake 8-device CPU mesh, so this module
pins JAX_PLATFORMS=cpu and the host-device-count flag BEFORE anything
imports jax — same bootstrap as tests/conftest.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _ensure_fake_devices() -> None:
    # ``python -m distrifuser_tpu.analysis`` imports the parent package
    # (and therefore jax) before this module runs, but XLA reads these
    # only at BACKEND initialization — the first jax.devices() call —
    # so setting them here still works as long as no checker (or caller)
    # touched a device yet.  overlap_gate verifies the count and emits a
    # finding if a pre-initialized backend got in first.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _repo_root() -> str:
    # the directory CONTAINING the distrifuser_tpu package
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_baseline_path(root: str) -> str:
    return os.path.join(root, "distrifuser_tpu", "analysis",
                        "baseline.txt")


def main(argv=None) -> int:
    _ensure_fake_devices()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from . import registry
    from .core import Baseline, BaselineError, CheckContext, \
        apply_baseline, render_baseline

    parser = argparse.ArgumentParser(
        prog="python -m distrifuser_tpu.analysis",
        description="distrilint: machine-check the repo's cross-cutting "
                    "invariants (see docs/ANALYSIS.md)")
    parser.add_argument("--strict", action="store_true",
                        help="fail on ANY non-baselined finding and on "
                        "stale baseline entries (the CI gate mode)")
    parser.add_argument("--json", metavar="PATH",
                        help="write the findings report as JSON")
    parser.add_argument("--baseline", metavar="PATH",
                        help="baseline file (default: "
                        "distrifuser_tpu/analysis/baseline.txt)")
    parser.add_argument("--checker", action="append", default=None,
                        metavar="NAME",
                        help="run only this checker (repeatable)")
    parser.add_argument("--root", default=None,
                        help="repo root to analyze (default: the "
                        "checkout this package lives in).  Must be the "
                        "SAME checkout as the importable package: the "
                        "compile-identity/route-tables/jaxpr-overlap "
                        "checkers read the imported modules, not --root")
    parser.add_argument("--list", action="store_true",
                        help="list checkers and exit")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings as the "
                        "baseline (new entries get an UNREVIEWED "
                        "placeholder reason the validator rejects — "
                        "replace each with a real justification)")
    args = parser.parse_args(argv)

    if args.list:
        for c in registry.all_checkers():
            print(f"{c.NAME:26s} {c.DESCRIPTION}")
        return 0

    root = os.path.abspath(args.root) if args.root else _repo_root()
    if os.path.realpath(root) != os.path.realpath(_repo_root()):
        # import-based checkers read the sys.path package; mixing trees
        # would let an AST-side removal pass against import-side truth
        print(f"--root {root} is not the importable checkout "
              f"({_repo_root()}): import-based checkers would read the "
              "wrong tree — run the target checkout's own entry point",
              file=sys.stderr)
        return 2
    ctx = CheckContext(root)
    baseline_path = args.baseline or default_baseline_path(root)

    results = registry.run_checkers(ctx, args.checker)
    findings = [f for fs in results.values() for f in fs]

    if args.write_baseline:
        try:
            previous = Baseline.load(baseline_path)
        except BaselineError:
            previous = Baseline(entries=(), path=baseline_path)
        header = ("# distrilint baseline — reviewed suppressions "
                  "(docs/ANALYSIS.md).\n"
                  "# Every entry needs a '# provenance:' reason line; "
                  "stale entries fail --strict.\n")
        with open(baseline_path, "w") as f:
            f.write(render_baseline(findings, previous, header=header))
        print(f"wrote {len(findings)} entr{'y' if len(findings) == 1 else 'ies'} "
              f"to {baseline_path}")
        return 0

    try:
        baseline = Baseline.load(baseline_path)
    except BaselineError as exc:
        print(f"BASELINE INVALID: {exc}", file=sys.stderr)
        return 1
    result = apply_baseline(findings, baseline,
                            active_checkers=list(results))

    for f in sorted(result.new, key=lambda f: (f.path, f.line)):
        print(f.render(), file=sys.stderr)
    for e in result.stale:
        line = (f"STALE BASELINE ENTRY {e.fingerprint} ({e.checker} "
                f"{e.path}): no checker emits this fingerprint any more "
                f"— remove it from {baseline_path}")
        print(line, file=sys.stderr)

    counts = {name: len(fs) for name, fs in results.items()}
    summary = {
        "schema": 1,
        "new": len(result.new),
        "suppressed": len(result.suppressed),
        "stale_baseline": len(result.stale),
        "baseline_size": len(baseline.entries),
        "by_checker": counts,
    }
    if args.json:
        report = dict(summary)
        report["findings"] = [f.to_json() for f in result.new]
        report["suppressed_findings"] = [
            {**f.to_json(), "provenance": e.reason}
            for f, e in result.suppressed
        ]
        report["stale_entries"] = [
            {"fingerprint": e.fingerprint, "checker": e.checker,
             "path": e.path} for e in result.stale
        ]
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)

    errors = [f for f in result.new if f.severity == "error"]
    failed = bool(errors) or (args.strict
                              and (result.new or result.stale))
    status = "FAIL" if failed else "ok"
    print(f"distrilint {status}: {len(result.new)} new, "
          f"{len(result.suppressed)} suppressed, "
          f"{len(result.stale)} stale baseline entries "
          f"({sum(counts.values())} raw across {len(counts)} checkers)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

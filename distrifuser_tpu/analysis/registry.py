"""Checker registry: the one list CI and tests run.

Ordering is cheap-first so a syntax-level failure surfaces before the
trace-based gate spends seconds building the tiny model.  Adding a
checker = adding a module under analysis/checkers/ with ``NAME``,
``DESCRIPTION``, ``run(ctx)`` and listing it here (docs/ANALYSIS.md
walks through it).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .core import CheckContext, Finding


def all_checkers() -> List[object]:
    from .checkers import (
        collective_containment,
        compile_identity,
        lock_discipline,
        overlap_gate,
        route_tables,
        sync_containment,
        typed_raises,
    )

    return [
        typed_raises,
        collective_containment,
        sync_containment,
        lock_discipline,
        compile_identity,
        route_tables,
        overlap_gate,
    ]


def get_checker(name: str):
    for c in all_checkers():
        if c.NAME == name:
            return c
    raise KeyError(
        f"unknown checker {name!r}; have "
        f"{[c.NAME for c in all_checkers()]}")


def run_checkers(ctx: CheckContext,
                 names: Optional[Sequence[str]] = None
                 ) -> Dict[str, List[Finding]]:
    """Run the (selected) checkers; a checker CRASH becomes an error
    finding rather than aborting the run — a broken gate must fail
    loudly, not skip silently."""
    checkers = (all_checkers() if not names
                else [get_checker(n) for n in names])
    results: Dict[str, List[Finding]] = {}
    for checker in checkers:
        try:
            results[checker.NAME] = list(checker.run(ctx))
        except Exception as exc:  # noqa: BLE001 — surfaced as a finding
            results[checker.NAME] = [Finding(
                checker=checker.NAME, path="distrifuser_tpu/analysis",
                line=0,
                message=(f"checker crashed: {type(exc).__name__}: {exc} "
                         "— a crashed gate fails the run, it never "
                         "skips"),
                identity="checker-crash",
            )]
    return results

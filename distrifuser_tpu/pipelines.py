"""User-facing pipelines: DistriSDXLPipeline and DistriSDPipeline.

API parity with the reference (/root/reference/distrifuser/pipelines.py):
``from_pretrained(distri_config, pretrained_model_name_or_path, ...)`` then
``pipeline(prompt=..., seed=...)`` returning an object with ``.images``.
Differences are the TPU-native ones:

* The reference wraps a diffusers pipeline and swaps the UNet
  (pipelines.py:26-42); here the whole stack (text encoders, UNet, VAE,
  scheduler, denoise loop) is native JAX, and the denoise loop is one
  compiled program (parallel/runner.py) instead of CUDA-graph replay.
* ``prepare()`` (pipelines.py:60-165: record passes, buffer allocation,
  graph capture) reduces to ahead-of-time compilation of the loop — state
  buffers are created *by* the first traced step.
* Weights come from a local HuggingFace snapshot directory (safetensors),
  converted once via models/weights.py; ``from_params`` builds a pipeline
  from in-memory pytrees (tests, random weights).

Height/width are fixed at DistriConfig time exactly like the reference
(pipelines.py:47-55 forbids per-call height/width); guidance_scale is forced
to 1 when CFG is disabled (pipelines.py:52-58 — with its double-negation bug
fixed, SURVEY.md §2.6).
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .models import clip as clip_mod
from .models import unet as unet_mod
from .models import vae as vae_mod
from .models.weights import (
    convert_clip_state_dict,
    convert_unet_state_dict,
    convert_vae_state_dict,
    load_sharded_safetensors,
    params_nbytes,
    quantize_params,
)
from .parallel.runner import make_runner
from .schedulers import BaseScheduler, FlowMatchEulerScheduler, get_scheduler
from .utils.config import DistriConfig


class SimpleTokenizer:
    """Deterministic hash fallback tokenizer.

    Real generation quality needs the CLIP BPE vocab (pass a HF tokenizer or
    a snapshot dir to from_pretrained); this fallback keeps every pipeline
    path runnable — tests, benchmarks, random-weight smoke runs — on a box
    with no vocab files.
    """

    model_max_length = 77

    def __init__(self, vocab_size: int = 49408, eos: int = 49407, bos: int = 49406):
        self.vocab_size = vocab_size
        self.eos = eos
        self.bos = bos

    def __call__(self, texts: List[str], max_length: int = 77):
        import zlib

        ids = np.full((len(texts), max_length), self.eos, np.int64)
        for i, t in enumerate(texts):
            # crc32, not hash(): process-independent, so multi-host pods and
            # repeated runs tokenize identically
            toks = [self.bos] + [
                zlib.crc32(w.encode()) % (self.vocab_size - 2)
                for w in t.lower().split()
            ][: max_length - 2]
            toks.append(self.eos)
            ids[i, : len(toks)] = toks
        return ids


def _hf_tokenizer(path: str):
    from transformers import CLIPTokenizer

    return CLIPTokenizer.from_pretrained(path)


def _tokenizer_or_fallback(path: str):
    """Native BPE tokenizer, else transformers, else the hash tokenizer with
    a LOUD warning.

    The primary is the in-repo native engine (native/bpe.py + clip_bpe.cc),
    which reads the snapshot's vocab.json/merges.txt directly — id-level
    parity with transformers is pinned by tests/test_native_tokenizer.py.
    The last-resort fallback keeps weightless smoke tests running, but on a
    real snapshot a broken tokenizer dir would silently ruin every generated
    image — so the degradation must never be silent."""
    try:
        from .native.bpe import NativeCLIPTokenizer

        return NativeCLIPTokenizer(path)
    except Exception:
        pass  # fall through to transformers (missing files error below)
    try:
        return _hf_tokenizer(path)
    except Exception as e:
        print(
            f"WARNING: failed to load CLIP tokenizer from {path!r} "
            f"({type(e).__name__}: {e}); falling back to the hash-based "
            "SimpleTokenizer. Generated images will NOT match real-prompt "
            "outputs.",
            file=sys.stderr,
            flush=True,
        )
        return SimpleTokenizer()


def _config_from_snapshot(root: str, subdir: str, loader, fallback):
    """Derive a model config from the snapshot's `<subdir>/config.json`
    (the way diffusers from_pretrained instantiates the architecture for the
    reference, /root/reference/distrifuser/pipelines.py:30-42); fall back to
    the named preset for bare weight dumps without config files."""
    path = os.path.join(root, subdir, "config.json")
    return loader(path) if os.path.exists(path) else fallback()


def _scheduler_from_snapshot(root: str, name: str | BaseScheduler) -> BaseScheduler:
    """Build the scheduler, honoring the snapshot's scheduler_config.json
    (prediction_type / betas / train steps) — this is how SD 2.x's
    v-prediction flows in, the way diffusers from_pretrained wires it for the
    reference."""
    if isinstance(name, BaseScheduler):
        return name
    kwargs = {}
    cfg_path = os.path.join(root, "scheduler", "scheduler_config.json")
    if os.path.exists(cfg_path):
        import json

        with open(cfg_path) as f:
            sc = json.load(f)
        for k in ("num_train_timesteps", "beta_start", "beta_end",
                  "beta_schedule", "steps_offset", "prediction_type"):
            if k in sc:
                kwargs[k] = sc[k]
    return get_scheduler(name, **kwargs)


def _prepare_init_latents(cfg, scheduler, encode_image, vae_config, image,
                          strength, num_inference_steps, n_prompts,
                          num_images_per_prompt, seed):
    """Shared img2img entry for every pipeline family: VAE-encode the init
    image (with the SD3-family shift re-centering — zero for the legacy
    families), noise it to the strength-offset schedule point, and return
    (latents, start_step) for the tail-only denoise.

    Canonical input range: uint8 [0, 255] or float [0, 1] (what
    output_type="np" produces) — no value sniffing beyond the dtype.
    Expansion is prompt-major, matching _batched_generate.  At least one
    denoise step always runs (strength*steps < 1 would otherwise ask for
    a zero-length schedule)."""
    assert 0.0 < strength <= 1.0, strength
    init_timestep = min(max(int(num_inference_steps * strength), 1),
                        num_inference_steps)
    start_step = num_inference_steps - init_timestep
    arr = np.asarray(image)
    arr = (arr.astype(np.float32) / 255.0 if arr.dtype == np.uint8
           else arr.astype(np.float32))
    if arr.ndim == 3:
        arr = arr[None]
    if arr.min() < 0.0 or arr.max() > 1.0:
        raise ValueError(
            "init image must be uint8 [0,255] or float [0,1] "
            f"(got range [{arr.min():.3f}, {arr.max():.3f}])"
        )
    arr = arr * 2.0 - 1.0  # VAE input range [-1,1]
    n_img = arr.shape[0]
    assert n_img in (1, n_prompts), (
        f"{n_img} init images for {n_prompts} prompts"
    )
    init = (
        encode_image(jnp.asarray(arr)) - vae_config.shift_factor
    ) * vae_config.scaling_factor
    assert init.shape[1:3] == (cfg.latent_height, cfg.latent_width), (
        f"init image encodes to {init.shape[1:3]}, config wants "
        f"{(cfg.latent_height, cfg.latent_width)}"
    )
    if n_img == 1 and n_prompts > 1:
        init = jnp.tile(init, (n_prompts, 1, 1, 1))
    init = jnp.repeat(init, num_images_per_prompt, axis=0)
    noise = jax.random.normal(jax.random.PRNGKey(seed), init.shape,
                              jnp.float32)
    return scheduler.add_noise(init, noise, start_step), start_step


def _check_scheduler_family(scheduler: BaseScheduler, *, flow: bool,
                            family: str) -> None:
    """Reject scheduler/model-family mismatches LOUDLY at construction.

    A rectified-flow sampler integrates the model output as a velocity
    over flow sigmas; the diffusion samplers integrate it as
    epsilon/v over beta schedules.  Crossing them runs without error and
    produces garbage images — the one failure mode a user cannot debug
    from the output alone, so every pipeline constructor calls this.
    """
    is_flow = isinstance(scheduler, FlowMatchEulerScheduler)
    if flow and not is_flow:
        raise ValueError(
            f"{family} is a rectified-flow model family: the scheduler "
            "must be FlowMatchEulerScheduler ('flow-euler'), got "
            f"{type(scheduler).__name__}"
        )
    if not flow and is_flow:
        raise ValueError(
            f"'flow-euler' on {family}: this family predicts epsilon/v "
            "over a beta schedule, not a rectified-flow velocity — use "
            "ddim / euler / dpm-solver ('flow-euler' is for "
            "DistriSD3Pipeline)"
        )


def _tokenize(tok, texts: List[str]) -> np.ndarray:
    if isinstance(tok, SimpleTokenizer):
        return tok(texts)
    out = tok(
        texts, padding="max_length", max_length=tok.model_max_length,
        truncation=True, return_tensors="np",
    )
    return np.asarray(out["input_ids"])


@dataclasses.dataclass
class PipelineOutput:
    images: List[Any]
    # Set when any tokenizer degraded to the hash-based SimpleTokenizer
    # (weightless smoke/bench runs): the images are NOT real-prompt outputs
    # and must never be quality-judged.  Carried on the artifact itself —
    # a stderr warning alone scrolls away (VERDICT r4 weak #5).
    weightless_tokenizer: bool = False
    warning: Optional[str] = None


_WEIGHTLESS_WARNING = (
    "generated with the hash-based SimpleTokenizer fallback (no CLIP/T5 "
    "vocab files were loadable): latency characteristics are valid, image "
    "content is NOT comparable to real-prompt outputs"
)


@dataclasses.dataclass
class PipelineStages:
    """Stage programs for one prepared (pipeline, steps) pair — the split
    request path the staged serving executor (serve/staging.py) pipelines
    across micro-batches:

    * ``encode(prompts, negs) -> embeddings`` — tokenize + text-encode one
      compiled-batch-width chunk; the returned pytree is family-opaque
      (UNet: (embeds, added_cond); DiT: (embeds, caption_mask); MMDiT:
      (embeds, pooled)) and is exactly what ``denoise`` consumes;
    * ``denoise(embeddings, latents, guidance_scale) -> latent`` — the
      compiled denoise-loop program (the mesh bottleneck resource);
    * ``decode(latent) -> np images`` — chunked VAE decode plus the
      device->host conversion, float RGB [N,H,W,3] in [0,1].

    Every callable is the SAME code the monolithic ``__call__`` path runs
    (``_stage_encode`` / ``_denoise_chunk`` / ``_decode_to_np``), so staged
    and monolithic execution produce bit-identical images for identical
    (prompt, seed, steps) — pipelining changes WHEN stages run, never what
    they compute.  ``steps`` and the guidance mode are baked in: a stage
    set serves exactly one compiled executor identity (serve ExecKey).
    """

    steps: int
    batch_size: int
    encode: Any
    denoise: Any
    decode: Any
    init_noise_sigma: float


def _mk_output(images, tokenizers) -> PipelineOutput:
    weightless = any(isinstance(t, SimpleTokenizer) for t in tokenizers)
    return PipelineOutput(
        images=images,
        weightless_tokenizer=weightless,
        warning=_WEIGHTLESS_WARNING if weightless else None,
    )


def _build_decoder(cfg: DistriConfig, vae_config: vae_mod.VAEConfig):
    """(jitted decode fn, parallel?) for the config's geometry: sequence-
    parallel over sp when the latent divides, row-tiled above 2048px, plain
    whole-latent otherwise (shared by the UNet and DiT pipelines)."""
    parallel = (
        cfg.is_sp and cfg.vae_sp
        and cfg.latent_height % cfg.n_device_per_batch == 0
    )
    if parallel:
        # Sequence-parallel decode over the same sp axis as the denoiser
        # (beyond the reference, which decodes replicated on every rank):
        # exact, n x faster, 1/n activation footprint.
        from .utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from .parallel.collectives import gather_rows
        from .utils.config import DP_AXIS, SP_AXIS

        n = cfg.n_device_per_batch

        def _dec(p, l):
            return shard_map(
                lambda p_, l_: gather_rows(
                    vae_mod.decode_sp(p_, vae_config, l_, n)
                ),
                mesh=cfg.mesh,
                in_specs=(P(), P(DP_AXIS, SP_AXIS)),
                out_specs=P(DP_AXIS),
                check_vma=False,
            )(p, l)

        return jax.jit(_dec), True
    # Above 2048px the whole-latent decode's activations dominate HBM on one
    # chip; switch to the row-tiled decoder (models/vae.py).
    tile = 64 if cfg.latent_height > 128 else 0
    return jax.jit(
        lambda p, l: vae_mod.decode(p, vae_config, l, tile=tile)
    ), False


def _normalize_prompts(prompt, negative_prompt):
    """(prompts, negs) lists from the str-or-list call surface — one code
    path for every pipeline family's __call__ and the serve batcher."""
    prompts = [prompt] if isinstance(prompt, str) else list(prompt)
    negs = (
        [negative_prompt] * len(prompts)
        if isinstance(negative_prompt, str)
        else list(negative_prompt)
    )
    assert len(negs) == len(prompts), (
        f"{len(prompts)} prompts but {len(negs)} negative prompts"
    )
    return prompts, negs


def _wrap_chunk_callback(callback, n_real):
    """diffusers legacy signature callback(step, timestep, latents) with the
    padded tail rows stripped before the user sees them.  With more images
    than batch_size the callback fires per chunk (step indices restart per
    chunk)."""
    if callback is None:
        return None
    return lambda i, t, x: callback(i, t, x[:n_real])


def _pad_rows(arr, pad):
    """Pad a batch-major array to the compiled batch width by repeating its
    last row ``pad`` times (callers drop the padded outputs)."""
    if not pad:
        return arr
    return jnp.concatenate([arr, jnp.repeat(arr[-1:], pad, axis=0)])


def _pad_chunks(total: int, bs: int):
    """(start, stop, pad) triples covering [0, total) in fixed ``bs``-sized
    chunks — the ONE chunking convention shared by the denoise and decode
    paths (and, through generate_batch, the serve batcher): tail chunk
    padded, padded rows dropped by the caller."""
    for i in range(0, total, bs):
        n = min(bs, total - i)
        yield i, i + n, bs - n


def _batched_generate(cfg, scheduler, prompts, negs, num_images_per_prompt,
                      seed, latents, in_channels, run_chunk):
    """Arbitrary prompt counts over the fixed-batch jitted denoise loop.

    The reference passes diffusers' batching straight through
    (pipelines.py:47-58); here the compiled loop has a static batch of
    ``cfg.batch_size``, so each prompt is repeated ``num_images_per_prompt``
    times (diffusers order: a prompt's images are adjacent) and the expanded
    list runs in batch_size chunks — the tail chunk padded by repeating its
    last entry, the padded outputs dropped.  Initial noise is drawn ONCE for
    the whole expanded batch, so results do not depend on the chunking.
    """
    assert prompts, "need at least one prompt"
    assert num_images_per_prompt >= 1, num_images_per_prompt
    prompts = [p for p in prompts for _ in range(num_images_per_prompt)]
    negs = [n for n in negs for _ in range(num_images_per_prompt)]
    total = len(prompts)
    bs = cfg.batch_size
    lat_shape = (total, cfg.latent_height, cfg.latent_width, in_channels)
    if latents is None:
        latents = jax.random.normal(jax.random.PRNGKey(seed), lat_shape,
                                    jnp.float32)
        latents = latents * scheduler.init_noise_sigma
    else:
        latents = jnp.asarray(latents, jnp.float32)
        assert latents.shape == lat_shape, (latents.shape, lat_shape)
    outs = []
    for i, stop, pad in _pad_chunks(total, bs):
        cp, cn = prompts[i:stop], negs[i:stop]
        cl = latents[i:stop]
        if pad:
            cp = cp + [cp[-1]] * pad
            cn = cn + [cn[-1]] * pad
            cl = _pad_rows(cl, pad)
        out = run_chunk(cp, cn, cl, bs - pad)
        outs.append(out[:bs - pad] if pad else out)
    return jnp.concatenate(outs, axis=0)


def _decode_chunked(decode, vae_params, latent, bs, scaling, shift=0.0):
    """VAE-decode in fixed batch_size chunks (pad the tail, drop the padded
    rows): the jitted decoder traces once per shape, and the sequence-
    parallel decode's shard_map needs its dp-divisible batch — an arbitrary
    total from _batched_generate must not reach it directly.  ``shift`` is
    the SD3-family latent re-centering (VAEConfig.shift_factor)."""
    outs = []
    for i, stop, pad in _pad_chunks(latent.shape[0], bs):
        cl = _pad_rows(latent[i:stop], pad)
        img = decode(vae_params, cl / scaling + shift)
        outs.append(img[:bs - pad] if pad else img)
    return jnp.concatenate(outs, axis=0)


def _quantize_aux(cfg, vae_params, text_encoders=(), t5_params=None):
    """Load-time quantization of the AUXILIARY models (VAE, CLIP text
    encoders, T5) under the ``weight_quant_aux`` sub-knob — one place for
    the policy every pipeline family shares, so a constructor can't
    quantize one component under the wrong knob or skip one.  The DENOISER
    stays with its caller: its ``weight_quant`` step has per-family
    ordering constraints (PixArt folds the size conditioning first).
    Returns ``(vae_params, [(cfg, params), ...], t5_params-or-None)``.
    """
    q = lambda p: quantize_params(p, cfg.weight_quant_aux)  # noqa: E731
    return (
        q(vae_params),
        [(tc, q(tp)) for tc, tp in text_encoders],
        None if t5_params is None else q(t5_params),
    )


class _GenerationMixin:
    """Machinery shared by EVERY pipeline family (UNet, DiT, MMDiT): the
    output packaging tail of __call__, the staged-execution surface
    (`prepare_stages`), and the serve layer's pre-bucketed batched entry.
    Requires ``distri_config``, ``vae_config``, ``vae_params``, and
    ``_decode`` on the instance, plus the family hooks ``_stage_encode``
    (prompts, negs -> embeddings pytree) and ``_denoise_chunk``
    (embeddings, latents, ... -> latent)."""

    # SD3-family VAE latent re-centering (VAEConfig.shift_factor); zero for
    # the legacy families.  Instance attribute on DistriSD3Pipeline.
    _vae_shift: float = 0.0

    # Per-step denoise timeline (utils/trace.py StepTimeline), attached
    # via `attach_step_timeline`: None (the default) adds nothing to the
    # dispatch path.
    step_timeline = None

    def attach_step_timeline(self, timeline):
        """Record every generation's per-denoise-step wall timings
        (tagged warmup/full/shallow by the step-cache cadence) and LIVE
        comm-byte counters into ``timeline`` (`utils.trace.StepTimeline`).

        The live byte counter adds each *executed* step's per-phase wire
        bytes from the runner's byte model as the loop advances, so it
        equals the closed-form `comm_plan` exactly iff the loop really
        ran the phase sequence the plan predicts — the reconciliation
        tests/test_observability.py pins.  Timeline-carrying generations
        run the per-step callback dispatch path (host stepwise loop, or
        the fused io_callback program where the jaxlib supports it):
        per-step host visibility is that path's purpose — use for
        profiling, not steady-state serving."""
        self.step_timeline = timeline
        return timeline

    def _timeline_callback(self, num_inference_steps: int, callback,
                           start_step: int = 0, end_step=None):
        """Compose the user's per-step callback with the attached
        timeline's recorder (no-op passthrough when none is attached).
        Phase tags use the SAME arithmetic as the denoise loops and
        `stepcache.phase_step_counts`: steps [start, start + n_sync) are
        warmup, the rest follow the shallow-first cadence."""
        tl = self.step_timeline
        if tl is None:
            return callback
        from .parallel.stepcache import is_shallow_at

        cfg = self.distri_config
        steps_end = (num_inference_steps if end_step is None
                     else min(end_step, num_inference_steps))
        n_sync = min(cfg.warmup_steps + 1, steps_end - start_step)
        sc = cfg.step_cache_enabled
        interval = cfg.step_cache_interval

        def phase_of(i: int) -> str:
            if i < start_step + n_sync:
                return "warmup"
            if sc and is_shallow_at(i, start_step + n_sync, interval):
                return "shallow"
            return "full"

        try:
            plan = self.comm_plan(num_inference_steps)
            bytes_per_step = plan["bytes_per_step"]
        except (ValueError, AttributeError):
            # runner without a byte model (tensor parallelism, custom):
            # the timeline still records timings, bytes stay untracked
            bytes_per_step = None
        tl.begin_run(
            steps_end - start_step, phase_of, bytes_per_step=bytes_per_step,
            meta={"steps": num_inference_steps, "start_step": start_step,
                  "comm_compress": cfg.comm_compress},
        )

        def cb(i, t, x):
            tl.on_step(int(i))
            if callback is not None:
                callback(i, t, x)

        return cb

    def _timeline_end(self) -> None:
        if self.step_timeline is not None:
            self.step_timeline.end_run()

    def step_cache_plan(self, num_inference_steps: int) -> dict:
        """How the temporal step-cache cadence (docs/PERF.md) plays out over
        a run of ``num_inference_steps``: the serve executors read this for
        the shallow-step-share metrics, and it doubles as a user-facing
        what-will-actually-run probe."""
        from .parallel.stepcache import shallow_step_count

        cfg = self.distri_config
        shallow = (
            shallow_step_count(num_inference_steps, cfg.warmup_steps,
                               cfg.step_cache_interval)
            if cfg.step_cache_enabled else 0
        )
        return {
            "enabled": cfg.step_cache_enabled,
            "interval": cfg.step_cache_interval,
            "depth": cfg.step_cache_depth,
            "total_steps": num_inference_steps,
            "shallow_steps": shallow,
        }

    def comm_plan(self, num_inference_steps: int) -> dict:
        """What one generation will put on the wire: per-phase bytes per
        step (from the runner's comm report, compression-aware) times the
        phase step counts — the byte-level companion of step_cache_plan.
        ``total_bytes`` is per device, gathered-buffer convention; DiT/MMDiT
        shallow steps are scaled from the closed-form element ratio."""
        from .parallel.stepcache import phase_step_counts

        cfg = self.distri_config
        counts = phase_step_counts(
            num_inference_steps, cfg.warmup_steps,
            cfg.step_cache_interval if cfg.step_cache_enabled else 1,
        )
        per_step = {}
        runner = self.runner
        if hasattr(runner, "comm_volume_report"):  # UNet families
            rep = runner.comm_volume_report(per_phase=True)
            per_step = {ph: sum(kinds.values())
                        for ph, kinds in rep.get("bytes", {}).items()}
            if per_step and "stale" not in per_step:  # one-phase configs
                per_step["stale"] = per_step.get("sync", 0)
        elif hasattr(runner, "comm_report"):  # DiT/MMDiT closed forms
            rep = runner.comm_report()
            if "per_step_collective_bytes" in rep:
                per_step = {
                    "sync": rep.get("sync_step_collective_bytes", 0),
                    "stale": rep["per_step_collective_bytes"],
                }
                sc = rep.get("step_cache")
                elems = rep.get("per_step_collective_elems", 0)
                if sc and elems:
                    per_step["shallow"] = (
                        per_step["stale"]
                        * sc["shallow_per_step_collective_elems"] // elems
                    )
        if not per_step:
            # Every runner family now carries a byte model — the UNet
            # per-phase trace, the DiT/MMDiT closed forms (zero for
            # non-sp groups), and PipeFusionRunner.comm_report's per-hop
            # arithmetic.  A runner reaching this branch has NO byte
            # model (tensor parallelism, a custom runner): raise rather
            # than hand back a confident-looking empty plan a capacity
            # model would happily multiply by zero.
            raise ValueError(
                f"{type(runner).__name__} has no byte-modeled comm "
                "report (comm_volume_report bytes / comm_report "
                "per_step_collective_bytes): comm_plan cannot price this "
                "runner's traffic — add the closed form instead of "
                "guessing"
            )
        total = sum(per_step.get(ph, 0) * n for ph, n in counts.items())
        return {
            "comm_compress": cfg.comm_compress,
            # PCPP key (docs/PERF.md "Partial refresh"): the per-step
            # rows above are already fraction-aware — stale/shallow
            # refresh bytes shrink to fraction x full, sync stays whole —
            # so two plans differing only in refresh_fraction give the
            # byte-reduction ratio in closed form
            "refresh_fraction": cfg.refresh_fraction,
            "steps": counts,
            "bytes_per_step": per_step,
            "total_bytes": int(total),
        }

    def set_weight_quant(self, mode: str) -> None:
        """Re-quantize the DENOISER's weights to ``mode`` post-construction
        (docs/PERF.md "Quantized weights").

        The quantize direction ("none" -> int8/fp8) is the serve ladder's
        ``weight_quant_on`` rung promoted to a pipeline policy hook
        (serve.executors.apply_key_policy calls it for ExecKeys that
        request quantization from a full-precision builder): quantizing the
        already-converted dense tree is the exact same operation load-time
        quantization performs.  Call before `prepare()` — the quantized
        tree is a different pytree structure, so anything already compiled
        is dropped and retraces.

        The reverse direction raises: a quantized tree's full-precision
        values are gone (dequantizing bakes the rounding in), so a
        "full-precision" program recovered this way would silently carry
        quantization error — builders wanting both precisions must build
        from the dense weights per key."""
        from .parallel.compress import validate_weight_mode

        cfg = self.distri_config
        validate_weight_mode(mode)
        if mode == cfg.weight_quant:
            return
        if cfg.parallelism == "tensor":
            # same guard as DistriConfig.__post_init__: the tensor runner
            # pre-shards its kernels eagerly, and quantizing the sharded
            # tree post-hoc would feed QuantizedTensor leaves into lax
            # paths that never densify them.  (PipeFusion is fine: its
            # runner holds the full stacked tree and shard_map slices
            # payload and scale alike at trace time.)
            raise ValueError(
                f"weight_quant does not apply to parallelism="
                f"{cfg.parallelism!r} (pre-sharded kernels) — the ladder's "
                "weight_quant_on rung cannot degrade this pipeline"
            )
        if cfg.weight_quant != "none":
            raise ValueError(
                f"cannot switch weight_quant {cfg.weight_quant!r} -> "
                f"{mode!r}: the full-precision kernels are gone — rebuild "
                "the pipeline from the dense weights instead"
            )
        self.runner.params = quantize_params(
            self.runner.params, mode, compute=cfg.quant_compute)
        cfg.weight_quant = mode
        compiled = getattr(self.runner, "_compiled", None)
        if compiled:
            compiled.clear()

    def set_quant_compute(self, policy: str) -> None:
        """Re-tag the denoiser's quantized kernels with an EXECUTION
        policy (DistriConfig.quant_compute; docs/PERF.md "Quantized
        compute & GEMM routing").  Unlike set_weight_quant this is
        payload-free — no values change, only which matmul path the next
        trace routes through (ops/gemm_routing.py) — so it is safe in
        both directions and the serve layer forces it per
        ExecKey.quant_compute.  Drops compiled programs: policy lives in
        the pytree aux data, so a policy change is a different traced
        program."""
        from .parallel.compress import validate_quant_compute
        from .models.weights import set_quant_compute

        cfg = self.distri_config
        validate_quant_compute(policy, cfg.weight_quant)
        if policy == cfg.quant_compute:
            return
        self.runner.params = set_quant_compute(self.runner.params, policy)
        cfg.quant_compute = policy
        compiled = getattr(self.runner, "_compiled", None)
        if compiled:
            compiled.clear()

    def weight_report(self) -> dict:
        """Per-component weight-HBM bytes (models/weights.params_nbytes:
        quantized kernels count payload + scales) plus the active modes —
        what the serve executors surface into ``metrics_snapshot()`` next
        to the PR-4 wire bytes."""
        cfg = self.distri_config
        parts = {
            "denoiser": params_nbytes(self.runner.params),
            "vae": params_nbytes(self.vae_params),
        }
        text = 0
        for _tc, tparams in getattr(self, "text_encoders", ()) or ():
            text += params_nbytes(tparams)
        t5 = getattr(self, "t5", None)
        if t5 is not None and t5[1] is not None:
            text += params_nbytes(t5[1])
        parts["text_encoders"] = text
        return {
            "weight_quant": cfg.weight_quant,
            "weight_quant_aux": cfg.weight_quant_aux,
            "quant_compute": cfg.quant_compute,
            "per_component_nbytes": parts,
            "total_bytes": sum(parts.values()),
        }

    def set_stepwise(self, enabled: bool = True) -> None:
        """Switch the denoise loop between the fused compiled scan and
        the host-driven stepwise loop (the reference's --no_cuda_graph
        path) — same numerics, per-step dispatch instead of one program.

        This is the compat-shim fallback (utils/compat.py routes
        callback-carrying generates stepwise on jaxlibs that abort on the
        fused io_callback program) promoted to a *policy*: the serve
        layer's degradation ladder (serve/resilience.py) calls it when
        the fused program fails to compile or OOMs, because the stepwise
        loop is a far smaller program to compile and hold.  Call before
        `prepare()`/generation; already-compiled fused programs stay
        cached and are simply not dispatched to while disabled.

        PipeFusion pipelines reject the switch LOUDLY: `PipeFusionRunner`
        has no host-driven stepwise loop (its per-patch micro-pipeline IS
        the program), and silently flipping the flag after construction
        would report a degradation that changes nothing."""
        if enabled and self.distri_config.parallelism == "pipefusion":
            raise ValueError(
                "stepwise fallback does not apply to the PipeFusion patch "
                "pipeline: PipeFusionRunner has no host-driven stepwise "
                "loop (parallel/pipefusion.py).  The serve ladder never "
                "picks RUNG_STEPWISE for pipefusion keys — it degrades "
                "them via the pipeline_off rung (rebuild as displaced "
                "patch parallelism, serve/resilience.py) instead"
            )
        self.distri_config.use_cuda_graph = not enabled

    def _decode_to_np(self, latent) -> np.ndarray:
        """latent -> float RGB [N,H,W,3] in [0,1]: the chunked VAE decode
        plus device->host conversion tail — ONE code path shared by
        `_finalize` (the monolithic __call__) and the staged executor's
        decode stage, so the two execution modes decode identically."""
        image = _decode_chunked(
            self._decode, self.vae_params, latent,
            self.distri_config.batch_size, self.vae_config.scaling_factor,
            self._vae_shift,
        )
        image = np.asarray(image, np.float32)
        return np.clip(image / 2 + 0.5, 0.0, 1.0)

    def prepare_stages(self, num_inference_steps: int) -> "PipelineStages":
        """Pre-build the request path as three separately-dispatchable
        stage programs (text-encode / denoise / VAE-decode) for a staged
        serving executor to overlap across micro-batches — batch k+1
        encodes and batch k-1 decodes in the shadow of batch k's denoise
        (serve/staging.py; docs/SERVING.md "Staged pipelining").

        Compiles the denoise loop ahead of time (the same `prepare()` the
        monolithic path uses) and fixes the scheduler's timestep table
        here, OFF the dispatch path — stage invocations never mutate
        shared scheduler state.  The returned callables are the exact
        functions `__call__` runs, so staged and monolithic execution are
        bit-identical (see `PipelineStages`)."""
        self.scheduler.set_timesteps(num_inference_steps)
        self.runner.prepare(num_inference_steps)
        steps = num_inference_steps
        # __call__ forces guidance_scale to 1 when CFG is off; the staged
        # denoise program must apply the same normalization for identity
        cfg_on = self.distri_config.do_classifier_free_guidance

        def denoise(enc, latents, guidance_scale):
            return self._denoise_chunk(
                enc, latents, guidance_scale if cfg_on else 1.0, steps)

        return PipelineStages(
            steps=steps,
            batch_size=self.distri_config.batch_size,
            encode=self._stage_encode,
            denoise=denoise,
            decode=self._decode_to_np,
            init_noise_sigma=float(self.scheduler.init_noise_sigma),
        )

    def _finalize(self, latent, output_type, tokenizers) -> "PipelineOutput":
        """latent -> PipelineOutput for 'latent' | 'np' | 'pil'."""
        if output_type == "latent":
            # one entry per image, matching the 'np'/'pil' contract
            return _mk_output(list(np.asarray(latent)), tokenizers)
        image = self._decode_to_np(latent)
        if output_type == "np":
            return _mk_output(list(image), tokenizers)
        from PIL import Image

        return _mk_output(
            [Image.fromarray((im * 255).round().astype(np.uint8))
             for im in image],
            tokenizers,
        )

    def generate_batch(self, prompts, negative_prompts=None,
                       **kwargs) -> "PipelineOutput":
        """Pre-bucketed batched entry (the serve micro-batcher's call path,
        distrifuser_tpu/serve): EXACTLY ``distri_config.batch_size`` prompts
        — the batch the compiled program was built for — so the call is one
        chunk with zero padding and can never retrace on batch shape.
        Delegates to __call__, so the one-shot and serving paths share one
        code path; ``kwargs`` are the __call__ surface (num_inference_steps,
        guidance_scale, seed, latents, output_type, ...)."""
        prompts = list(prompts)
        bs = self.distri_config.batch_size
        if len(prompts) != bs:
            raise ValueError(
                f"generate_batch is the pre-bucketed entry: expected exactly "
                f"batch_size={bs} prompts, got {len(prompts)} (pad upstream "
                "— serve.executors.PipelineExecutor does — or call the "
                "pipeline directly for arbitrary counts)"
            )
        if negative_prompts is None or isinstance(negative_prompts, str):
            negs = negative_prompts or ""  # __call__ broadcasts a str
        else:
            negs = list(negative_prompts)
            if len(negs) != bs:
                raise ValueError(
                    f"{len(negs)} negative prompts for {bs} prompts"
                )
        if kwargs.get("num_images_per_prompt", 1) != 1:
            raise ValueError(
                "generate_batch batches across requests; "
                "num_images_per_prompt must stay 1"
            )
        return self(prompt=prompts, negative_prompt=negs, **kwargs)


class _DistriPipelineBase(_GenerationMixin):
    """Shared machinery; subclasses define the text-encoding recipe."""

    def __init__(
        self,
        distri_config: DistriConfig,
        unet_config: unet_mod.UNetConfig,
        unet_params,
        vae_config: vae_mod.VAEConfig,
        vae_params,
        scheduler: BaseScheduler,
        tokenizers,
        text_encoders,  # list of (CLIPTextConfig, params)
    ):
        _check_scheduler_family(scheduler, flow=False,
                                family=type(self).__name__)
        self.distri_config = distri_config
        self.unet_config = unet_config
        self.vae_config = vae_config
        # load-time weight quantization (docs/PERF.md "Quantized weights"):
        # the denoiser under weight_quant, the aux models (text encoders +
        # VAE) under their own tolerance sub-knob — "none" is a no-op, so
        # the default config stays bit-identical
        unet_params = quantize_params(unet_params, distri_config.weight_quant,
                                      compute=distri_config.quant_compute)
        self.vae_params, self.text_encoders, _ = _quantize_aux(
            distri_config, vae_params, text_encoders)
        self.scheduler = scheduler
        self.tokenizers = tokenizers
        self.runner = make_runner(distri_config, unet_config, unet_params, scheduler)
        cfg = distri_config
        # public introspection: which decode path was installed
        self._decode, self.vae_decode_parallel = _build_decoder(cfg, vae_config)
        # jit one encoder forward per text-encoder config (re-encoding the
        # prompt every call would otherwise dispatch hundreds of eager ops)
        self._clip_jitted = [
            jax.jit(lambda prm, ids, _cfg=ccfg: clip_mod.clip_text_forward(prm, _cfg, ids))
            for ccfg, _ in self.text_encoders
        ]
        # jitted init-image encode for img2img, for the same reason as the
        # text encoders above (eager per-call dispatch otherwise)
        self._encode_image = jax.jit(
            lambda prm, x: vae_mod.encode(prm, vae_config, x)
        )
        if distri_config.verbose and distri_config.parallelism == "patch":
            # buffer-volume report at construction, like the reference's
            # create_buffer prints (utils.py:152-158)
            self.runner.comm_volume_report(batch_size=distri_config.batch_size)

    # -- reference API ---------------------------------------------------
    def set_progress_bar_config(self, **kwargs):  # parity no-op (rank gating)
        pass

    def prepare(self, num_inference_steps: int = 50, **kwargs) -> None:
        """Pre-build the denoise loop program(s) (the reference's
        record/capture phase, pipelines.py:60-165).  Delegates to the
        runner so the prepared program is exactly the one generate() will
        dispatch to (fused, or the hybrid stale-scan).  In per-step mode
        (use_cuda_graph=False) steps compile lazily on first use, like the
        reference's no-graph path."""
        self.runner.prepare(num_inference_steps)

    def __call__(
        self,
        prompt: str | List[str],
        negative_prompt: str | List[str] = "",
        num_inference_steps: int = 50,
        guidance_scale: float = 5.0,
        seed: int = 0,
        output_type: str = "pil",
        latents=None,
        num_images_per_prompt: int = 1,
        image=None,
        strength: float = 0.8,
        denoising_start: float = None,
        denoising_end: float = None,
        original_size=None,
        crops_coords_top_left=(0, 0),
        target_size=None,
        aesthetic_score: float = 6.0,
        negative_original_size=None,
        negative_crops_coords_top_left=None,
        negative_target_size=None,
        negative_aesthetic_score: float = 2.5,
        callback=None,
        **kwargs,
    ) -> PipelineOutput:
        cfg = self.distri_config
        if "height" in kwargs or "width" in kwargs:
            raise ValueError(
                "height and width are fixed in DistriConfig (reference "
                "pipelines.py:47-55)"
            )
        if not cfg.do_classifier_free_guidance:
            guidance_scale = 1.0
        prompts, negs = _normalize_prompts(prompt, negative_prompt)
        self.scheduler.set_timesteps(num_inference_steps)

        # base+refiner split (diffusers denoising_end / denoising_start
        # fractions, index-based here): the base stage stops at end_step and
        # hands its latent to a second pipeline (e.g. an SDXL refiner
        # checkpoint, which from_pretrained loads like any SDXL UNet) that
        # resumes at the same fraction.
        start_step = 0
        end_step = None
        if denoising_end is not None:
            assert 0.0 < denoising_end < 1.0, denoising_end
            # same index mapping as denoising_start below, so matched
            # fractions hand off without overlap or gap
            end_step = int(round(num_inference_steps * denoising_end))
            if end_step < 1:
                raise ValueError(
                    f"denoising_end={denoising_end} rounds to zero steps at "
                    f"num_inference_steps={num_inference_steps}"
                )
        if denoising_start is not None:
            assert 0.0 < denoising_start < 1.0, denoising_start
            assert image is None, (
                "denoising_start resumes mid-trajectory latents; use "
                "image+strength for img2img instead"
            )
            assert latents is not None, (
                "denoising_start requires the mid-trajectory latents from "
                "the previous stage"
            )
            start_step = int(round(num_inference_steps * denoising_start))

        if image is not None:
            # img2img (beyond the reference, which is text2img-only):
            # diffusers Img2Img timestep convention via the shared helper
            assert latents is None, "pass either image or latents, not both"
            latents, start_step = _prepare_init_latents(
                cfg, self.scheduler,
                lambda x: self._encode_image(self.vae_params, x),
                self.vae_config, image, strength, num_inference_steps,
                len(prompts), num_images_per_prompt, seed,
            )

        # SDXL micro-conditioning pass-through (diffusers kwargs the
        # reference forwards, pipelines.py:47-58); SD 1.x/2.x ignores it
        micro_cond = {
            "original_size": original_size,
            "crops_coords_top_left": crops_coords_top_left,
            "target_size": target_size,
            "aesthetic_score": aesthetic_score,
            "negative_original_size": negative_original_size,
            "negative_crops_coords_top_left": negative_crops_coords_top_left,
            "negative_target_size": negative_target_size,
            "negative_aesthetic_score": negative_aesthetic_score,
        }

        def run_chunk(cp, cn, cl, n_real):
            enc = self._encode(cp, cn, micro_cond)
            # timeline recording brackets the denoise loop only (encode
            # stays outside the per-step wall timings); one run per chunk
            cb = self._timeline_callback(
                num_inference_steps, _wrap_chunk_callback(callback, n_real),
                start_step=start_step, end_step=end_step,
            )
            try:
                return self._denoise_chunk(
                    enc, cl, guidance_scale, num_inference_steps,
                    start_step=start_step, end_step=end_step, callback=cb,
                )
            finally:
                self._timeline_end()

        # seeded noise for the whole expanded batch (diffusers passes a torch
        # Generator; the JAX analog is the integer seed); caller-supplied
        # ``latents`` must cover len(prompts) * num_images_per_prompt images
        latent = _batched_generate(
            cfg, self.scheduler, prompts, negs, num_images_per_prompt, seed,
            latents, self.unet_config.in_channels, run_chunk,
        )
        return self._finalize(latent, output_type, self.tokenizers)

    # -- helpers ----------------------------------------------------------
    def _clip(self, which: int, ids):
        _, cparams = self.text_encoders[which]
        return self._clip_jitted[which](cparams, np.asarray(ids))

    def _encode(self, prompts, negs, micro_cond=None):
        raise NotImplementedError

    # -- stage hooks (prepare_stages / __call__ share these) ---------------
    def _stage_encode(self, prompts, negs):
        """Encode-stage program: no micro-conditioning (the serve surface
        has none), which `_encode` resolves to the same defaults __call__
        passes — identical embeddings either way."""
        return self._encode(prompts, negs, None)

    def _denoise_chunk(self, enc, latents, guidance_scale,
                       num_inference_steps, *, start_step=0, end_step=None,
                       callback=None):
        embeds, added = enc
        return self.runner.generate(
            latents, embeds,
            guidance_scale=guidance_scale,
            num_inference_steps=num_inference_steps,
            added_cond=added,
            start_step=start_step,
            end_step=end_step,
            callback=callback,
        )

    # -- step-granular carry hooks (serve/stepbatch.py; see mixin doc) ----
    def step_carry_init(self, latents, num_inference_steps):
        return self.runner.stepwise_carry_init(latents, num_inference_steps)

    def _step_pin_enc(self, enc):
        """The dtype pinning runner.generate applies before its stepwise
        loop — identical inputs => identical per-step programs."""
        embeds, added = enc
        embeds = jnp.asarray(embeds, self.distri_config.dtype)
        if added is not None and "text_embeds" in added:
            added = dict(added)
            added["text_embeds"] = jnp.asarray(added["text_embeds"],
                                               self.distri_config.dtype)
        return embeds, added

    def step_carry_step(self, carry, i, enc, guidance_scale,
                        num_inference_steps):
        embeds, added = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_step(
            carry, i, embeds, added,
            jnp.asarray(guidance_scale, jnp.float32), num_inference_steps)

    def step_carry_latent(self, carry):
        return self.runner.stepwise_carry_latent(carry)

    # -- packed cohort hooks (serve/executors.py step_run) ----------------
    def step_carry_pack_supported(self):
        return self.runner.stepwise_rows_supported()

    def step_carry_signature(self, carry, i, num_inference_steps):
        return self.runner.stepwise_carry_signature(carry, i,
                                                    num_inference_steps)

    def step_carry_rows_axes(self, carry, enc, num_inference_steps):
        embeds, added = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_rows_axes(carry, embeds, added,
                                                    num_inference_steps)

    def step_carry_pack_enc(self, encs, width):
        return _pack_enc_rows([self._step_pin_enc(e) for e in encs], width)

    def step_carry_step_rows(self, carry, i_rows, enc, gs_rows,
                             num_inference_steps):
        embeds, added = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_step_rows(
            carry, i_rows, embeds, added, gs_rows, num_inference_steps)


def _pack_enc_rows(encs, width):
    """One packed encoding from each member's SOLO encoding: every enc
    leaf carries the batch at axis 1 (branch-major [2, B, ...] CFG layout,
    the stepwise enc_spec P(None, DP)), and a solo enc's rows are identical
    by construction (`_pad_batch` repeats the one real prompt), so member
    r's row 0 becomes packed row r, padded to ``width`` by repeating the
    last member."""
    def pack_leaves(*leaves):
        blocks = [jax.lax.index_in_dim(l, 0, axis=1, keepdims=True)
                  for l in leaves]
        blocks = blocks + [blocks[-1]] * (width - len(blocks))
        return jnp.concatenate(blocks, axis=1)

    return jax.tree.map(pack_leaves, *encs)


class DistriSDXLPipeline(_DistriPipelineBase):
    """SDXL: two text encoders, penultimate hidden states concatenated, pooled
    embeds + micro-conditioning time_ids (reference pipelines.py:10-167)."""

    @classmethod
    def from_pretrained(
        cls,
        distri_config: DistriConfig,
        pretrained_model_name_or_path: str,
        scheduler: str | BaseScheduler = "ddim",
        dtype=None,
        variant: Optional[str] = None,
        **kwargs,
    ) -> "DistriSDXLPipeline":
        root = pretrained_model_name_or_path
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{root!r} is not a local model directory. This box has no "
                "network egress; download a HF snapshot (unet/, vae/, "
                "text_encoder/, text_encoder_2/, tokenizer/) first."
            )
        dtype = dtype or distri_config.dtype
        unet_params = convert_unet_state_dict(
            load_sharded_safetensors(os.path.join(root, "unet"), variant=variant), dtype
        )
        vae_params = convert_vae_state_dict(
            load_sharded_safetensors(os.path.join(root, "vae"), variant=variant), dtype
        )
        te1 = convert_clip_state_dict(
            load_sharded_safetensors(os.path.join(root, "text_encoder"), variant=variant), dtype
        )
        te2 = convert_clip_state_dict(
            load_sharded_safetensors(os.path.join(root, "text_encoder_2"), variant=variant), dtype
        )
        from .native import release_mappings

        release_mappings()  # converted trees are jax copies; unmap the shards
        tok1 = _tokenizer_or_fallback(os.path.join(root, "tokenizer"))
        tok2 = _tokenizer_or_fallback(os.path.join(root, "tokenizer_2"))
        sched = _scheduler_from_snapshot(root, scheduler)
        return cls(
            distri_config,
            _config_from_snapshot(
                root, "unet", unet_mod.unet_config_from_json, unet_mod.sdxl_config
            ),
            unet_params,
            _config_from_snapshot(
                root, "vae", vae_mod.vae_config_from_json, vae_mod.sdxl_vae_config
            ),
            vae_params,
            sched,
            [tok1, tok2],
            [
                (
                    _config_from_snapshot(
                        root, "text_encoder",
                        clip_mod.clip_config_from_json, clip_mod.clip_vit_l_config,
                    ),
                    te1,
                ),
                (
                    _config_from_snapshot(
                        root, "text_encoder_2",
                        clip_mod.clip_config_from_json, clip_mod.open_clip_bigg_config,
                    ),
                    te2,
                ),
            ],
        )

    @classmethod
    def from_params(cls, distri_config, unet_config, unet_params, vae_config,
                    vae_params, text_configs, text_params, scheduler="ddim",
                    tokenizers=None):
        sched = scheduler if isinstance(scheduler, BaseScheduler) else get_scheduler(scheduler)
        toks = tokenizers or [SimpleTokenizer(tc.vocab_size) for tc in text_configs]
        return cls(
            distri_config, unet_config, unet_params, vae_config, vae_params,
            sched, toks, list(zip(text_configs, text_params)),
        )

    def _encode(self, prompts, negs, micro_cond=None):
        cfg = self.distri_config
        texts = negs + prompts if cfg.do_classifier_free_guidance else prompts
        n_br = 2 if cfg.do_classifier_free_guidance else 1
        b = len(prompts)

        ids1 = _tokenize(self.tokenizers[0], texts)
        ids2 = _tokenize(self.tokenizers[1], texts)
        out1 = self._clip(0, ids1)
        out2 = self._clip(1, ids2)
        # SDXL conditioning: concat penultimate hidden states of both encoders
        emb = jnp.concatenate(
            [out1["hidden_states"][-2], out2["hidden_states"][-2]], axis=-1
        )
        emb = emb.reshape(n_br, b, *emb.shape[1:])
        pooled = out2["text_embeds"].reshape(n_br, b, -1)
        # time-id count is derived from the UNet's add-embedding width:
        # (proj_in - pooled) / per-id embed dim = 6 for SDXL-base
        # (orig h, w, crop top/left, target h, w) and 5 for refiner-style
        # configs (orig h, w, crop top/left, aesthetic score).
        ucfg = self.unet_config
        extra = ucfg.projection_class_embeddings_input_dim - pooled.shape[-1]
        n_ids = extra // ucfg.addition_time_embed_dim
        if n_ids not in (5, 6) or extra % ucfg.addition_time_embed_dim:
            raise ValueError(
                f"cannot derive time-ids: add-embedding expects {n_ids} ids "
                f"(proj_in={ucfg.projection_class_embeddings_input_dim}, "
                f"pooled={pooled.shape[-1]}, "
                f"per-id={ucfg.addition_time_embed_dim}); only the SDXL-base "
                "(6) and refiner-style (5) layouts are supported"
            )
        mc = micro_cond or {}
        o_sz = mc.get("original_size") or (cfg.height, cfg.width)
        crops = mc.get("crops_coords_top_left") or (0, 0)
        t_sz = mc.get("target_size") or (cfg.height, cfg.width)

        def _ids(size, crop, target, score):
            if n_ids == 5:
                return [size[0], size[1], crop[0], crop[1], score]
            return [size[0], size[1], crop[0], crop[1], target[0], target[1]]

        pos = _ids(o_sz, crops, t_sz, mc.get("aesthetic_score", 6.0))
        if n_br == 2:
            # diffusers semantics differ by layout: the base (6-id) pipeline
            # reuses the positive add_time_ids for the uncond branch unless
            # BOTH negative_original_size AND negative_target_size are
            # passed (only then does it build a negative set, with uncond
            # crops defaulting to (0, 0)); the refiner (5-id) layout always
            # builds the branches separately because
            # negative_aesthetic_score defaults to 2.5, not 6.0
            both_neg_sizes = (mc.get("negative_original_size") is not None
                              and mc.get("negative_target_size") is not None)
            if n_ids == 6 and not both_neg_sizes:
                neg = pos
            else:
                neg = _ids(
                    mc.get("negative_original_size") or o_sz,
                    mc.get("negative_crops_coords_top_left") or (0, 0),
                    mc.get("negative_target_size") or t_sz,
                    mc.get("negative_aesthetic_score", 2.5),
                )
            time_ids = jnp.asarray([neg, pos], jnp.float32)[:, None]
        else:
            time_ids = jnp.asarray([pos], jnp.float32)[:, None]
        time_ids = jnp.tile(time_ids, (1, b, 1))
        added = {"text_embeds": pooled, "time_ids": time_ids}
        return emb, added


class DistriSDPipeline(_DistriPipelineBase):
    """SD 1.4/1.5/2.x: single text encoder, final hidden state
    (reference pipelines.py:170-299)."""

    @classmethod
    def from_pretrained(
        cls,
        distri_config: DistriConfig,
        pretrained_model_name_or_path: str,
        scheduler: str | BaseScheduler = "ddim",
        dtype=None,
        variant: Optional[str] = None,
        **kwargs,
    ) -> "DistriSDPipeline":
        root = pretrained_model_name_or_path
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{root!r} is not a local model directory (no network egress)."
            )
        dtype = dtype or distri_config.dtype
        unet_params = convert_unet_state_dict(
            load_sharded_safetensors(os.path.join(root, "unet"), variant=variant), dtype
        )
        vae_params = convert_vae_state_dict(
            load_sharded_safetensors(os.path.join(root, "vae"), variant=variant), dtype
        )
        te = convert_clip_state_dict(
            load_sharded_safetensors(os.path.join(root, "text_encoder"), variant=variant), dtype
        )
        from .native import release_mappings

        release_mappings()
        tok = _tokenizer_or_fallback(os.path.join(root, "tokenizer"))
        sched = _scheduler_from_snapshot(root, scheduler)
        return cls(
            distri_config,
            _config_from_snapshot(
                root, "unet", unet_mod.unet_config_from_json, unet_mod.sd15_config
            ),
            unet_params,
            _config_from_snapshot(
                root, "vae", vae_mod.vae_config_from_json, vae_mod.sd_vae_config
            ),
            vae_params,
            sched,
            [tok],
            [
                (
                    _config_from_snapshot(
                        root, "text_encoder",
                        clip_mod.clip_config_from_json, clip_mod.clip_vit_l_config,
                    ),
                    te,
                )
            ],
        )

    @classmethod
    def from_params(cls, distri_config, unet_config, unet_params, vae_config,
                    vae_params, text_configs, text_params, scheduler="ddim",
                    tokenizers=None):
        sched = scheduler if isinstance(scheduler, BaseScheduler) else get_scheduler(scheduler)
        toks = tokenizers or [SimpleTokenizer(tc.vocab_size) for tc in text_configs]
        return cls(
            distri_config, unet_config, unet_params, vae_config, vae_params,
            sched, toks, list(zip(text_configs, text_params)),
        )

    def _encode(self, prompts, negs, micro_cond=None):
        # SD 1.x/2.x has no micro-conditioning; the kwarg is accepted for
        # the shared __call__ contract and ignored
        cfg = self.distri_config
        texts = negs + prompts if cfg.do_classifier_free_guidance else prompts
        n_br = 2 if cfg.do_classifier_free_guidance else 1
        b = len(prompts)
        ids = _tokenize(self.tokenizers[0], texts)
        out = self._clip(0, ids)
        emb = out["last_hidden_state"]
        return emb.reshape(n_br, b, *emb.shape[1:]), None


class DistriPixArtPipeline(_GenerationMixin):
    """PixArt-alpha (DiT family): T5 text encoder + PixArt transformer + KL
    VAE, driven by the displaced-patch DiT runner or, with
    ``parallelism="pipefusion"``, the patch-pipeline runner.

    The model family is beyond the reference (it targets SD/SDXL only); the
    pipeline surface mirrors DistriSDXLPipeline so framework users switch
    model families without switching APIs.  Padded caption tokens are masked
    out of cross-attention (PixArt semantics) and the 1024-class micro-
    conditioning on (resolution, aspect) is folded into the timestep
    embedding bias ahead of the loop (models/dit.py fold_size_condition —
    exact, because the size embedding is timestep-independent).
    """

    # PixArt-alpha trains with 120 caption tokens
    max_token_length = 120

    def __init__(
        self,
        distri_config: DistriConfig,
        dit_config,
        dit_params,
        vae_config: vae_mod.VAEConfig,
        vae_params,
        scheduler: BaseScheduler,
        tokenizer,
        t5_config,
        t5_params,
    ):
        from .models import dit as dit_mod
        from .parallel.dit_sp import DiTDenoiseRunner
        from .parallel.pipefusion import PipeFusionRunner

        _check_scheduler_family(scheduler, flow=False,
                                family="DistriPixArtPipeline")
        cfg = distri_config
        self.distri_config = cfg
        self.dit_config = dit_config
        self.vae_config = vae_config
        self.vae_params, _, t5_q = _quantize_aux(cfg, vae_params,
                                                 t5_params=t5_params)
        self.scheduler = scheduler
        self.tokenizer = tokenizer
        self.t5 = (t5_config, t5_q)
        # fold the size conditioning BEFORE quantizing: it edits embedding
        # biases the quantizer must see in their final form
        dit_params = dit_mod.fold_size_condition(
            dit_params, dit_config, float(cfg.height), float(cfg.width)
        )
        dit_params = quantize_params(dit_params, cfg.weight_quant,
                                     compute=cfg.quant_compute)
        runner_cls = (
            PipeFusionRunner if cfg.parallelism == "pipefusion"
            else DiTDenoiseRunner
        )
        self.runner = runner_cls(cfg, dit_config, dit_params, scheduler)
        self._decode, self.vae_decode_parallel = _build_decoder(cfg, vae_config)
        if t5_params is not None:
            from .models.t5 import t5_encode

            self._t5_jitted = jax.jit(
                lambda prm, ids, mask: t5_encode(prm, t5_config, ids, mask)
            )

    @classmethod
    def from_pretrained(
        cls,
        distri_config: DistriConfig,
        pretrained_model_name_or_path: str,
        scheduler: str | BaseScheduler = "dpm-solver",
        dtype=None,
        variant: Optional[str] = None,
        **kwargs,
    ) -> "DistriPixArtPipeline":
        """Load a local PixArt snapshot (transformer/, vae/, text_encoder/
        (T5), tokenizer/)."""
        from .models import dit as dit_mod
        from .models import t5 as t5_mod
        from .models.weights import convert_pixart_state_dict, convert_t5_state_dict

        root = pretrained_model_name_or_path
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{root!r} is not a local model directory (no network egress)."
            )
        dtype = dtype or distri_config.dtype
        dcfg = _config_from_snapshot(
            root, "transformer", dit_mod.dit_config_from_json,
            dit_mod.pixart_config,
        )
        dit_params = convert_pixart_state_dict(
            load_sharded_safetensors(os.path.join(root, "transformer"),
                                     variant=variant),
            patch_size=dcfg.patch_size, eps_channels=dcfg.out_channels,
            dtype=dtype,
        )
        vae_params = convert_vae_state_dict(
            load_sharded_safetensors(os.path.join(root, "vae"),
                                     variant=variant), dtype
        )
        t5cfg = _config_from_snapshot(
            root, "text_encoder", t5_mod.t5_config_from_json,
            t5_mod.t5_v1_1_xxl_config,
        )
        t5_params = convert_t5_state_dict(
            load_sharded_safetensors(os.path.join(root, "text_encoder"),
                                     variant=variant), dtype
        )
        from .native import release_mappings

        release_mappings()
        tok = _t5_tokenizer_or_fallback(
            os.path.join(root, "tokenizer"), t5cfg.vocab_size
        )
        sched = _scheduler_from_snapshot(root, scheduler)
        return cls(distri_config, dcfg, dit_params,
                   _config_from_snapshot(root, "vae",
                                         vae_mod.vae_config_from_json,
                                         vae_mod.sd_vae_config),
                   vae_params, sched, tok, t5cfg, t5_params)

    @classmethod
    def from_params(cls, distri_config, dit_config, dit_params, vae_config,
                    vae_params, t5_config=None, t5_params=None,
                    scheduler="ddim", tokenizer=None):
        sched = (scheduler if isinstance(scheduler, BaseScheduler)
                 else get_scheduler(scheduler))
        tok = tokenizer or SimpleTokenizer(
            vocab_size=t5_config.vocab_size if t5_config else 32128,
            eos=1, bos=0,
        )
        return cls(distri_config, dit_config, dit_params, vae_config,
                   vae_params, sched, tok, t5_config, t5_params)

    # -- reference API ----------------------------------------------------
    def set_progress_bar_config(self, **kwargs):
        pass

    def prepare(self, num_inference_steps: int = 20, **kwargs) -> None:
        self.runner.prepare(num_inference_steps)

    def _encode(self, prompts, negs):
        cfg = self.distri_config
        texts = negs + prompts if cfg.do_classifier_free_guidance else prompts
        n_br = 2 if cfg.do_classifier_free_guidance else 1
        b = len(prompts)
        t5cfg, t5p = self.t5
        if t5p is None:
            # weight-free smoke path: deterministic pseudo-embeddings, so the
            # random-weight runners still exercise the full pipeline surface
            if isinstance(self.tokenizer, SimpleTokenizer):
                ids = np.asarray(self.tokenizer(texts, self.max_token_length))
            else:
                # explicit max_length: tok.model_max_length is 512 (or unset
                # = effectively unbounded) for T5 tokenizers; the pipeline
                # contract is 120 caption tokens
                out = self.tokenizer(
                    texts, padding="max_length",
                    max_length=self.max_token_length, truncation=True,
                    return_tensors="np",
                )
                ids = np.asarray(out["input_ids"])
            emb = jnp.stack([
                jax.random.normal(
                    jax.random.PRNGKey(int(s) % (2**31)),
                    (ids.shape[1], self.dit_config.caption_dim), jnp.float32,
                )
                for s in ids.sum(axis=1)
            ])
            mask = np.ones(ids.shape, np.float32)
        else:
            if isinstance(self.tokenizer, SimpleTokenizer):
                ids = self.tokenizer(texts, self.max_token_length)
                # real tokens + the first (sentinel) EOS are attended, like a
                # transformers T5 attention_mask; the eos-padding tail is not
                mask = (ids != self.tokenizer.eos).astype(np.float32)
                first_eos = np.argmax(ids == self.tokenizer.eos, axis=1)
                mask[np.arange(len(ids)), first_eos] = 1.0
            else:
                out = self.tokenizer(
                    texts, padding="max_length",
                    max_length=self.max_token_length, truncation=True,
                    return_tensors="np",
                )
                ids = np.asarray(out["input_ids"])
                mask = np.asarray(out["attention_mask"], np.float32)
            emb = self._t5_jitted(
                t5p, jnp.asarray(ids, jnp.int32), jnp.asarray(mask)
            )
        emb = jnp.asarray(emb)
        emb = emb.reshape(n_br, b, emb.shape[1], emb.shape[2])
        mask = jnp.asarray(np.asarray(mask).reshape(n_br, b, -1))
        return emb, mask

    def __call__(
        self,
        prompt: str | List[str],
        negative_prompt: str | List[str] = "",
        num_inference_steps: int = 20,
        guidance_scale: float = 4.5,
        seed: int = 0,
        output_type: str = "pil",
        latents=None,
        num_images_per_prompt: int = 1,
        callback=None,
        **kwargs,
    ) -> PipelineOutput:
        cfg = self.distri_config
        if "height" in kwargs or "width" in kwargs:
            raise ValueError(
                "height and width are fixed in DistriConfig (reference "
                "pipelines.py:47-55)"
            )
        if not cfg.do_classifier_free_guidance:
            guidance_scale = 1.0
        prompts, negs = _normalize_prompts(prompt, negative_prompt)
        self.scheduler.set_timesteps(num_inference_steps)

        def run_chunk(cp, cn, cl, n_real):
            enc = self._encode(cp, cn)
            cb = self._timeline_callback(
                num_inference_steps, _wrap_chunk_callback(callback, n_real))
            try:
                return self._denoise_chunk(
                    enc, cl, guidance_scale, num_inference_steps,
                    callback=cb)
            finally:
                self._timeline_end()

        latent = _batched_generate(
            cfg, self.scheduler, prompts, negs, num_images_per_prompt, seed,
            latents, self.dit_config.in_channels, run_chunk,
        )
        return self._finalize(latent, output_type, [self.tokenizer])

    # -- stage hooks (prepare_stages / __call__ share these) ---------------
    def _stage_encode(self, prompts, negs):
        return self._encode(prompts, negs)

    def _denoise_chunk(self, enc, latents, guidance_scale,
                       num_inference_steps, *, callback=None):
        emb, mask = enc
        return self.runner.generate(
            latents, emb, guidance_scale=guidance_scale,
            num_inference_steps=num_inference_steps, cap_mask=mask,
            callback=callback,
        )

    # -- step-granular carry hooks (serve/stepbatch.py) -------------------
    def step_carry_init(self, latents, num_inference_steps):
        return self.runner.stepwise_carry_init(latents, num_inference_steps)

    def _step_pin_enc(self, enc):
        """The mask default + pinning generate() applies before its
        stepwise loop — identical inputs => identical per-step programs."""
        emb, mask = enc
        if mask is None:
            mask = jnp.ones(emb.shape[:3], jnp.float32)
        return emb, jnp.asarray(mask, jnp.float32)

    def step_carry_step(self, carry, i, enc, guidance_scale,
                        num_inference_steps):
        emb, mask = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_step(
            carry, i, emb, mask,
            jnp.asarray(guidance_scale, jnp.float32), num_inference_steps)

    def step_carry_latent(self, carry):
        return self.runner.stepwise_carry_latent(carry)

    # -- packed cohort hooks (serve/executors.py step_run) ----------------
    def step_carry_pack_supported(self):
        return self.runner.stepwise_rows_supported()

    def step_carry_signature(self, carry, i, num_inference_steps):
        return self.runner.stepwise_carry_signature(carry, i,
                                                    num_inference_steps)

    def step_carry_rows_axes(self, carry, enc, num_inference_steps):
        return self.runner.stepwise_carry_rows_axes(carry,
                                                    num_inference_steps)

    def step_carry_pack_enc(self, encs, width):
        return _pack_enc_rows([self._step_pin_enc(e) for e in encs], width)

    def step_carry_step_rows(self, carry, i_rows, enc, gs_rows,
                             num_inference_steps):
        emb, mask = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_step_rows(
            carry, i_rows, emb, mask, gs_rows, num_inference_steps)


def _t5_tokenizer_or_fallback(path: str, vocab_size: int):
    """transformers T5 tokenizer from the snapshot dir, else the hash
    fallback with a LOUD warning (same policy as the CLIP loader)."""
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(path)
    except Exception as e:
        print(
            f"WARNING: failed to load T5 tokenizer from {path!r} "
            f"({type(e).__name__}: {e}); falling back to the hash-based "
            "SimpleTokenizer. Generated images will NOT match real-prompt "
            "outputs.",
            file=sys.stderr,
            flush=True,
        )
        return SimpleTokenizer(vocab_size=vocab_size, eos=1, bos=0)


class DistriSD3Pipeline(_GenerationMixin):
    """SD3-class MMDiT pipeline — a model family BEYOND the reference
    (whose diffusers 0.24 pin predates SD3 entirely); built so the same
    displaced-patch machinery covers the current diffusion architecture.

    Text conditioning follows the published SD3 recipe: both CLIP
    encoders' penultimate hidden states concatenate along features and
    zero-pad to joint_attention_dim; T5 states (or zeros when no T5 is
    loaded — SD3 supports dropping it) append along the TOKEN axis; the
    pooled vector is the concat of both CLIP projected embeddings.
    Sampling is rectified-flow Euler (schedulers.FlowMatchEulerScheduler),
    denoising runs on parallel/mmdit_sp.MMDiTDenoiseRunner, and the
    SD3-family VAE re-centering (shift_factor) applies at decode.
    """

    def __init__(
        self,
        distri_config: DistriConfig,
        mmdit_config,
        mmdit_params,
        vae_config: vae_mod.VAEConfig,
        vae_params,
        scheduler: BaseScheduler,
        tokenizers,       # [clip_l_tok, clip_g_tok, t5_tok_or_None]
        text_encoders,    # [(CLIPTextConfig, params) x 2]
        t5_config=None,
        t5_params=None,
        max_t5_tokens: int = 77,
    ):
        from .parallel.mmdit_sp import MMDiTDenoiseRunner

        _check_scheduler_family(scheduler, flow=True,
                                family="DistriSD3Pipeline (SD3-class MMDiT)")
        cfg = distri_config
        self.distri_config = cfg
        self.mmdit_config = mmdit_config
        self.vae_config = vae_config
        self.vae_params, self.text_encoders, t5_q = _quantize_aux(
            cfg, vae_params, text_encoders, t5_params)
        self._vae_shift = vae_config.shift_factor
        self.scheduler = scheduler
        self.tokenizers = tokenizers
        text_encoders = self.text_encoders
        mmdit_params = quantize_params(mmdit_params, cfg.weight_quant,
                                       compute=cfg.quant_compute)
        self.t5 = (t5_config, t5_q)
        self.max_t5_tokens = max_t5_tokens
        pooled_dim = sum(
            tc.projection_dim or tc.hidden_size for tc, _ in text_encoders
        )
        if pooled_dim != mmdit_config.pooled_projection_dim:
            raise ValueError(
                f"CLIP projected widths sum to {pooled_dim}, but the "
                f"transformer expects pooled_projection_dim="
                f"{mmdit_config.pooled_projection_dim}"
            )
        clip_dim = sum(tc.hidden_size for tc, _ in text_encoders)
        if clip_dim > mmdit_config.joint_attention_dim:
            raise ValueError(
                f"CLIP hidden widths sum to {clip_dim} > joint_attention_dim "
                f"{mmdit_config.joint_attention_dim}"
            )
        self.runner = MMDiTDenoiseRunner(cfg, mmdit_config, mmdit_params,
                                         scheduler)
        self._decode, self.vae_decode_parallel = _build_decoder(cfg, vae_config)
        self._encode_image = jax.jit(
            lambda prm, x: vae_mod.encode(prm, vae_config, x)
        )
        self._clip_jitted = [
            jax.jit(lambda prm, ids, _cfg=ccfg: clip_mod.clip_text_forward(
                prm, _cfg, ids))
            for ccfg, _ in text_encoders
        ]
        if t5_params is not None:
            from .models.t5 import t5_encode

            self._t5_jitted = jax.jit(
                lambda prm, ids, mask: t5_encode(prm, t5_config, ids, mask)
            )

    @classmethod
    def from_pretrained(
        cls,
        distri_config: DistriConfig,
        pretrained_model_name_or_path: str,
        scheduler: str | BaseScheduler = "flow-euler",
        dtype=None,
        variant: Optional[str] = None,
        max_t5_tokens: int = 77,
        **kwargs,
    ) -> "DistriSD3Pipeline":
        """Load a local SD3 snapshot (transformer/, vae/, text_encoder/,
        text_encoder_2/, optional text_encoder_3/ (T5), tokenizer*/).
        The T5 encoder is optional exactly as in the published pipeline —
        absent weights degrade to the zero-embedding path."""
        from .models import mmdit as mmdit_mod
        from .models import t5 as t5_mod
        from .models.weights import convert_mmdit_state_dict, convert_t5_state_dict

        root = pretrained_model_name_or_path
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{root!r} is not a local model directory (no network egress)."
            )
        dtype = dtype or distri_config.dtype
        mcfg = _config_from_snapshot(
            root, "transformer", mmdit_mod.mmdit_config_from_json,
            mmdit_mod.sd3_config,
        )
        mmdit_params = convert_mmdit_state_dict(
            load_sharded_safetensors(os.path.join(root, "transformer"),
                                     variant=variant), dtype
        )
        vae_params = convert_vae_state_dict(
            load_sharded_safetensors(os.path.join(root, "vae"),
                                     variant=variant), dtype
        )
        encs, toks = [], []
        for sub, tok_sub in (("text_encoder", "tokenizer"),
                             ("text_encoder_2", "tokenizer_2")):
            ccfg = _config_from_snapshot(
                root, sub, clip_mod.clip_config_from_json,
                clip_mod.tiny_clip_config,
            )
            cparams = convert_clip_state_dict(
                load_sharded_safetensors(os.path.join(root, sub),
                                         variant=variant), dtype
            )
            encs.append((ccfg, cparams))
            toks.append(_tokenizer_or_fallback(os.path.join(root, tok_sub)))
        t5cfg = t5p = None
        if os.path.isdir(os.path.join(root, "text_encoder_3")):
            t5cfg = _config_from_snapshot(
                root, "text_encoder_3", t5_mod.t5_config_from_json,
                t5_mod.t5_v1_1_xxl_config,
            )
            t5p = convert_t5_state_dict(
                load_sharded_safetensors(os.path.join(root, "text_encoder_3"),
                                         variant=variant), dtype
            )
            toks.append(_t5_tokenizer_or_fallback(
                os.path.join(root, "tokenizer_3"), t5cfg.vocab_size))
        else:
            toks.append(None)
        from .native import release_mappings

        release_mappings()
        if isinstance(scheduler, BaseScheduler):
            sched = scheduler  # family-checked by __init__
        elif scheduler != "flow-euler":
            raise ValueError(
                f"scheduler={scheduler!r}: SD3-class MMDiTs are "
                "rectified-flow models — only 'flow-euler' (or a "
                "FlowMatchEulerScheduler instance) is valid"
            )
        else:
            # SD3 scheduler_config carries the flow shift, not betas
            shift = 3.0
            sc_path = os.path.join(root, "scheduler", "scheduler_config.json")
            if os.path.exists(sc_path):
                import json as _json

                with open(sc_path) as f:
                    shift = _json.load(f).get("shift", 3.0)
            sched = FlowMatchEulerScheduler(shift=shift)
        return cls(distri_config, mcfg, mmdit_params,
                   _config_from_snapshot(root, "vae",
                                         vae_mod.vae_config_from_json,
                                         vae_mod.sd_vae_config),
                   vae_params, sched, toks, encs, t5cfg, t5p,
                   max_t5_tokens=max_t5_tokens)

    @classmethod
    def from_params(cls, distri_config, mmdit_config, mmdit_params,
                    vae_config, vae_params, clip_configs, clip_params,
                    t5_config=None, t5_params=None, scheduler="flow-euler",
                    tokenizers=None, max_t5_tokens: int = 77):
        sched = (scheduler if isinstance(scheduler, BaseScheduler)
                 else get_scheduler(scheduler))
        toks = tokenizers or [
            SimpleTokenizer(tc.vocab_size) for tc in clip_configs
        ] + [SimpleTokenizer(t5_config.vocab_size, eos=1, bos=0)
             if t5_config else None]
        return cls(distri_config, mmdit_config, mmdit_params, vae_config,
                   vae_params, sched, toks, list(zip(clip_configs,
                                                     clip_params)),
                   t5_config, t5_params, max_t5_tokens=max_t5_tokens)

    # -- reference API ----------------------------------------------------
    def set_progress_bar_config(self, **kwargs):
        pass

    def prepare(self, num_inference_steps: int = 20, **kwargs) -> None:
        self.runner.prepare(num_inference_steps)

    def _encode(self, prompts, negs):
        cfg = self.distri_config
        mcfg = self.mmdit_config
        texts = negs + prompts if cfg.do_classifier_free_guidance else prompts
        n_br = 2 if cfg.do_classifier_free_guidance else 1
        b = len(prompts)

        clip_states, pooleds = [], []
        for which in range(2):
            ids = _tokenize(self.tokenizers[which], texts)
            out = self._clip_jitted[which](
                self.text_encoders[which][1], np.asarray(ids))
            clip_states.append(out["hidden_states"][-2])
            pooleds.append(out.get("text_embeds", out["pooler_output"]))
        clip_emb = jnp.concatenate(clip_states, axis=-1)
        pad = mcfg.joint_attention_dim - clip_emb.shape[-1]
        clip_emb = jnp.pad(clip_emb, ((0, 0), (0, 0), (0, pad)))
        pooled = jnp.concatenate(pooleds, axis=-1)

        t5cfg, t5p = self.t5
        if t5p is None:
            t5_emb = jnp.zeros(
                (clip_emb.shape[0], self.max_t5_tokens,
                 mcfg.joint_attention_dim), clip_emb.dtype,
            )
        else:
            tok = self.tokenizers[2]
            if isinstance(tok, SimpleTokenizer):
                ids = tok(texts, self.max_t5_tokens)
                mask = (ids != tok.eos).astype(np.float32)
                first_eos = np.argmax(ids == tok.eos, axis=1)
                mask[np.arange(len(ids)), first_eos] = 1.0
            else:
                out = tok(texts, padding="max_length",
                          max_length=self.max_t5_tokens, truncation=True,
                          return_tensors="np")
                ids = np.asarray(out["input_ids"])
                mask = np.asarray(out["attention_mask"], np.float32)
            t5_emb = self._t5_jitted(
                t5p, jnp.asarray(ids, jnp.int32), jnp.asarray(mask))
        enc = jnp.concatenate([clip_emb, t5_emb.astype(clip_emb.dtype)],
                              axis=1)
        enc = enc.reshape(n_br, b, *enc.shape[1:])
        pooled = pooled.reshape(n_br, b, -1)
        return enc, pooled

    def __call__(
        self,
        prompt: str | List[str],
        negative_prompt: str | List[str] = "",
        num_inference_steps: int = 28,
        guidance_scale: float = 7.0,
        seed: int = 0,
        output_type: str = "pil",
        latents=None,
        num_images_per_prompt: int = 1,
        image=None,
        strength: float = 0.8,
        callback=None,
        **kwargs,
    ) -> PipelineOutput:
        cfg = self.distri_config
        if "height" in kwargs or "width" in kwargs:
            raise ValueError(
                "height and width are fixed in DistriConfig (reference "
                "pipelines.py:47-55)"
            )
        if not cfg.do_classifier_free_guidance:
            guidance_scale = 1.0
        prompts, negs = _normalize_prompts(prompt, negative_prompt)
        self.scheduler.set_timesteps(num_inference_steps)

        start_step = 0
        if image is not None:
            # img2img under rectified flow: the flow add_noise interpolates
            # to the strength-offset sigma — same timestep convention and
            # shared helper as the UNet pipelines' img2img path
            assert latents is None, "pass either image or latents, not both"
            latents, start_step = _prepare_init_latents(
                cfg, self.scheduler,
                lambda x: self._encode_image(self.vae_params, x),
                self.vae_config, image, strength, num_inference_steps,
                len(prompts), num_images_per_prompt, seed,
            )

        def run_chunk(cp, cn, cl, n_real):
            enc = self._encode(cp, cn)
            cb = self._timeline_callback(
                num_inference_steps, _wrap_chunk_callback(callback, n_real),
                start_step=start_step)
            try:
                return self._denoise_chunk(
                    enc, cl, guidance_scale, num_inference_steps,
                    start_step=start_step, callback=cb,
                )
            finally:
                self._timeline_end()

        latent = _batched_generate(
            cfg, self.scheduler, prompts, negs, num_images_per_prompt, seed,
            latents, self.mmdit_config.in_channels, run_chunk,
        )
        toks = [t for t in self.tokenizers if t is not None]
        return self._finalize(latent, output_type, toks)

    # -- stage hooks (prepare_stages / __call__ share these) ---------------
    def _stage_encode(self, prompts, negs):
        return self._encode(prompts, negs)

    def _denoise_chunk(self, enc, latents, guidance_scale,
                       num_inference_steps, *, start_step=0, callback=None):
        emb, pooled = enc
        return self.runner.generate(
            latents, emb, pooled, guidance_scale=guidance_scale,
            num_inference_steps=num_inference_steps,
            start_step=start_step,
            callback=callback,
        )

    # -- step-granular carry hooks (serve/stepbatch.py) -------------------
    def step_carry_init(self, latents, num_inference_steps):
        return self.runner.stepwise_carry_init(latents, num_inference_steps)

    def _step_pin_enc(self, enc):
        """The pooled pinning _generate_stepwise applies — identical
        inputs => identical per-step programs."""
        emb, pooled = enc
        return emb, jnp.asarray(pooled)

    def step_carry_step(self, carry, i, enc, guidance_scale,
                        num_inference_steps):
        emb, pooled = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_step(
            carry, i, emb, pooled,
            jnp.asarray(guidance_scale, jnp.float32), num_inference_steps)

    def step_carry_latent(self, carry):
        return self.runner.stepwise_carry_latent(carry)

    # -- packed cohort hooks (serve/executors.py step_run) ----------------
    def step_carry_pack_supported(self):
        return self.runner.stepwise_rows_supported()

    def step_carry_signature(self, carry, i, num_inference_steps):
        return self.runner.stepwise_carry_signature(carry, i,
                                                    num_inference_steps)

    def step_carry_rows_axes(self, carry, enc, num_inference_steps):
        return self.runner.stepwise_carry_rows_axes(carry,
                                                    num_inference_steps)

    def step_carry_pack_enc(self, encs, width):
        return _pack_enc_rows([self._step_pin_enc(e) for e in encs], width)

    def step_carry_step_rows(self, carry, i_rows, enc, gs_rows,
                             num_inference_steps):
        emb, pooled = self._step_pin_enc(enc)
        return self.runner.stepwise_carry_step_rows(
            carry, i_rows, emb, pooled, gs_rows, num_inference_steps)

"""HLO-level comm/compute overlap verification.

The displaced-patch design claims its stale-refresh collectives are *latency
hidden*: each stale step's halo exchanges and KV all-gathers produce values
consumed only by the NEXT scan iteration, so XLA's latency-hiding scheduler
is free to run them concurrently with the current step's convs/matmuls.  The
reference gets the same effect imperatively with async NCCL all-gathers
waited one step later (/root/reference/distrifuser/utils.py:170-190,
modules/pp/attn.py:123-143); here the property is structural — and therefore
checkable from the compiled HLO, not assumed.

`analyze_loop_collectives(hlo_text)` parses every while-loop body in a
compiled module and classifies each collective (all-gather / collective-
permute / all-reduce / reduce-scatter, sync or async-start form) as

* **deferred** — its value reaches ONLY the loop carry (the ROOT tuple),
  travelling exclusively through data-movement ops (copies, reshapes,
  concatenates, layout fusions that contain no arithmetic).  Nothing in the
  current iteration computes with it; the scheduler may overlap it with all
  remaining compute of the iteration.
* **inline** — some transitive consumer does arithmetic this iteration
  (attention matmuls on sync-phase KV gathers, scheduler math on the final
  output gather).  These serialize against compute.

The steady-state (stale scan) body of a patch-parallel program must have
inline collectives ONLY for the per-step full-output gather + CFG combine
(the reference's output gather is synchronous too, distri_sdxl_unet_pp.py:
162-169); every refresh collective must classify deferred.
tests/test_overlap.py asserts this, with the sync path as negative control.
`python -m distrifuser_tpu.utils.overlap <file.hlo>` prints the report for
any dumped module (e.g. from a real-chip run with XLA dump flags).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List

_COLLECTIVES = (
    "all-gather(", "collective-permute(", "all-reduce(", "reduce-scatter(",
    "all-gather-start(", "collective-permute-start(", "all-reduce-start(",
    "all-to-all(",
)
# pure data movement: consuming a value through these does not compute with it
_DM_OPS = frozenset({
    "copy", "bitcast", "bitcast-convert", "convert", "reshape", "transpose",
    "concatenate", "pad", "slice", "dynamic-slice", "dynamic-update-slice",
    "broadcast", "reverse", "tuple", "get-tuple-element",
    "all-gather-done", "collective-permute-done", "all-reduce-done",
    "optimization-barrier",
})
# ops that may appear in a data-movement fusion without consuming anything
_DM_SOURCES = frozenset({"parameter", "constant", "iota"})
# cheap elementwise arithmetic a *carry-only* chain may traverse and still
# count as latency-hidden (``elementwise_carry=True``): the compressed
# refresh path's dequantize (convert x scale-multiply [+ residual add],
# parallel/compress.py) lands here — the scheduler can sink these past all
# of the iteration's real compute exactly like a copy, since nothing this
# iteration reads their result.  Deliberately excludes dot/convolution/
# reduce and every collective opcode: traversing those means real compute
# (or another exchange) consumed the value this iteration.
_EW_OPS = frozenset({
    "add", "subtract", "multiply", "divide", "negate", "abs", "sign",
    "maximum", "minimum", "clamp", "compare", "select",
    "round-nearest-even", "round-nearest-afz",
})

_ATTR_REF = re.compile(r"(?:condition|body)=%[\w.\-]+")
_CALLS = re.compile(r"calls=%?([\w.\-]+)")
_TOKEN = re.compile(r"%([\w.\-]+)")
_DEF = re.compile(r"^(ROOT )?%?([\w.\-]+) = ")
_BLOCK_HEAD = re.compile(r"^(?:ENTRY )?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{$")
_OPCODE = re.compile(r"([\w\-]+)\(")


def parse_computations(hlo_text: str) -> Dict[str, List[str]]:
    """Split printed HLO into {computation name: [instruction lines]}."""
    blocks: Dict[str, List[str]] = {}
    cur, acc = None, []
    for line in hlo_text.splitlines():
        m = _BLOCK_HEAD.match(line)
        if m:
            cur, acc = m.group(1), []
            continue
        if line.startswith("}"):
            if cur is not None:
                blocks[cur] = acc
            cur = None
            continue
        if cur is not None:
            acc.append(line.strip())
    return blocks


def _opcode(line: str) -> str:
    m = _OPCODE.search(line.split(" = ", 1)[1])
    return m.group(1) if m else "?"


@dataclasses.dataclass
class LoopReport:
    body: str
    deferred: Dict[str, str]  # instruction name -> opcode
    inline: Dict[str, str]
    # collectives whose value reaches only the carry but through cheap
    # elementwise arithmetic (the compressed-refresh dequantize chain);
    # populated only under ``elementwise_carry=True`` — the default
    # classification keeps them in ``inline``, preserving the strict
    # pure-data-movement invariant of the uncompressed program.
    deferred_compute: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def n_deferred(self) -> int:
        return len(self.deferred)

    @property
    def n_inline(self) -> int:
        return len(self.inline)

    @property
    def n_deferred_compute(self) -> int:
        return len(self.deferred_compute)


class _Analyzer:
    def __init__(self, hlo_text: str):
        self.blocks = parse_computations(hlo_text)
        self._dm_comp: Dict[str, bool] = {}
        self._ew_comp: Dict[str, bool] = {}

    def _computation_is_dm(self, name: str) -> bool:
        """True if a (fusion) computation contains no arithmetic at all."""
        return self._computation_ok(name, self._dm_comp, _DM_OPS)

    def _computation_is_ew(self, name: str) -> bool:
        """True if a (fusion) computation contains at most data movement
        and the cheap elementwise arithmetic of ``_EW_OPS``."""
        return self._computation_ok(name, self._ew_comp, _DM_OPS | _EW_OPS)

    def _computation_ok(self, name: str, cache: Dict[str, bool],
                        allowed) -> bool:
        if name in cache:
            return cache[name]
        cache[name] = False  # cycle guard
        ok = True
        for ln in self.blocks.get(name, ()):
            if " = " not in ln:
                continue
            op = _opcode(ln)
            if op in allowed or op in _DM_SOURCES:
                continue
            if op == "fusion":
                m = _CALLS.search(ln)
                if m and self._computation_ok(m.group(1), cache, allowed):
                    continue
            ok = False
            break
        cache[name] = ok
        return ok

    def analyze_body(self, body: str,
                     elementwise_carry: bool = False) -> LoopReport | None:
        lines = self.blocks.get(body, [])
        defs: Dict[str, str] = {}
        root = None
        for ln in lines:
            m = _DEF.match(ln)
            if m:
                defs[m.group(2)] = ln
                if m.group(1):
                    root = m.group(2)
        if root is None:
            return None
        consumers: Dict[str, List[str]] = {n: [] for n in defs}
        for n, ln in defs.items():
            rhs = _ATTR_REF.sub("", ln.split(" = ", 1)[1])
            rhs = _CALLS.sub("", rhs)
            for op in _TOKEN.findall(rhs):
                if op in defs and op != n:
                    consumers[op].append(n)

        def passthrough_consumer(name: str, allow_ew: bool) -> bool:
            """Consuming instruction is pure data movement (or, with
            ``allow_ew``, cheap elementwise arithmetic)?"""
            ln = defs[name]
            op = _opcode(ln)
            if op in _DM_OPS or (allow_ew and op in _EW_OPS):
                return True
            if op == "fusion":
                m = _CALLS.search(ln)
                if not m:
                    return False
                if allow_ew:
                    return self._computation_is_ew(m.group(1))
                return self._computation_is_dm(m.group(1))
            return False

        def deferred(coll: str, allow_ew: bool = False) -> bool:
            """Value reaches only the carry, via passthrough ops only."""
            seen, frontier = set(), [coll]
            while frontier:
                n = frontier.pop()
                if n in seen:
                    continue
                seen.add(n)
                if not consumers[n] and n != root:
                    continue  # dead value: harmless
                for u in consumers[n]:
                    if u == root and _opcode(defs[u]) == "tuple":
                        continue
                    if passthrough_consumer(u, allow_ew):
                        frontier.append(u)
                    else:
                        return False
            return True

        d, dc, i = {}, {}, {}
        for n, ln in defs.items():
            if any(c in ln for c in _COLLECTIVES):
                if deferred(n):
                    d[n] = _opcode(ln)
                elif elementwise_carry and deferred(n, allow_ew=True):
                    dc[n] = _opcode(ln)
                else:
                    i[n] = _opcode(ln)
        if d or dc or i:
            return LoopReport(body, d, i, dc)
        return None


def analyze_loop_collectives(
    hlo_text: str, elementwise_carry: bool = False
) -> List[LoopReport]:
    """Classify every while-body collective as deferred (carry-only through
    data movement) or inline (computed with this iteration).

    ``elementwise_carry=True`` adds a third bucket, ``deferred_compute``:
    carry-only through data movement PLUS cheap elementwise arithmetic —
    where the compressed refresh path's quantize/dequantize converts land
    (comm_compress, parallel/compress.py).  Off by default so the strict
    invariant of uncompressed programs (pure data movement to the carry)
    keeps being checked as-is."""
    analyzer = _Analyzer(hlo_text)
    bodies = set(re.findall(r"body=%?([\w.\-]+)", hlo_text))
    reports = []
    for body in sorted(bodies):
        r = analyzer.analyze_body(body, elementwise_carry)
        if r is not None:
            reports.append(r)
    return reports


def format_report(reports: List[LoopReport]) -> str:
    from collections import Counter

    out = []
    for r in reports:
        out.append(
            f"loop body {r.body}: {r.n_deferred} deferred"
            + (f" / {r.n_deferred_compute} deferred-compute"
               if r.deferred_compute else "")
            + f" / {r.n_inline} inline"
        )
        if r.deferred:
            out.append(f"  deferred (overlappable): {dict(Counter(r.deferred.values()))}")
        if r.deferred_compute:
            out.append(
                "  deferred-compute (dequant chains): "
                f"{dict(Counter(r.deferred_compute.values()))}"
            )
        if r.inline:
            out.append(f"  inline (serializing):    {dict(Counter(r.inline.values()))}")
    return "\n".join(out) if out else "no while-loop collectives found"


if __name__ == "__main__":
    import sys

    with open(sys.argv[1]) as f:
        print(format_report(analyze_loop_collectives(f.read())))

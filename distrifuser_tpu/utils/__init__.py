from .config import CFG_AXIS, SP_AXIS, DistriConfig, init_multihost
from .env import check_env, default_backend, is_power_of_2

from .config import (
    CFG_AXIS,
    DEFAULT_BUCKETS,
    SP_AXIS,
    DistriConfig,
    ServeConfig,
    init_multihost,
)
from .env import check_env, default_backend, is_power_of_2

"""AOT executable store activation hook + runtime fingerprint.

Twin of `utils/chaos.py`, for the same layering reason: the LOW layer
(`parallel/runner.py`) builds the compiled denoise programs, but the
store that persists them (`serve/aotcache.py`) lives in the serving
subsystem — the runner must be able to ask "is a store active for the
build I am inside?" without importing serve.  `ExecutorCache` wraps
each executor build in `aot_activation(store, key.short())`, and
`DenoiseRunner.compiled_handle` captures the active (store, scope) pair
exactly where it consults `active_fault_plan()`: a later first dispatch
then deserializes instead of compiling on hit, or compiles and persists
on miss.

The activation is THREAD-LOCAL, not process-global (unlike the chaos
plan): a fleet start compiles many replicas' warmup keys in parallel
threads, and a global scope would stamp one replica's ExecKey onto
another's programs.  Each build thread sees exactly its own activation,
and the scope travels inside the objects the build creates.

The hook stores the store opaquely (anything with ``fingerprint`` /
``load_executable`` / ``save_executable``); no cache semantics live
here.  Production code without an `aot_cache` config block never
activates one; `active_aot_scope()` returning None is the steady state.

`runtime_fingerprint()` is the version half of every cache key: a
serialized executable is only provably "the program that would have
been compiled here" under the same jax/jaxlib/backend, so the store
bakes these fields into the envelope header and rejects on any skew.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Iterator, Optional, Tuple

_TLS = threading.local()


@contextlib.contextmanager
def aot_activation(store: Any, scope: str) -> Iterator[None]:
    """Activate ``store`` for builds on THIS thread, tagged ``scope``
    (the ExecKey.short() compile identity).  Nests: the innermost
    activation wins, the previous one is restored on exit."""
    prev = getattr(_TLS, "active", None)
    _TLS.active = (store, str(scope))
    try:
        yield
    finally:
        _TLS.active = prev


def active_aot_scope() -> Optional[Tuple[Any, str]]:
    """The (store, scope) pair active on this thread, or None."""
    return getattr(_TLS, "active", None)


def runtime_fingerprint() -> Dict[str, str]:
    """jax/jaxlib/backend identity of THIS process — the invalidation
    boundary for persisted executables.  Lazy jax import keeps this
    module a stdlib-only leaf at import time (same rule as chaos.py)."""
    try:
        import jax

        jax_version = str(getattr(jax, "__version__", "unknown"))
        try:
            backend = str(jax.default_backend())
        except Exception:
            backend = "unknown"
    except Exception:  # pragma: no cover - jax always present in-image
        return {"jax": "unavailable", "jaxlib": "unavailable",
                "backend": "unknown"}
    try:
        import jaxlib

        jaxlib_version = str(
            getattr(jaxlib, "__version__", None)
            or getattr(getattr(jaxlib, "version", None), "__version__",
                       "unknown"))
    except Exception:  # pragma: no cover
        jaxlib_version = "unavailable"
    return {"jax": jax_version, "jaxlib": jaxlib_version,
            "backend": backend}

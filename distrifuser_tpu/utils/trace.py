"""Request-scoped tracing: spans, events, and Perfetto-loadable export.

DistriFusion's value proposition is latency — the async stale exchange is
*hidden under compute* — yet until this module the repo could only infer
where a request's time went from aggregate histograms.  `Tracer` records
the full life of every request through the serve layer (enqueue, queue
wait, coalescing into a micro-batch, executor cache hit/miss/build, retry
attempts, breaker/ladder events, per-stage execution, completion) as
spans and instant events on named tracks, and `StepTimeline` records the
per-denoise-step view inside one generation (wall time per step, tagged
warmup/full/shallow, plus live comm-byte counters reconciled against the
closed-form `pipelines.comm_plan`).

Design constraints, in order:

* **Deterministic** — the clock is injectable (the PR-3 pattern: policy
  math testable without sleeping), every id comes from tracer-local
  counters (never the process-global request id), and `export()` orders
  events by (timestamp, sequence) with stable JSON serialization — same
  injected clock + same call sequence ⇒ byte-identical export, which the
  trace tests pin.
* **Bounded** — completed records land in a ring (``capacity``); a
  service that has traced a million requests still answers "what
  happened *lately*" in O(capacity) memory, with the drop count
  reported, never silent (`RingLog` convention).
* **Zero cost when off** — the serve layer holds ``tracer = None`` when
  tracing is disabled and guards every call site, so the tracing-off
  request path executes no tracing code at all (the ≤2% serve_bench
  overhead budget in ISSUE 8 is met by not running, not by being fast).

Export is the Chrome/Perfetto trace-event JSON format
(``{"traceEvents": [...]}``, "X"/"B"/"i"/"s"/"f" phases): load the file
at https://ui.perfetto.dev or chrome://tracing.  Tracks are logical
(``req/<trace>``, ``scheduler``, ``cache``, ``stage/denoise``, ...), not
OS threads — each maps to a synthetic tid with a thread_name metadata
record, so the UI shows one swimlane per logical actor.
"""

from __future__ import annotations

import dataclasses
import json
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional
from . import sync

# One synthetic process for the whole service; tracks are "threads".
_PID = 1


def _us(t: float) -> int:
    """Seconds (clock domain) -> integer microseconds (trace domain).
    Integer so serialization is exact and exports byte-stable."""
    return int(round(t * 1e6))


@dataclasses.dataclass
class RequestTrace:
    """The per-request handle the serve layer stashes on `Request.trace`:
    the tracer-local trace id, the request's track name, and the span ids
    the lifecycle hooks close later.  Tracer-local ids (NOT the process-
    global request_id) keep exports deterministic across runs."""

    trace_id: int
    track: str
    root: int
    queue_span: Optional[int] = None
    flow_id: Optional[int] = None
    done: bool = False


class Tracer:
    """Bounded, thread-safe span/event recorder (module docstring).

    ``begin``/``end`` bracket open spans (cross-thread: begin on the
    submit thread, end on the scheduler thread); ``complete`` records a
    span whose start/end times are already known; ``event`` records an
    instant.  ``trace`` groups records belonging to one request;
    ``track`` picks the swimlane.  All timestamps come from the injected
    ``clock`` unless passed explicitly (same domain).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 capacity: int = 8192):
        assert capacity >= 1, capacity
        self.clock = clock
        self.capacity = capacity
        self._lock = sync.Lock()
        self._records: deque = deque()
        self._open: Dict[int, Dict[str, Any]] = {}
        self._next_trace = 0
        self._next_span = 0
        self._next_seq = 0
        self._next_flow = 0
        self.dropped = 0
        self._t0 = clock()  # export origin: traces start near ts=0

    # -- id allocation ------------------------------------------------------

    def new_trace(self) -> int:
        with self._lock:
            self._next_trace += 1
            return self._next_trace

    def new_flow(self) -> int:
        with self._lock:
            self._next_flow += 1
            return self._next_flow

    # -- recording ----------------------------------------------------------

    def _push(self, rec: Dict[str, Any]) -> None:
        """Append one finished record to the ring (caller holds no lock)."""
        with self._lock:
            rec["seq"] = self._next_seq
            self._next_seq += 1
            if len(self._records) >= self.capacity:
                self._records.popleft()
                self.dropped += 1
            self._records.append(rec)

    def begin(self, name: str, *, track: str, trace: Optional[int] = None,
              parent: Optional[int] = None, args: Optional[dict] = None,
              t: Optional[float] = None) -> int:
        """Open a span; returns its id for `end`.  ``parent`` is another
        span id, recorded in args for structural assertions (the UI nests
        by track + time containment)."""
        with self._lock:
            self._next_span += 1
            sid = self._next_span
            self._open[sid] = {
                "name": name, "track": track, "trace": trace,
                "parent": parent, "t0": self.clock() if t is None else t,
                "args": dict(args or {}),
            }
        return sid

    def end(self, span_id: Optional[int], args: Optional[dict] = None,
            t: Optional[float] = None) -> None:
        """Close a span opened by `begin` (tolerates None/unknown ids —
        a raced double-close must never take down the scheduler)."""
        if span_id is None:
            return
        with self._lock:
            sp = self._open.pop(span_id, None)
        if sp is None:
            return
        t1 = self.clock() if t is None else t
        a = sp["args"]
        if args:
            a.update(args)
        self._emit_span(sp["name"], sp["track"], sp["trace"], sp["parent"],
                        span_id, sp["t0"], t1, a)

    def complete(self, name: str, t0: float, t1: float, *, track: str,
                 trace: Optional[int] = None, parent: Optional[int] = None,
                 args: Optional[dict] = None) -> int:
        """Record a span whose start/end are already measured (e.g. the
        executor invocation window the dispatch path timed anyway)."""
        with self._lock:
            self._next_span += 1
            sid = self._next_span
        self._emit_span(name, track, trace, parent, sid, t0, t1,
                        dict(args or {}))
        return sid

    def _emit_span(self, name, track, trace, parent, sid, t0, t1, args):
        a = dict(args)
        if trace is not None:
            a["trace"] = trace
        if parent is not None:
            a["parent"] = parent
        a["span"] = sid
        self._push({
            "ph": "X", "name": name, "track": track,
            "ts": _us(t0 - self._t0), "dur": max(0, _us(t1 - t0)),
            "args": a,
        })

    def event(self, name: str, *, track: str, trace: Optional[int] = None,
              args: Optional[dict] = None, t: Optional[float] = None) -> None:
        """Instant event on a track."""
        a = dict(args or {})
        if trace is not None:
            a["trace"] = trace
        self._push({
            "ph": "i", "name": name, "track": track,
            "ts": _us((self.clock() if t is None else t) - self._t0),
            "s": "t", "args": a,
        })

    def flow(self, flow_id: int, phase: str, *, track: str,
             t: Optional[float] = None, name: str = "link") -> None:
        """One end of a flow arrow (``phase`` "s" = start, "f" = finish):
        the serve layer draws batch-span -> member-request links with
        these.  Timestamps must fall inside an enclosing slice on the
        same track for the UI to anchor the arrow."""
        assert phase in ("s", "f"), phase
        rec: Dict[str, Any] = {
            "ph": phase, "name": name, "track": track, "id": flow_id,
            "ts": _us((self.clock() if t is None else t) - self._t0),
        }
        if phase == "f":
            rec["bp"] = "e"
        self._push(rec)

    # -- export -------------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """Finished records, oldest first (copies — safe to mutate)."""
        with self._lock:
            return [dict(r) for r in self._records]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "records": len(self._records),
                "dropped": self.dropped,
                "open_spans": len(self._open),
                "capacity": self.capacity,
                "traces": self._next_trace,
            }

    def trace_events(self) -> List[Dict[str, Any]]:
        """The Chrome trace-event list: metadata (track names) first, then
        every record ordered by (ts, seq) with tracks mapped to synthetic
        tids by sorted name — deterministic regardless of which thread
        registered a track first."""
        with self._lock:
            records = [dict(r) for r in self._records]
            open_spans = [
                (sid, dict(sp)) for sid, sp in sorted(self._open.items())
            ]
        # un-ended spans surface as "B" (begin-only) records so a trace
        # snapshotted mid-request still shows the in-flight work
        for sid, sp in open_spans:
            a = dict(sp["args"])
            if sp["trace"] is not None:
                a["trace"] = sp["trace"]
            if sp["parent"] is not None:
                a["parent"] = sp["parent"]
            a["span"] = sid
            records.append({
                "ph": "B", "name": sp["name"], "track": sp["track"],
                "ts": _us(sp["t0"] - self._t0), "args": a,
                "seq": 10**9 + sid,  # after every finished record at its ts
            })
        tracks = sorted({r["track"] for r in records})
        tids = {name: i + 1 for i, name in enumerate(tracks)}
        events: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": _PID, "tid": 0,
             "args": {"name": "distrifuser-serve"}},
        ]
        for name in tracks:
            events.append({
                "ph": "M", "name": "thread_name", "pid": _PID,
                "tid": tids[name], "args": {"name": name},
            })
        for r in sorted(records, key=lambda r: (r["ts"], r["seq"])):
            e = {k: v for k, v in r.items() if k not in ("track", "seq")}
            e["pid"] = _PID
            e["tid"] = tids[r["track"]]
            events.append(e)
        return events

    def export(self, path: Optional[str] = None) -> Dict[str, Any]:
        """The Perfetto-loadable payload; with ``path``, also written to
        disk with stable formatting (sorted keys, no whitespace churn) so
        deterministic runs produce byte-identical files."""
        payload = {"traceEvents": self.trace_events(),
                   "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(payload, f, sort_keys=True,
                          separators=(",", ":"))
                f.write("\n")
        return payload


# --------------------------------------------------------------------------
# Per-step denoise timeline
# --------------------------------------------------------------------------

# StepTimeline phase tags -> pipelines.comm_plan / stepcache phase keys
PHASE_TO_COMM = {"warmup": "sync", "full": "stale", "shallow": "shallow"}


class StepTimeline:
    """Wall-time and live comm-byte accounting per denoise step.

    Attach to a pipeline (``pipeline.step_timeline = StepTimeline()``) and
    every generation records one run: per-step wall timings tagged
    ``warmup``/``full``/``shallow`` (the step-cache cadence phases), plus
    a live comm-byte counter that adds each *executed* step's wire bytes
    from the runner's per-phase byte model as the loop advances.  Because
    the closed-form ``pipelines.comm_plan`` multiplies the same per-step
    bytes by `stepcache.phase_step_counts`, the two agree exactly iff the
    loop really executed the phase sequence the plan predicts — the byte
    model becomes a checked invariant instead of documentation
    (``tests/test_observability.py`` pins it).

    Driven by the per-step callback, so a timeline-carrying generation
    runs the callback dispatch path (the host stepwise loop, or the fused
    loop's ``io_callback`` variant where the jaxlib supports it) — per-
    step host visibility is exactly what that path exists for.  Single
    writer (the loop thread); ``snapshot()`` is read-anywhere.

    ``tracer``/``track`` optionally mirror every step into a `Tracer` as
    ``step/<phase>`` spans, putting the denoise micro-timeline on the
    same Perfetto timeline as the request spans around it.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 tracer: Optional[Tracer] = None, track: str = "denoise"):
        self.clock = clock
        self.tracer = tracer
        self.track = track
        self._lock = sync.Lock()
        self.runs: List[Dict[str, Any]] = []
        self._cur: Optional[Dict[str, Any]] = None
        self._phase_of: Optional[Callable[[int], str]] = None
        self._bytes_per_step: Dict[str, int] = {}
        self._t_last = 0.0

    def begin_run(self, num_steps: int,
                  phase_of: Callable[[int], str],
                  bytes_per_step: Optional[Dict[str, int]] = None,
                  meta: Optional[dict] = None) -> None:
        """Start recording one generation: ``phase_of(i)`` tags each step
        (the pipeline passes the exact cadence arithmetic the loop runs);
        ``bytes_per_step`` is comm_plan's per-phase wire-byte model keyed
        ``sync``/``stale``/``shallow`` (None = bytes untracked, e.g. a
        runner without a byte model)."""
        with self._lock:
            self._cur = {
                "num_steps": int(num_steps),
                "steps": [],
                "phase_steps": {"warmup": 0, "full": 0, "shallow": 0},
                "phase_wall_s": {"warmup": 0.0, "full": 0.0, "shallow": 0.0},
                "comm_bytes": 0,
                "comm_bytes_tracked": bytes_per_step is not None,
                "meta": dict(meta or {}),
            }
            self._phase_of = phase_of
            self._bytes_per_step = dict(bytes_per_step or {})
            self._t_last = self.clock()

    def on_step(self, i: int) -> None:
        """Record step ``i`` finishing now (the per-step callback)."""
        t = self.clock()
        with self._lock:
            cur = self._cur
            if cur is None:
                return
            phase = self._phase_of(int(i))
            dt = t - self._t_last
            cur["steps"].append(
                {"step": int(i), "phase": phase, "wall_s": dt}
            )
            cur["phase_steps"][phase] += 1
            cur["phase_wall_s"][phase] += dt
            cur["comm_bytes"] += int(
                self._bytes_per_step.get(PHASE_TO_COMM[phase], 0)
            )
            t_prev, self._t_last = self._t_last, t
        if self.tracer is not None:
            self.tracer.complete(f"step/{phase}", t_prev, t,
                                 track=self.track, args={"step": int(i)})

    def end_run(self) -> None:
        with self._lock:
            if self._cur is not None:
                self.runs.append(self._cur)
                self._cur = None

    # -- reads --------------------------------------------------------------

    @property
    def comm_bytes(self) -> int:
        """Live wire bytes across every completed run (per device,
        gathered-buffer convention — the same unit as comm_plan)."""
        with self._lock:
            return sum(r["comm_bytes"] for r in self.runs)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly aggregate: per-phase step counts and wall time
        across runs, live comm bytes, and the per-run records."""
        with self._lock:
            runs = [dict(r) for r in self.runs]
        agg_steps = {"warmup": 0, "full": 0, "shallow": 0}
        agg_wall = {"warmup": 0.0, "full": 0.0, "shallow": 0.0}
        for r in runs:
            for ph in agg_steps:
                agg_steps[ph] += r["phase_steps"][ph]
                agg_wall[ph] += r["phase_wall_s"][ph]
        return {
            "runs": len(runs),
            "phase_steps": agg_steps,
            "phase_wall_s": agg_wall,
            "comm_bytes": sum(r["comm_bytes"] for r in runs),
            "comm_bytes_tracked": all(
                r["comm_bytes_tracked"] for r in runs) if runs else False,
            "per_run": runs,
        }

"""JAX version-portability shims.

The framework is written against the jax >= 0.8 surface (`jax.shard_map`
with `check_vma`), but deployment images pin older runtimes — the current
container ships 0.4.x, where the same machinery lives at
`jax.experimental.shard_map.shard_map` and the replication-check kwarg is
spelled `check_rep`.  Every shard_map call site in the repo imports from
here so the version split is handled exactly once.
"""

from __future__ import annotations

try:  # jax >= 0.8: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older lines: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# The kwarg spelling does NOT track the import location (top-level
# jax.shard_map existed before the check_rep -> check_vma rename), so probe
# the signature instead of keying on where the import succeeded.
import inspect as _inspect

_REP_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep"
)


# jaxlib 0.4.x hard-aborts (SIGABRT inside backend_compile) on the fused
# per-step callback program: `io_callback(ordered=True)` inside a
# shard_map'd lax.scan.  Runners route callback-carrying generates through
# the host-driven stepwise loop when this is False — same step numerics,
# per-step dispatch instead of one fused program.
SUPPORTS_FUSED_CALLBACK = _REP_KW == "check_vma"


# Ahead-of-time executable serialization (the serve/aotcache.py store).
# jax 0.4.x ships it as `jax.experimental.serialize_executable`:
# ``serialize(compiled) -> (payload, in_tree, out_tree)`` and
# ``deserialize_and_load(payload, in_tree, out_tree) -> Compiled``.
# Newer lines fold the same capability into `jax.export`; probe for the
# 0.4.x surface and flag it, so the store degrades to compile-always
# (never a crash) on runtimes without it.
try:
    from jax.experimental import serialize_executable as _serialize_executable
    SUPPORTS_EXECUTABLE_SERIALIZATION = (
        hasattr(_serialize_executable, "serialize")
        and hasattr(_serialize_executable, "deserialize_and_load")
    )
except Exception:  # pragma: no cover - absent on exotic jax lines
    _serialize_executable = None
    SUPPORTS_EXECUTABLE_SERIALIZATION = False


def serialize_compiled(compiled) -> bytes:
    """Compiled jax executable -> opaque bytes.

    The 0.4.x serializer returns (payload, in_tree, out_tree); all three
    are needed to reload, so the byte form is a pickle of the triple.
    Raises whatever the runtime raises on unserializable programs
    (callbacks, host-pinned buffers) — callers treat any failure as
    "this program is not cacheable", never fatal.
    """
    import pickle

    payload, in_tree, out_tree = _serialize_executable.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree), protocol=4)


def deserialize_compiled(data: bytes):
    """Inverse of `serialize_compiled`: bytes -> loaded executable.

    Raises on malformed bytes or version-incompatible payloads; the AOT
    store wraps every failure in its typed rejection and falls back to a
    fresh compile.
    """
    import pickle

    payload, in_tree, out_tree = pickle.loads(data)
    return _serialize_executable.deserialize_and_load(
        payload, in_tree, out_tree)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` with the repo's calling convention on any jax line.

    ``check_vma`` follows the >= 0.8 spelling; on 0.4.x it forwards to
    ``check_rep`` (same semantics: disable the replication/varying-axis
    checker, required for all-gather-style replicated outputs).
    """
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_REP_KW: check_vma},
    )

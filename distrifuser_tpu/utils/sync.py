"""The one place serve-plane code constructs synchronization primitives.

distrisched (analysis/concurrency/, docs/ANALYSIS.md "Concurrency
analysis") explores the serve control plane's interleavings on a
deterministic scheduler.  That only works if EVERY cross-thread
interaction passes through a sync point the scheduler can see — so the
whole serve layer (and the utils metric/trace classes it shares across
threads) constructs its primitives here instead of calling ``threading``
directly, and distrilint's ``sync-containment`` checker fails tier-1 on
any raw constructor that escapes this module.

Production is a zero-overhead passthrough: with no runtime installed
(the default, always true outside the analysis harness) every factory
returns the stdlib object itself — not a proxy — so steady-state serving
pays nothing for the instrumentability.  Under the harness,
`install_runtime` routes the factories to the runtime's virtual
primitives, which yield to the seeded scheduler at every
acquire/release, wait/notify, queue op, and thread start/join.

``Empty`` is re-exported so ``except sync.Empty`` works against both the
stdlib queue and the virtual one (the virtual queue raises the stdlib
exception type).
"""

from __future__ import annotations

import queue as _queue_mod
import threading as _threading
from queue import Empty  # noqa: F401  (re-export; virtual queues raise it)

#: the active deterministic runtime (analysis/concurrency/sched.py), or
#: None in production.  Installed/removed by the harness only.
_runtime = None


def install_runtime(runtime) -> None:
    """Route the factories to ``runtime`` (harness-only; one at a time)."""
    global _runtime
    if _runtime is not None and runtime is not None:
        raise RuntimeError("a sync runtime is already installed")
    _runtime = runtime


def uninstall_runtime() -> None:
    global _runtime
    _runtime = None


def active_runtime():
    """The installed runtime, or None (production)."""
    return _runtime


# -- factories ---------------------------------------------------------------
#
# Signatures mirror the stdlib constructors the serve layer actually
# uses.  Each returns the stdlib object when no runtime is installed.


def Lock():
    if _runtime is None:
        return _threading.Lock()
    return _runtime.create_lock()


def RLock():
    if _runtime is None:
        return _threading.RLock()
    return _runtime.create_rlock()


def Condition(lock=None):
    if _runtime is None:
        return _threading.Condition(lock)
    return _runtime.create_condition(lock)


def Event():
    if _runtime is None:
        return _threading.Event()
    return _runtime.create_event()


def Semaphore(value: int = 1):
    if _runtime is None:
        return _threading.Semaphore(value)
    return _runtime.create_semaphore(value)


def Queue(maxsize: int = 0):
    if _runtime is None:
        return _queue_mod.Queue(maxsize)
    return _runtime.create_queue(maxsize)


def Thread(target=None, *, args=(), kwargs=None, name=None, daemon=None):
    if _runtime is None:
        return _threading.Thread(target=target, args=args, kwargs=kwargs,
                                 name=name, daemon=daemon)
    return _runtime.create_thread(target=target, args=args,
                                  kwargs=kwargs or {}, name=name)

"""Native metrics: image quality (PSNR, LPIPS, FID) and serving latency.

The reference computes PSNR via torchmetrics, LPIPS via the `lpips` package
and FID via `cleanfid` (/root/reference/scripts/compute_metrics.py:62-79) —
all of which download pretrained weights at first use.  This box has zero
egress, so the metrics are implemented natively here and the *weights* are
the only pluggable piece:

* PSNR — pure numpy, no weights.
* LPIPS — the Zhang et al. (arXiv:1801.03924) metric with the AlexNet trunk
  written out in torch (no torchvision dependency).  `lpips_weights` is a
  state-dict file holding the torchvision-AlexNet `features.*` tensors plus
  the LPIPS `lin{0..4}` 1x1 heads (the official `alex.pth` merged with the
  backbone; see `LPIPS_EXPECTED_KEYS`).
* FID — Fréchet distance between InceptionV3-pool3 feature Gaussians
  (Heusel et al., arXiv:1706.08500).  `fid_extractor` is any callable
  mapping uint8 RGB [N,H,W,3] -> features [N,D]; `load_fid_extractor` wraps
  a TorchScript file (the standard `pt_inception-2015-12-05` export used by
  pytorch-fid works offline).

The *math* (normalization, Fréchet distance incl. the sqrtm branch cuts,
feature statistics) is fully tested with random weights; only the numbers'
comparability to published tables depends on the pretrained files.

The serving metrics (`LatencyHistogram`, `Counter`) back the request
lifecycle instrumentation in `distrifuser_tpu/serve`: streaming accumulators
in the same spirit as `RunningStatistics` — bounded memory regardless of
request count, JSON-friendly snapshots for `bench.py`-style artifacts.
"""

from __future__ import annotations
try:
    from . import sync
except ImportError:  # scripts/compute_metrics.py execs this file by path
    # (no package parent — an offline metrics box need not import jax via
    # the distrifuser_tpu package): load the sibling passthrough the same
    # way, so there is still exactly one sync implementation
    import importlib.util as _ilu
    import os as _os

    _spec = _ilu.spec_from_file_location(
        "_distrifuser_sync",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "sync.py"))
    sync = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(sync)

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

# --------------------------------------------------------------------------
# PSNR
# --------------------------------------------------------------------------


def psnr(a: np.ndarray, b: np.ndarray, data_range: float = 1.0) -> float:
    """Peak signal-to-noise ratio between same-shape float images."""
    mse = float(np.mean((np.asarray(a, np.float64) - np.asarray(b, np.float64)) ** 2))
    return 10.0 * float(np.log10(data_range**2 / max(mse, 1e-12)))


# --------------------------------------------------------------------------
# LPIPS (AlexNet trunk, torch; no torchvision)
# --------------------------------------------------------------------------

# (out_ch, in_ch, kernel, stride, pad, maxpool_after)
_ALEX_CONVS = (
    (64, 3, 11, 4, 2, True),
    (192, 64, 5, 1, 2, True),
    (384, 192, 3, 1, 1, False),
    (256, 384, 3, 1, 1, False),
    (256, 256, 3, 1, 1, False),
)
# torchvision AlexNet state-dict indices of the conv layers in `features`
_ALEX_IDX = (0, 3, 6, 8, 10)

LPIPS_EXPECTED_KEYS = tuple(
    [f"features.{i}.{p}" for i in _ALEX_IDX for p in ("weight", "bias")]
    + [f"lin{i}.model.1.weight" for i in range(5)]
)

# LPIPS input scaling layer (inputs in [-1, 1])
_SHIFT = (-0.030, -0.088, -0.188)
_SCALE = (0.458, 0.448, 0.450)


class LPIPS:
    """Learned Perceptual Image Patch Similarity, AlexNet variant.

    ``state`` maps LPIPS_EXPECTED_KEYS to arrays (torch or numpy).  Use
    `LPIPS.from_file(path)` for a merged offline checkpoint, or
    `LPIPS.random(seed)` for math-level tests.
    """

    def __init__(self, state: Dict[str, np.ndarray]):
        import torch

        missing = [k for k in LPIPS_EXPECTED_KEYS if k not in state]
        if missing:
            raise KeyError(f"LPIPS state dict missing {missing[:4]}...")
        self._t = torch
        self._convs = []
        for i in _ALEX_IDX:
            w = torch.as_tensor(np.asarray(state[f"features.{i}.weight"]), dtype=torch.float32)
            b = torch.as_tensor(np.asarray(state[f"features.{i}.bias"]), dtype=torch.float32)
            self._convs.append((w, b))
        self._lins = [
            torch.as_tensor(np.asarray(state[f"lin{i}.model.1.weight"]), dtype=torch.float32)
            for i in range(5)
        ]
        self._shift = torch.tensor(_SHIFT, dtype=torch.float32).view(1, 3, 1, 1)
        self._scale = torch.tensor(_SCALE, dtype=torch.float32).view(1, 3, 1, 1)

    @classmethod
    def from_file(cls, path: str) -> "LPIPS":
        import torch

        state = torch.load(path, map_location="cpu", weights_only=True)
        return cls({k: v.numpy() for k, v in state.items()})

    @classmethod
    def random(cls, seed: int = 0) -> "LPIPS":
        r = np.random.RandomState(seed)
        state: Dict[str, np.ndarray] = {}
        for i, (co, ci, k, _, _, _) in zip(_ALEX_IDX, _ALEX_CONVS):
            state[f"features.{i}.weight"] = r.randn(co, ci, k, k).astype(np.float32) * 0.05
            state[f"features.{i}.bias"] = np.zeros(co, np.float32)
        for i, (co, _, _, _, _, _) in enumerate(_ALEX_CONVS):
            state[f"lin{i}.model.1.weight"] = np.abs(
                r.randn(1, co, 1, 1).astype(np.float32)
            )
        return cls(state)

    def _features(self, x):
        t, F = self._t, self._t.nn.functional
        x = (x - self._shift) / self._scale
        feats = []
        for (w, b), (_, _, _, stride, pad, pool) in zip(self._convs, _ALEX_CONVS):
            x = F.relu(F.conv2d(x, w, b, stride=stride, padding=pad))
            feats.append(x)
            if pool:
                x = F.max_pool2d(x, kernel_size=3, stride=2)
        return feats

    def __call__(self, a: np.ndarray, b: np.ndarray) -> float:
        """Images as float RGB [H,W,3] (or [N,H,W,3]) in [0,1]."""
        t, F = self._t, self._t.nn.functional
        with t.no_grad():
            ta = self._to_input(a)
            tb = self._to_input(b)
            total = t.zeros(ta.shape[0])
            for fa, fb, lin in zip(self._features(ta), self._features(tb), self._lins):
                na = fa / fa.norm(dim=1, keepdim=True).clamp_min(1e-10)
                nb = fb / fb.norm(dim=1, keepdim=True).clamp_min(1e-10)
                d = (na - nb) ** 2
                total = total + F.conv2d(d, lin).mean(dim=(1, 2, 3))
            return float(total.mean())

    def _to_input(self, img: np.ndarray):
        t = self._t
        x = np.asarray(img, np.float32)
        if x.ndim == 3:
            x = x[None]
        x = x * 2.0 - 1.0  # [0,1] -> [-1,1]
        return t.as_tensor(x).permute(0, 3, 1, 2)


# --------------------------------------------------------------------------
# FID
# --------------------------------------------------------------------------


def feature_statistics(features: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(mu, sigma) of a [N, D] feature matrix (rowvar-free covariance)."""
    f = np.asarray(features, np.float64)
    mu = f.mean(axis=0)
    sigma = np.cov(f, rowvar=False)
    return mu, np.atleast_2d(sigma)


class RunningStatistics:
    """Streaming (mu, sigma) accumulator — feature batches in, Gaussian out.

    FID over the reference workload (5k-30k COCO images, generate_coco.py)
    cannot hold all images in memory at once; only the [D] sum and [D, D]
    outer-product sum persist between batches."""

    def __init__(self):
        self.n = 0
        self._sum = None
        self._outer = None

    def update(self, features: np.ndarray) -> None:
        f = np.asarray(features, np.float64)
        if self._sum is None:
            self._sum = np.zeros(f.shape[1])
            self._outer = np.zeros((f.shape[1], f.shape[1]))
        self.n += f.shape[0]
        self._sum += f.sum(axis=0)
        self._outer += f.T @ f

    def finalize(self) -> Tuple[np.ndarray, np.ndarray]:
        if self.n < 2:
            raise ValueError("need at least 2 samples for covariance")
        mu = self._sum / self.n
        # unbiased covariance, matching np.cov
        sigma = (self._outer - self.n * np.outer(mu, mu)) / (self.n - 1)
        return mu, sigma


def frechet_distance(
    mu1: np.ndarray, sigma1: np.ndarray, mu2: np.ndarray, sigma2: np.ndarray,
    eps: float = 1e-6,
) -> float:
    """||mu1-mu2||^2 + tr(s1 + s2 - 2 sqrt(s1 s2)) with the standard
    numerical guards (arXiv:1706.08500 eq. 6; complex residue dropped)."""
    from scipy import linalg

    diff = np.asarray(mu1, np.float64) - np.asarray(mu2, np.float64)
    # sqrtm's `disp` kwarg is deprecated (removal in scipy 1.18); singular
    # products surface as non-finite entries, handled by the eps-offset retry
    covmean = np.atleast_2d(linalg.sqrtm(sigma1 @ sigma2))
    if not np.isfinite(covmean).all():
        offset = np.eye(sigma1.shape[0]) * eps
        covmean = linalg.sqrtm((sigma1 + offset) @ (sigma2 + offset))
    if np.iscomplexobj(covmean):
        covmean = covmean.real
    return float(diff @ diff + np.trace(sigma1) + np.trace(sigma2) - 2 * np.trace(covmean))


def fid_from_features(f0: np.ndarray, f1: np.ndarray) -> float:
    return frechet_distance(*feature_statistics(f0), *feature_statistics(f1))


def load_fid_extractor(path: str, batch: int = 32) -> Callable[[np.ndarray], np.ndarray]:
    """Wrap a TorchScript feature extractor file: uint8 RGB [N,H,W,3] -> [N,D].

    The standard offline artifact is pytorch-fid's `pt_inception-2015-12-05`
    TorchScript export (maps [N,3,299,299] in [0,1]-scaled float to pool3
    features); any module with that contract works.
    """
    import torch

    mod = torch.jit.load(path, map_location="cpu").eval()

    def extract(imgs: np.ndarray) -> np.ndarray:
        outs = []
        with torch.no_grad():
            for i in range(0, len(imgs), batch):
                x = torch.as_tensor(
                    np.asarray(imgs[i : i + batch], np.float32) / 255.0
                ).permute(0, 3, 1, 2)
                if x.shape[-2:] != (299, 299):
                    x = torch.nn.functional.interpolate(
                        x, size=(299, 299), mode="bilinear", align_corners=False
                    )
                y = mod(x)
                if isinstance(y, (list, tuple)):
                    y = y[0]
                outs.append(np.asarray(y.reshape(y.shape[0], -1)))
        return np.concatenate(outs, axis=0)

    return extract


# --------------------------------------------------------------------------
# Serving-latency metrics (streaming, bounded memory — like RunningStatistics)
# --------------------------------------------------------------------------


class LatencyHistogram:
    """Streaming latency histogram over geometric buckets.

    Serving metrics must survive millions of requests, so raw samples are
    never retained: observations land in log-spaced buckets (factor
    ``2**0.25`` per bucket ≈ 19% relative resolution — tighter than the
    2x-per-bucket Prometheus default) plus exact running count/sum/min/max.
    Quantiles interpolate within the bucket (log-midpoint), so reported
    percentiles carry the bucket's relative error, never more.

    Range: ``lo`` seconds to ``hi`` seconds; observations outside clamp to
    the boundary buckets (and still count exactly in min/max/sum).
    """

    _FACTOR = 2.0 ** 0.25

    def __init__(self, lo: float = 1e-4, hi: float = 1e3):
        assert 0 < lo < hi, (lo, hi)
        self.lo = lo
        self.hi = hi
        import math

        self._n_buckets = (
            int(math.ceil(math.log(hi / lo) / math.log(self._FACTOR))) + 1
        )
        self._counts = np.zeros(self._n_buckets, np.int64)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        # observe() is a read-modify-write on numpy storage; the staged
        # serving pipeline observes from stage workers concurrently with
        # the scheduler thread (serve/staging.py), same reason as Counter
        self._lock = sync.Lock()

    def _bucket(self, v: float) -> int:
        import math

        if v <= self.lo:
            return 0
        i = int(math.log(v / self.lo) / math.log(self._FACTOR))
        return min(i, self._n_buckets - 1)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._counts[self._bucket(v)] += 1
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)

    def _quantile_locked(self, q: float) -> float:
        if self.count == 0:
            return float("nan")
        rank = q * (self.count - 1)
        cum = 0
        for i, c in enumerate(self._counts):
            cum += int(c)
            if cum > rank:
                # log-midpoint of bucket i, clamped to the observed range
                mid = self.lo * self._FACTOR ** (i + 0.5)
                return float(min(max(mid, self.min), self.max))
        return float(self.max)

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (0 <= q <= 1) by bucket interpolation,
        clamped to the exact observed [min, max].  Locked like observe():
        a reader walking ``_counts`` concurrently with a writer must not
        see a cumulative count ahead of ``self.count`` (the PR-8
        thread-safety audit — readers take the same lock writers do)."""
        assert 0.0 <= q <= 1.0, q
        with self._lock:
            return self._quantile_locked(q)

    @property
    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else float("nan")

    def snapshot(self) -> Dict[str, float]:
        """JSON-friendly summary (the serve artifact schema).  One lock
        hold for the whole read, so count/sum/min/max and the quantiles
        all come from the same instant."""
        with self._lock:
            if self.count == 0:
                return {"count": 0}
            return {
                "count": self.count,
                "sum": self.sum,
                "mean": self.sum / self.count,
                "min": self.min,
                "max": self.max,
                "p50": self._quantile_locked(0.50),
                "p90": self._quantile_locked(0.90),
                "p99": self._quantile_locked(0.99),
            }


class Counter:
    """Named monotonic counters with a JSON-friendly snapshot.

    Locked: the serve layer increments from client threads (submit-path
    rejections) concurrently with the scheduler thread, and a bare
    read-modify-write would drop counts under that interleaving."""

    def __init__(self):

        self._c: Dict[str, int] = {}
        self._lock = sync.Lock()

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + by

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._c.items()))


class GapTracker:
    """Busy/idle accounting for one serially-used resource.

    Backs the staged serving pipeline's **denoise-gap fraction**
    (serve/staging.py): the denoise stage owns the mesh, so the fraction
    of wall-time between its first and last invocation that the mesh sat
    idle is exactly the latency the stage overlap failed to hide — the
    measurable form of the ISSUE's "throughput ceiling moves from
    1/sum(stage) to 1/max(stage)".  `begin(t)`/`end(t)` bracket each busy
    interval (single consumer — the stage worker); `snapshot()` is
    any-thread."""

    def __init__(self):

        self._lock = sync.Lock()
        self._t0 = None  # current interval start
        self.first_start = None
        self.last_end = None
        self.busy_s = 0.0
        self.intervals = 0

    def begin(self, t: float) -> None:
        with self._lock:
            assert self._t0 is None, "unbalanced GapTracker.begin"
            self._t0 = float(t)
            if self.first_start is None:
                self.first_start = float(t)

    def end(self, t: float) -> None:
        with self._lock:
            assert self._t0 is not None, "GapTracker.end without begin"
            self.busy_s += float(t) - self._t0
            self.last_end = float(t)
            self._t0 = None
            self.intervals += 1

    def snapshot(self) -> Dict[str, float]:
        """JSON-friendly summary.  ``gap_fraction`` is idle/span over the
        busy envelope [first_start, last_end]; 0.0 before two intervals
        exist (a single invocation has no between-batch gap to report)."""
        with self._lock:
            if self.first_start is None or self.last_end is None:
                return {"intervals": 0, "busy_s": 0.0, "span_s": 0.0,
                        "gap_s": 0.0, "gap_fraction": 0.0}
            span = self.last_end - self.first_start
            gap = max(0.0, span - self.busy_s)
            return {
                "intervals": self.intervals,
                "busy_s": self.busy_s,
                "span_s": span,
                "gap_s": gap,
                "gap_fraction": (gap / span) if span > 0 else 0.0,
            }


class RingLog:
    """Bounded ring of recent event strings (newest last).

    Backs the serve layer's ``last_errors`` health field: a service that
    has failed a million times must still answer "what went wrong
    *lately*" in O(capacity) memory.  Entries carry a monotonically
    increasing sequence number so a reader can tell two snapshots apart
    even when the ring content looks identical.  Locked for the same
    reason as `Counter` (scheduler + watchdog + snapshot threads)."""

    def __init__(self, capacity: int = 16):
        from collections import deque

        assert capacity >= 1, capacity
        self.capacity = capacity
        self._items = deque(maxlen=capacity)
        self._seq = 0
        self._lock = sync.Lock()

    def add(self, message: str) -> None:
        with self._lock:
            self._seq += 1
            self._items.append((self._seq, str(message)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def total(self) -> int:
        """How many events were EVER added (>= len, which is bounded)."""
        with self._lock:
            return self._seq

    def snapshot(self) -> list:
        """JSON-friendly ``[{"seq": n, "message": s}, ...]``, oldest first."""
        with self._lock:
            return [{"seq": n, "message": m} for n, m in self._items]


# --------------------------------------------------------------------------
# Unified metrics plane: registry + SLO signals + HTTP exposition
# --------------------------------------------------------------------------


class Gauge:
    """A point-in-time value: either callback-backed (``fn`` sampled at
    read time — queue depth, cache residency) or set-backed (`set`).
    Locked for the set path; callback gauges read whatever their callable
    reads (the callable owns its own consistency)."""

    def __init__(self, fn: Optional[Callable[[], float]] = None):

        self._fn = fn
        self._value = 0.0
        self._lock = sync.Lock()

    def set(self, value: float) -> None:
        assert self._fn is None, "callback gauge cannot be set"
        with self._lock:
            self._value = float(value)

    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a dead callback must not
                # take down a metrics scrape; NaN is the honest answer
                return float("nan")
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value()


class RollingQuantile:
    """Rolling-window latency quantiles over a fixed-size ring buffer.

    The SLO controller (ROADMAP item 3) steers on *recent* p50/p99 per
    SLO class — a lifetime histogram answers "how has this service ever
    behaved", not "is the SLO holding right now".  ``observe`` is O(1)
    (ring write + counter); ``quantile`` sorts a copy of the window
    (O(w log w) on the rare read path — w is small and scrape-rate, not
    request-rate).  Locked like the other serve metrics: request
    completions land from the scheduler thread and the staged decode
    worker concurrently.

    ``max_age_s`` (with ``clock``) bounds how long a sample steers the
    reads: a count-only ring is time-blind — after a burst, entries from
    minutes ago keep pinning the p99 an idle server reports, and a
    closed-loop controller would keep steering on load that no longer
    exists.  Observations older than ``max_age_s`` at read time are
    excluded from every quantile/snapshot (the ring still holds them;
    ``count`` stays the lifetime total, the snapshot's ``window`` is the
    LIVE sample count)."""

    def __init__(self, window: int = 512,
                 clock: Optional[Callable[[], float]] = None,
                 max_age_s: Optional[float] = None):
        import time as _time

        assert window >= 1, window
        assert max_age_s is None or max_age_s > 0, max_age_s
        self.window = window
        self.max_age_s = max_age_s
        self.clock = clock if clock is not None else _time.monotonic
        self._buf = np.zeros(window, np.float64)
        self._ts = np.zeros(window, np.float64)
        self._n = 0  # total ever observed
        self._lock = sync.Lock()

    def observe(self, v: float) -> None:
        t = self.clock() if self.max_age_s is not None else 0.0
        with self._lock:
            i = self._n % self.window
            self._buf[i] = float(v)
            self._ts[i] = t
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def _window_locked(self) -> np.ndarray:
        n = min(self._n, self.window)
        vals = self._buf[:n]
        if self.max_age_s is not None and n:
            vals = vals[self._ts[:n] >= self.clock() - self.max_age_s]
        return np.sort(vals.copy())

    @staticmethod
    def _rank(w: np.ndarray, q: float) -> float:
        """Nearest-rank value of sorted window ``w`` — the ONE indexing
        convention quantile() and snapshot() share."""
        return float(w[min(int(q * (w.size - 1) + 0.5), w.size - 1)])

    def quantile(self, q: float) -> float:
        assert 0.0 <= q <= 1.0, q
        with self._lock:
            w = self._window_locked()
        if w.size == 0:
            return float("nan")
        return self._rank(w, q)

    def snapshot(self) -> Dict[str, float]:
        """JSON-friendly window summary — the SLO-signal record shape
        (docs/OBSERVABILITY.md): total count, window fill, and the
        rolling p50/p90/p99.  Count and window come from the same lock
        hold, so the fields are mutually consistent."""
        with self._lock:
            w = self._window_locked()
            n = self._n
        if w.size == 0:
            # every sample may have AGED out of the window while the
            # lifetime total keeps counting — a monotonic counter must
            # never go backwards on an idle server
            return {"count": n, "window": 0}
        return {
            "count": n,
            "window": int(w.size),
            "mean": float(w.mean()),
            "p50": self._rank(w, 0.50),
            "p90": self._rank(w, 0.90),
            "p99": self._rank(w, 0.99),
        }


def _prom_name(name: str) -> str:
    """Sanitize a hierarchical metric name to the Prometheus charset."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch in "_:"
                   else "_")
    s = "".join(out)
    return s if not s[:1].isdigit() else "_" + s


def _prom_label_value(v) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r"\""))


def _prom_value(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


class MetricsRegistry:
    """One owner for every serving metric, under hierarchical names with
    labels — the unified plane `InferenceServer.metrics_snapshot()` and
    the ``--metrics_port`` endpoint render from.

    Helpers get-or-create (same name + labels returns the SAME instance,
    so e.g. the staged pipeline and the server share one histogram
    family); registering a different metric *object* under an existing
    (name, labels) raises — two writers silently splitting one identity
    is how dashboards lie.  Any object with a ``snapshot()`` (Counter,
    LatencyHistogram, GapTracker, RingLog, RollingQuantile, Gauge)
    registers via `register`.

    Rendering: `snapshot()` is the JSON form (one entry per (name,
    labels)); `to_prometheus()` is the text exposition format — counters
    as counter families (the multi-key `Counter` renders one sample per
    key under a ``key`` label), histograms and rolling windows as
    summaries (quantile label + _sum/_count), gauges and gap trackers as
    gauges.  RingLogs are JSON-only (free-text events have no place in
    the numeric exposition)."""

    def __init__(self):

        self._lock = sync.Lock()
        # name -> list of (labels_dict, metric); list keeps insertion
        # order so renders are stable
        self._families: Dict[str, list] = {}

    @staticmethod
    def _label_key(labels: Optional[Dict[str, str]]):
        return tuple(sorted((labels or {}).items()))

    def register(self, name: str, metric, labels: Optional[Dict] = None):
        """Register (or fetch) ``metric`` under (name, labels)."""
        assert name, "metric name must be non-empty"
        lk = self._label_key(labels)
        with self._lock:
            fam = self._families.setdefault(name, [])
            for lbls, m in fam:
                if self._label_key(lbls) == lk:
                    if m is not metric:
                        raise ValueError(
                            f"metric {name!r} with labels {dict(lk)} is "
                            "already registered to a different object"
                        )
                    return m
            fam.append((dict(labels or {}), metric))
            return metric

    def get(self, name: str, labels: Optional[Dict] = None):
        lk = self._label_key(labels)
        with self._lock:
            for lbls, m in self._families.get(name, []):
                if self._label_key(lbls) == lk:
                    return m
        return None

    def family(self, name: str):
        """Every (labels, metric) registered under ``name`` — lets a
        reader snapshot ONE family (e.g. the SLO windows) without
        rendering the whole registry."""
        with self._lock:
            return [(dict(lbls), m) for lbls, m in
                    self._families.get(name, [])]

    def _get_or_create(self, name, labels, factory, kind):
        existing = self.get(name, labels)
        if existing is None:
            try:
                existing = self.register(name, factory(), labels)
            except ValueError:
                # lost a creation race to another thread (e.g. two
                # workers both completing the first request of a new SLO
                # class): use whoever won
                existing = self.get(name, labels)
        if not isinstance(existing, kind):
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{type(existing).__name__}, not {kind.__name__}"
            )
        return existing

    # typed get-or-create helpers.  A repeat call with DIFFERENT
    # construction parameters raises instead of silently handing back
    # the first instance — same rationale as the object-conflict check:
    # two writers thinking they own different configurations of one
    # identity is how dashboards lie.

    @staticmethod
    def _check_params(name, existing, requested: Dict[str, Any]) -> None:
        for attr, want in requested.items():
            have = getattr(existing, attr)
            if have != want and not (have is want):
                raise ValueError(
                    f"metric {name!r} already registered with "
                    f"{attr}={have!r}; a second registration requested "
                    f"{attr}={want!r}"
                )

    def counter(self, name: str, labels: Optional[Dict] = None) -> Counter:
        return self._get_or_create(name, labels, Counter, Counter)

    def histogram(self, name: str, labels: Optional[Dict] = None,
                  lo: float = 1e-4, hi: float = 1e3) -> LatencyHistogram:
        h = self._get_or_create(
            name, labels, lambda: LatencyHistogram(lo, hi), LatencyHistogram
        )
        self._check_params(name, h, {"lo": lo, "hi": hi})
        return h

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              labels: Optional[Dict] = None) -> Gauge:
        g = self._get_or_create(name, labels, lambda: Gauge(fn), Gauge)
        if fn is not None and g._fn is not fn:
            raise ValueError(
                f"gauge {name!r} is already registered with a different "
                "callback — re-registering would silently drop one of them"
            )
        return g

    def rolling(self, name: str, window: int = 512,
                labels: Optional[Dict] = None,
                clock: Optional[Callable[[], float]] = None,
                max_age_s: Optional[float] = None) -> RollingQuantile:
        rq = self._get_or_create(
            name, labels,
            lambda: RollingQuantile(window, clock=clock, max_age_s=max_age_s),
            RollingQuantile,
        )
        self._check_params(name, rq, {"window": window,
                                      "max_age_s": max_age_s})
        if clock is not None and rq.clock is not clock:
            raise ValueError(
                f"rolling window {name!r} is already registered with a "
                "different clock — two time bases under one identity is "
                "how aging lies"
            )
        return rq

    def gap(self, name: str, labels: Optional[Dict] = None) -> GapTracker:
        return self._get_or_create(name, labels, GapTracker, GapTracker)

    def ring(self, name: str, capacity: int = 16,
             labels: Optional[Dict] = None) -> RingLog:
        r = self._get_or_create(
            name, labels, lambda: RingLog(capacity), RingLog
        )
        self._check_params(name, r, {"capacity": capacity})
        return r

    # renders ---------------------------------------------------------------

    def scoped(self, labels: Dict[str, str]) -> "ScopedRegistry":
        """A label-scoping view over this registry: every metric created
        or fetched through the view carries ``labels`` merged in.  The
        fleet layer (serve/fleet.py) gives each replica's server a
        ``{"replica": name}`` scope over ONE shared registry, so two
        replicas' otherwise-identical gauges land as distinct label sets
        instead of colliding."""
        return ScopedRegistry(self, labels)

    def unregister(self, name: str, labels: Optional[Dict] = None) -> bool:
        """Remove ONE (name, labels) registration; True if it existed.
        For callback-backed gauges being handed to a successor owner
        (e.g. a rebuilt FleetRouter over the same shared registry) —
        get-or-create would return the predecessor's stale closure, and
        re-registering would conflict."""
        lk = self._label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if not fam:
                return False
            keep = [(l, m) for l, m in fam if self._label_key(l) != lk]
            if len(keep) == len(fam):
                return False
            if keep:
                self._families[name] = keep
            else:
                del self._families[name]
            return True

    def prune(self, labels: Dict[str, str]) -> int:
        """Unregister every metric whose labels carry ALL of ``labels``;
        returns how many were removed.  A restarted fleet replica prunes
        its previous server generation's scope here — without this, each
        generation's gauges (whose closures pin the dead server) would
        accumulate in the shared registry forever."""
        want = {str(k): str(v) for k, v in labels.items()}
        removed = 0
        with self._lock:
            for name in list(self._families):
                fam = self._families[name]
                keep = [
                    (lbls, m) for lbls, m in fam
                    if not all(lbls.get(k) == v for k, v in want.items())
                ]
                removed += len(fam) - len(keep)
                if keep:
                    self._families[name] = keep
                else:
                    del self._families[name]
        return removed

    def _items(self):
        with self._lock:
            return [
                (name, dict(lbls), m)
                for name, fam in sorted(self._families.items())
                for lbls, m in fam
            ]

    def snapshot(self) -> Dict[str, Any]:
        """JSON snapshot: ``{name: [{"labels": {...}, "type": ...,
        "data": snapshot()}, ...]}`` — one stable shape for artifacts and
        the ``/metrics.json`` endpoint."""
        out: Dict[str, Any] = {}
        for name, lbls, m in self._items():
            out.setdefault(name, []).append({
                "labels": lbls,
                "type": type(m).__name__,
                "data": m.snapshot(),
            })
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: list = []
        typed: set = set()

        def labelstr(lbls: Dict[str, str], extra: Dict[str, str] = None):
            merged = dict(lbls)
            if extra:
                merged.update(extra)
            if not merged:
                return ""
            body = ",".join(
                f'{_prom_name(k)}="{_prom_label_value(v)}"'
                for k, v in sorted(merged.items())
            )
            return "{" + body + "}"

        def emit_type(pname: str, kind: str):
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} {kind}")

        for name, lbls, m in self._items():
            pname = _prom_name(name)
            if isinstance(m, Counter):
                emit_type(pname, "counter")
                for key, v in m.snapshot().items():
                    lines.append(
                        f"{pname}{labelstr(lbls, {'key': key})} "
                        f"{_prom_value(v)}"
                    )
            elif isinstance(m, (LatencyHistogram, RollingQuantile)):
                emit_type(pname, "summary")
                snap = m.snapshot()
                for q, qv in (("0.5", "p50"), ("0.9", "p90"),
                              ("0.99", "p99")):
                    if qv in snap:
                        lines.append(
                            f"{pname}{labelstr(lbls, {'quantile': q})} "
                            f"{_prom_value(snap[qv])}"
                        )
                if isinstance(m, LatencyHistogram):
                    # _sum comes from the SAME locked snapshot as the
                    # count/quantiles — no torn cross-field reads
                    lines.append(f"{pname}_sum{labelstr(lbls)} "
                                 f"{_prom_value(snap.get('sum', 0.0))}")
                lines.append(f"{pname}_count{labelstr(lbls)} "
                             f"{_prom_value(snap.get('count', 0))}")
            elif isinstance(m, GapTracker):
                snap = m.snapshot()
                for field in ("gap_fraction", "busy_s", "span_s",
                              "intervals"):
                    sub = f"{pname}_{field}"
                    emit_type(sub, "gauge")
                    lines.append(f"{sub}{labelstr(lbls)} "
                                 f"{_prom_value(snap[field])}")
            elif isinstance(m, Gauge):
                emit_type(pname, "gauge")
                lines.append(f"{pname}{labelstr(lbls)} "
                             f"{_prom_value(m.value())}")
            elif isinstance(m, RingLog):
                continue  # free-text events: JSON render only
            else:  # generic snapshot()-bearing object: flatten numerics
                snap = m.snapshot()
                if isinstance(snap, dict):
                    for k, v in snap.items():
                        if isinstance(v, (int, float)):
                            sub = f"{pname}_{_prom_name(str(k))}"
                            emit_type(sub, "gauge")
                            lines.append(f"{sub}{labelstr(lbls)} "
                                         f"{_prom_value(v)}")
        return "\n".join(lines) + ("\n" if lines else "")


class ScopedRegistry:
    """A label-injecting proxy over one `MetricsRegistry`.

    Every typed helper (`counter`/`histogram`/`gauge`/`rolling`/`gap`/
    `ring`/`register`/`get`) merges the scope labels into the call's
    labels before delegating, so code written against a plain registry
    (the server, the staged pipeline, the controller) namespaces itself
    per replica without knowing the fleet exists.  `family` filters to
    entries whose labels carry the scope, so per-replica readers (e.g.
    `InferenceServer.slo_snapshot`) never see a sibling replica's
    windows.  `snapshot`/`to_prometheus` render the WHOLE base registry —
    one scrape surface for the fleet, which is the point of sharing it.
    """

    def __init__(self, base: "MetricsRegistry", labels: Dict[str, str]):
        # flatten nested scopes so .base is always the real registry
        scope: Dict[str, str] = {}
        while isinstance(base, ScopedRegistry):
            merged = dict(base.scope)
            merged.update(scope)
            scope = merged
            base = base.base
        scope.update({str(k): str(v) for k, v in (labels or {}).items()})
        self.base = base
        self.scope = scope

    def _merged(self, labels: Optional[Dict]) -> Dict[str, str]:
        merged = dict(self.scope)
        merged.update(labels or {})
        return merged

    def scoped(self, labels: Dict[str, str]) -> "ScopedRegistry":
        return ScopedRegistry(self, labels)

    def register(self, name: str, metric, labels: Optional[Dict] = None):
        return self.base.register(name, metric, self._merged(labels))

    def get(self, name: str, labels: Optional[Dict] = None):
        return self.base.get(name, self._merged(labels))

    def counter(self, name: str, labels: Optional[Dict] = None) -> Counter:
        return self.base.counter(name, self._merged(labels))

    def histogram(self, name: str, labels: Optional[Dict] = None,
                  lo: float = 1e-4, hi: float = 1e3) -> LatencyHistogram:
        return self.base.histogram(name, self._merged(labels), lo=lo, hi=hi)

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              labels: Optional[Dict] = None) -> Gauge:
        return self.base.gauge(name, fn, self._merged(labels))

    def rolling(self, name: str, window: int = 512,
                labels: Optional[Dict] = None,
                clock: Optional[Callable[[], float]] = None,
                max_age_s: Optional[float] = None) -> RollingQuantile:
        return self.base.rolling(name, window, self._merged(labels),
                                 clock=clock, max_age_s=max_age_s)

    def gap(self, name: str, labels: Optional[Dict] = None) -> GapTracker:
        return self.base.gap(name, self._merged(labels))

    def ring(self, name: str, capacity: int = 16,
             labels: Optional[Dict] = None) -> RingLog:
        return self.base.ring(name, capacity, self._merged(labels))

    def family(self, name: str):
        """Only the base-family entries carrying this scope's labels."""
        return [
            (lbls, m) for lbls, m in self.base.family(name)
            if all(lbls.get(k) == v for k, v in self.scope.items())
        ]

    def unregister(self, name: str, labels: Optional[Dict] = None) -> bool:
        return self.base.unregister(name, self._merged(labels))

    def prune(self, labels: Optional[Dict] = None) -> int:
        return self.base.prune(self._merged(labels))

    def snapshot(self) -> Dict[str, Any]:
        return self.base.snapshot()

    def to_prometheus(self) -> str:
        return self.base.to_prometheus()


class MetricsHTTPEndpoint:
    """Stdlib-only HTTP exposition for a metrics plane:

    * ``GET /metrics`` — Prometheus text (``prom()``);
    * ``GET /metrics.json`` — the JSON snapshot (``json_snapshot()``);
    * ``GET /healthz`` — the health callback (503 when its ``status``
      is not "ok"/"degraded" — liveness stays cheap and JSON).

    ``port=0`` binds an ephemeral port (read ``.port`` after `start`).
    The server plumbing (SO_REUSEADDR-safe rebind on replica restart,
    bounded handler threads, deterministic shutdown) lives in
    `serve/httpbase.HTTPServerHost`, shared with the generation gateway;
    scrapes never touch the scheduler thread, and all three callbacks
    must therefore be any-thread-safe (the serve snapshots are, by
    construction)."""

    def __init__(self, *, prom: Callable[[], str],
                 json_snapshot: Optional[Callable[[], Dict]] = None,
                 health: Optional[Callable[[], Dict]] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self._prom = prom
        self._json = json_snapshot
        self._health = health
        self.host = host
        self.port = int(port)
        self._host = None

    def start(self) -> "MetricsHTTPEndpoint":
        import http.server
        import json as json_mod

        # lazy: utils.metrics is imported by the serve package, so a
        # module-level import of serve.httpbase would be circular
        from ..serve.httpbase import HTTPServerHost

        endpoint = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: D102 — scrape spam
                pass

            def _send(self, code: int, body: str, ctype: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — stdlib name
                try:
                    if self.path in ("/metrics", "/metrics/"):
                        self._send(200, endpoint._prom(),
                                   "text/plain; version=0.0.4")
                    elif self.path == "/metrics.json" and endpoint._json:
                        self._send(
                            200,
                            json_mod.dumps(endpoint._json(), sort_keys=True),
                            "application/json")
                    elif self.path == "/healthz" and endpoint._health:
                        h = endpoint._health()
                        ok = h.get("status") in ("ok", "degraded")
                        self._send(200 if ok else 503,
                                   json_mod.dumps(h, sort_keys=True),
                                   "application/json")
                    else:
                        self._send(404, "not found\n", "text/plain")
                except Exception as exc:  # noqa: BLE001 — scrape != crash
                    try:
                        self._send(500, f"{type(exc).__name__}: {exc}\n",
                                   "text/plain")
                    except Exception:
                        pass

        self._host = HTTPServerHost(
            Handler, host=self.host, port=self.port,
            thread_name="distrifuser-metrics-http",
        ).start()
        self.port = self._host.port
        return self

    def stop(self) -> None:
        if self._host is not None:
            self._host.stop()
            self._host = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def fid_between_dirs(
    root0: str,
    root1: str,
    extractor: Callable[[np.ndarray], np.ndarray],
    batch: int = 32,
) -> float:
    """FID between all images of two directories (reference cleanfid call,
    compute_metrics.py:79).  Streams images batch-by-batch — the 5k+ COCO
    result dirs never sit in memory whole; mixed image sizes within a
    directory fall back to one-image batches (the extractor resizes)."""
    import os

    from PIL import Image

    def dir_stats(root):
        names = sorted(
            f for f in os.listdir(root) if f.lower().endswith((".png", ".jpg"))
        )
        stats = RunningStatistics()
        for i in range(0, len(names), batch):
            imgs = [
                np.asarray(Image.open(os.path.join(root, n)).convert("RGB"))
                for n in names[i : i + batch]
            ]
            if len({im.shape for im in imgs}) == 1:
                stats.update(extractor(np.stack(imgs)))
            else:
                for im in imgs:
                    stats.update(extractor(im[None]))
        return stats.finalize()

    return frechet_distance(*dir_stats(root0), *dir_stats(root1))

"""Native metrics: image quality (PSNR, LPIPS, FID) and serving latency.

The reference computes PSNR via torchmetrics, LPIPS via the `lpips` package
and FID via `cleanfid` (/root/reference/scripts/compute_metrics.py:62-79) —
all of which download pretrained weights at first use.  This box has zero
egress, so the metrics are implemented natively here and the *weights* are
the only pluggable piece:

* PSNR — pure numpy, no weights.
* LPIPS — the Zhang et al. (arXiv:1801.03924) metric with the AlexNet trunk
  written out in torch (no torchvision dependency).  `lpips_weights` is a
  state-dict file holding the torchvision-AlexNet `features.*` tensors plus
  the LPIPS `lin{0..4}` 1x1 heads (the official `alex.pth` merged with the
  backbone; see `LPIPS_EXPECTED_KEYS`).
* FID — Fréchet distance between InceptionV3-pool3 feature Gaussians
  (Heusel et al., arXiv:1706.08500).  `fid_extractor` is any callable
  mapping uint8 RGB [N,H,W,3] -> features [N,D]; `load_fid_extractor` wraps
  a TorchScript file (the standard `pt_inception-2015-12-05` export used by
  pytorch-fid works offline).

The *math* (normalization, Fréchet distance incl. the sqrtm branch cuts,
feature statistics) is fully tested with random weights; only the numbers'
comparability to published tables depends on the pretrained files.

The serving metrics (`LatencyHistogram`, `Counter`) back the request
lifecycle instrumentation in `distrifuser_tpu/serve`: streaming accumulators
in the same spirit as `RunningStatistics` — bounded memory regardless of
request count, JSON-friendly snapshots for `bench.py`-style artifacts.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

# --------------------------------------------------------------------------
# PSNR
# --------------------------------------------------------------------------


def psnr(a: np.ndarray, b: np.ndarray, data_range: float = 1.0) -> float:
    """Peak signal-to-noise ratio between same-shape float images."""
    mse = float(np.mean((np.asarray(a, np.float64) - np.asarray(b, np.float64)) ** 2))
    return 10.0 * float(np.log10(data_range**2 / max(mse, 1e-12)))


# --------------------------------------------------------------------------
# LPIPS (AlexNet trunk, torch; no torchvision)
# --------------------------------------------------------------------------

# (out_ch, in_ch, kernel, stride, pad, maxpool_after)
_ALEX_CONVS = (
    (64, 3, 11, 4, 2, True),
    (192, 64, 5, 1, 2, True),
    (384, 192, 3, 1, 1, False),
    (256, 384, 3, 1, 1, False),
    (256, 256, 3, 1, 1, False),
)
# torchvision AlexNet state-dict indices of the conv layers in `features`
_ALEX_IDX = (0, 3, 6, 8, 10)

LPIPS_EXPECTED_KEYS = tuple(
    [f"features.{i}.{p}" for i in _ALEX_IDX for p in ("weight", "bias")]
    + [f"lin{i}.model.1.weight" for i in range(5)]
)

# LPIPS input scaling layer (inputs in [-1, 1])
_SHIFT = (-0.030, -0.088, -0.188)
_SCALE = (0.458, 0.448, 0.450)


class LPIPS:
    """Learned Perceptual Image Patch Similarity, AlexNet variant.

    ``state`` maps LPIPS_EXPECTED_KEYS to arrays (torch or numpy).  Use
    `LPIPS.from_file(path)` for a merged offline checkpoint, or
    `LPIPS.random(seed)` for math-level tests.
    """

    def __init__(self, state: Dict[str, np.ndarray]):
        import torch

        missing = [k for k in LPIPS_EXPECTED_KEYS if k not in state]
        if missing:
            raise KeyError(f"LPIPS state dict missing {missing[:4]}...")
        self._t = torch
        self._convs = []
        for i in _ALEX_IDX:
            w = torch.as_tensor(np.asarray(state[f"features.{i}.weight"]), dtype=torch.float32)
            b = torch.as_tensor(np.asarray(state[f"features.{i}.bias"]), dtype=torch.float32)
            self._convs.append((w, b))
        self._lins = [
            torch.as_tensor(np.asarray(state[f"lin{i}.model.1.weight"]), dtype=torch.float32)
            for i in range(5)
        ]
        self._shift = torch.tensor(_SHIFT, dtype=torch.float32).view(1, 3, 1, 1)
        self._scale = torch.tensor(_SCALE, dtype=torch.float32).view(1, 3, 1, 1)

    @classmethod
    def from_file(cls, path: str) -> "LPIPS":
        import torch

        state = torch.load(path, map_location="cpu", weights_only=True)
        return cls({k: v.numpy() for k, v in state.items()})

    @classmethod
    def random(cls, seed: int = 0) -> "LPIPS":
        r = np.random.RandomState(seed)
        state: Dict[str, np.ndarray] = {}
        for i, (co, ci, k, _, _, _) in zip(_ALEX_IDX, _ALEX_CONVS):
            state[f"features.{i}.weight"] = r.randn(co, ci, k, k).astype(np.float32) * 0.05
            state[f"features.{i}.bias"] = np.zeros(co, np.float32)
        for i, (co, _, _, _, _, _) in enumerate(_ALEX_CONVS):
            state[f"lin{i}.model.1.weight"] = np.abs(
                r.randn(1, co, 1, 1).astype(np.float32)
            )
        return cls(state)

    def _features(self, x):
        t, F = self._t, self._t.nn.functional
        x = (x - self._shift) / self._scale
        feats = []
        for (w, b), (_, _, _, stride, pad, pool) in zip(self._convs, _ALEX_CONVS):
            x = F.relu(F.conv2d(x, w, b, stride=stride, padding=pad))
            feats.append(x)
            if pool:
                x = F.max_pool2d(x, kernel_size=3, stride=2)
        return feats

    def __call__(self, a: np.ndarray, b: np.ndarray) -> float:
        """Images as float RGB [H,W,3] (or [N,H,W,3]) in [0,1]."""
        t, F = self._t, self._t.nn.functional
        with t.no_grad():
            ta = self._to_input(a)
            tb = self._to_input(b)
            total = t.zeros(ta.shape[0])
            for fa, fb, lin in zip(self._features(ta), self._features(tb), self._lins):
                na = fa / fa.norm(dim=1, keepdim=True).clamp_min(1e-10)
                nb = fb / fb.norm(dim=1, keepdim=True).clamp_min(1e-10)
                d = (na - nb) ** 2
                total = total + F.conv2d(d, lin).mean(dim=(1, 2, 3))
            return float(total.mean())

    def _to_input(self, img: np.ndarray):
        t = self._t
        x = np.asarray(img, np.float32)
        if x.ndim == 3:
            x = x[None]
        x = x * 2.0 - 1.0  # [0,1] -> [-1,1]
        return t.as_tensor(x).permute(0, 3, 1, 2)


# --------------------------------------------------------------------------
# FID
# --------------------------------------------------------------------------


def feature_statistics(features: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(mu, sigma) of a [N, D] feature matrix (rowvar-free covariance)."""
    f = np.asarray(features, np.float64)
    mu = f.mean(axis=0)
    sigma = np.cov(f, rowvar=False)
    return mu, np.atleast_2d(sigma)


class RunningStatistics:
    """Streaming (mu, sigma) accumulator — feature batches in, Gaussian out.

    FID over the reference workload (5k-30k COCO images, generate_coco.py)
    cannot hold all images in memory at once; only the [D] sum and [D, D]
    outer-product sum persist between batches."""

    def __init__(self):
        self.n = 0
        self._sum = None
        self._outer = None

    def update(self, features: np.ndarray) -> None:
        f = np.asarray(features, np.float64)
        if self._sum is None:
            self._sum = np.zeros(f.shape[1])
            self._outer = np.zeros((f.shape[1], f.shape[1]))
        self.n += f.shape[0]
        self._sum += f.sum(axis=0)
        self._outer += f.T @ f

    def finalize(self) -> Tuple[np.ndarray, np.ndarray]:
        if self.n < 2:
            raise ValueError("need at least 2 samples for covariance")
        mu = self._sum / self.n
        # unbiased covariance, matching np.cov
        sigma = (self._outer - self.n * np.outer(mu, mu)) / (self.n - 1)
        return mu, sigma


def frechet_distance(
    mu1: np.ndarray, sigma1: np.ndarray, mu2: np.ndarray, sigma2: np.ndarray,
    eps: float = 1e-6,
) -> float:
    """||mu1-mu2||^2 + tr(s1 + s2 - 2 sqrt(s1 s2)) with the standard
    numerical guards (arXiv:1706.08500 eq. 6; complex residue dropped)."""
    from scipy import linalg

    diff = np.asarray(mu1, np.float64) - np.asarray(mu2, np.float64)
    # sqrtm's `disp` kwarg is deprecated (removal in scipy 1.18); singular
    # products surface as non-finite entries, handled by the eps-offset retry
    covmean = np.atleast_2d(linalg.sqrtm(sigma1 @ sigma2))
    if not np.isfinite(covmean).all():
        offset = np.eye(sigma1.shape[0]) * eps
        covmean = linalg.sqrtm((sigma1 + offset) @ (sigma2 + offset))
    if np.iscomplexobj(covmean):
        covmean = covmean.real
    return float(diff @ diff + np.trace(sigma1) + np.trace(sigma2) - 2 * np.trace(covmean))


def fid_from_features(f0: np.ndarray, f1: np.ndarray) -> float:
    return frechet_distance(*feature_statistics(f0), *feature_statistics(f1))


def load_fid_extractor(path: str, batch: int = 32) -> Callable[[np.ndarray], np.ndarray]:
    """Wrap a TorchScript feature extractor file: uint8 RGB [N,H,W,3] -> [N,D].

    The standard offline artifact is pytorch-fid's `pt_inception-2015-12-05`
    TorchScript export (maps [N,3,299,299] in [0,1]-scaled float to pool3
    features); any module with that contract works.
    """
    import torch

    mod = torch.jit.load(path, map_location="cpu").eval()

    def extract(imgs: np.ndarray) -> np.ndarray:
        outs = []
        with torch.no_grad():
            for i in range(0, len(imgs), batch):
                x = torch.as_tensor(
                    np.asarray(imgs[i : i + batch], np.float32) / 255.0
                ).permute(0, 3, 1, 2)
                if x.shape[-2:] != (299, 299):
                    x = torch.nn.functional.interpolate(
                        x, size=(299, 299), mode="bilinear", align_corners=False
                    )
                y = mod(x)
                if isinstance(y, (list, tuple)):
                    y = y[0]
                outs.append(np.asarray(y.reshape(y.shape[0], -1)))
        return np.concatenate(outs, axis=0)

    return extract


# --------------------------------------------------------------------------
# Serving-latency metrics (streaming, bounded memory — like RunningStatistics)
# --------------------------------------------------------------------------


class LatencyHistogram:
    """Streaming latency histogram over geometric buckets.

    Serving metrics must survive millions of requests, so raw samples are
    never retained: observations land in log-spaced buckets (factor
    ``2**0.25`` per bucket ≈ 19% relative resolution — tighter than the
    2x-per-bucket Prometheus default) plus exact running count/sum/min/max.
    Quantiles interpolate within the bucket (log-midpoint), so reported
    percentiles carry the bucket's relative error, never more.

    Range: ``lo`` seconds to ``hi`` seconds; observations outside clamp to
    the boundary buckets (and still count exactly in min/max/sum).
    """

    _FACTOR = 2.0 ** 0.25

    def __init__(self, lo: float = 1e-4, hi: float = 1e3):
        assert 0 < lo < hi, (lo, hi)
        self.lo = lo
        self.hi = hi
        import math
        import threading

        self._n_buckets = (
            int(math.ceil(math.log(hi / lo) / math.log(self._FACTOR))) + 1
        )
        self._counts = np.zeros(self._n_buckets, np.int64)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        # observe() is a read-modify-write on numpy storage; the staged
        # serving pipeline observes from stage workers concurrently with
        # the scheduler thread (serve/staging.py), same reason as Counter
        self._lock = threading.Lock()

    def _bucket(self, v: float) -> int:
        import math

        if v <= self.lo:
            return 0
        i = int(math.log(v / self.lo) / math.log(self._FACTOR))
        return min(i, self._n_buckets - 1)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._counts[self._bucket(v)] += 1
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (0 <= q <= 1) by bucket interpolation,
        clamped to the exact observed [min, max]."""
        assert 0.0 <= q <= 1.0, q
        if self.count == 0:
            return float("nan")
        rank = q * (self.count - 1)
        cum = 0
        for i, c in enumerate(self._counts):
            cum += int(c)
            if cum > rank:
                # log-midpoint of bucket i, clamped to the observed range
                mid = self.lo * self._FACTOR ** (i + 0.5)
                return float(min(max(mid, self.min), self.max))
        return float(self.max)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")

    def snapshot(self) -> Dict[str, float]:
        """JSON-friendly summary (the serve artifact schema)."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


class Counter:
    """Named monotonic counters with a JSON-friendly snapshot.

    Locked: the serve layer increments from client threads (submit-path
    rejections) concurrently with the scheduler thread, and a bare
    read-modify-write would drop counts under that interleaving."""

    def __init__(self):
        import threading

        self._c: Dict[str, int] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + by

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._c.items()))


class GapTracker:
    """Busy/idle accounting for one serially-used resource.

    Backs the staged serving pipeline's **denoise-gap fraction**
    (serve/staging.py): the denoise stage owns the mesh, so the fraction
    of wall-time between its first and last invocation that the mesh sat
    idle is exactly the latency the stage overlap failed to hide — the
    measurable form of the ISSUE's "throughput ceiling moves from
    1/sum(stage) to 1/max(stage)".  `begin(t)`/`end(t)` bracket each busy
    interval (single consumer — the stage worker); `snapshot()` is
    any-thread."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._t0 = None  # current interval start
        self.first_start = None
        self.last_end = None
        self.busy_s = 0.0
        self.intervals = 0

    def begin(self, t: float) -> None:
        with self._lock:
            assert self._t0 is None, "unbalanced GapTracker.begin"
            self._t0 = float(t)
            if self.first_start is None:
                self.first_start = float(t)

    def end(self, t: float) -> None:
        with self._lock:
            assert self._t0 is not None, "GapTracker.end without begin"
            self.busy_s += float(t) - self._t0
            self.last_end = float(t)
            self._t0 = None
            self.intervals += 1

    def snapshot(self) -> Dict[str, float]:
        """JSON-friendly summary.  ``gap_fraction`` is idle/span over the
        busy envelope [first_start, last_end]; 0.0 before two intervals
        exist (a single invocation has no between-batch gap to report)."""
        with self._lock:
            if self.first_start is None or self.last_end is None:
                return {"intervals": 0, "busy_s": 0.0, "span_s": 0.0,
                        "gap_s": 0.0, "gap_fraction": 0.0}
            span = self.last_end - self.first_start
            gap = max(0.0, span - self.busy_s)
            return {
                "intervals": self.intervals,
                "busy_s": self.busy_s,
                "span_s": span,
                "gap_s": gap,
                "gap_fraction": (gap / span) if span > 0 else 0.0,
            }


class RingLog:
    """Bounded ring of recent event strings (newest last).

    Backs the serve layer's ``last_errors`` health field: a service that
    has failed a million times must still answer "what went wrong
    *lately*" in O(capacity) memory.  Entries carry a monotonically
    increasing sequence number so a reader can tell two snapshots apart
    even when the ring content looks identical.  Locked for the same
    reason as `Counter` (scheduler + watchdog + snapshot threads)."""

    def __init__(self, capacity: int = 16):
        import threading
        from collections import deque

        assert capacity >= 1, capacity
        self.capacity = capacity
        self._items = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()

    def add(self, message: str) -> None:
        with self._lock:
            self._seq += 1
            self._items.append((self._seq, str(message)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def total(self) -> int:
        """How many events were EVER added (>= len, which is bounded)."""
        with self._lock:
            return self._seq

    def snapshot(self) -> list:
        """JSON-friendly ``[{"seq": n, "message": s}, ...]``, oldest first."""
        with self._lock:
            return [{"seq": n, "message": m} for n, m in self._items]


def fid_between_dirs(
    root0: str,
    root1: str,
    extractor: Callable[[np.ndarray], np.ndarray],
    batch: int = 32,
) -> float:
    """FID between all images of two directories (reference cleanfid call,
    compute_metrics.py:79).  Streams images batch-by-batch — the 5k+ COCO
    result dirs never sit in memory whole; mixed image sizes within a
    directory fall back to one-image batches (the extractor resizes)."""
    import os

    from PIL import Image

    def dir_stats(root):
        names = sorted(
            f for f in os.listdir(root) if f.lower().endswith((".png", ".jpg"))
        )
        stats = RunningStatistics()
        for i in range(0, len(names), batch):
            imgs = [
                np.asarray(Image.open(os.path.join(root, n)).convert("RGB"))
                for n in names[i : i + batch]
            ]
            if len({im.shape for im in imgs}) == 1:
                stats.update(extractor(np.stack(imgs)))
            else:
                for im in imgs:
                    stats.update(extractor(im[None]))
        return stats.finalize()

    return frechet_distance(*dir_stats(root0), *dir_stats(root1))

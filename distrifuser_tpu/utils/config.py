"""Distributed run configuration and mesh bootstrap.

TPU-native re-design of the reference's `DistriConfig`
(/root/reference/distrifuser/utils.py:23-109).  The reference bootstraps one
NCCL process per GPU under torchrun, derives (rank, world_size), and builds
`batch_group` / `split_group` NCCL communicators.  On TPU the idiomatic shape
is single-controller SPMD: one process drives every local chip through a named
`jax.sharding.Mesh`, and the two process-group families become mesh axes
(plus a data-parallel axis the reference lacks):

* axis ``"cfg"`` (size 2 when classifier-free guidance is batch-split, else 1)
  — the reference's *split_group* direction (utils.py:91-94): ranks holding the
  same spatial patch for the two CFG branches.
* axis ``"sp"`` (size ``n_device_per_batch``) — the reference's *batch_group*
  direction (utils.py:87-90): the patch/sequence-parallel peers within one CFG
  branch.
* axis ``"dp"`` (size ``dp_degree``, default 1) — independent image groups,
  an extension over the reference's separate-job sweeps.

Device order matches the reference's rank layout (utils.py:98-109):
linear device index r maps to ``cfg_idx = r // n_device_per_batch`` and
``split_idx = r % n_device_per_batch``, so ``mesh.devices.reshape(cfg, sp)``
is row-major over the device list.

Multi-host pods: call `jax.distributed.initialize()` (via ``init_multihost``)
before constructing the config; `jax.devices()` then spans every host and the
same mesh code scales from one chip to a pod with collectives riding ICI/DCN.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .env import check_env, default_backend, is_power_of_2

# Axis names used across the whole framework.
DP_AXIS = "dp"
CFG_AXIS = "cfg"
SP_AXIS = "sp"
# USP (attn_impl="usp") factors the sp axis into two named sub-axes:
# all_to_all head-sharding rides SP_U, the exact KV ring rides SP_R.
SP_U_AXIS = "sp_u"
SP_R_AXIS = "sp_r"

SYNC_MODES = (
    "separate_gn",
    "stale_gn",
    "corrected_async_gn",
    "sync_gn",
    "full_sync",
    "no_sync",
)
PARALLELISMS = ("patch", "tensor", "naive_patch", "pipefusion")
SPLIT_SCHEMES = ("row", "col", "alternate")


def validate_step_cache_knobs(interval: int, depth: int) -> None:
    """The step-cache knob pairing contract, shared by DistriConfig and
    ServeConfig so the serve layer rejects a bad cadence at config time
    with the same rule the pipeline builder will enforce."""
    if interval < 1:
        raise ValueError(f"step_cache_interval must be >= 1, got {interval}")
    if depth < 0:
        raise ValueError(f"step_cache_depth must be >= 0, got {depth}")
    if (interval > 1) != (depth > 0):
        raise ValueError(
            "step-cache needs BOTH knobs: step_cache_interval >= 2 picks "
            "the full/shallow cadence and step_cache_depth >= 1 picks how "
            f"deep the shallow steps cut (got interval={interval}, "
            f"depth={depth})"
        )


def init_multihost(**kwargs: Any) -> None:
    """Multi-host bootstrap: the TPU analog of `torchrun` + NCCL rendezvous.

    The reference's process rendezvous is `dist.init_process_group("nccl")`
    inside DistriConfig (utils.py:40).  On a TPU pod slice the runtime already
    knows the topology; `jax.distributed.initialize` wires the hosts together
    and is a no-op on a single host.
    """
    try:
        jax.distributed.initialize(**kwargs)
    except (RuntimeError, ValueError) as e:
        # Already initialized, or single-process environment: mirror the
        # reference's graceful single-device fallback (utils.py:44-47),
        # which also prints the failure so pod misconfigurations are visible.
        print(f"jax.distributed.initialize failed ({e}); continuing single-process")


@dataclasses.dataclass
class DistriConfig:
    """All run parameters plus the device mesh.

    Field names follow the reference (utils.py:24-37) so users can port call
    sites unchanged; TPU-specific fields are appended at the end.
    ``use_cuda_graph`` is kept for API parity and exposed under its honest
    TPU name via the ``use_compiled_step`` property — on TPU the compiled
    jit step *is* the graph.
    """

    height: int = 1024
    width: int = 1024
    do_classifier_free_guidance: bool = True
    split_batch: bool = True
    warmup_steps: int = 4
    # Parity knob (utils.py:31): the reference flushes its async all-gather
    # queue every `comm_checkpoint` tensors to bound NCCL launch overhead.
    # XLA schedules and fuses collectives at compile time, so this has no
    # effect here; it is validated and carried for API compatibility.
    comm_checkpoint: int = 60
    mode: str = "corrected_async_gn"
    use_cuda_graph: bool = True  # parity alias; see use_compiled_step
    parallelism: str = "patch"
    split_scheme: str = "row"
    verbose: bool = False
    # Patch self-attention layout: "gather" assembles full KV per device
    # (reference-faithful, pp/attn.py:134-138); "ring" streams peer KV chunks
    # around the sp axis with ppermute + online softmax, shrinking per-layer
    # state from O(L) to O(L/n) — the idiomatic TPU long-context path.
    attn_impl: str = "gather"
    # attn_impl="usp" only: factor the sp axis into ulysses_degree (head-
    # sharding all_to_all sub-axis) x ring sub-axis — the xDiT-style USP
    # composition.  Must divide n_device_per_batch.
    ulysses_degree: int = 1
    # Batch the stale-phase refresh collectives into one flat exchange per
    # step (per collective kind) — the TPU-native analog of the reference's
    # `comm_checkpoint` buffer batching (utils.py:181-190).  Off by default:
    # per-layer deferred collectives give XLA's latency-hiding scheduler a
    # wider overlap window; turn on if an ICI profile shows per-collective
    # launch overhead dominating (~60 small collectives/step at 8-way).
    comm_batch: bool = False
    # Lossy compression of the stale-phase refresh payloads
    # (parallel/compress.py): "none" (default, bit-identical), "int8"
    # (symmetric per-tile int8 + fp32 scales, ~2x bf16 / ~4x fp32 byte
    # reduction), "fp8" (float8_e4m3fn payload where the jax build has it),
    # or "int8_residual" (int8 over the delta against the previous stale
    # value carried in the patch state — adjacent denoising steps are
    # near-identical, so the residual's dynamic range and hence the error
    # is far smaller).  Warmup/sync exchanges always stay full-precision;
    # GroupNorm moment exchanges never compress (tiny, cancellation-
    # sensitive).  Composes with comm_batch and the step cache.  Under
    # parallelism="pipefusion" the same knob compresses the inter-stage
    # activation ring hops instead (parallel/pipefusion.py; the residual
    # mode delta-codes against the previous step's chunk for the same
    # (patch, stage) pair); warmup mega-patch hops never compress.
    comm_compress: str = "none"
    # Quantized-weight serving (parallel/compress.py QuantizedTensor;
    # models/weights.py quantize_params): hold the DENOISER's matmul/conv
    # kernels as int8 (or fp8 where the jax build has float8_e4m3fn)
    # payloads with one fp32 scale per output-channel tile, dequantized on
    # the fly at the consuming dot/conv — XLA fuses the convert, so HBM
    # residency and weight streaming drop to ~1 byte/element.  "none"
    # (default) is bit-identical to today.  Norm/bias/embedding leaves
    # never quantize.  Composes with the step cache, comm_compress,
    # comm_batch, and the fused/stepwise loops.  PipeFusion quantizes its
    # stacked block tree BEFORE the depth split (the per-tile scales keep
    # the depth-leading layout, so shard_map slices payload and scale
    # alike and the stage-local payloads never densify); tensor
    # parallelism pre-shards its kernels eagerly and still rejects the
    # knob loudly.
    weight_quant: str = "none"
    # Same knob for the AUXILIARY models (CLIP/T5 text encoders + VAE):
    # a separate sub-knob because their tolerance budgets differ from the
    # denoiser's — the text embedding feeds every denoise step, and VAE
    # decode error lands directly in output pixels (docs/PERF.md
    # "Quantized weights" for the measured tolerances).
    weight_quant_aux: str = "none"
    # Quantized COMPUTE (ops/gemm_routing.py + ops/quant_matmul.py): how
    # the weight_quant kernels execute at their consuming matmuls.  "off"
    # pins PR-6 storage-only semantics (dequantize to the compute dtype,
    # dense matmul — bytes saved, zero FLOPs).  "auto" (default) resolves
    # per shape: env override -> the measured per-shape GEMM table ->
    # analytic default (real int8/fp8 dot_general on TPU at the MXU's 2x
    # int8 MAC rate, with dynamic per-token activation quantization and
    # the per-channel-tile scale applied after the accumulate; dequant on
    # CPU).  "dot"/"pallas" force one low-precision path (require
    # weight_quant != "none").  Changes numerics vs "off" — activations
    # quantize too; docs/PERF.md "Quantized compute & GEMM routing" pins
    # the tolerances.  No effect when weight_quant="none".
    quant_compute: str = "auto"
    # Sequence-parallel VAE decode over the sp axis (exact: fresh halo convs,
    # psum'd GroupNorm, ring mid attention — models/vae.py decode_sp).  The
    # reference decodes the full latent replicated on every rank; this is n x
    # faster with 1/n the activation HBM.  Disable to replicate the dense
    # decode instead.
    vae_sp: bool = True
    # Hybrid loop (displaced patch only): sync warmup through the per-step
    # programs + ONE fused stale-only scan.  Same numerics as the fully
    # fused loop; the big program carries one UNet body instead of two, so
    # its (remote) compile roughly halves — the resilient choice when the
    # compile service is slow.  Per-step dispatch overhead applies only to
    # the warmup steps.
    hybrid_loop: bool = False
    # Temporal step-cache (parallel/stepcache.py): after warmup, run only
    # one FULL network evaluation every `step_cache_interval` steps; the
    # other steps execute just the shallow layers and reuse the carried
    # deep-block output (UNet: mid + deepest `step_cache_depth` levels;
    # DiT/MMDiT: the deepest `step_cache_depth` transformer blocks).  Off by
    # default (interval=1, depth=0); enable BOTH knobs together.  The
    # cadence is static per compilation — two requests differing only in
    # cadence run different XLA programs (serve keys them separately).
    step_cache_interval: int = 1
    step_cache_depth: int = 0
    # PCPP partial refresh (Partially Conditioned Patch Parallelism,
    # arXiv 2412.02962; parallel/context.py): fraction 1/k of each stale
    # step's refresh payload actually moves — step i refreshes only the
    # strided row group {i%k, i%k + k, ...} of every KV slab (token rows)
    # and conv halo (columns), the rest of the carried buffer stays as the
    # previous reconstruction (at most k steps stale).  Per-step refresh
    # bytes are exactly fraction x full; GroupNorm moments always refresh
    # whole (tiny, cancellation-sensitive — same exclusion as
    # comm_compress).  1.0 (default) is the exact DistriFusion protocol.
    # Composes with comm_compress and the step cache; requires
    # parallelism="patch" (the displaced-patch families) and is mutually
    # exclusive with comm_batch (the flat batched exchange assumes
    # whole-buffer records).  The fraction is part of the compiled
    # program's identity (serve ExecKey.refresh_fraction).
    refresh_fraction: float = 1.0
    # PipeFusion only (parallelism="pipefusion"): how many token-chunks
    # ("patches") stream through the pipeline stages.  None = one per
    # stage (the minimum); more patches shrink the per-hop payload and
    # deepen the overlap at the cost of more in-flight scheduler state.
    # Part of the compiled program's identity (serve ExecKey.pipe_patches).
    pipe_patches: Optional[int] = None

    # --- TPU-specific ---
    devices: Optional[Sequence[Any]] = None  # explicit device list (tests)
    dtype: Any = None  # computation/param dtype; default bf16 on tpu, f32 on cpu
    batch_size: int = 1  # images per CFG branch (total across dp groups)
    # Data parallelism over images — beyond the reference, which runs
    # multi-image sweeps as separate torchrun jobs (generate_coco.py --split,
    # SURVEY.md §2.1 "Data parallelism: no"). dp_degree independent image
    # groups each run cfg x sp displaced-patch generation.
    dp_degree: int = 1

    # derived (filled in __post_init__)
    world_size: int = dataclasses.field(init=False, default=1)
    n_device_per_batch: int = dataclasses.field(init=False, default=1)
    mesh: Mesh = dataclasses.field(init=False, default=None)

    def __post_init__(self) -> None:
        check_env()
        if self.mode not in SYNC_MODES:
            raise ValueError(f"mode must be one of {SYNC_MODES}, got {self.mode!r}")
        if self.parallelism not in PARALLELISMS:
            raise ValueError(
                f"parallelism must be one of {PARALLELISMS}, got {self.parallelism!r}"
            )
        if self.split_scheme not in SPLIT_SCHEMES:
            raise ValueError(
                f"split_scheme must be one of {SPLIT_SCHEMES}, got {self.split_scheme!r}"
            )
        if self.attn_impl not in ("gather", "ring", "ulysses", "usp"):
            raise ValueError(
                "attn_impl must be 'gather', 'ring', 'ulysses', or 'usp' "
                f"(ulysses/usp: DiT only), got {self.attn_impl!r}"
            )
        if self.ulysses_degree < 1:
            raise ValueError(
                f"ulysses_degree must be >= 1, got {self.ulysses_degree}"
            )
        if self.ulysses_degree > 1 and self.attn_impl != "usp":
            raise ValueError(
                "ulysses_degree applies to attn_impl='usp' only (pure "
                "head-sharding is attn_impl='ulysses')"
            )
        if self.height % 8 != 0 or self.width % 8 != 0:
            # Same constraint as the reference pipelines (pipelines.py:71).
            raise ValueError("height and width must be multiples of 8")
        # lazy import: parallel.compress imports SP_AXIS from this module
        from ..parallel.compress import validate_mode, validate_weight_mode

        validate_mode(self.comm_compress)
        if (self.comm_compress != "none"
                and self.parallelism not in ("patch", "pipefusion")):
            raise ValueError(
                "comm_compress targets the displaced-patch refresh "
                "exchanges (parallelism='patch') or the PipeFusion "
                f"inter-stage activation hops; {self.parallelism!r} has "
                "no stale refresh traffic to compress"
            )
        from ..parallel.compress import validate_refresh_fraction

        validate_refresh_fraction(self.refresh_fraction)
        if self.refresh_fraction < 1.0:
            if self.parallelism != "patch":
                raise ValueError(
                    "refresh_fraction < 1 (PCPP partial refresh) rides the "
                    "displaced-patch stale-refresh exchanges "
                    f"(parallelism='patch'); {self.parallelism!r} has no "
                    "per-step refresh traffic to thin"
                )
            if self.comm_batch:
                raise ValueError(
                    "refresh_fraction < 1 and comm_batch are mutually "
                    "exclusive: the flat batched exchange defers whole-"
                    "buffer records — use the per-layer deferred path for "
                    "partial refresh"
                )
        validate_weight_mode(self.weight_quant)
        validate_weight_mode(self.weight_quant_aux)
        from ..parallel.compress import validate_quant_compute

        validate_quant_compute(self.quant_compute, self.weight_quant)
        if self.weight_quant != "none" and self.parallelism == "tensor":
            raise ValueError(
                "weight_quant quantizes whole kernels ahead of the mesh "
                "split; parallelism='tensor' pre-shards its param tree "
                "eagerly and would silently densify the payloads — keep "
                "weight_quant='none' there (PipeFusion quantizes the "
                "stacked block tree before the depth split and is fine)"
            )
        validate_step_cache_knobs(self.step_cache_interval,
                                  self.step_cache_depth)
        if self.step_cache_enabled:
            if self.parallelism not in ("patch", "pipefusion"):
                raise ValueError(
                    "step-cache rides the displaced-patch carry state "
                    "(parallelism='patch') or the PipeFusion per-stage "
                    f"delta carry; {self.parallelism!r} has no cross-step "
                    "activation carry to stash the deep cache in"
                )
            if self.hybrid_loop:
                raise ValueError(
                    "step-cache and hybrid_loop are mutually exclusive: the "
                    "cadence adds a second (shallow) body to the steady-state "
                    "scan, defeating hybrid's one-body compile-time rationale "
                    "— use the fully fused loop with the step cache"
                )
        if self.pipe_patches is not None:
            if self.parallelism != "pipefusion":
                raise ValueError(
                    "pipe_patches configures the PipeFusion patch stream "
                    f"(parallelism='pipefusion'); {self.parallelism!r} has "
                    "no pipeline to stream patches through"
                )
            if self.pipe_patches < 1:
                raise ValueError(
                    f"pipe_patches must be >= 1, got {self.pipe_patches}"
                )

        if self.devices is None:
            try:
                self.devices = tuple(jax.devices())
            except RuntimeError as e:
                # Mirror the reference's explicit failure surface
                # (utils.py:44-47) with TPU guidance instead of hanging.
                raise RuntimeError(
                    "no usable JAX backend (TPU runtime failed to initialize "
                    "and no CPU fallback is configured); set JAX_PLATFORMS=cpu "
                    f"for a CPU run. Original error: {e}"
                ) from e
        else:
            self.devices = tuple(self.devices)
        world_size = len(self.devices)
        # Reference asserts power-of-2 world size (utils.py:49).
        assert is_power_of_2(world_size), "world size must be a power of 2"
        self.world_size = world_size

        if self.dp_degree < 1:
            raise ValueError(f"dp_degree must be >= 1, got {self.dp_degree}")
        if world_size % self.dp_degree != 0:
            raise ValueError(
                f"dp_degree {self.dp_degree} must divide world size {world_size}"
            )
        if self.batch_size % self.dp_degree != 0:
            raise ValueError(
                f"batch_size {self.batch_size} must be divisible by dp_degree "
                f"{self.dp_degree}"
            )
        group = world_size // self.dp_degree  # devices per image group

        if self.do_classifier_free_guidance and self.split_batch:
            self.n_device_per_batch = max(group // 2, 1)
        else:
            self.n_device_per_batch = group

        cfg_dim = group // self.n_device_per_batch  # 2 or 1
        dev_array = np.array(self.devices, dtype=object).reshape(
            self.dp_degree, cfg_dim, self.n_device_per_batch
        )
        self.mesh = Mesh(dev_array, axis_names=(DP_AXIS, CFG_AXIS, SP_AXIS))
        if self.attn_impl == "usp" and (
            self.n_device_per_batch % self.ulysses_degree != 0
        ):
            raise ValueError(
                f"ulysses_degree {self.ulysses_degree} must divide the sp "
                f"degree {self.n_device_per_batch}"
            )

        if self.dtype is None:
            import jax.numpy as jnp

            self.dtype = jnp.bfloat16 if default_backend() == "tpu" else jnp.float32

    # ------------------------------------------------------------------
    # Rank bookkeeping, kept for parity with the reference (utils.py:98-109).
    # In single-controller SPMD there is no per-process "rank"; these map a
    # linear device index to its mesh coordinates.
    # ------------------------------------------------------------------
    def usp_mesh(self) -> Mesh:
        """The 4-axis view of the same device grid for attn_impl='usp':
        sp factored into (SP_U_AXIS, SP_R_AXIS) with |sp_u| = ulysses_degree.
        Linearized (sp_u, sp_r) coordinates equal the 3-axis mesh's sp index,
        so rank bookkeeping (batch_idx/split_idx) is unchanged."""
        u = self.ulysses_degree
        n = self.n_device_per_batch
        cfg_dim = self.group_size // n
        dev_array = np.array(self.devices, dtype=object).reshape(
            self.dp_degree, cfg_dim, u, n // u
        )
        return Mesh(
            dev_array, axis_names=(DP_AXIS, CFG_AXIS, SP_U_AXIS, SP_R_AXIS)
        )

    @property
    def use_compiled_step(self) -> bool:
        """TPU-native alias for ``use_cuda_graph``: run the denoise loop as a
        single compiled program rather than per-step dispatch."""
        return self.use_cuda_graph

    @property
    def step_cache_enabled(self) -> bool:
        """Temporal step-cache cadence active? (parallel/stepcache.py)."""
        return self.step_cache_interval > 1 and self.step_cache_depth > 0

    @property
    def group_size(self) -> int:
        """Devices per image group (world / dp_degree)."""
        return self.world_size // self.dp_degree

    @property
    def cfg_split(self) -> bool:
        return (
            self.do_classifier_free_guidance
            and self.split_batch
            and self.group_size >= 2
        )

    def batch_idx(self, rank: int) -> int:
        """CFG-branch index of linear device `rank` (utils.py:98-104).

        The reference returns ``1 - int(rank < world//2)`` i.e. ranks
        [0, n) are branch 0 (unconditional), [n, 2n) branch 1 (conditional).
        With dp_degree > 1 the mapping applies within each image group.
        """
        if self.cfg_split:
            return (rank % self.group_size) // self.n_device_per_batch
        return 0

    def split_idx(self, rank: int) -> int:
        """Patch index of linear device `rank` (utils.py:106-109)."""
        return rank % self.n_device_per_batch

    def dp_idx(self, rank: int) -> int:
        """Image-group index of linear device `rank` (dp extension)."""
        return rank // self.group_size

    # latent-space geometry -------------------------------------------------
    @property
    def latent_height(self) -> int:
        return self.height // 8

    @property
    def latent_width(self) -> int:
        return self.width // 8

    def patch_height(self, scale: int = 1) -> int:
        """Rows per device at a given down-sampling scale of the latent."""
        h = self.latent_height // scale
        n = self.n_device_per_batch
        assert h % n == 0, (
            f"latent height {h} (scale {scale}) not divisible by {n} devices"
        )
        return h // n

    @property
    def is_sp(self) -> bool:
        """True when the spatial/sequence axis is actually split."""
        return self.parallelism in ("patch", "naive_patch") and self.n_device_per_batch > 1

    @property
    def mesh_plan(self) -> str:
        """Compact mesh descriptor, e.g. ``"dp1.cfg2.sp4"`` — part of the
        serve layer's compiled-executable cache key: two configs with the
        same resolution but different meshes compile different programs."""
        cfg_dim = self.group_size // self.n_device_per_batch
        return f"dp{self.dp_degree}.cfg{cfg_dim}.sp{self.n_device_per_batch}"


# Default resolution bucket table for the serve layer: the SDXL training
# resolutions ladder up to the repo's benchmarked 2048px high-res point.
DEFAULT_BUCKETS = (
    (512, 512),
    (768, 768),
    (1024, 1024),
    (1024, 2048),
    (2048, 1024),
    (2048, 2048),
)


@dataclasses.dataclass
class ObservabilityConfig:
    """Observability knobs for the serve layer (utils/trace.py +
    utils/metrics.py; docs/OBSERVABILITY.md); lives beside ServeConfig so
    one module owns every run-shaping knob.

    * ``trace`` — request-scoped tracing on/off.  Off (the default) the
      request path executes no tracing code at all (`InferenceServer`
      holds no Tracer); on, every request records its whole life as
      spans exportable via ``server.tracer.export(path)`` /
      ``server.dump_observability(dir)`` as Perfetto-loadable JSON.
    * ``trace_capacity`` — ring bound on retained trace records (oldest
      dropped first, drop count reported): bounded memory no matter how
      long the service runs, same convention as `RingLog`.
    * ``metrics_port`` — when not None, `server.start()` serves the
      unified `MetricsRegistry` over stdlib HTTP on this port
      (``/metrics`` Prometheus text, ``/metrics.json``, ``/healthz``);
      0 binds an ephemeral port (read ``server.metrics_endpoint.port``).
    * ``metrics_host`` — bind address for that endpoint.  Loopback by
      default (a metrics plane should not be world-readable by
      accident); set "0.0.0.0" for containerized deployments whose
      scraper lives outside the host.
    * ``slo_window`` — ring size of the per-SLO-class rolling p50/p99
      windows (`RollingQuantile`) — the signal ROADMAP item 3's
      closed-loop controller reads via ``server.slo_snapshot()``.
    * ``slo_max_age_s`` — maximum age of a sample in those windows
      (server clock).  Without it the windows are time-blind: completions
      from minutes ago keep steering the SLO controller long after the
      load that produced them is gone — an idle server would pin its old
      p99 forever.  Samples older than this are excluded from every
      quantile/snapshot read (the ring still holds them; they simply stop
      counting).  None disables aging.
    """

    trace: bool = False
    trace_capacity: int = 8192
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    slo_window: int = 512
    slo_max_age_s: Optional[float] = 300.0

    def __post_init__(self) -> None:
        if self.trace_capacity < 1:
            raise ValueError(
                f"trace_capacity must be >= 1, got {self.trace_capacity}"
            )
        if self.metrics_port is not None and not (
                0 <= int(self.metrics_port) <= 65535):
            raise ValueError(
                f"metrics_port must be in [0, 65535], got {self.metrics_port}"
            )
        if not self.metrics_host:
            raise ValueError("metrics_host must be a non-empty bind address")
        if self.slo_window < 1:
            raise ValueError(
                f"slo_window must be >= 1, got {self.slo_window}"
            )
        if self.slo_max_age_s is not None and self.slo_max_age_s <= 0:
            raise ValueError(
                f"slo_max_age_s must be > 0 or None, got {self.slo_max_age_s}"
            )


@dataclasses.dataclass
class StepBatchConfig:
    """Step-level continuous batching (serve/stepbatch.py `StepBatcher`);
    lives beside ServeConfig so one module owns every run-shaping knob.

    With ``enabled``, the server's denoise loop becomes a SLOT POOL of
    per-request (latent, PRNG, step-index, timestep-schedule) state:
    between any two denoise steps the scheduler admits queued requests
    into free slots, retires finished ones, reorders the step cohort by
    deadline slack (EDF over remaining-steps x calibrated per-step
    service), and can preempt the slackest running request mid-denoise —
    its slot state parks and later resumes bit-identically.  Executors
    run step-granular (``ExecKey.exec_mode="step"``, compile-distinct
    from the fused loop).  Mutually exclusive with ``pipeline_stages``
    (the staged pipeline owns whole batches; the slot pool owns steps)
    and with pipefusion buckets (no host-driven per-step loop exists
    there).

    Knobs:
      * ``slots`` — slot-pool capacity: how many requests hold denoise
        state (latents + patch carry) resident at once.  The HBM analog
        of ``max_inflight_batches``.
      * ``step_width`` — max slots advanced per scheduling round (0 =
        all occupied).  Below ``slots`` it turns EDF from an admission
        policy into true per-round step reordering: the cohort is the
        ``step_width`` tightest-slack slots.
      * ``preview_interval`` — every K steps an occupied slot emits a
        cheap downsampled-latent preview through the request's
        ``on_progress`` callback (0 disables).  Previews are host-side
        (no new compiled program) and traced as their own span.
      * ``preview_size`` — max edge length of the preview image (the
        latent decode is downsampled to at most this).
      * ``allow_preemption`` — let an arriving request that would miss
        its deadline park the occupied slot with the MOST deadline
        slack (state resumes bit-identically when a slot frees).
      * ``preempt_margin_s`` — a victim is only parked when its own
        slack exceeds the newcomer's shortfall by this margin, so
        preemption never trades one miss for another.
      * ``step_service_prior_s`` — per-step service-time estimate used
        for EDF slack until measured steps calibrate it (the controller's
        calibrated estimate takes over when the controller is on).
      * ``export_carries`` — on server stop/drain, serialize each
        resident request's denoise carry (serve/migration.py) and fail
        its future with `CarryExportedError` carrying the snapshot, so
        the fleet router can migrate the request to a healthy replica
        and resume at the SAME step instead of re-running from step 0.
        Off, stop falls back to the plain `ServerClosedError` path
        (every completed step is wasted and re-executed on retry).
      * ``pack_align`` — when ``step_width`` truncates the cohort, fill
        it with slots that share the EDF head's compiled step signature
        (same phase / patch-state stage / shallow flag — the grouping
        the executor packs into ONE dispatch) before the rest, so the
        width the round pays for lands in the fewest compiled calls.
        The tightest-slack request always runs first regardless; off,
        the cohort is the plain ``step_width`` tightest slots.
    """

    enabled: bool = False
    slots: int = 8
    step_width: int = 0
    preview_interval: int = 0
    preview_size: int = 64
    allow_preemption: bool = True
    preempt_margin_s: float = 0.0
    step_service_prior_s: float = 0.01
    export_carries: bool = True
    pack_align: bool = True

    def __post_init__(self) -> None:
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.step_width < 0:
            raise ValueError(
                f"step_width must be >= 0 (0 = all occupied), got "
                f"{self.step_width}"
            )
        if self.preview_interval < 0:
            raise ValueError(
                f"preview_interval must be >= 0 (0 disables), got "
                f"{self.preview_interval}"
            )
        if self.preview_size < 1:
            raise ValueError(
                f"preview_size must be >= 1, got {self.preview_size}"
            )
        if self.preempt_margin_s < 0:
            raise ValueError(
                f"preempt_margin_s must be >= 0, got {self.preempt_margin_s}"
            )
        if self.step_service_prior_s <= 0:
            raise ValueError(
                "step_service_prior_s must be > 0, got "
                f"{self.step_service_prior_s}"
            )


@dataclasses.dataclass
class ResilienceConfig:
    """Failure-handling policy for the serve layer (serve/resilience.py);
    lives beside ServeConfig so one module owns every run-shaping knob.

    Retry/backoff:
      * ``max_retries`` — extra attempts per batch dispatch beyond the
        first (0 disables in-server retries).
      * ``retry_budget`` — GLOBAL retry token bucket across all requests;
        when a correlated failure storm empties it, failures surface
        immediately instead of amplifying load.
        ``retry_budget_refill_per_s`` trickles tokens back (up to the
        bucket size) so routine transient blips over days of uptime never
        permanently strip a long-lived server of retries; 0 makes the
        budget a strict lifetime cap.
      * ``backoff_*`` — exponential schedule between attempts:
        ``min(base * multiplier**n, max)`` with ± ``jitter`` fraction of
        seeded randomness (``seed``).

    Circuit breaking (per compiled-executor key):
      * ``breaker_failure_threshold`` consecutive TERMINAL dispatch
        failures (a batch whose retries were exhausted, a fatal error, a
        contract violation — never an individual retried attempt) trip
        the key's breaker OPEN; requests for it shed fast with
        `CircuitOpenError` (503-style) instead of burning queue time.
      * ``breaker_cooldown_s`` later the breaker goes HALF_OPEN and lets
        one probe batch through; success closes it, failure re-opens.

    Watchdog:
      * ``watchdog_timeout_s`` — wall-time bound on one batch execution;
        a hung batch fails with `WatchdogTimeoutError` (and is retried)
        while the scheduler thread keeps serving.  0 disables.

    Degradation ladder (OOM / compile failure, serve/resilience.py):
      * ``allow_batch_split`` — halve an OOM'd coalesced batch and retry
        the halves (bit-identical outputs: per-request seeded latents).
      * ``allow_step_cache_off`` — recompile the bucket without the
        temporal step-cache cadence.
      * ``allow_stepwise_fallback`` — swap the fused scan for the
        host-driven stepwise loop (same numerics, far smaller program).
      * ``allow_bucket_fallback`` — serve at the next smaller bucket;
        OFF by default because it changes the output-resolution contract.
      * ``max_degradations`` — cap on sticky per-key rungs.
    """

    max_retries: int = 2
    retry_budget: int = 10_000
    retry_budget_refill_per_s: float = 1.0
    backoff_base_s: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 2.0
    backoff_jitter: float = 0.1
    breaker_failure_threshold: int = 3
    breaker_cooldown_s: float = 5.0
    watchdog_timeout_s: float = 120.0
    max_degradations: int = 3
    # LRU bound on per-key resilience state (breakers, degradation rungs):
    # ExecKey space is request-controlled, so tracked keys — and the
    # health payload serializing them — must not grow one entry per
    # distinct key ever seen.  Eviction prefers closed/undegraded state.
    max_tracked_keys: int = 256
    allow_batch_split: bool = True
    # staged servers only (ServeConfig.pipeline_stages): let the ladder
    # stop pipelining an OOM-ing key's batches — overlap holds up to
    # max_inflight_batches of residency, the cheapest HBM to give back,
    # and the rung changes neither the program nor the numerics
    allow_staging_off: bool = True
    allow_step_cache_off: bool = True
    # PipeFusion keys only (ExecKey.parallelism="pipefusion"): on OOM or
    # compile failure, rebuild the key as displaced patch parallelism
    # (parallelism="patch", pipe_patches dropped) — the degraded key is
    # EXACTLY the key a patch-parallel bucket would use, so the rebuild is
    # bit-identical to a fresh patch executor for the same bucket.  This
    # replaces stepwise_fallback for pipefusion keys (the fused tick
    # schedule has no host-driven stepwise loop to fall back to; the
    # stepwise rung never applies to them).  ON by default: the
    # alternative for a failing pipefusion key is no program-level rung at
    # all.  Outputs change only as much as the two parallelization
    # strategies differ (both are tolerance-pinned against the same
    # oracles).
    allow_pipeline_off: bool = True
    allow_stepwise_fallback: bool = True
    # OOM/compile ladder rung below stepwise: rebuild the key with int8
    # quantized weights (ExecKey.weight_quant="int8") — roughly halves the
    # executor's weight HBM, the biggest single give-back on the ladder.
    # OFF by default because, unlike the rungs above it, outputs change
    # (within the pinned parity tolerances, docs/PERF.md "Quantized
    # weights"); opt in like bucket_fallback when availability under OOM
    # outranks bit-stability.
    allow_weight_quant_on: bool = False
    allow_bucket_fallback: bool = False
    last_errors_capacity: int = 16
    seed: int = 0

    def __post_init__(self) -> None:
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.retry_budget < 0:
            raise ValueError(
                f"retry_budget must be >= 0, got {self.retry_budget}"
            )
        if self.retry_budget_refill_per_s < 0:
            raise ValueError(
                "retry_budget_refill_per_s must be >= 0, got "
                f"{self.retry_budget_refill_per_s}"
            )
        if self.backoff_base_s < 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError(
                "need 0 <= backoff_base_s <= backoff_max_s, got "
                f"base={self.backoff_base_s}, max={self.backoff_max_s}"
            )
        if self.backoff_multiplier < 1.0:
            raise ValueError(
                f"backoff_multiplier must be >= 1, got {self.backoff_multiplier}"
            )
        if not (0.0 <= self.backoff_jitter < 1.0):
            raise ValueError(
                f"backoff_jitter must be in [0, 1), got {self.backoff_jitter}"
            )
        if self.breaker_failure_threshold < 1:
            raise ValueError(
                "breaker_failure_threshold must be >= 1, got "
                f"{self.breaker_failure_threshold}"
            )
        if self.breaker_cooldown_s < 0:
            raise ValueError(
                f"breaker_cooldown_s must be >= 0, got {self.breaker_cooldown_s}"
            )
        if self.max_degradations < 0:
            raise ValueError(
                f"max_degradations must be >= 0, got {self.max_degradations}"
            )
        if self.max_tracked_keys < 1:
            raise ValueError(
                f"max_tracked_keys must be >= 1, got {self.max_tracked_keys}"
            )
        if self.last_errors_capacity < 1:
            raise ValueError(
                "last_errors_capacity must be >= 1, got "
                f"{self.last_errors_capacity}"
            )


@dataclasses.dataclass
class ControllerConfig:
    """Closed-loop SLO controller policy (serve/controller.py); lives
    beside ServeConfig so one module owns every run-shaping knob.

    The controller walks an ordered *tier table* over the quality/cost
    lattice per SLO class — full quality first, then progressively
    cheaper compiled programs (step cache, wire compression, PCPP partial
    refresh, reduced steps), with admission control past the last tier —
    and dispatches each batch at the least-degraded tier whose PREDICTED
    latency holds the class's p99 target under the current queue depth
    and rolling windows (``server.slo_snapshot()``).  All decisions run
    on the injected server clock, so replayed load produces identical
    tier walks.

    Knobs:
      * ``enabled`` — off (default) keeps today's behavior exactly: no
        controller object is built, no per-dispatch work added.
      * ``slo_p99_s`` — {slo_class: p99 target seconds}.  Classes absent
        from the map use the ``"default"`` entry (one is required).
      * ``tiers`` — the tier table (serve/controller.py TierSpec list);
        () uses the built-in DEFAULT_TIERS.  Validated: unique names,
        strictly decreasing predicted-cost multipliers, first tier cost
        1.0 (the identity/full tier).
      * ``escalate_cooldown_s`` / ``retract_cooldown_s`` — minimum time
        between tier moves per class, one rung per move (the hysteresis
        that keeps a boundary load from flapping).  Retraction (back
        toward full quality) additionally requires the richer tier's
        predicted latency to hold with ``retract_margin`` headroom.
      * ``min_samples`` — observed-p99 breach checks wait for this many
        live window samples (prediction steers from the first dispatch).
      * ``service_prior_s`` — per-batch service-time estimate used until
        real completions calibrate it (``service_window`` ring).
      * ``encode_share`` — fraction of a batch's service time spent in
        text-encode: with a prompt cache attached, predicted service
        scales by ``1 - encode_share * hit_rate`` (a cache hit is a
        cheaper tier input).
    """

    enabled: bool = False
    slo_p99_s: Any = dataclasses.field(
        default_factory=lambda: {"default": 2.0}
    )
    tiers: Sequence[Any] = ()
    escalate_cooldown_s: float = 0.25
    retract_cooldown_s: float = 1.0
    retract_margin: float = 0.6
    min_samples: int = 4
    service_prior_s: float = 0.05
    service_window: int = 32
    encode_share: float = 0.0

    def __post_init__(self) -> None:
        slo = dict(self.slo_p99_s or {})
        if "default" not in slo:
            raise ValueError(
                "slo_p99_s needs a 'default' entry — classes absent from "
                "the map fall back to it"
            )
        for cls, target in slo.items():
            if float(target) <= 0:
                raise ValueError(
                    f"slo_p99_s[{cls!r}] must be > 0, got {target}"
                )
        self.slo_p99_s = {str(c): float(t) for c, t in slo.items()}
        if self.escalate_cooldown_s < 0 or self.retract_cooldown_s < 0:
            raise ValueError(
                "cooldowns must be >= 0, got escalate="
                f"{self.escalate_cooldown_s}, retract="
                f"{self.retract_cooldown_s}"
            )
        if not (0.0 < self.retract_margin <= 1.0):
            raise ValueError(
                f"retract_margin must be in (0, 1], got {self.retract_margin}"
            )
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}"
            )
        if self.service_prior_s <= 0:
            raise ValueError(
                f"service_prior_s must be > 0, got {self.service_prior_s}"
            )
        if self.service_window < 1:
            raise ValueError(
                f"service_window must be >= 1, got {self.service_window}"
            )
        if not (0.0 <= self.encode_share < 1.0):
            raise ValueError(
                f"encode_share must be in [0, 1), got {self.encode_share}"
            )
        # Lazy import, same convention as BucketTable below: the serve
        # package imports this module at load time.  Normalization owns
        # the tier-table invariants (ordering, knob validity) in ONE place.
        from ..serve.controller import normalize_tier_table

        self.tiers = normalize_tier_table(self.tiers)


@dataclasses.dataclass
class AotCacheConfig:
    """Persistent AOT executable store (serve/aotcache.py): compiled
    denoise programs serialized to a content-addressed on-disk cache so
    a fresh replica warms from deserialized executables in seconds
    instead of paying the full XLA compile campaign (the elastic-
    autoscale gate, ROADMAP item 2).

    * ``dir`` — store directory; None (default) disables the store
      entirely.  Replicas sharing a config share the directory, which
      is the point: a scale-up replica warms from an earlier replica's
      compiles.
    * ``max_bytes`` — on-disk byte budget; least-recently-LOADED
      entries evict first once a save pushes the total over.
    * ``readonly`` — CI/canary mode: loads serve, saves count a skip
      and write nothing (a test run never grows or reorders the shared
      store).
    """

    dir: Optional[str] = None
    max_bytes: int = 2 * 1024**3
    readonly: bool = False

    def __post_init__(self) -> None:
        if self.max_bytes < 1:
            raise ValueError(
                f"aot_cache.max_bytes must be >= 1, got {self.max_bytes}"
            )


@dataclasses.dataclass
class AutoscaleConfig:
    """Elastic replica-pool autoscaling (serve/autoscale.py
    `Autoscaler`, driven from the fleet housekeeping tick).

    Pressure is the fleet's step-granular utilization: (occupied step
    slots + queued/parked work, weighted by remaining steps) over the
    SERVING replicas' slot capacity — the PR-15 occupancy model the SLO
    controller already trusts.  Sustained pressure above
    ``pressure_high`` for ``up_sustain_s`` starts one stopped replica
    (warm-from-cache when an `aot_cache` store is configured);
    sustained pressure below ``pressure_low`` for ``down_sustain_s``
    drains one (bounded by ``drain_deadline_s`` — the drain rides the
    PR-17 carry-migration path, so scale-down discards no steps).
    ``cooldown_s`` separates consecutive scale actions so one load
    swing never slams the pool between bounds; ``min_replicas`` /
    ``max_replicas`` (0 = every configured slot) bound the pool.
    """

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 0
    pressure_high: float = 0.8
    pressure_low: float = 0.25
    up_sustain_s: float = 0.5
    down_sustain_s: float = 5.0
    cooldown_s: float = 5.0
    drain_deadline_s: float = 30.0

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(
                f"autoscale.min_replicas must be >= 1, got "
                f"{self.min_replicas}"
            )
        if self.max_replicas < 0:
            raise ValueError(
                "autoscale.max_replicas must be >= 0 (0 = all configured "
                f"replicas), got {self.max_replicas}"
            )
        if self.max_replicas and self.max_replicas < self.min_replicas:
            raise ValueError(
                f"autoscale.max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.pressure_high <= 0:
            raise ValueError(
                f"autoscale.pressure_high must be > 0, got "
                f"{self.pressure_high}"
            )
        if not (0.0 <= self.pressure_low < self.pressure_high):
            raise ValueError(
                "autoscale.pressure_low must be in [0, pressure_high), "
                f"got {self.pressure_low} (high={self.pressure_high})"
            )
        for name in ("up_sustain_s", "down_sustain_s", "cooldown_s"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"autoscale.{name} must be >= 0, got "
                    f"{getattr(self, name)}"
                )
        if self.drain_deadline_s <= 0:
            raise ValueError(
                "autoscale.drain_deadline_s must be > 0, got "
                f"{self.drain_deadline_s}"
            )


@dataclasses.dataclass
class FleetConfig:
    """Multi-replica fleet policy (serve/fleet.py `FleetRouter`); lives
    beside ServeConfig so one module owns every run-shaping knob.

    Routing and health scoring:
      * Each replica is scored in [0, 1] from its own serve signals
        (`Replica.health_score`): open-circuit share, SLO-controller tier
        depth, and rolling p99 vs ``p99_ref_s`` (None skips the latency
        term).  The router dispatches to the serving replica maximizing
        ``score * capacity_weight / (1 + queue_depth + inflight)`` —
        weighted least-degraded, so mixed-capability replicas
        (``Replica.capacity_weight``) are held to one SLO by steering
        load toward spare healthy capacity.

    Failover:
      * A replica's TERMINAL dispatch failure (retries exhausted,
        circuit open, watchdog, replica killed) re-dispatches the request
        onto a different replica, at most ``max_failovers`` times per
        request, each drawing from the fleet-wide `RetryBudget`
        (``failover_budget`` + ``failover_budget_refill_per_s`` — the
        same storm-bounding token bucket the in-server retry loop uses).
        A request is only ever re-dispatched after its prior replica's
        outcome is terminal, so its result is delivered exactly once and
        a dispatch that failed before completing never runs twice (a
        watchdog-ABANDONED dispatch may still finish in the background
        with its result discarded — the single-server watchdog caveat,
        unchanged).  When no replica can take the request right now it
        is PARKED in the router and re-dispatched from the housekeeping
        tick.

    Fleet-level graceful degradation (the per-key `CircuitBreaker`
    semantics lifted one level up):
      * ``health_floor`` — a serving replica whose score reaches this
        floor is auto-DRAINED (stops admitting, finishes in-flight);
        so is one that accumulates ``drain_failure_threshold``
        consecutive terminal failures.
      * ``probe_cooldown_s`` later the drained replica is probed
        half-open style: exactly one live request routes to it; success
        returns it to serving, failure re-drains and re-arms the
        cooldown.
      * ``auto_restart`` (+ ``restart_cooldown_s``) — a replica whose
        server STOPPED (e.g. the ``"replica"`` fault site's kill) is
        rebuilt and re-warmed in the background instead of probed.

    ``tick_s`` is the housekeeping cadence (auto-drain checks, probe
    arming, parked re-dispatch); 0 disables the tick thread — tests
    drive `FleetRouter.tick()` manually on an injected clock.
    """

    health_floor: float = 0.05
    drain_failure_threshold: int = 3
    probe_cooldown_s: float = 5.0
    max_failovers: int = 3
    failover_budget: int = 10_000
    failover_budget_refill_per_s: float = 1.0
    tick_s: float = 0.05
    p99_ref_s: Optional[float] = None
    auto_restart: bool = False
    restart_cooldown_s: float = 10.0
    # Elastic pool sizing between min/max bounds from the step-granular
    # occupancy model, riding drain/warm-up + carry migration so scale
    # events drop no steps — see AutoscaleConfig above and
    # docs/SERVING.md "AOT cache & elastic autoscale".  Off by default.
    autoscale: "AutoscaleConfig" = dataclasses.field(
        default_factory=AutoscaleConfig
    )

    def __post_init__(self) -> None:
        if not isinstance(self.autoscale, AutoscaleConfig):
            raise ValueError(
                "autoscale must be an AutoscaleConfig, got "
                f"{type(self.autoscale).__name__}"
            )
        if not (0.0 <= self.health_floor < 1.0):
            raise ValueError(
                f"health_floor must be in [0, 1), got {self.health_floor}"
            )
        if self.drain_failure_threshold < 1:
            raise ValueError(
                "drain_failure_threshold must be >= 1, got "
                f"{self.drain_failure_threshold}"
            )
        if self.probe_cooldown_s < 0:
            raise ValueError(
                f"probe_cooldown_s must be >= 0, got {self.probe_cooldown_s}"
            )
        if self.max_failovers < 0:
            raise ValueError(
                f"max_failovers must be >= 0, got {self.max_failovers}"
            )
        if self.failover_budget < 0:
            raise ValueError(
                f"failover_budget must be >= 0, got {self.failover_budget}"
            )
        if self.failover_budget_refill_per_s < 0:
            raise ValueError(
                "failover_budget_refill_per_s must be >= 0, got "
                f"{self.failover_budget_refill_per_s}"
            )
        if self.tick_s < 0:
            raise ValueError(f"tick_s must be >= 0, got {self.tick_s}")
        if self.p99_ref_s is not None and self.p99_ref_s <= 0:
            raise ValueError(
                f"p99_ref_s must be > 0 or None, got {self.p99_ref_s}"
            )
        if self.restart_cooldown_s < 0:
            raise ValueError(
                "restart_cooldown_s must be >= 0, got "
                f"{self.restart_cooldown_s}"
            )


@dataclasses.dataclass
class TenantConfig:
    """One tenant's share of the serve plane (serve/tenancy.py).

    * ``weight`` — relative long-run share of scheduler service under
      contention: the deficit-round-robin queue credits each tenant
      ``drr_quantum * weight`` denoise steps per round, so a weight-3
      tenant sustains 3x a weight-1 tenant's step throughput when both
      are backlogged.  Idle share is never reserved — a lone tenant gets
      the whole scheduler regardless of weight.
    * ``rate_rps`` / ``burst`` — token-bucket admission quota: sustained
      requests/second and the bucket capacity (how large an instant
      burst admits before the rate limit bites).  ``rate_rps=0`` means
      unlimited (no bucket); ``burst=0`` with a positive rate defaults
      the capacity to ``max(1, rate_rps)``.
    """

    name: str
    weight: float = 1.0
    rate_rps: float = 0.0
    burst: float = 0.0

    def __post_init__(self) -> None:
        if not isinstance(self.name, str) or not self.name:
            raise ValueError(
                f"tenant name must be a non-empty string, got {self.name!r}"
            )
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got {self.weight}"
            )
        if self.rate_rps < 0:
            raise ValueError(
                f"tenant {self.name!r}: rate_rps must be >= 0, got "
                f"{self.rate_rps}"
            )
        if self.burst < 0:
            raise ValueError(
                f"tenant {self.name!r}: burst must be >= 0, got {self.burst}"
            )
        if self.rate_rps > 0 and self.burst == 0:
            self.burst = max(1.0, float(self.rate_rps))


@dataclasses.dataclass
class GatewayConfig:
    """HTTP/SSE gateway + multi-tenancy block (serve/gateway.py,
    serve/tenancy.py; docs/SERVING.md "Gateway & multi-tenancy").

    * ``port`` — gateway listen port (0 = ephemeral); None means no
      gateway is auto-started (the tenancy knobs still apply to
      in-process submits).
    * ``tenants`` — the tenant table.  Empty (default) disables tenant
      accounting entirely: the queue stays the PR-15 pure-EDF queue.
      Non-empty activates per-tenant token buckets + weighted DRR; a
      tenant named ``default_tenant`` is implicitly added (weight 1,
      unlimited rate) if absent, so untagged requests keep working.
    * ``drr_quantum`` — denoise-step credit added to a backlogged
      tenant's deficit per round-robin pass (scaled by its weight).
      Larger quanta batch a tenant's turns together (fewer executor
      key switches); smaller quanta interleave tenants more finely.
    * ``max_events`` — per-request SSE buffer depth; a slow consumer's
      preview frames drop OLDEST beyond this (counted, never blocking
      the scheduler thread).  Terminal events are never dropped.
    * ``max_threads`` — bound on concurrent gateway handler threads
      (excess connections wait in the listen backlog).
    * ``max_requests`` — retention bound on the gateway's connection
      table; oldest FINISHED entries are evicted beyond it (pending
      entries are never evicted).
    """

    port: Optional[int] = None
    host: str = "127.0.0.1"
    tenants: Sequence["TenantConfig"] = ()
    default_tenant: str = "default"
    drr_quantum: float = 8.0
    max_events: int = 64
    max_threads: int = 8
    max_requests: int = 1024

    def __post_init__(self) -> None:
        if self.port is not None and int(self.port) < 0:
            raise ValueError(f"gateway port must be >= 0, got {self.port}")
        seen = set()
        for t in self.tenants:
            if not isinstance(t, TenantConfig):
                raise ValueError(
                    f"tenants entries must be TenantConfig, got "
                    f"{type(t).__name__}"
                )
            if t.name in seen:
                raise ValueError(f"duplicate tenant name {t.name!r}")
            seen.add(t.name)
        self.tenants = tuple(self.tenants)
        if not self.default_tenant:
            raise ValueError("default_tenant must be non-empty")
        if self.drr_quantum <= 0:
            raise ValueError(
                f"drr_quantum must be > 0, got {self.drr_quantum}"
            )
        if self.max_events < 2:
            raise ValueError(
                f"max_events must be >= 2 (room for one preview plus the "
                f"terminal event), got {self.max_events}"
            )
        if self.max_threads < 1:
            raise ValueError(
                f"max_threads must be >= 1, got {self.max_threads}"
            )
        if self.max_requests < 1:
            raise ValueError(
                f"max_requests must be >= 1, got {self.max_requests}"
            )


@dataclasses.dataclass
class ServeConfig:
    """Configuration block for ``distrifuser_tpu.serve`` (the long-lived
    inference service).  Kept here, beside DistriConfig, so one module owns
    every run-shaping knob; the serve subsystem never invents defaults.

    Admission control:
      * ``max_queue_depth`` — bound on requests waiting for a batch slot;
        submissions beyond it are rejected 429-style (QueueFullError), the
        backpressure signal for upstream load balancers.
      * ``default_ttl_s`` — per-request deadline when the caller gives none;
        a request that waits past its deadline is *rejected*, never executed
        (late work is wasted mesh time).

    Micro-batching:
      * ``max_batch_size`` — cap on requests coalesced into one invocation.
      * ``batch_window_s`` — how long the batcher lingers for compatible
        followers after the first request of a batch arrives.  0 disables
        coalescing-by-wait (batches still form from a backlog).

    Shape bucketing / compiled cache:
      * ``buckets`` — (height, width) table; a request snaps to the smallest
        bucket covering it, so the compiled program for a bucket is reused
        across nearby resolutions.
      * ``cache_capacity`` — LRU bound on resident compiled executables.
      * ``warmup_buckets`` — (height, width[, steps]) tuples compiled at
        startup so steady-state traffic never pays a request-path retrace;
        ``warmup_cfg`` is the guidance mode they compile for (match it to
        your traffic — a CFG-off service warming cfg=True executors buys
        nothing and burns an LRU slot).
    """

    max_queue_depth: int = 64
    default_ttl_s: float = 120.0
    max_batch_size: int = 8
    batch_window_s: float = 0.02
    buckets: Sequence[Sequence[int]] = DEFAULT_BUCKETS
    cache_capacity: int = 8
    warmup_buckets: Sequence[Sequence[int]] = ()
    warmup_cfg: bool = True
    default_steps: int = 50
    # Service-wide step-cache cadence (DistriConfig.step_cache_* semantics):
    # threaded into every ExecKey so a cadence change invalidates compiled
    # executors, and surfaced as the shallow-step share in serve metrics.
    # The pipeline builder behind executor_factory must construct its
    # DistriConfig with the same knobs.
    step_cache_interval: int = 1
    step_cache_depth: int = 0
    # Service-wide stale-refresh compression (DistriConfig.comm_compress
    # semantics): threaded into every ExecKey — a mode change invalidates
    # compiled executors, the same contract as the cadence knobs.  The
    # pipeline builder behind executor_factory must construct its
    # DistriConfig with the same mode.
    comm_compress: str = "none"
    # Service-wide DENOISER weight quantization (DistriConfig.weight_quant
    # semantics): threaded into every ExecKey — full-precision and
    # quantized executables are different compiled programs and coexist in
    # one fleet under distinct keys.  The pipeline builder behind
    # executor_factory must construct its DistriConfig with the same mode
    # (serve.executors.apply_key_policy force-quantizes builders that
    # ignore the field, so ladder-degraded keys work against any builder).
    # The aux-model sub-knob (weight_quant_aux) stays a builder decision:
    # it is fixed per builder, so it needs no per-key identity.
    weight_quant: str = "none"
    # Service-wide quantized-COMPUTE policy (DistriConfig.quant_compute
    # semantics): threaded into every ExecKey — storage-only ("off") and
    # compute-routed ("auto"/"dot"/"pallas") programs trace different
    # matmul paths, so they are distinct executables.  "auto" (default)
    # means the PR-9 tier ladder's int8 rungs and the fleet inherit the
    # low-precision execution path with no further serve-layer changes.
    quant_compute: str = "auto"
    # Service-wide PCPP partial-refresh fraction (DistriConfig.
    # refresh_fraction semantics): threaded into every ExecKey — the
    # strided refresh schedule is traced into the program, so a fraction
    # change is a different executable.  1.0 (default) is the exact
    # protocol; the SLO controller's partial_refresh tier overrides this
    # per dispatch.  The pipeline builder behind executor_factory must
    # construct its DistriConfig from key.refresh_fraction
    # (serve.executors.apply_key_policy forces the field pre-prepare).
    refresh_fraction: float = 1.0
    # Service-wide parallelization strategy (DistriConfig.parallelism
    # semantics, "patch" or "pipefusion"): threaded into every ExecKey —
    # patch-parallel and pipeline-parallel executors are different XLA
    # programs coexisting in one fleet under distinct keys.  The builder
    # behind executor_factory must construct its DistriConfig from
    # key.parallelism (serve.executors.apply_key_policy rejects a
    # mismatch with a typed error so the ladder can retract).
    parallelism: str = "patch"
    # With parallelism="pipefusion": DistriConfig.pipe_patches for the
    # built pipelines (None = one patch per stage), a compile-identity
    # field on ExecKey like the cadence knobs.
    pipe_patches: Optional[int] = None
    # Per-resolution-bucket strategy overrides: {(height, width):
    # "patch" | "pipefusion"} keyed by BUCKET (post-snap) resolution.
    # PipeFusion wins at high resolution and deep meshes (docs/PERF.md
    # "When pipeline beats displaced patches"); the map lets one fleet
    # serve small buckets patch-parallel and big buckets
    # pipeline-parallel simultaneously.  Buckets absent from the map use
    # the service-wide ``parallelism``.
    bucket_parallelism: Any = dataclasses.field(default_factory=dict)
    # Staged pipelining (serve/staging.py, docs/SERVING.md "Staged
    # pipelining"): overlap text-encode, denoise, and VAE-decode across
    # micro-batches so batch k+1 encodes and batch k-1 decodes in the
    # shadow of batch k's denoise.  Off by default: staged and monolithic
    # execution are bit-identical per request, but staging holds up to
    # ``max_inflight_batches`` batches of device buffers resident (the
    # HBM cap) and trades the in-line retry loop for throughput (a stage
    # failure is one terminal dispatch failure; sticky degradations —
    # including the staging_off rung — handle repeat offenders).
    pipeline_stages: bool = False
    max_inflight_batches: int = 2
    # Step-level continuous batching (serve/stepbatch.py, docs/SERVING.md
    # "Step-level continuous batching"): the denoise loop becomes a slot
    # pool of per-request state — requests join and leave the in-flight
    # denoise BETWEEN STEPS, the cohort reorders by deadline slack (EDF),
    # low-slack arrivals can preempt the slackest slot (park + bit-
    # identical resume), and occupied slots stream cheap latent previews
    # every K steps.  Executors key at ExecKey.exec_mode="step" (compile-
    # distinct).  Off by default; see StepBatchConfig above.  Mutually
    # exclusive with pipeline_stages and with pipefusion parallelism.
    step_batching: "StepBatchConfig" = dataclasses.field(
        default_factory=StepBatchConfig
    )
    # Prompt/embedding LRU cache in front of the text-encode stage
    # (serve/promptcache.py): repeated prompts — the dominant production
    # pattern — skip text-encode entirely.  Keyed by (family, tokenizer
    # hash, prompt chunk); hit rate lands in the MetricsRegistry
    # (serve_prompt_cache) and feeds the SLO controller's predicted
    # service time (ControllerConfig.encode_share).  0 (default) disables.
    prompt_cache_capacity: int = 0
    # Closed-loop SLO controller (serve/controller.py, docs/SERVING.md
    # "Closed-loop SLO control"): load-driven tier selection over the
    # quality/cost lattice per slo_class, with admission control at the
    # extreme.  Off by default — see ControllerConfig above.
    controller: "ControllerConfig" = dataclasses.field(
        default_factory=ControllerConfig
    )
    # Failure handling: retries/backoff, per-key circuit breakers, the
    # execution watchdog, and the graceful-degradation ladder — see
    # ResilienceConfig above and docs/SERVING.md "Failure modes & tuning".
    resilience: ResilienceConfig = dataclasses.field(
        default_factory=ResilienceConfig
    )
    # Tracing + metrics plane: request-scoped spans, the unified
    # MetricsRegistry HTTP endpoint, and the per-SLO-class rolling
    # latency windows — see ObservabilityConfig above and
    # docs/OBSERVABILITY.md.
    observability: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig
    )
    # HTTP/SSE gateway + per-tenant fair queuing (serve/gateway.py,
    # serve/tenancy.py): the wire front end over submit(), and the
    # tenant table that turns the request queue into token-bucket +
    # weighted-DRR fair queuing — see GatewayConfig above and
    # docs/SERVING.md "Gateway & multi-tenancy".
    gateway: GatewayConfig = dataclasses.field(default_factory=GatewayConfig)
    # Persistent AOT executable store (serve/aotcache.py): warmup and
    # ladder rebuilds consult it before compiling and populate it on
    # miss, so a fresh replica warms from serialized executables instead
    # of a compile campaign — see AotCacheConfig above and
    # docs/SERVING.md "AOT cache & elastic autoscale".  Disabled unless
    # ``aot_cache.dir`` is set.
    aot_cache: "AotCacheConfig" = dataclasses.field(
        default_factory=AotCacheConfig
    )

    def __post_init__(self) -> None:
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}"
            )
        if self.max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {self.max_batch_size}"
            )
        if self.default_ttl_s <= 0:
            raise ValueError(
                f"default_ttl_s must be > 0, got {self.default_ttl_s}"
            )
        if self.batch_window_s < 0:
            raise ValueError(
                f"batch_window_s must be >= 0, got {self.batch_window_s}"
            )
        if self.cache_capacity < 1:
            raise ValueError(
                f"cache_capacity must be >= 1, got {self.cache_capacity}"
            )
        if self.max_inflight_batches < 1:
            raise ValueError(
                "max_inflight_batches must be >= 1, got "
                f"{self.max_inflight_batches}"
            )
        if self.prompt_cache_capacity < 0:
            raise ValueError(
                "prompt_cache_capacity must be >= 0, got "
                f"{self.prompt_cache_capacity}"
            )
        validate_step_cache_knobs(self.step_cache_interval,
                                  self.step_cache_depth)
        from ..parallel.compress import (
            validate_mode,
            validate_quant_compute,
            validate_refresh_fraction,
            validate_weight_mode,
        )

        validate_mode(self.comm_compress)
        validate_refresh_fraction(self.refresh_fraction)
        validate_weight_mode(self.weight_quant)
        validate_quant_compute(self.quant_compute, self.weight_quant)
        _SERVE_PARALLELISMS = ("patch", "pipefusion")
        if self.parallelism not in _SERVE_PARALLELISMS:
            raise ValueError(
                f"ServeConfig.parallelism must be one of "
                f"{_SERVE_PARALLELISMS}, got {self.parallelism!r}"
            )
        if self.pipe_patches is not None and int(self.pipe_patches) < 1:
            raise ValueError(
                f"pipe_patches must be >= 1, got {self.pipe_patches}"
            )
        norm_bp = {}
        for hw, strat in dict(self.bucket_parallelism or {}).items():
            if strat not in _SERVE_PARALLELISMS:
                raise ValueError(
                    f"bucket_parallelism[{tuple(hw)}] must be one of "
                    f"{_SERVE_PARALLELISMS}, got {strat!r}"
                )
            norm_bp[(int(hw[0]), int(hw[1]))] = strat
        self.bucket_parallelism = norm_bp
        # BucketTable owns bucket validation and the area-major ordering
        # invariant ("smallest covering bucket" scans front-to-back) — one
        # normalization, not a copy here that could drift.  Lazy import:
        # the serve package imports this module at load time.
        from ..serve.batcher import BucketTable

        self.buckets = BucketTable(self.buckets).buckets
        for hw in self.bucket_parallelism:
            if hw not in self.buckets:
                raise ValueError(
                    f"bucket_parallelism key {hw} is not a configured "
                    f"bucket (buckets: {tuple(self.buckets)}) — the map is "
                    "keyed by post-snap bucket resolution"
                )
        warm = []
        for b in self.warmup_buckets:
            if len(b) not in (2, 3):
                raise ValueError(
                    f"warmup bucket {tuple(b)}: expected (h, w) or (h, w, steps)"
                )
            warm.append(tuple(int(x) for x in b))
        self.warmup_buckets = tuple(warm)
        if not isinstance(self.resilience, ResilienceConfig):
            raise ValueError(
                "resilience must be a ResilienceConfig, got "
                f"{type(self.resilience).__name__}"
            )
        if not isinstance(self.controller, ControllerConfig):
            raise ValueError(
                "controller must be a ControllerConfig, got "
                f"{type(self.controller).__name__}"
            )
        if not isinstance(self.step_batching, StepBatchConfig):
            raise ValueError(
                "step_batching must be a StepBatchConfig, got "
                f"{type(self.step_batching).__name__}"
            )
        if self.step_batching.enabled:
            if self.pipeline_stages:
                raise ValueError(
                    "step_batching and pipeline_stages are mutually "
                    "exclusive: the staged pipeline owns whole batches "
                    "while the slot pool owns individual steps — pick one "
                    "dispatch mode per server"
                )
            if (self.parallelism == "pipefusion"
                    or "pipefusion" in set(self.bucket_parallelism.values())):
                raise ValueError(
                    "step_batching requires patch-parallel buckets: the "
                    "PipeFusion tick pipeline has no host-driven per-step "
                    "loop to schedule at step granularity"
                )
        if not isinstance(self.observability, ObservabilityConfig):
            raise ValueError(
                "observability must be an ObservabilityConfig, got "
                f"{type(self.observability).__name__}"
            )
        if not isinstance(self.gateway, GatewayConfig):
            raise ValueError(
                "gateway must be a GatewayConfig, got "
                f"{type(self.gateway).__name__}"
            )
        if not isinstance(self.aot_cache, AotCacheConfig):
            raise ValueError(
                "aot_cache must be an AotCacheConfig, got "
                f"{type(self.aot_cache).__name__}"
            )

"""Environment checks for the TPU runtime.

TPU-native analog of the reference's CUDA/NCCL environment gate
(/root/reference/distrifuser/utils.py:6-16, `check_env`): instead of asserting
CUDA >= 11.3 and torch >= 2.2 (NCCL-inside-CUDA-graph support), we assert a JAX
new enough for `shard_map` + compiled collectives, and report which backend
(tpu / cpu) the mesh will be built on.  There is no CUDA-graph prerequisite on
TPU: a single `jax.jit`-compiled step already gives static-shape replay with
collectives fused into the program.
"""

from __future__ import annotations

import jax

# The mesh/collective code is written against jax.shard_map (>= 0.8 spelling,
# `check_vma`); utils/compat.py bridges back to the 0.4.x experimental API
# (`check_rep`).  The floor is the oldest line the compat shim covers.
_MIN_JAX = (0, 4, 30)


def _version_tuple(v: str) -> tuple[int, ...]:
    parts = []
    for piece in v.split(".")[:3]:
        digits = "".join(ch for ch in piece if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def check_env() -> None:
    """Raise if the JAX runtime is too old for the collective machinery we use."""
    if _version_tuple(jax.__version__) < _MIN_JAX:
        raise RuntimeError(
            f"distrifuser_tpu requires jax >= {'.'.join(map(str, _MIN_JAX))} "
            f"(shard_map + async collective scheduling); found {jax.__version__}"
        )
    from . import compat  # noqa: F401 -- raises ImportError if no shard_map


def default_backend() -> str:
    """Best available platform *class*: 'tpu' when TPU chips are attached
    (including through the axon PJRT plugin, whose backend registers under
    the name "axon" while lowering canonicalizes axon->tpu), else whatever
    JAX reports ('cpu', 'gpu').

    Callers key behavior (bf16 default dtype, kernel routing) on the class,
    so tunnelled TPU backends MUST normalize to 'tpu' here: before this,
    DistriConfig defaulted to float32 on the real chip — 2x the HBM bytes
    of bf16 on every activation and weight.
    """
    try:
        backend = jax.default_backend()
    except RuntimeError:
        return "cpu"
    return "tpu" if backend in ("axon", "tpu") else backend


def is_power_of_2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0

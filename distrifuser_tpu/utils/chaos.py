"""Process-global chaos hook: the installed fault plan, if any.

A stdlib-only leaf module so LOW layers can consult the hook without
importing the serving subsystem: `parallel/runner.py` checks it on every
fused-loop build (`DenoiseRunner.compiled_handle`, site
``"runner.compile"``), while the plan itself is authored with
`distrifuser_tpu.serve.faults.FaultPlan` — which re-exports these three
functions, so chaos tools keep one import surface.  Production code never
installs a plan; `active_fault_plan()` returning None is the steady
state.

The registry stores the plan opaquely (anything with a
``check(site, **kw)`` method); no fault semantics live here.
"""

from __future__ import annotations

from typing import Any, Optional

_ACTIVE_PLAN: Optional[Any] = None


def install_fault_plan(plan: Optional[Any]) -> None:
    """Install (or, with None, clear) the process-global fault plan."""
    global _ACTIVE_PLAN
    _ACTIVE_PLAN = plan


def active_fault_plan() -> Optional[Any]:
    return _ACTIVE_PLAN


def clear_fault_plan() -> None:
    install_fault_plan(None)

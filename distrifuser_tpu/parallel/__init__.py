from .collectives import all_gather, all_gather_seq, gather_cols, gather_rows, halo_exchange, psum_mean
from .context import PHASE_STALE, PHASE_SYNC, PatchContext


def __getattr__(name):
    # Lazy: runner imports models.unet, which imports parallel.context -
    # an eager re-export here would close an import cycle.
    if name in ("DenoiseRunner", "make_runner"):
        from . import runner

        return getattr(runner, name)
    if name == "PipeFusionRunner":
        from . import pipefusion

        return pipefusion.PipeFusionRunner
    if name == "DiTDenoiseRunner":
        from . import dit_sp

        return dit_sp.DiTDenoiseRunner
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

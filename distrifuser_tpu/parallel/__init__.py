from .collectives import all_gather, all_gather_seq, gather_cols, gather_rows, halo_exchange, psum_mean
from .context import PHASE_STALE, PHASE_SYNC, PatchContext
from .runner import DenoiseRunner, make_runner

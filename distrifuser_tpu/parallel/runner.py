"""The compiled denoising loop: displaced patch parallelism as one XLA program.

This is the TPU-native replacement for the reference's hot path
(SURVEY.md §3.3): where the reference replays three CUDA graphs per
counter phase (pipelines.py:147-165, distri_sdxl_unet_pp.py:74-116) around a
replicated diffusers scheduler loop, here the *entire* generation — warmup
steps, stale steps, CFG combination, scheduler — is a single `jax.jit`
program over the ("dp", "cfg", "sp") mesh:

* step 0 runs the synchronous path and *creates* the stale-activation state
  pytree (the reference needs two recording passes + buffer allocation,
  pipelines.py:131-145; here the state is just the step's return value);
* steps 1..warmup run the sync path in `lax.fori_loop` (reference: counter <=
  warmup_steps selects sync everywhere, §2.3);
* the remaining steps run the displaced path in `lax.scan`, carrying
  (latents, patch-state, scheduler-state).  Each step's refresh collectives
  produce values consumed only by the *next* iteration, so XLA's latency-
  hiding scheduler overlaps them with compute — the role of the reference's
  async NCCL all-gathers (utils.py:170-190);
* every device computes the full gathered output and runs the scheduler
  replicated, matching the reference contract (distri_sdxl_unet_pp.py:162-169).

`use_compiled_step=False` (the reference's --no_cuda_graph) swaps the single
fused program for per-step jitted calls driven from Python — same numerics,
visible per-step latency.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ..models.unet import (
    DenseDispatch,
    PatchDispatch,
    UNetConfig,
    precompute_text_kv,
    unet_forward,
)
from ..schedulers import BaseScheduler
from ..utils.config import CFG_AXIS, DP_AXIS, SP_AXIS, DistriConfig
from .collectives import gather_cols, gather_rows
from .context import (
    CARRIED_REGISTRY,
    KIND_REGISTRY,
    PHASE_STALE,
    PHASE_SYNC,
    WIRE_REGISTRY,
    PatchContext,
)
from .guidance import branch_select, combine_guidance
from .stepcache import STEPCACHE_KEY, is_shallow_at, run_cadence


class _AotProgramHandle:
    """Lazily compiled-OR-deserialized wrapper around one jitted program.

    `compiled_handle` returns an uncompiled `jax.jit` callable — XLA
    compilation happens at the first dispatch, when concrete argument
    shapes exist.  When a persistent AOT store was active for the build
    (`utils.aot.aot_activation`, installed by the serve layer's
    `ExecutorCache` around every executor build), this wrapper captures
    the (store, scope) pair at build time and intercepts that first
    dispatch: it fingerprints the program as
    ``scope | tag | abstract-value signature`` plus mesh shape and
    donation layout, loads a persisted executable when one matches
    (milliseconds), and otherwise compiles via ``lower().compile()`` and
    persists the result for the next replica.  A loaded executable IS
    the serialized compile — same XLA program, bit-identical outputs.

    Any failure in the AOT path (an executable the runtime refuses to
    serialize, an exotic call signature) falls back PERMANENTLY to the
    plain jitted callable — the store is an accelerator, never a
    correctness dependency.  Attribute access (``lower`` for
    `compiled_hlo`, etc.) delegates to the wrapped jit handle.
    """

    def __init__(self, fn, *, store, scope: str, tag: str,
                 mesh_shape: str, layout: str):
        self._fn = fn
        self._store = store
        self._scope = scope
        self._tag = tag
        self._mesh_shape = mesh_shape
        self._layout = layout
        self._executables: Dict[str, Any] = {}
        self._fallback = False

    def _signature(self, args) -> str:
        parts = []
        for leaf in jax.tree_util.tree_leaves(args):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                parts.append(f"py.{type(leaf).__name__}")
            else:
                parts.append(f"{np.dtype(dtype).name}{tuple(shape)}")
        import hashlib

        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]

    def _acquire(self, sig: str, args):
        fp = self._store.fingerprint(
            f"{self._scope}|{self._tag}|{sig}",
            mesh_shape=self._mesh_shape, layout=self._layout)
        ex = self._store.load_executable(fp)
        if ex is None:
            ex = self._fn.lower(*args).compile()
            self._store.save_executable(fp, ex)
        return ex

    def __call__(self, *args):
        if self._fallback:
            return self._fn(*args)
        sig = self._signature(args)
        ex = self._executables.get(sig)
        if ex is None:
            try:
                ex = self._acquire(sig, args)
            except Exception:
                # the jit path is always correct; the store only ever
                # saves time.  One bad interaction disables it for this
                # handle rather than risking a dispatch loop of retries.
                self._fallback = True
                return self._fn(*args)
            self._executables[sig] = ex
        return ex(*args)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _check_geometry(cfg: DistriConfig, ucfg: UNetConfig) -> None:
    if not cfg.is_sp:
        return
    depth = len(ucfg.block_out_channels) - 1  # number of downsamples
    n = cfg.n_device_per_batch
    h = cfg.latent_height
    if cfg.parallelism == "patch" or cfg.split_scheme in ("row", "alternate"):
        if h % (n * (1 << depth)) != 0:
            raise ValueError(
                f"latent height {h} must be divisible by n_devices*2^depth = "
                f"{n * (1 << depth)} for row patching"
            )
    if cfg.parallelism == "naive_patch" and cfg.split_scheme in ("col", "alternate"):
        w = cfg.latent_width
        if w % (n * (1 << depth)) != 0:
            raise ValueError(
                f"latent width {w} must be divisible by n_devices*2^depth = "
                f"{n * (1 << depth)} for column patching"
            )


class DenoiseRunner:
    """Builds and runs the compiled generation loop for one (config, model).

    Functional analog of the reference's model wrappers + pipeline prepare():
    `DistriUNetPP` / `NaivePatchUNet` behavior is selected by
    ``distri_config.parallelism`` ("patch" | "naive_patch"); tensor
    parallelism has its own dispatch (models/unet_tp.py) wired through
    ``tp_dispatch_factory``.
    """

    def __init__(
        self,
        distri_config: DistriConfig,
        unet_config: UNetConfig,
        params,
        scheduler: BaseScheduler,
        tp_dispatch_factory=None,
        param_specs=None,
    ):
        self.cfg = distri_config
        self.ucfg = unet_config
        self.params = params
        self.scheduler = scheduler
        self.tp_dispatch_factory = tp_dispatch_factory
        # Weight sharding layout: P() (replicated) for patch/naive modes —
        # the reference also replicates weights in PP mode (§2.1) — and the
        # per-leaf TP spec tree for tensor parallelism.
        self.param_specs = param_specs if param_specs is not None else P()
        if distri_config.parallelism == "tensor" and tp_dispatch_factory is None:
            raise ValueError("tensor parallelism needs a tp_dispatch_factory")
        if distri_config.parallelism == "pipefusion":
            raise ValueError(
                "pipefusion is a DiT strategy (parallel/pipefusion.py); the "
                "UNet's heterogeneous stages cannot pipeline — use "
                "parallelism='patch' here"
            )
        if distri_config.attn_impl in ("ulysses", "usp"):
            raise ValueError(
                f"attn_impl={distri_config.attn_impl!r} is a DiT strategy "
                "(parallel/dit_sp.py): head counts vary per UNet level, so "
                "the all-to-all head shard does not apply — use 'gather' or "
                "'ring' here"
            )
        n_levels = len(unet_config.block_out_channels)
        if distri_config.step_cache_enabled and not (
            1 <= distri_config.step_cache_depth < n_levels
        ):
            raise ValueError(
                f"step_cache_depth={distri_config.step_cache_depth} must be "
                f"in [1, {n_levels - 1}] for this {n_levels}-level UNet "
                "(at least one level must stay shallow)"
            )
        _check_geometry(distri_config, unet_config)
        self._compiled: Dict[Any, Any] = {}
        self._builds = 0  # fused-loop builds (cache_info observability)
        # fused-mode per-step callback target (_build_fused_callback): the
        # compiled program's io_callback reads this indirection so one
        # program serves any callback object
        self._active_callback = None

    # ------------------------------------------------------------------
    # per-device pieces (run inside shard_map)
    # ------------------------------------------------------------------

    def _branch_inputs(self, enc, added):
        """Select this device's CFG branch (cfg_split) or fold branches into
        the batch dim (single-device CFG, reference world_size==1 path)."""
        return branch_select(self.cfg, enc, added)

    def _unet_local(self, params, x_in, t, my_enc, my_added, text_kv, phase,
                    pstate, shallow=False, step=None):
        """One UNet evaluation on this device; returns (full-latent output
        for this branch-batch, new patch state).  ``shallow`` (step-cache
        cadence) skips the deep subtree and substitutes the carried deep
        feature; a non-shallow call with the cache enabled re-emits it.
        ``step`` is the traced absolute step index — the PCPP partial-
        refresh rotation schedule reads it off the context."""
        cfg, ucfg = self.cfg, self.ucfg
        if cfg.parallelism == "patch":
            ctx = PatchContext(
                n=cfg.n_device_per_batch,
                mode=cfg.mode,
                phase=phase,
                attn_impl=cfg.attn_impl,
                batch_comm=cfg.comm_batch,
                compress=cfg.comm_compress,
                refresh_fraction=cfg.refresh_fraction,
                step=step,
                state_in=pstate,
                text_kv=text_kv,
            )
            cd = cfg.step_cache_depth if cfg.step_cache_enabled else 0
            if cd:
                out_local, deep = unet_forward(
                    params, ucfg, x_in, t, my_enc,
                    dispatch=PatchDispatch(ctx), added_cond=my_added,
                    cache_depth=cd,
                    deep_cache=ctx.stale(STEPCACHE_KEY) if shallow else None,
                )
                if deep is not None:  # full step: refresh the temporal cache
                    ctx.emit(STEPCACHE_KEY, deep, kind="stepcache")
            else:
                out_local = unet_forward(
                    params, ucfg, x_in, t, my_enc,
                    dispatch=PatchDispatch(ctx), added_cond=my_added,
                )
            ctx.flush()  # batched refresh exchange (no-op unless comm_batch)
            if cd:
                # skipped layers' buffers (and, on shallow steps, the deep
                # cache) ride the carry untouched: the full/shallow bodies
                # must return one pytree structure
                ctx.carry_unconsumed()
            out = gather_rows(out_local) if cfg.is_sp else out_local
            new_state = ctx.state_out if ctx.state_out else pstate
            return out, new_state
        if cfg.parallelism == "naive_patch":
            return self._naive_patch_unet(params, x_in, t, my_enc, my_added, text_kv, pstate)
        # tensor parallelism: activations stay full-size, no patch state
        d = self.tp_dispatch_factory(text_kv)
        out = unet_forward(
            params, ucfg, x_in, t, my_enc, dispatch=d, added_cond=my_added
        )
        return out, pstate

    def _naive_patch_unet(self, params, x_in, t, my_enc, my_added, text_kv, step_or_state):
        """Naive patch parallelism (models/naive_patch_sdxl.py): slice the
        latent, run the *unmodified* UNet on the slice, gather.  No cross-
        patch ops, no state; `alternate` flips row/col by step parity
        (naive_patch_sdxl.py:157-174)."""
        cfg = self.cfg
        n = cfg.n_device_per_batch
        d = DenseDispatch(text_kv=text_kv)
        idx = lax.axis_index(SP_AXIS)

        def run_rows(x):
            h_loc = x.shape[1] // n
            xs = lax.dynamic_slice_in_dim(x, idx * h_loc, h_loc, axis=1)
            y = unet_forward(params, self.ucfg, xs, t, my_enc, dispatch=d,
                             added_cond=my_added)
            return gather_rows(y)

        def run_cols(x):
            w_loc = x.shape[2] // n
            xs = lax.dynamic_slice_in_dim(x, idx * w_loc, w_loc, axis=2)
            y = unet_forward(params, self.ucfg, xs, t, my_enc, dispatch=d,
                             added_cond=my_added)
            return gather_cols(y)

        if not cfg.is_sp:
            out = unet_forward(params, self.ucfg, x_in, t, my_enc, dispatch=d,
                               added_cond=my_added)
        elif cfg.split_scheme == "row":
            out = run_rows(x_in)
        elif cfg.split_scheme == "col":
            out = run_cols(x_in)
        else:  # alternate
            step_idx = step_or_state["step"]
            out = lax.cond(step_idx % 2 == 0, run_rows, run_cols, x_in)
        return out, step_or_state

    def _cfg_combine(self, out, gs, batch):
        return combine_guidance(self.cfg, out, gs, batch)

    def _make_step(self, phase, shallow=False):
        sched = self.scheduler

        def step(params, i, x, pstate, sstate, my_enc, my_added, text_kv, gs):
            cfg = self.cfg
            batch = x.shape[0]
            t = sched.timesteps()[i]
            x_in = sched.scale_model_input(x, i)
            if not cfg.cfg_split and cfg.do_classifier_free_guidance:
                x_in = jnp.concatenate([x_in, x_in], axis=0)
                if jnp.ndim(t):
                    # per-row step indices (packed cohort dispatch): the
                    # timestep vector folds branch-major exactly like x_in
                    t = jnp.concatenate([t, t], axis=0)
            if cfg.parallelism == "naive_patch" and cfg.split_scheme == "alternate":
                pstate = {"step": i}
            out, new_pstate = self._unet_local(
                params, x_in, t, my_enc, my_added, text_kv, phase, pstate,
                shallow=shallow, step=i,
            )
            guided = self._cfg_combine(out, gs, batch)
            x_next, sstate = sched.step(x, guided.astype(jnp.float32), i, sstate)
            return x_next, new_pstate, sstate

        return step

    # ------------------------------------------------------------------
    # the full loop (traced once per num_steps)
    # ------------------------------------------------------------------

    def _device_loop(self, params, latents, enc, added, gs, num_steps,
                     start_step=0, end_step=None):
        # end_step: exclusive stop index (diffusers denoising_end analog);
        # the schedule tables stay those of the full num_steps run, only
        # the executed range narrows.  Stateful schedulers (DPM-Solver 2M)
        # resume a split run with FRESH solver history — the first resumed
        # step is first-order, exactly as diffusers behaves across separate
        # base/refiner pipeline objects; only stateless schedulers (DDIM,
        # Euler) replay the uninterrupted trajectory bit-for-bit.
        num_steps = num_steps if end_step is None else end_step
        cfg = self.cfg
        sched = self.scheduler
        my_enc, my_added, _ = self._branch_inputs(enc, added)
        # Text KV computed once per generation (reference kv_cache at
        # counter==0, pp/attn.py:56).  TP recomputes per step with sharded
        # kernels, like the reference's TP attention (no cache there).
        text_kv = (
            {} if cfg.parallelism == "tensor" else precompute_text_kv(params, my_enc)
        )

        step_sync = self._make_step(PHASE_SYNC)
        step_stale = self._make_step(PHASE_STALE)

        x = latents.astype(jnp.float32)
        sstate = sched.init_state(x.shape)

        def state_zeros(pstate_seed):
            """The patch-state carry structure, discovered WITHOUT inlining an
            extra UNet copy: sync steps never read their input state (each
            re-emits fresh gathered activations — _unet_local returns
            ctx.state_out), so the fori carry can start as zeros of the right
            shape instead of unrolling step 0.  The unroll was a third full
            UNet body in the 50-step program — a third of the multi-ten-minute
            remote compile that cost round 2 its benchmark number."""
            _, pshape, _ = jax.eval_shape(
                step_sync, params, jnp.asarray(0), x, pstate_seed, sstate,
                my_enc, my_added, text_kv, gs,
            )
            return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), pshape)

        if cfg.step_cache_enabled:
            # Temporal step-cache cadence (parallel/stepcache.py): full sync
            # warmup, then super-steps of (interval-1) shallow + 1 full —
            # exactly two step bodies composed into the scan, the same
            # full-program shape as the sync/stale pair.  In one-phase
            # configs (full_sync / single-device patch) both cadence bodies
            # run the sync phase; the temporal deep reuse applies either way.
            one_phase = cfg.mode == "full_sync" or not cfg.is_sp
            step_full = step_sync if one_phase else step_stale
            step_shallow = self._make_step(
                PHASE_SYNC if one_phase else PHASE_STALE, shallow=True
            )
            interval = cfg.step_cache_interval
            n_sync = min(cfg.warmup_steps + 1, num_steps - start_step)

            def warm_body(i, carry):
                x, ps, ss = carry
                return step_sync(params, i, x, ps, ss, my_enc, my_added,
                                 text_kv, gs)

            x, pstate, sstate = lax.fori_loop(
                start_step, start_step + n_sync, warm_body,
                (x, state_zeros(None), sstate)
            )
            s0 = start_step + n_sync

            def run_step(carry, i, shallow):
                x, ps, ss = carry
                fn = step_shallow if shallow else step_full
                return fn(params, i, x, ps, ss, my_enc, my_added, text_kv,
                          gs)

            x, _, _ = run_cadence((x, pstate, sstate), s0, num_steps - s0,
                                  interval, run_step)
            return x

        if cfg.parallelism != "patch" or cfg.mode == "full_sync" or not cfg.is_sp:
            # one phase for everything: naive_patch / tensor / full_sync —
            # and single-device patch, where _unet_local ignores the phase
            # entirely (not is_sp), so compiling a separate stale body would
            # double the program (and the remote compile) for nothing.
            # The {} seed also covers naive_patch/alternate: step()
            # unconditionally overwrites pstate with {"step": i} there, so
            # eval_shape returns the right carry structure from any seed.

            def body(i, carry):
                x, ps, ss = carry
                return step_sync(params, i, x, ps, ss, my_enc, my_added, text_kv, gs)

            x, _, _ = lax.fori_loop(
                start_step, num_steps, body, (x, state_zeros({}), sstate)
            )
            return x

        # displaced patch parallelism: sync warmup then stale steady state.
        # counter <= warmup_steps selects sync (reference §2.3), so steps
        # 0..warmup inclusive are synchronous.  An img2img entry (start_step
        # > 0) counts its warmup from the first step actually executed.
        n_sync = min(cfg.warmup_steps + 1, num_steps - start_step)

        def sync_body(i, carry):
            x, ps, ss = carry
            return step_sync(params, i, x, ps, ss, my_enc, my_added, text_kv, gs)

        x, pstate, sstate = lax.fori_loop(
            start_step, start_step + n_sync, sync_body,
            (x, state_zeros(None), sstate)
        )

        if start_step + n_sync >= num_steps:
            # all steps synchronous (e.g. short A/B runs): a zero-length scan
            # would still compile its dead stale UNet body
            return x

        def stale_body(carry, i):
            x, ps, ss = carry
            x, ps, ss = step_stale(params, i, x, ps, ss, my_enc, my_added, text_kv, gs)
            return (x, ps, ss), None

        (x, _, _), _ = lax.scan(
            stale_body, (x, pstate, sstate),
            jnp.arange(start_step + n_sync, num_steps)
        )
        return x

    def _build(self, num_steps: int, start_step: int = 0,
               end_step: int = None):
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)

        device_loop = partial(self._device_loop, num_steps=num_steps,
                              start_step=start_step, end_step=end_step)

        # Inputs/outputs shard over the dp axis on the image-batch dim; with
        # dp_degree == 1 this degenerates to replication.
        lat_spec = P(DP_AXIS)
        enc_spec = P(None, DP_AXIS)

        def loop(params, latents, enc, added, gs):
            return shard_map(
                device_loop,
                mesh=cfg.mesh,
                in_specs=(self.param_specs, lat_spec, enc_spec, enc_spec, P()),
                out_specs=lat_spec,
                check_vma=False,
            )(params, latents, enc, added, gs)

        return jax.jit(loop)

    def _build_stale_scan(self, num_steps: int, n_start: int):
        """Fused stale steady-state ONLY (hybrid loop mode).

        The sync warmup runs through the per-step programs; their returned
        patch state enters here across the shard_map boundary in the
        stepwise layout.  The payoff is compile time: this program carries
        ONE UNet body (the stale step) where the fully fused loop carries
        two (sync fori + stale scan) — on slow remote-compile days the
        difference decides whether a fused-quality number lands inside the
        bench watchdog window, while per-step dispatch overhead still only
        applies to the handful of warmup steps.
        """
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)
        state_spec = P((DP_AXIS, CFG_AXIS, SP_AXIS))
        lat_spec = P(DP_AXIS)
        enc_spec = P(None, DP_AXIS)

        def device_scan(params, x, pstate, sstate, enc, added, gs):
            my_enc, my_added, _ = self._branch_inputs(enc, added)
            text_kv = precompute_text_kv(params, my_enc)
            step_stale = self._make_step(PHASE_STALE)

            def body(carry, i):
                x, ps, ss = carry
                return step_stale(params, i, x, ps, ss, my_enc, my_added,
                                  text_kv, gs), None

            (x, _, _), _ = lax.scan(
                body, (x, pstate, sstate), jnp.arange(n_start, num_steps)
            )
            return x

        def loop(params, x, pstate, sstate, enc, added, gs):
            return shard_map(
                device_scan,
                mesh=cfg.mesh,
                in_specs=(self.param_specs, lat_spec, state_spec, P(),
                          enc_spec, enc_spec, P()),
                out_specs=lat_spec,
                check_vma=False,
            )(params, x, pstate, sstate, enc, added, gs)

        # x and the incoming state die at this call; let XLA reuse the HBM
        return jax.jit(loop, donate_argnums=(1, 2))

    def _hybrid_dispatch(self) -> bool:
        cfg = self.cfg
        return (cfg.hybrid_loop and cfg.parallelism == "patch"
                and cfg.mode != "full_sync" and cfg.is_sp)

    def _aot_wrap(self, fn, tag: str, layout: str = "donate="):
        """Wrap a freshly built jitted program in the persistent-AOT
        handle when a store is active for this build thread (the serve
        layer's `ExecutorCache` activates one around executor builds
        when `ServeConfig.aot_cache.dir` is configured).  No store, no
        wrapper — the production default is byte-for-byte today's path."""
        from ..utils.aot import active_aot_scope

        act = active_aot_scope()
        if act is None:
            return fn
        store, scope = act
        return _AotProgramHandle(
            fn, store=store, scope=scope, tag=tag,
            mesh_shape=str(dict(self.cfg.mesh.shape)), layout=layout)

    def _ensure_stale_scan(self, num_steps: int, n_sync: int):
        skey = ("stale_scan", num_steps, n_sync)
        if skey not in self._compiled:
            self._compiled[skey] = self._aot_wrap(
                self._build_stale_scan(num_steps, n_sync),
                tag=f"stale_scan:{num_steps}:{n_sync}",
                layout="donate=1,2")
        return self._compiled[skey]

    def compiled_handle(self, num_steps: int, start_step: int = 0,
                        end_step: Optional[int] = None):
        """The jitted fused-loop callable for this signature, built (and
        cached) on first use — the handle generate() dispatches to.

        Public so callers that manage their own executable lifecycle (the
        serve layer's compiled-executable cache, warmup prefetchers) can pin
        or pre-build programs without a throwaway generate() call, and so a
        cached handle is observably the SAME object across calls instead of
        an implementation detail."""
        key = (num_steps if start_step == 0 and end_step is None
               else (num_steps, start_step, end_step))
        if key not in self._compiled:
            # Chaos hook (utils/chaos.py, plans authored in serve/faults.py):
            # the process-global fault plan, when installed, can fail this
            # build deterministically — the injection site for "the compile
            # service is down" scenarios that the serve layer's degradation
            # ladder must survive.  The registry is a stdlib-only utils
            # leaf, so this does NOT pull the serving subsystem into the
            # parallel layer; production runs never install a plan.
            from ..utils.chaos import active_fault_plan

            plan = active_fault_plan()
            if plan is not None:
                plan.check("runner.compile")
            self._builds += 1
            # AOT store hook (utils/aot.py, store in serve/aotcache.py):
            # same layering as the chaos hook above — when the serve
            # layer activated a persistent executable store around this
            # build, the handle's first dispatch deserializes a persisted
            # compile instead of paying XLA, and persists fresh compiles
            # for the next replica.  No activation = plain jit handle.
            self._compiled[key] = self._aot_wrap(
                self._build(num_steps, start_step, end_step),
                tag=f"fused:{key}")
        return self._compiled[key]

    def cache_info(self) -> Dict[str, Any]:
        """Compiled-program cache observability: which signatures are
        resident and how many builds have happened (a retrace on the request
        path shows up as builds growing after warmup)."""
        return {
            "entries": sorted(str(k) for k in self._compiled),
            "builds": self._builds,
        }

    def prepare(self, num_steps: int) -> None:
        """Pre-build exactly the program(s) generate() will dispatch to
        (pipelines.prepare delegates here).  Per-step programs build
        lazily; hybrid mode pre-builds the big stale-scan program."""
        if not self.cfg.use_compiled_step:
            return
        if self._hybrid_dispatch():
            n_sync = min(self.cfg.warmup_steps + 1, num_steps)
            if n_sync < num_steps:
                self._ensure_stale_scan(num_steps, n_sync)
            return
        # scheduler tables must match the trace (see generate()'s re-pin)
        self.scheduler.set_timesteps(num_steps)
        self.compiled_handle(num_steps)

    def _generate_hybrid(self, latents, enc, added, gs, num_steps):
        """Sync warmup via per-step programs + one fused stale-only scan."""
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)
        x = jnp.asarray(latents, jnp.float32)
        sstate = self.scheduler.init_state(x.shape)
        pstate = None
        n_sync = min(cfg.warmup_steps + 1, num_steps)

        fns = self._compiled.setdefault(("stepwise", num_steps), {})
        for i in range(n_sync):
            fkey = (PHASE_SYNC, pstate is not None, False)
            if fkey not in fns:
                fns[fkey] = self._build_stepwise(PHASE_SYNC, pstate is not None)
            x, pstate, sstate = fns[fkey](
                self.params, jnp.asarray(i), x, pstate, sstate, enc, added, gs
            )
        if n_sync >= num_steps:
            return x
        return self._ensure_stale_scan(num_steps, n_sync)(
            self.params, x, pstate, sstate, enc, added, gs
        )

    # ------------------------------------------------------------------
    # per-step (uncompiled-loop) mode: the reference's --no_cuda_graph
    # ------------------------------------------------------------------

    def _make_stepper(self, phase, with_state: bool, shallow: bool = False):
        """Un-jitted shard_map'd single step with the global-array signature.

        The patch state crosses the shard_map boundary here, so its leaves are
        laid out along ("cfg","sp") on axis 0: stale activations vary across
        CFG branches and (for the ring layout) across patch peers.
        Returns (stepper, donate_argnums): _build_stepwise jits it directly;
        _build_fused_callback embeds it in a compiled scan.
        """
        cfg = self.cfg
        # Patch-parallel state varies across CFG branches and (ring layout)
        # across sp peers -> lay leaves out along ("cfg","sp") on axis 0.
        # naive_patch's step counter / tensor's empty state are replicated.
        state_spec = (
            P((DP_AXIS, CFG_AXIS, SP_AXIS))
            if cfg.parallelism == "patch" and with_state
            else P()
        )

        def device_step(params, i, x, pstate, sstate, enc, added, gs):
            my_enc, my_added, _ = self._branch_inputs(enc, added)
            text_kv = (
                {} if cfg.parallelism == "tensor" else precompute_text_kv(params, my_enc)
            )
            step = self._make_step(phase, shallow=shallow)
            return step(params, i, x, pstate, sstate, my_enc, my_added, text_kv, gs)

        lat_spec = P(DP_AXIS)
        enc_spec = P(None, DP_AXIS)

        def stepper(params, i, x, pstate, sstate, enc, added, gs):
            return shard_map(
                device_step,
                mesh=cfg.mesh,
                in_specs=(self.param_specs, P(), lat_spec, state_spec, P(),
                          enc_spec, enc_spec, P()),
                out_specs=(
                    lat_spec,
                    P((DP_AXIS, CFG_AXIS, SP_AXIS))
                    if cfg.parallelism == "patch"
                    else state_spec,
                    P(),
                ),
                check_vma=False,
            )(params, i, x, pstate, sstate, enc, added, gs)

        # Donate the stale-state buffers: each step's input state is dead the
        # moment the refreshed state returns, so XLA reuses the HBM in place
        # (gather-layout state is O(L) per layer — the dominant allocation at
        # high resolution).  The fused loop gets this for free from the scan.
        donate = (3,) if with_state and cfg.parallelism == "patch" else ()
        return stepper, donate

    def _build_stepwise(self, phase, with_state: bool, shallow: bool = False):
        """One jitted denoising step driven from Python."""
        stepper, donate = self._make_stepper(phase, with_state, shallow)
        return jax.jit(stepper, donate_argnums=donate)

    def _stepwise_state_seed(self):
        """Initial patch-state value for a host-driven loop — mirrors what
        each parallelism mode expects before its first step."""
        cfg = self.cfg
        if cfg.parallelism == "naive_patch" and cfg.split_scheme == "alternate":
            return {"step": jnp.asarray(0)}
        return {} if cfg.parallelism != "patch" else None

    def _fire_callback(self, i, t, x):
        """Host-side trampoline for the fused-mode per-step callback
        (io_callback target).  Reads the active callback from the instance
        so one compiled program serves any callback object."""
        cb = self._active_callback
        if cb is not None:
            cb(int(i), t, x)

    def _build_fused_callback(self, num_steps: int, start_step: int = 0,
                              end_step: int = None):
        """Fused loop variant that fires per-step host callbacks.

        The reference gets diffusers' legacy callback for free in ALL modes
        because even its CUDA-graph path keeps the step loop in Python
        (pipelines.py:47-58 delegation to diffusers __call__).  Our fused
        mode has no host loop, so the callback rides
        ``jax.experimental.io_callback(ordered=True)`` inside the compiled
        program: the scan body is the shard_map'd stepwise step (stepwise
        state layout crossing the shard_map boundary each step), and after
        each step the GLOBAL latents ship to the host and reach
        ``self._active_callback``.  Both segments use ``lax.scan`` — ordered
        effects are unsupported in ``while_loop``/``fori_loop`` bodies.

        Built only when a callback is actually passed: the callback-free
        fused program keeps its in-device carry and never syncs the host.
        """
        from jax.experimental import io_callback

        cfg = self.cfg
        sched = self.scheduler
        sched.set_timesteps(num_steps)
        num_exec_end = num_steps if end_step is None else end_step
        one_phase = (cfg.parallelism != "patch" or cfg.mode == "full_sync"
                     or not cfg.is_sp)
        n_sync = (num_exec_end - start_step if one_phase
                  else min(cfg.warmup_steps + 1, num_exec_end - start_step))
        seed = self._stepwise_state_seed()
        seed_step, _ = self._make_stepper(PHASE_SYNC, seed is not None)
        sync_step, _ = self._make_stepper(PHASE_SYNC, True)
        stale_step, _ = self._make_stepper(PHASE_STALE, True)

        def loop(params, latents, enc, added, gs):
            x = latents.astype(jnp.float32)
            sstate = sched.init_state(x.shape)
            tsteps = sched.timesteps()
            # carry structure without unrolling a step: sync steps never
            # read their input state (see _device_loop.state_zeros), so
            # zeros of the eval_shape'd GLOBAL state layout start the scan
            _, pshape, _ = jax.eval_shape(
                seed_step, params, jnp.asarray(0), x, seed, sstate, enc,
                added, gs,
            )
            ps = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), pshape)

            def body_for(step_fn):
                def body(carry, i):
                    x, ps, ss = carry
                    x, ps, ss = step_fn(params, i, x, ps, ss, enc, added, gs)
                    io_callback(self._fire_callback, None, i, tsteps[i], x,
                                ordered=True)
                    return (x, ps, ss), None
                return body

            (x, ps, sstate), _ = lax.scan(
                body_for(sync_step), (x, ps, sstate),
                jnp.arange(start_step, start_step + n_sync),
            )
            if start_step + n_sync < num_exec_end:
                (x, ps, sstate), _ = lax.scan(
                    body_for(stale_step), (x, ps, sstate),
                    jnp.arange(start_step + n_sync, num_exec_end),
                )
            return x

        return jax.jit(loop)

    def _stepwise_phase(self, i: int, start_step: int, num_exec_end: int):
        """(phase, shallow) of step ``i`` in a host-driven loop — a pure
        function of the step index and config, shared by the in-place
        stepwise loop and the explicit-carry API so interleaved and
        contiguous executions replay the identical per-step programs."""
        cfg = self.cfg
        sc = cfg.step_cache_enabled
        one_phase = (cfg.parallelism != "patch" or cfg.mode == "full_sync"
                     or not cfg.is_sp)
        n_sync = (num_exec_end - start_step if one_phase and not sc
                  else min(cfg.warmup_steps + 1, num_exec_end - start_step))
        phase = (PHASE_SYNC if one_phase or i < start_step + n_sync
                 else PHASE_STALE)
        # the same shallow-first pattern run_cadence compiles
        shallow = sc and is_shallow_at(
            i, start_step + n_sync, cfg.step_cache_interval
        )
        return phase, shallow

    def _stepwise_fn(self, num_steps: int, phase, with_state: bool,
                     shallow: bool):
        """The jitted single-step program for one (phase, state, shallow)
        signature, built on first use and shared by every host-driven
        loop at this step count."""
        key = ("stepwise", num_steps)
        if key not in self._compiled:
            self._compiled[key] = {}
        fns = self._compiled[key]
        fkey = (phase, with_state, shallow)
        if fkey not in fns:
            fns[fkey] = self._build_stepwise(phase, with_state, shallow)
        return fns[fkey]

    def _generate_stepwise(self, latents, enc, added, gs, num_steps,
                           start_step=0, end_step=None, callback=None):
        """Python loop over per-step compiled calls (reference no-CUDA-graph
        path, distri_sdxl_unet_pp.py:117-193): same numerics as the fused
        loop, per-step latency visible from the host.
        ``callback(step_index, timestep, latents)`` fires after each step —
        the diffusers legacy-callback signature; only this mode has a host
        loop to fire it from."""
        num_exec_end = num_steps if end_step is None else end_step
        self.scheduler.set_timesteps(num_steps)
        x = jnp.asarray(latents, jnp.float32)
        sstate = self.scheduler.init_state(x.shape)
        pstate: Any = self._stepwise_state_seed()
        for i in range(start_step, num_exec_end):
            phase, shallow = self._stepwise_phase(i, start_step,
                                                  num_exec_end)
            fn = self._stepwise_fn(num_steps, phase, pstate is not None,
                                   shallow)
            x, pstate, sstate = fn(
                self.params, jnp.asarray(i), x, pstate, sstate, enc, added, gs
            )
            if callback is not None:
                callback(i, self.scheduler.timesteps()[i], x)
        return x

    # ------------------------------------------------------------------
    # explicit-carry stepwise API (the step-granular serve substrate)
    # ------------------------------------------------------------------

    def stepwise_carry_init(self, latents, num_steps: int):
        """Start a host-driven denoise with the carry held EXTERNALLY:
        returns ``(x, pstate, sstate)`` — exactly the state one iteration
        of `_generate_stepwise` threads.  The step-granular serve layer
        (serve/stepbatch.py) holds one carry per slot, so requests park,
        resume, and interleave between steps while each carry replays the
        identical per-step programs a contiguous solo loop runs —
        bit-identical by construction."""
        self.scheduler.set_timesteps(num_steps)
        x = jnp.asarray(latents, jnp.float32)
        return (x, self._stepwise_state_seed(),
                self.scheduler.init_state(x.shape))

    def stepwise_carry_step(self, carry, i: int, enc, added, gs,
                            num_steps: int):
        """Advance one explicit carry by exactly step ``i``; returns the
        new carry.  The per-step program is the SAME compiled fn
        `_generate_stepwise` dispatches for this (phase, state, shallow)
        signature, so solo, interleaved, and parked-then-resumed
        executions of one request are byte-identical.  ``enc`` must be
        dtype-pinned like generate() pins it (the serve executor does)."""
        x, pstate, sstate = carry
        phase, shallow = self._stepwise_phase(i, 0, num_steps)
        fn = self._stepwise_fn(num_steps, phase, pstate is not None,
                               shallow)
        return fn(self.params, jnp.asarray(i), x, pstate, sstate, enc,
                  added, gs)

    def stepwise_carry_latent(self, carry):
        """The carry's current latent [B, H/8, W/8, C] (preview + decode
        input) — does not consume the carry."""
        return carry[0]

    # -- packed cohort rows (serve/executors.py step_run; parallel/rowpack) --

    def stepwise_rows_supported(self) -> bool:
        """Whether this config's per-step program accepts per-row step
        indices (the packed cohort dispatch).  Gated off — falling back to
        sequential per-slot dispatch — where a vector step index would
        change the traced program's CONTROL FLOW or couple batch rows:
        naive-alternate's row/col parity cond, the PCPP partial-refresh
        rotation, lossy refresh compression (per-tensor scales couple
        rows), and dp sharding (the replicated [B] index does not shard
        with the dp-split batch)."""
        cfg = self.cfg
        return (cfg.dp_degree == 1
                and cfg.refresh_fraction >= 1
                and cfg.comm_compress == "none"
                and not (cfg.parallelism == "naive_patch"
                         and cfg.split_scheme == "alternate"))

    def stepwise_carry_signature(self, carry, i: int, num_steps: int):
        """Hashable compiled-program identity of advancing ``carry`` by
        step ``i``: carries sharing a signature run the SAME per-step
        program and may pack into one dispatch's batch rows."""
        phase, shallow = self._stepwise_phase(i, 0, num_steps)
        return ("unet", phase, carry[1] is not None, shallow, num_steps)

    def stepwise_carry_rows_axes(self, carry, enc, added, num_steps: int):
        """Per-leaf batch-axis plan (parallel/rowpack.py) for this
        carry's structure, discovered by shape comparison at two widths:
        latents/scheduler state analytically, the patch-state tree via
        ``jax.eval_shape`` of the sync stepper (which CREATES the state
        structure from the seed — no layout table to drift)."""
        from . import rowpack

        x, pstate, sstate = carry
        w = x.shape[0]

        def widen(leaf, axis, k):
            shape = list(jnp.shape(leaf))
            shape[axis] = shape[axis] * k
            return jax.ShapeDtypeStruct(tuple(shape), jnp.result_type(leaf))

        def carry_shapes(k):
            xs = widen(x, 0, k)
            ss = self.scheduler.init_state((w * k,) + x.shape[1:])
            if pstate is None or not jax.tree_util.tree_leaves(pstate):
                return (xs, pstate, ss)
            seed = self._stepwise_state_seed()
            stepper, _ = self._make_stepper(PHASE_SYNC, seed is not None)
            enc_k = jax.tree.map(lambda l: widen(l, 1, k), enc)
            added_k = (None if added is None
                       else jax.tree.map(lambda l: widen(l, 1, k), added))
            _, pshape, _ = jax.eval_shape(
                stepper, self.params, jnp.asarray(0), xs, seed, ss, enc_k,
                added_k, jnp.asarray(1.0, jnp.float32),
            )
            return (xs, pshape, ss)

        return rowpack.axes_from_shapes(carry_shapes(1), carry_shapes(2))

    def stepwise_carry_step_rows(self, carry, i_rows, enc, added, gs_rows,
                                 num_steps: int):
        """Advance a PACKED carry: row ``r`` moves by exactly step
        ``i_rows[r]`` at guidance ``gs_rows[r]``.  All rows must share
        one compiled signature (the executor groups by
        `stepwise_carry_signature`); the dispatched program is the SAME
        jitted `_stepwise_fn` the solo path uses — the step index and
        guidance scale are traced inputs, so the [B]-shaped call is just
        another cached trace of the same program and each row's numerics
        are byte-identical to its solo dispatch (batch-row independence,
        pinned in tests/test_stepbatch.py)."""
        x, pstate, sstate = carry
        sigs = {self._stepwise_phase(int(i), 0, num_steps)
                for i in i_rows}
        if len(sigs) != 1:
            raise ValueError(
                f"packed rows span {len(sigs)} step signatures: {sigs}"
            )
        (phase, shallow), = sigs
        fn = self._stepwise_fn(num_steps, phase, pstate is not None,
                               shallow)
        return fn(self.params, jnp.asarray(list(i_rows)), x, pstate,
                  sstate, enc, added,
                  jnp.asarray(list(gs_rows), jnp.float32))

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def comm_volume_report(self, batch_size: int = None, text_len: int = 77,
                           *, per_phase: bool = False):
        """Per-layer-type stale-buffer element counts.

        Parity with the reference's verbose buffer stats at create_buffer
        time (utils.py:152-158): reports how many elements per device the
        displaced-patch state holds, grouped by layer type.  Computed with
        jax.eval_shape — no device work.

        ``per_phase=True`` returns the step-cache-aware breakdown instead:
        ``{"phases": {"sync"|"stale"|"shallow": {kind: fresh-exchange
        elements}}, "bytes": {phase: {kind: wire bytes}}, "flops": {...}}``
        — per phase, only the state a step FRESHLY exchanges is counted
        (carried-through deep buffers are excluded via CARRIED_REGISTRY).
        ``bytes`` is wire-accurate: compressed refresh payloads count their
        int8/fp8 elements + fp32 scales (context.WIRE_REGISTRY, populated
        at emit time by the exchanging op itself), wire-free local carries
        (the step-cache deep feature, residual own-rows) count zero, and
        everything else defaults to elements x dtype itemsize — so
        warmup/sync bytes are identical across comm_compress modes by
        construction, and the stale-phase reduction is a checked number.
        ``flops`` estimates the full-vs-shallow step cost via XLA cost
        analysis (``_flop_estimate``), so the cache's compute and comm
        savings are inspectable without a chip.
        """
        cfg = self.cfg
        if per_phase:
            return self._comm_volume_per_phase(batch_size, text_len)
        if cfg.parallelism != "patch" or not cfg.is_sp:
            return {}
        self.scheduler.set_timesteps(2)
        step = self._make_step(PHASE_SYNC)

        def one_step(params, latents, enc, added, gs):
            my_enc, my_added, _ = self._branch_inputs(enc, added)
            text_kv = (
                {} if cfg.parallelism == "tensor" else precompute_text_kv(params, my_enc)
            )
            sstate = self.scheduler.init_state(latents.shape)
            _, pstate, _ = step(
                params, 0, latents.astype(jnp.float32), None, sstate,
                my_enc, my_added, text_kv, gs,
            )
            return pstate

        lat, enc, added, gs = self._abstract_inputs(
            batch_size, text_len, per_group=True
        )

        shapes = jax.eval_shape(
            lambda p, l, e, a, g: shard_map(
                one_step, mesh=cfg.mesh,
                in_specs=(self.param_specs, P(), P(), P(), P()),
                out_specs=P(), check_vma=False,
            )(p, l, e, a, g),
            self.params, lat, enc, added, gs,
        )

        # The eval_shape trace above just populated KIND_REGISTRY: each op
        # declares its own kind at emit time, so classification never falls
        # back to name heuristics.
        report: Dict[str, int] = {}
        for name, s in shapes.items():
            t = KIND_REGISTRY.get(name, "other")
            report[t] = report.get(t, 0) + int(np.prod(s.shape))
        if cfg.verbose:
            total = sum(report.values())
            print(
                f"Stale-state buffers: {total / 1e6:.3f}M elements over "
                f"{len(shapes)} tensors per device."
            )
            for t, numel in sorted(report.items()):
                print(f"  {t}: {numel / 1e6:.3f}M elements")
        return report

    def _comm_volume_per_phase(self, batch_size: int = None,
                               text_len: int = 77) -> Dict[str, Any]:
        """Step-cache-aware comm/compute breakdown (comm_volume_report
        per_phase=True).  Each phase is traced with jax.eval_shape through
        the same step closures the loops run; a phase's count is the
        elements it freshly exchanges (state it merely carries — skipped
        deep layers, the deep cache on shallow steps — is subtracted via
        CARRIED_REGISTRY)."""
        cfg = self.cfg
        if cfg.parallelism != "patch":
            return {"phases": {}, "bytes": {}, "flops": None}
        self.scheduler.set_timesteps(2)
        lat, enc, added, gs = self._abstract_inputs(
            batch_size, text_len, per_group=True
        )
        # kinds that live in the carry without ever touching the wire
        wire_free = ("stepcache", "local")

        def trace(step, pstate_in):
            has_state = pstate_in is not None

            def one_step(params, latents, enc, added, gs, *maybe_state):
                my_enc, my_added, _ = self._branch_inputs(enc, added)
                text_kv = precompute_text_kv(params, my_enc)
                sstate = self.scheduler.init_state(latents.shape)
                _, pout, _ = step(
                    params, 1, latents.astype(jnp.float32),
                    maybe_state[0] if has_state else None, sstate,
                    my_enc, my_added, text_kv, gs,
                )
                return pout

            args = (self.params, lat, enc, added, gs)
            specs = (self.param_specs, P(), P(), P(), P())
            if has_state:
                args += (pstate_in,)
                specs += (P(),)
            CARRIED_REGISTRY.clear()
            WIRE_REGISTRY.clear()
            shapes = jax.eval_shape(
                lambda *a: shard_map(
                    one_step, mesh=cfg.mesh, in_specs=specs,
                    out_specs=P(), check_vma=False,
                )(*a),
                *args,
            )
            carried = set(CARRIED_REGISTRY)
            wire = dict(WIRE_REGISTRY)
            if shapes is None:  # stateless step (single device, cache off)
                shapes = {}
            report: Dict[str, int] = {}
            nbytes: Dict[str, int] = {}
            for name, s in shapes.items():
                if name in carried:
                    continue
                t = KIND_REGISTRY.get(name, "other")
                numel = int(np.prod(s.shape))
                report[t] = report.get(t, 0) + numel
                if name in wire:
                    b = wire[name]
                elif t in wire_free:
                    b = 0
                else:
                    b = numel * jnp.dtype(s.dtype).itemsize
                nbytes[t] = nbytes.get(t, 0) + b
            return shapes, report, nbytes

        phases: Dict[str, Dict[str, int]] = {}
        bytes_: Dict[str, Dict[str, int]] = {}
        sync_shapes, phases["sync"], bytes_["sync"] = trace(
            self._make_step(PHASE_SYNC), None
        )
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        if not one_phase:
            _, phases["stale"], bytes_["stale"] = trace(
                self._make_step(PHASE_STALE), sync_shapes
            )
        if cfg.step_cache_enabled:
            steady = PHASE_SYNC if one_phase else PHASE_STALE
            _, phases["shallow"], bytes_["shallow"] = trace(
                self._make_step(steady, shallow=True), sync_shapes
            )
        return {"phases": phases, "bytes": bytes_,
                # PCPP key: the stale/shallow byte rows above are already
                # fraction-aware (WIRE_REGISTRY entries register the
                # strided subset the emit actually gathers) — this records
                # WHICH fraction priced them, so comm_plan and the benches
                # can label the reduction
                "refresh_fraction": cfg.refresh_fraction,
                "flops": self._flop_estimate(batch_size, text_len)}

    def _flop_estimate(self, batch_size: int = None,
                       text_len: int = 77) -> Optional[Dict[str, float]]:
        """{"full", "shallow", "shallow_ratio"}: estimated FLOPs of one
        steady-state denoise step vs its shallow-cadence counterpart, from
        XLA cost analysis of the lowered per-step programs (abstract inputs
        — no execution, no chip).  None when the cache is off or the
        backend's cost model is unavailable."""
        cfg = self.cfg
        if not cfg.step_cache_enabled:
            return None
        lat, enc, added, gs = self._abstract_inputs(batch_size, text_len)
        self.scheduler.set_timesteps(2)
        sstate = self.scheduler.init_state(lat.shape)
        seed_step, _ = self._make_stepper(PHASE_SYNC, False)
        _, pshape, _ = jax.eval_shape(
            seed_step, self.params, jnp.asarray(1), lat, None, sstate, enc,
            added, gs,
        )
        steady = (PHASE_SYNC if cfg.mode == "full_sync" or not cfg.is_sp
                  else PHASE_STALE)
        out: Dict[str, float] = {}
        for name, shallow in (("full", False), ("shallow", True)):
            stepper, _ = self._make_stepper(steady, True, shallow)
            try:
                ca = jax.jit(stepper).lower(
                    self.params, jnp.asarray(1), lat, pshape, sstate, enc,
                    added, gs,
                ).cost_analysis()
                if not isinstance(ca, dict):  # older API: list per device
                    ca = ca[0]
                out[name] = float(ca["flops"])
            except Exception:
                return None
        if out["full"] > 0:
            out["shallow_ratio"] = out["shallow"] / out["full"]
        return out

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def _abstract_inputs(self, batch_size: int = None, text_len: int = 77,
                         *, per_group: bool = False):
        """ShapeDtypeStructs for (lat, enc, added, gs) — the single source of
        truth for the abstract program signature, shared by
        comm_volume_report and compiled_hlo so the two observability paths
        can never trace different programs (they once drifted on the enc
        dtype).  generate() casts its real inputs to the same dtypes, so a
        program lowered from these specs is the program that runs.

        ``per_group=False`` gives the global-batch signature of the fused
        loop (batch splits over the dp axis inside shard_map);
        ``per_group=True`` gives the per-image-group shapes
        comm_volume_report feeds its replicated-spec trace."""
        cfg = self.cfg
        b = cfg.batch_size if batch_size is None else batch_size
        if b % cfg.dp_degree != 0:
            raise ValueError(
                f"batch_size {b} not divisible by dp_degree {cfg.dp_degree}"
            )
        if per_group:
            b = b // cfg.dp_degree
        n_br = 2 if cfg.do_classifier_free_guidance else 1
        lat = jax.ShapeDtypeStruct(
            (b, cfg.latent_height, cfg.latent_width, self.ucfg.in_channels),
            jnp.float32,
        )
        enc = jax.ShapeDtypeStruct(
            (n_br, b, text_len, self.ucfg.cross_attention_dim), cfg.dtype
        )
        added = None
        if self.ucfg.addition_embed_type == "text_time":
            emb = (
                self.ucfg.projection_class_embeddings_input_dim
                - 6 * self.ucfg.addition_time_embed_dim
            )
            added = {
                "text_embeds": jax.ShapeDtypeStruct((n_br, b, emb), cfg.dtype),
                "time_ids": jax.ShapeDtypeStruct((n_br, b, 6), jnp.float32),
            }
        gs = jax.ShapeDtypeStruct((), jnp.float32)
        return lat, enc, added, gs

    def compiled_hlo(self, num_inference_steps: int = 4, batch_size: int = None,
                     text_len: int = 77) -> str:
        """Optimized-HLO text of the fused loop (abstract inputs, no device
        execution beyond compilation).  Feed to utils/overlap.py to verify
        the refresh collectives stay carry-only on this backend."""
        lat, enc, added, gs = self._abstract_inputs(batch_size, text_len)
        # seed the jit cache: a following generate() with the same step count
        # reuses this program instead of re-compiling (jit caches by shape)
        fn = self.compiled_handle(num_inference_steps)
        return fn.lower(self.params, lat, enc, added, gs).compile().as_text()

    def generate(
        self,
        latents,
        prompt_embeds,
        *,
        guidance_scale: float = 5.0,
        num_inference_steps: int = 50,
        added_cond: Optional[Dict[str, Any]] = None,
        start_step: int = 0,
        end_step: Optional[int] = None,
        callback=None,
    ):
        """Run the denoising loop.

        ``latents``: [B, H/8, W/8, C] initial noise **already scaled** by
        ``scheduler.init_noise_sigma`` — or, with ``start_step > 0``
        (img2img), a clean latent noised to that schedule point via
        ``scheduler.add_noise``.  ``prompt_embeds``: [n_branches, B, L, C]
        with branch 0 = unconditional (reference rank layout,
        utils.py:98-104).  Returns the denoised latent [B, H/8, W/8, C].
        """
        added = added_cond if added_cond is not None else None
        if jax.process_count() > 1:
            # Multi-controller (pod) mode: host-local numpy must become
            # global replicated arrays before entering the jitted program —
            # the analog of every torchrun rank feeding identical inputs.
            from jax.sharding import NamedSharding

            sharding = NamedSharding(self.cfg.mesh, P())
            mk = lambda x: jax.make_array_from_process_local_data(  # noqa: E731
                sharding, np.asarray(x)
            )
            latents = mk(latents)
            prompt_embeds = mk(prompt_embeds)
            if added is not None:
                added = jax.tree.map(mk, added)
        # Pin inputs to the abstract signature (_abstract_inputs): embeds in
        # the model dtype, latents/time_ids fp32.  Without this, fp32-embeds
        # callers silently retrace a second program that a compiled_hlo-seeded
        # jit cache (and its overlap analysis) never describes.
        prompt_embeds = jnp.asarray(prompt_embeds, self.cfg.dtype)
        if added is not None and "text_embeds" in added:
            added = dict(added)
            added["text_embeds"] = jnp.asarray(added["text_embeds"], self.cfg.dtype)
        assert 0 <= start_step < num_inference_steps, (start_step,
                                                       num_inference_steps)
        assert end_step is None or start_step < end_step <= num_inference_steps, (
            start_step, end_step, num_inference_steps)
        if callback is not None and self.cfg.use_compiled_step:
            from ..utils.compat import SUPPORTS_FUSED_CALLBACK

            if not SUPPORTS_FUSED_CALLBACK or self.cfg.step_cache_enabled:
                # this jaxlib aborts compiling the ordered-io_callback
                # program (utils/compat.py) — host-driven loop instead.
                # Step-cache runs also take the host loop when a callback is
                # requested: the stepwise steppers replay the exact cadence
                # without teaching the io_callback program a third body.
                return self._generate_stepwise(
                    jnp.asarray(latents), prompt_embeds, added,
                    jnp.asarray(guidance_scale, jnp.float32),
                    num_inference_steps, start_step, end_step, callback,
                )
            # fused/hybrid modes: the callback rides io_callback inside a
            # dedicated compiled loop (_build_fused_callback) — same step
            # numerics, one dispatch, per-step host sync only in THIS
            # program.  Callback-free generates keep the host-free loop.
            self.scheduler.set_timesteps(num_inference_steps)
            key = ("fused_cb", num_inference_steps, start_step, end_step)
            if key not in self._compiled:
                self._compiled[key] = self._build_fused_callback(
                    num_inference_steps, start_step, end_step
                )
            self._active_callback = callback
            try:
                out = self._compiled[key](
                    self.params,
                    jnp.asarray(latents),
                    prompt_embeds,
                    added,
                    jnp.asarray(guidance_scale, jnp.float32),
                )
                # block_until_ready only waits on the OUTPUT buffer; host
                # callbacks drain on a separate thread, so without this
                # barrier an async-dispatch backend could reach the finally
                # (clearing _active_callback) before the last steps fire
                jax.effects_barrier()
                jax.block_until_ready(out)
                return out
            finally:
                self._active_callback = None
        if not self.cfg.use_compiled_step:
            return self._generate_stepwise(
                jnp.asarray(latents),
                jnp.asarray(prompt_embeds),
                added,
                jnp.asarray(guidance_scale, jnp.float32),
                num_inference_steps,
                start_step,
                end_step,
                callback,
            )
        if (self._hybrid_dispatch()
                and start_step == 0 and end_step is None):
            return self._generate_hybrid(
                jnp.asarray(latents), jnp.asarray(prompt_embeds), added,
                jnp.asarray(guidance_scale, jnp.float32), num_inference_steps,
            )
        # Re-pin the scheduler tables on every call, not just at build time:
        # a cached jitted loop can RE-trace later (new input shapes), and the
        # trace reads the mutable scheduler — which a generate() with a
        # different step count may have re-tabled in between.
        self.scheduler.set_timesteps(num_inference_steps)
        fn = self.compiled_handle(num_inference_steps, start_step, end_step)
        return fn(
            self.params,
            jnp.asarray(latents),
            jnp.asarray(prompt_embeds),
            added,
            jnp.asarray(guidance_scale, jnp.float32),
        )


def make_runner(
    distri_config: DistriConfig,
    unet_config: UNetConfig,
    params,
    scheduler: BaseScheduler,
) -> DenoiseRunner:
    """Wire the right parallelism for ``distri_config.parallelism``.

    The analog of the reference's model selection in from_pretrained
    (pipelines.py:30-37): patch -> DistriUNetPP, naive_patch ->
    NaivePatchUNet, tensor -> DistriUNetTP (weights sharded in place).
    """
    if distri_config.parallelism == "tensor" and distri_config.n_device_per_batch > 1:
        from ..models.unet_tp import TPDispatch, head_dim_table, prepare_tp_params

        n = distri_config.n_device_per_batch
        tp_params, specs = prepare_tp_params(params, unet_config, n)
        head_dims = head_dim_table(unet_config)
        factory = lambda text_kv: TPDispatch(n, head_dims)  # noqa: E731
        return DenoiseRunner(
            distri_config, unet_config, tp_params, scheduler,
            tp_dispatch_factory=factory, param_specs=specs,
        )
    if distri_config.parallelism == "tensor":
        # single device: TP degenerates to dense
        from ..models.unet import DenseDispatch

        return DenoiseRunner(
            distri_config, unet_config, params, scheduler,
            tp_dispatch_factory=lambda text_kv: DenseDispatch(text_kv=text_kv),
        )
    return DenoiseRunner(distri_config, unet_config, params, scheduler)

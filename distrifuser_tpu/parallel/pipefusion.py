"""Patch-level pipeline parallelism (PipeFusion) as one XLA program.

The displaced-patch runner (parallel/runner.py) keeps every weight on every
device and shards the *sequence*; this runner shards the *depth*: the DiT's
stacked blocks are split over the ``sp`` mesh axis into P pipeline stages,
and the image's M token-chunks ("patches") stream through the stages like
micro-batches — patch-level pipeline parallelism for diffusion transformers
(PipeFusion, arXiv 2405.14430; PAPERS.md).  Weights per device shrink to
``depth/P`` blocks, and the per-hop traffic is ONE activation chunk
``[B, N/M, hidden]`` between mesh neighbors per tick — O(L/M) point-to-point
instead of the O(L) all-gather the displaced-patch layout refreshes.

Staleness makes the pipeline dense: a patch's self-attention at stage p
attends over the full sequence using each block's carried KV cache, where
its own rows are fresh-this-tick and other patches' rows are
newest-available (fresh-this-step for patches already through stage p this
step, previous-step otherwise) — the same input-temporal-redundancy argument
as DistriFusion's displaced patches, applied along the depth axis.

Schedule (steady state, item q = (step - warmup)*M + patch):
* stage p computes item q at tick ``q + p``; a ring `ppermute` hands its
  output to stage p+1 for tick q+p+1;
* stage P-1's output is the epsilon chunk; the same ring delivers it to
  stage 0 at tick ``q + P``, which CFG-combines it (all_gather over the
  ``cfg`` axis), scheduler-steps that patch's latent rows, and — in the very
  same tick with M == P — embeds the patch for its next step.  ``M >= P`` is
  exactly the condition that the refreshed latent is ready when re-embedding
  needs it.
* Warmup steps (reference counter <= warmup_steps semantics) run the full
  sequence as ONE mega-patch through the pipeline — serial across stages but
  numerically exact, and each stage's pass leaves fresh full-sequence KV in
  its caches, so the first displaced item is one-step-stale, never colder.

Everything — warmup, steady ticks, drain — is two `lax.scan`s inside one
`shard_map`/`jit` program over the (dp, cfg, sp) mesh; there is no host
round-trip per tick.  The per-tick KV commit is a `dynamic_update_slice`
into the scan carry, which XLA aliases in place.

Composition: the ``cfg`` axis still batch-parallelizes classifier-free
guidance (epsilon chunks are gathered and combined at stage 0), ``dp`` still
shards independent images, and the scheduler family (DDIM/Euler/DPM++ 2M)
steps patch-wise — its state is carried stacked per patch so DPM's
cross-step scalars stay correct while patches of adjacent steps interleave.

First-class knob composition (PR 7; ROADMAP item 2):

* **Temporal step cache** (``step_cache_interval``/``step_cache_depth``,
  parallel/stepcache.py): ``step_cache_depth`` counts *pipeline stages*
  here — on shallow steps the deepest K stages do not run their blocks.
  Each deep stage carries a per-patch residual delta ``out - in`` recorded
  at its last full pass (warmup passes record it too, so the first
  post-warmup step may already be shallow); on a shallow item the stage's
  tick body takes a `lax.cond` branch that emits ``h_in + delta[patch]``
  and leaves its KV cache untouched — the stage's block FLOPs and KV
  commits vanish from the shallow path while the tick schedule (and hence
  the static scan shape) stays uniform, so the compiled program carries
  exactly two tick bodies (full + pass-through) like the displaced
  runners' full/shallow pair.  The ring hops themselves still run on
  shallow ticks (a chunk must still travel to stage 0 for its scheduler
  update), so shallow wire bytes equal full-step bytes — ``comm_report``
  says so explicitly.
* **Wire compression** (``comm_compress``, parallel/compress.py): the
  inter-stage activation chunk is quantized before each steady-state
  `ppermute` hop and dequantized right after (int8/fp8 payload + one fp32
  scale per token row).  ``int8_residual`` delta-codes against the
  previous step's chunk for the same (patch, sender-stage) pair,
  closed-loop: sender and receiver both carry the *reconstructed*
  previous payload (seeded from the exact warmup hops), so quantization
  error never accumulates.  Warmup mega-patch hops never compress —
  warmup-only runs stay bit-identical.
* **Quantized weights** (``weight_quant``): the stacked block tree is
  quantized BEFORE the depth split with depth-leading per-tile scales
  (compress.QuantizedTensor), so shard_map slices payload and scale alike
  and each stage holds 1-byte stage-local kernels, dequantized at the
  consuming dot.
"""

from __future__ import annotations

import types
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ..models import dit as dit_mod
from ..models.dit import DiTConfig
from ..ops.linear import linear
from .compress import dequantize, fp8_dtype, quantize, wire_nbytes
from .guidance import branch_select, combine_guidance
from ..schedulers import BaseScheduler
from ..utils.config import CFG_AXIS, DP_AXIS, SP_AXIS, DistriConfig


def _tree_dynamic_index(tree, i):
    return jax.tree.map(
        lambda l: lax.dynamic_index_in_dim(l, i, axis=0, keepdims=False), tree
    )


def _tree_dynamic_update(tree, sub, i, pred):
    """Write ``sub`` at index ``i`` of stacked ``tree`` where ``pred``."""

    def upd(l, s):
        new = lax.dynamic_update_index_in_dim(l, s.astype(l.dtype), i, axis=0)
        return jnp.where(pred, new, l)

    return jax.tree.map(upd, tree, sub)


def _buf_update(buf, val, i, pred):
    """Write ``val`` at index ``i`` of per-patch buffer ``buf`` where
    ``pred`` (the masked commit idiom shared by the delta and predictor
    carries)."""
    new = lax.dynamic_update_index_in_dim(buf, val.astype(buf.dtype), i,
                                          axis=0)
    return jnp.where(pred, new, buf)


class PipeFusionRunner:
    """Compiled PipeFusion generation loop for a DiT.

    API mirrors DenoiseRunner.generate: latents/enc in, final latent out,
    every device returning the full denoised latent.
    """

    def __init__(
        self,
        distri_config: DistriConfig,
        dit_config: DiTConfig,
        params,
        scheduler: BaseScheduler,
        pipe_patches: Optional[int] = None,
    ):
        self.cfg = distri_config
        self.dcfg = dit_config
        self.params = params
        self.scheduler = scheduler
        cfg, dcfg = distri_config, dit_config
        if cfg.attn_impl != "gather":
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} applies to the displaced DiT "
                "runner (parallel/dit_sp.py); the pipeline's per-block KV "
                "cache is its own attention layout"
            )
        if cfg.mode == "no_sync":
            raise ValueError(
                "mode='no_sync' does not apply to the patch pipeline: its KV "
                "caches refresh every tick by construction (freezing warmup "
                "KV is the displaced runners' knob); use the displaced DiT "
                "runner for no_sync"
            )
        if not cfg.use_cuda_graph:
            raise ValueError(
                "use_cuda_graph=False (--no_cuda_graph) does not apply to "
                "the patch pipeline: the tick schedule exists only inside "
                "the fused scan program — there is no per-step host loop to "
                "fall back to"
            )
        self.stages = cfg.n_device_per_batch
        if pipe_patches is None:
            pipe_patches = cfg.pipe_patches  # may still be None
        self.patches = self.stages if pipe_patches is None else pipe_patches
        if cfg.step_cache_enabled and cfg.step_cache_depth >= self.stages:
            raise ValueError(
                "under PipeFusion, step_cache_depth counts PIPELINE STAGES "
                f"skipped on shallow steps: depth {cfg.step_cache_depth} "
                f"must be < the {self.stages} stages (stage 0 embeds and "
                "scheduler-steps, it can never be skipped)"
            )
        n_tok = dcfg.num_tokens
        if dcfg.depth % self.stages != 0:
            raise ValueError(
                f"DiT depth {dcfg.depth} must divide evenly into "
                f"{self.stages} pipeline stages"
            )
        if self.patches < self.stages:
            raise ValueError(
                f"pipe_patches ({self.patches}) must be >= pipeline stages "
                f"({self.stages}): the scheduler refresh of a patch returns to "
                "stage 0 exactly P ticks after it left, so fewer patches than "
                "stages would re-embed a latent that is not yet stepped"
            )
        if n_tok % self.patches != 0:
            raise ValueError(
                f"token count {n_tok} must be divisible by pipe_patches "
                f"({self.patches})"
            )
        if dcfg.hidden_size < dcfg.token_out_dim:
            raise ValueError(
                "hidden_size must be >= patch_size^2*out_channels so the "
                "epsilon chunk rides the activation ring payload"
            )
        if (cfg.height // 8 != dcfg.sample_size) or (cfg.width // 8 != dcfg.sample_size):
            raise ValueError(
                f"DistriConfig {cfg.height}x{cfg.width} implies latent "
                f"{cfg.latent_height}, but DiTConfig.sample_size is "
                f"{dcfg.sample_size} (square latents only for the DiT)"
            )
        self._compiled: Dict[Any, Any] = {}

    # ------------------------------------------------------------------
    # pieces
    # ------------------------------------------------------------------

    def _branch_enc(self, enc):
        """Select this device's CFG branch of the text encoding [2, B, Lt, D]
        (same contract as DenoiseRunner._branch_inputs)."""
        my_enc, _, _ = branch_select(self.cfg, enc)
        return my_enc

    def _combine_eps(self, eps, gs, batch):
        """Guided epsilon from per-branch epsilon (chunk or full)."""
        return combine_guidance(self.cfg, eps, gs, batch)

    def _run_stage(self, blocks_local, cap_kv_local, kv_cache, h, c6, offset,
                   valid, cap_bias):
        """Run this device's Lp blocks on ``h`` [B, Lq, hid] against the
        full-sequence stale caches; returns (h_out, committed kv_cache)."""

        def body(carry, xs):
            hcur = carry
            bp, ckv, cache = xs
            h_out, (k_new, v_new) = dit_mod.dit_block(
                bp, self.dcfg, hcur, c6, ckv,
                self_kv=(cache[0], cache[1]), patch_start=offset,
                cap_bias=cap_bias,
            )
            return h_out, jnp.stack([k_new, v_new])

        h_out, fresh = lax.scan(body, h, (blocks_local, cap_kv_local, kv_cache))
        # fresh: [Lp, 2, B, Lq, hid] -> commit at the patch rows
        committed = lax.dynamic_update_slice(
            kv_cache, fresh.astype(kv_cache.dtype), (0, 0, 0, offset, 0)
        )
        kv_cache = jnp.where(valid, committed, kv_cache)
        return h_out, kv_cache

    # ------------------------------------------------------------------
    # the device program
    # ------------------------------------------------------------------

    def _tick_ctx(self, params, enc, cap_mask, gs, batch, num_steps, n_sync):
        """Setup + the two tick closures, shared by the fused loop and the
        hybrid pair of programs (everything here is carry-free: the ticks
        are pure functions of their carry)."""
        cfg, dcfg = self.cfg, self.dcfg
        sched = self.scheduler
        n_stage = self.stages
        n_patch = self.patches
        n_tok = dcfg.num_tokens
        chunk = n_tok // n_patch
        hid = dcfg.hidden_size
        d_in = dcfg.token_dim
        d_out = dcfg.token_out_dim
        p_idx = lax.axis_index(SP_AXIS)
        is_first = p_idx == 0
        is_last = p_idx == n_stage - 1

        my_enc = self._branch_enc(enc)
        my_mask, _, _ = branch_select(cfg, cap_mask)
        cap_bias = dit_mod.caption_mask_bias(my_mask)
        bloc = my_enc.shape[0]  # batch inside the pipeline (2B when folded)

        # knob composition (module docstring): wire compression of the
        # steady ring hops + the stage-skipping step cache
        mode = cfg.comm_compress
        use_sc = cfg.step_cache_enabled
        n_deep = cfg.step_cache_depth if use_sc else 0
        interval = cfg.step_cache_interval
        is_deep = p_idx >= (n_stage - n_deep)  # False everywhere when off

        compute_dtype = params["proj_in"]["kernel"].dtype
        pos = dit_mod.pos_embed_table(dcfg, compute_dtype)

        blocks_local = params["blocks"]  # leaves [Lp, ...] (sharded over sp)
        # model-dtype entry cast, exactly like precompute_caption_kv's (its
        # docstring explains the silent upcast leak): fp32 caption embeds
        # would otherwise yield fp32 cross-attention KV that promotes the
        # whole residual stream — at bf16 that broke the _run_stage scan
        # carry outright (f32 out vs bf16 in)
        y_cap = dit_mod.caption_project(
            params, my_enc.astype(compute_dtype))  # loop-invariant
        cap_kv_local = jax.vmap(lambda kvp: linear(kvp, y_cap))(
            blocks_local["cross_kv"]
        )  # [Lp, Bl, Lt, 2*hid]

        ts = sched.timesteps()
        temb_all = jax.vmap(lambda t: dit_mod.t_embed(params, dcfg, t))(ts)  # [T, hid]
        c6_all = jax.vmap(lambda e: dit_mod.adaln_table(params, dcfg, e))(temb_all)

        def embed_chunk(x_full, m, s):
            """Patch m of the latent, scaled + embedded for step s."""
            rows = lax.dynamic_slice(
                x_full, (0, m * chunk, 0), (batch, chunk, d_in)
            )
            rows = sched.scale_model_input(rows, s)
            tok = rows.astype(compute_dtype)
            if not cfg.cfg_split and cfg.do_classifier_free_guidance:
                tok = jnp.concatenate([tok, tok], axis=0)
            pos_rows = lax.dynamic_slice(pos, (m * chunk, 0), (chunk, hid))
            return dit_mod.embed_tokens(params, dcfg, tok, pos_rows)

        def sched_patch(x_full, sstate, eps_guided, m, s, pred):
            """Scheduler-step patch m's rows with its stacked state slice."""
            rows = lax.dynamic_slice(
                x_full, (0, m * chunk, 0), (batch, chunk, d_in)
            )
            st = _tree_dynamic_index(sstate, m)
            new_rows, new_st = sched.step(rows, eps_guided.astype(jnp.float32), s, st)
            x_new = lax.dynamic_update_slice(
                x_full, new_rows.astype(x_full.dtype), (0, m * chunk, 0)
            )
            x_full = jnp.where(pred, x_new, x_full)
            sstate = _tree_dynamic_update(sstate, new_st, m, pred)
            return x_full, sstate

        def split_patches(full):
            """[bloc, n_tok, hid] -> [n_patch, bloc, chunk, hid]."""
            return full.reshape(bloc, n_patch, chunk, hid).transpose(
                1, 0, 2, 3)

        def init_aux():
            """Knob-dependent extra carry: the per-stage step-cache delta
            and/or the residual coder's sender/receiver predictors.  One
            pytree shared by every tick body (warmup records, steady
            consumes), so the scan carry structure never depends on which
            step body runs."""
            aux = {}
            if use_sc:
                aux["delta"] = jnp.zeros(
                    (n_patch, bloc, chunk, hid), compute_dtype)
            if mode == "int8_residual":
                aux["send_pred"] = jnp.zeros(
                    (n_patch, bloc, chunk, hid), jnp.float32)
                aux["recv_pred"] = jnp.zeros(
                    (n_patch, bloc, chunk, hid), jnp.float32)
            return aux

        def steady_ring0():
            """Zero ring for the steady phase: raw chunk, or the
            (payload, scale) pair the compressed hops permute."""
            if mode == "none":
                return jnp.zeros((bloc, chunk, hid), compute_dtype)
            pdt = fp8_dtype() if mode == "fp8" else jnp.int8
            return (jnp.zeros((bloc, chunk, hid), pdt),
                    jnp.zeros((bloc, chunk), jnp.float32))

        def decode_hop(ring, aux, m_recv, ok_recv):
            """Reconstruct the received activation chunk from the ring
            carry (dequantize + residual predictor add), updating the
            receiver-side predictor closed-loop."""
            if mode == "none":
                return ring, aux
            payload, scale = ring
            dec = dequantize(payload, scale, jnp.float32)
            if mode == "int8_residual":
                pred = lax.dynamic_index_in_dim(
                    aux["recv_pred"], m_recv, axis=0, keepdims=False)
                dec = pred + dec
                aux = dict(aux)
                aux["recv_pred"] = _buf_update(
                    aux["recv_pred"], dec, m_recv, ok_recv)
            return dec.astype(compute_dtype), aux

        def encode_hop(payload, aux, m_my, ok_my):
            """Quantize the outgoing chunk (delta-coded for the residual
            mode, with the sender predictor advanced to the same
            reconstruction the receiver will compute)."""
            if mode == "none":
                return payload, aux
            src = payload.astype(jnp.float32)
            if mode == "int8_residual":
                pred = lax.dynamic_index_in_dim(
                    aux["send_pred"], m_my, axis=0, keepdims=False)
                q, s = quantize(src - pred, mode)
                recon = pred + dequantize(q, s, jnp.float32)
                aux = dict(aux)
                aux["send_pred"] = _buf_update(
                    aux["send_pred"], recon, m_my, ok_my)
            else:
                q, s = quantize(src, mode)
            return (q, s), aux

        def ring_permute(payload):
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            return jax.tree.map(
                lambda l: lax.ppermute(l, SP_AXIS, perm), payload)

        # ---------------- phase 1: synchronous mega-patch warmup ----------
        def warmup_tick(carry, tau):
            x_full, sstate, kv_cache, aux, ring = carry
            active = tau % n_stage
            s = tau // n_stage  # step being fed through the pipeline

            # stage-0 receive: epsilon of step s-1 completes as step s starts
            eps_full = ring[..., :d_out]
            guided = self._combine_eps(eps_full, gs, batch)
            do_recv = is_first & (active == 0) & (s >= 1) & (s <= num_steps)

            def step_all(args):
                x_full, sstate = args
                xs = x_full.reshape(batch, n_patch, chunk, -1).transpose(1, 0, 2, 3)
                gch = guided.reshape(batch, n_patch, chunk, -1).transpose(1, 0, 2, 3)
                new_xs, new_st = jax.vmap(
                    lambda xr, gr, st: sched.step(xr, gr, s - 1, st)
                )(xs, gch, sstate)
                x_new = new_xs.transpose(1, 0, 2, 3).reshape(x_full.shape)
                return x_new.astype(x_full.dtype), jax.tree.map(
                    lambda a, b: b.astype(a.dtype), sstate, new_st
                )

            x_new, st_new = step_all((x_full, sstate))
            x_full = jnp.where(do_recv, x_new, x_full)
            sstate = jax.tree.map(
                lambda old, new: jnp.where(do_recv, new, old), sstate, st_new
            )

            # stage-0 embed of step s (only when a fresh step enters)
            s_c = jnp.clip(s, 0, num_steps - 1)
            x_in = sched.scale_model_input(x_full, s_c).astype(compute_dtype)
            if not cfg.cfg_split and cfg.do_classifier_free_guidance:
                x_in = jnp.concatenate([x_in, x_in], axis=0)
            h0 = dit_mod.embed_tokens(params, dcfg, x_in, pos)

            h_in = jnp.where(is_first, h0, ring.astype(compute_dtype))
            valid = (p_idx == active) & (s < n_sync)
            c6 = c6_all[s_c]
            h_out, kv_cache = self._run_stage(
                blocks_local, cap_kv_local, kv_cache, h_in, c6, 0, valid,
                cap_bias,
            )
            if use_sc:
                # every warmup pass is a full run: refresh this stage's
                # per-patch deep delta so the first post-warmup step may
                # already be shallow (shallow-first cadence)
                aux = dict(aux)
                aux["delta"] = jnp.where(
                    valid, split_patches((h_out - h_in).astype(compute_dtype)),
                    aux["delta"])

            eps_out = dit_mod.final_layer(params, dcfg, h_out, temb_all[s_c])
            pad = jnp.zeros((bloc, n_tok, hid - d_out), eps_out.dtype)
            payload = jnp.where(
                is_last, jnp.concatenate([eps_out, pad], axis=-1), h_out
            )
            if mode == "int8_residual":
                # warmup hops are exact (never compressed); both coder ends
                # seed their predictors from the SAME raw values, so the
                # first steady-state delta is coded against a shared,
                # consistent reference
                aux = dict(aux)
                aux["send_pred"] = jnp.where(
                    valid, split_patches(payload.astype(jnp.float32)),
                    aux["send_pred"])
                consumed = (valid & ~is_first) | do_recv
                aux["recv_pred"] = jnp.where(
                    consumed, split_patches(ring.astype(jnp.float32)),
                    aux["recv_pred"])
            ring = lax.ppermute(
                payload, SP_AXIS,
                [(i, (i + 1) % n_stage) for i in range(n_stage)],
            )
            return (x_full, sstate, kv_cache, aux, ring), None

        # ---------------- phase 2: displaced patch streaming --------------
        n_items = (num_steps - n_sync) * n_patch

        def steady_tick(carry, tau):
            x_full, sstate, kv_cache, aux, ring = carry

            # what my ring predecessor processed last tick (= what I am
            # consuming now): item tau - p for stages > 0, item
            # tau - n_stage (the returning epsilon) for stage 0
            q_recv = (tau - 1) - ((p_idx - 1) % n_stage)
            ok_recv = (q_recv >= 0) & (q_recv < n_items)
            m_recv = jnp.clip(q_recv, 0, n_items - 1) % n_patch
            h_recv, aux = decode_hop(ring, aux, m_recv, ok_recv)

            # stage-0 receive: epsilon chunk of item tau - n_stage
            q_arr = tau - n_stage
            ok_arr = (q_arr >= 0) & (q_arr < n_items)
            q_arr_c = jnp.clip(q_arr, 0, n_items - 1)
            s_arr = n_sync + q_arr_c // n_patch
            m_arr = q_arr_c % n_patch
            eps_chunk = h_recv[..., :d_out]
            guided = self._combine_eps(eps_chunk, gs, batch)
            x_full, sstate = sched_patch(
                x_full, sstate, guided, m_arr, s_arr, is_first & ok_arr
            )

            # stage-0 embed: item tau enters the pipeline
            q_in = jnp.clip(tau, 0, n_items - 1)
            s_in = n_sync + q_in // n_patch
            m_in = q_in % n_patch
            h0 = embed_chunk(x_full, m_in, s_in)

            h_in = jnp.where(is_first, h0, h_recv.astype(compute_dtype))

            # my item this tick
            q_my = tau - p_idx
            ok_my = (q_my >= 0) & (q_my < n_items)
            q_my_c = jnp.clip(q_my, 0, n_items - 1)
            s_my = n_sync + q_my_c // n_patch
            m_my = q_my_c % n_patch
            c6 = c6_all[s_my]

            def run_blocks(h, kv):
                return self._run_stage(
                    blocks_local, cap_kv_local, kv, h, c6,
                    m_my * chunk, ok_my, cap_bias,
                )

            if use_sc:
                # shallow-first cadence over the post-warmup step index:
                # deep stages take a pass-through branch (carried delta,
                # untouched KV) on shallow items — a real lax.cond, so the
                # block FLOPs exist only on the full path
                shallow_my = (s_my - n_sync) % interval < interval - 1

                def full_branch(ops):
                    h, kv, delta = ops
                    h_out, kv = run_blocks(h, kv)
                    delta = _buf_update(
                        delta, h_out - h, m_my, ok_my & is_deep)
                    return h_out, kv, delta

                def shallow_branch(ops):
                    h, kv, delta = ops
                    d = lax.dynamic_index_in_dim(
                        delta, m_my, axis=0, keepdims=False)
                    return h + d.astype(h.dtype), kv, delta

                aux = dict(aux)
                h_out, kv_cache, aux["delta"] = lax.cond(
                    is_deep & shallow_my, shallow_branch, full_branch,
                    (h_in, kv_cache, aux["delta"]),
                )
            else:
                h_out, kv_cache = run_blocks(h_in, kv_cache)

            eps_out = dit_mod.final_layer(params, dcfg, h_out, temb_all[s_my])
            pad = jnp.zeros((bloc, chunk, hid - d_out), eps_out.dtype)
            payload = jnp.where(
                is_last, jnp.concatenate([eps_out, pad], axis=-1), h_out
            )
            payload, aux = encode_hop(payload, aux, m_my, ok_my)
            ring = ring_permute(payload)
            return (x_full, sstate, kv_cache, aux, ring), None

        return types.SimpleNamespace(
            warmup_tick=warmup_tick, steady_tick=steady_tick,
            init_aux=init_aux, steady_ring0=steady_ring0,
            n_items=n_items, n_stage=n_stage, is_first=is_first, bloc=bloc,
            chunk=chunk, hid=hid, compute_dtype=compute_dtype,
            l_per=dcfg.depth // n_stage, n_tok=n_tok,
        )

    def _init_carry(self, ctx, latents):
        """(x tokens, per-patch scheduler state, stale KV cache)."""
        dcfg, sched = self.dcfg, self.scheduler
        batch = latents.shape[0]
        x = dit_mod.patchify(dcfg, latents.astype(jnp.float32))
        # scheduler state stacked per patch (DPM's scalars must advance with
        # each patch's own step sequence while steps interleave in flight)
        sstate = jax.vmap(
            lambda _: sched.init_state((batch, ctx.chunk, dcfg.token_dim))
        )(jnp.arange(self.patches))
        kv_cache = jnp.zeros(
            (ctx.l_per, 2, ctx.bloc, ctx.n_tok, ctx.hid), ctx.compute_dtype
        )
        return x, sstate, kv_cache

    def _device_loop(self, params, latents, enc, cap_mask, gs, num_steps):
        cfg, dcfg = self.cfg, self.dcfg
        batch = latents.shape[0]
        # full_sync runs every step as the exact mega-patch (mirroring
        # dit_sp.py): the displaced schedule never engages
        n_sync = (
            num_steps
            if cfg.mode == "full_sync"
            else min(cfg.warmup_steps + 1, num_steps)
        )
        ctx = self._tick_ctx(params, enc, cap_mask, gs, batch, num_steps,
                             n_sync)
        x, sstate, kv_cache = self._init_carry(ctx, latents)

        ring0 = jnp.zeros((ctx.bloc, ctx.n_tok, ctx.hid), ctx.compute_dtype)
        carry = (x, sstate, kv_cache, ctx.init_aux(), ring0)
        n_warm_ticks = n_sync * ctx.n_stage + 1
        carry, _ = lax.scan(ctx.warmup_tick, carry, jnp.arange(n_warm_ticks))
        x, sstate, kv_cache, aux, _ = carry

        if n_sync >= num_steps:
            x_full = lax.psum(jnp.where(ctx.is_first, x, 0.0), SP_AXIS)
            return dit_mod.unpatchify(dcfg, x_full, dcfg.in_channels)

        carry = (x, sstate, kv_cache, aux, ctx.steady_ring0())
        carry, _ = lax.scan(
            ctx.steady_tick, carry, jnp.arange(ctx.n_items + ctx.n_stage)
        )
        x = carry[0]

        x_full = lax.psum(jnp.where(ctx.is_first, x, 0.0), SP_AXIS)
        return dit_mod.unpatchify(dcfg, x_full, dcfg.in_channels)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def comm_report(self, batch_size: int = 1) -> Dict[str, Any]:
        """Per-device memory/traffic accounting (counterpart of
        DenoiseRunner.comm_volume_report for the pipeline layout).

        Static arithmetic — no device work: PipeFusion's whole point is that
        weights shrink depth/P-fold and the per-hop wire traffic is one
        [B, N/M, hidden] chunk instead of the displaced-patch O(L) gathers.

        Byte accounting (``*_bytes`` keys, the contract
        ``pipelines.comm_plan`` consumes): one steady step is exactly
        ``patches`` ring ticks, each permuting one compressed-or-raw
        activation chunk between sp neighbors; one warmup (sync) step is
        ``stages`` ticks of the full-precision mega-patch payload.
        Shallow (step-cache) steps skip deep-stage COMPUTE and KV commits
        but the chunk still rides every hop to reach stage 0 for its
        scheduler update, so shallow wire bytes equal full-step bytes
        (``step_cache.shallow_per_step_collective_elems`` says so rather
        than implying a saving that does not exist).  The cfg-axis guidance
        gather is reported separately (``per_step_cfg_gather_bytes``) and
        excluded from ``per_step_collective_bytes``, matching the displaced
        DiT report which also counts only sp-axis traffic.
        """
        cfg, dcfg = self.cfg, self.dcfg
        n_tok = dcfg.num_tokens
        hid = dcfg.hidden_size
        l_per = dcfg.depth // self.stages
        chunk = n_tok // self.patches
        bloc = batch_size * (
            2 if (cfg.do_classifier_free_guidance and not cfg.cfg_split)
            else 1
        )
        one_block_params = sum(
            int(np.prod(l.shape[1:]))  # leading axis is the depth stack
            for l in jax.tree.leaves(self.params["blocks"])
        )
        shared_params = sum(
            int(np.prod(np.shape(l)))
            for k, v in self.params.items() if k != "blocks"
            for l in jax.tree.leaves(v)
        )
        itemsize = jnp.dtype(cfg.dtype).itemsize
        ring_active = self.stages > 1  # a 1-stage "ring" is a self-permute
        hop_bytes = (
            wire_nbytes((bloc, chunk, hid), itemsize, cfg.comm_compress)
            if ring_active else 0
        )
        warm_hop_bytes = bloc * n_tok * hid * itemsize if ring_active else 0
        per_step_elems = (self.patches * bloc * chunk * hid
                          if ring_active else 0)
        report = {
            "stages": self.stages,
            "patches": self.patches,
            "params_per_device": shared_params + one_block_params * l_per,
            "params_replicated_equiv": shared_params + one_block_params * dcfg.depth,
            "kv_cache_elems_per_device": l_per * 2 * bloc * n_tok * hid,
            "ring_payload_elems_per_tick": bloc * chunk * hid,
            "ticks_per_step_steady": self.patches,
            "bubble_ticks": self.stages,
            # wire bytes, closed form (compression-aware; warmup never
            # compresses)
            "comm_compress": cfg.comm_compress,
            "per_hop_bytes": int(hop_bytes),
            "warmup_hop_bytes": int(warm_hop_bytes),
            "per_step_collective_elems": int(per_step_elems),
            "per_step_collective_bytes": int(self.patches * hop_bytes),
            "sync_step_collective_bytes": int(self.stages * warm_hop_bytes),
            "per_step_cfg_gather_bytes": int(
                self.patches * batch_size * chunk * dcfg.token_out_dim
                * itemsize
                if cfg.cfg_split else 0
            ),
        }
        if cfg.step_cache_enabled:
            report["step_cache"] = {
                "interval": cfg.step_cache_interval,
                "depth": cfg.step_cache_depth,  # PIPELINE STAGES skipped
                # hops persist on shallow steps (docstring): bytes equal
                "shallow_per_step_collective_elems": int(per_step_elems),
            }
        return report

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def _specs(self):
        """(param_specs, lat_spec, enc_spec) shared by both builders."""
        block_specs = jax.tree.map(lambda _: P(SP_AXIS), self.params["blocks"])
        param_specs = {
            k: (block_specs if k == "blocks" else jax.tree.map(lambda _: P(), v))
            for k, v in self.params.items()
        }
        return param_specs, P(DP_AXIS), P(None, DP_AXIS)

    def _build(self, num_steps: int):
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)
        device_loop = partial(self._device_loop, num_steps=num_steps)

        param_specs, lat_spec, enc_spec = self._specs()

        def loop(params, latents, enc, cap_mask, gs):
            return shard_map(
                device_loop,
                mesh=cfg.mesh,
                in_specs=(param_specs, lat_spec, enc_spec, enc_spec, P()),
                out_specs=lat_spec,
                check_vma=False,
            )(params, latents, enc, cap_mask, gs)

        return jax.jit(loop)

    def _build_hybrid(self, num_steps: int):
        """Warmup and steady phases as two ONE-body programs
        (cfg.hybrid_loop; same lever as dit_sp._build_hybrid): each program
        traces the stage stack once instead of twice, roughly halving the
        big program's (remote) compile.  The inter-phase carry — tokens,
        per-patch scheduler state, stale KV cache — is per-device state; it
        crosses the jit boundary with a fresh leading axis laid out over
        (dp, cfg, sp).  The ring buffer does NOT cross: the steady phase
        starts from a zero ring exactly as the fused loop does."""
        cfg, dcfg = self.cfg, self.dcfg
        self.scheduler.set_timesteps(num_steps)
        n_sync = min(cfg.warmup_steps + 1, num_steps)

        param_specs, lat_spec, enc_spec = self._specs()
        state_spec = P((DP_AXIS, CFG_AXIS, SP_AXIS))  # prefix for any pytree

        def device_warm(params, latents, enc, cap_mask, gs):
            batch = latents.shape[0]
            ctx = self._tick_ctx(params, enc, cap_mask, gs, batch, num_steps,
                                 n_sync)
            x, sstate, kv_cache = self._init_carry(ctx, latents)
            ring0 = jnp.zeros((ctx.bloc, ctx.n_tok, ctx.hid),
                              ctx.compute_dtype)
            carry, _ = lax.scan(
                ctx.warmup_tick, (x, sstate, kv_cache, ctx.init_aux(), ring0),
                jnp.arange(n_sync * ctx.n_stage + 1),
            )
            x, sstate, kv_cache, aux, _ = carry
            add_dev = lambda t: jax.tree.map(lambda l: l[None], t)  # noqa: E731
            return add_dev(x), add_dev(sstate), add_dev(kv_cache), add_dev(aux)

        def device_steady(params, x, sstate, kv_cache, aux, enc, cap_mask,
                          gs):
            x, sstate, kv_cache, aux = jax.tree.map(
                lambda l: l[0], (x, sstate, kv_cache, aux)
            )
            batch = x.shape[0]
            ctx = self._tick_ctx(params, enc, cap_mask, gs, batch, num_steps,
                                 n_sync)
            carry, _ = lax.scan(
                ctx.steady_tick, (x, sstate, kv_cache, aux,
                                  ctx.steady_ring0()),
                jnp.arange(ctx.n_items + ctx.n_stage),
            )
            x = carry[0]
            x_full = lax.psum(jnp.where(ctx.is_first, x, 0.0), SP_AXIS)
            return dit_mod.unpatchify(dcfg, x_full, dcfg.in_channels)

        warm = jax.jit(lambda p, l, e, m, g: shard_map(
            device_warm, mesh=cfg.mesh,
            in_specs=(param_specs, lat_spec, enc_spec, enc_spec, P()),
            out_specs=(state_spec, state_spec, state_spec, state_spec),
            check_vma=False,
        )(p, l, e, m, g))
        steady = jax.jit(lambda p, x, ss, kv, ax, e, m, g: shard_map(
            device_steady, mesh=cfg.mesh,
            in_specs=(param_specs, state_spec, state_spec, state_spec,
                      state_spec, enc_spec, enc_spec, P()),
            out_specs=lat_spec,
            check_vma=False,
        )(p, x, ss, kv, ax, e, m, g), donate_argnums=(1, 2, 3, 4))
        return warm, steady

    def generate(self, latents, enc, guidance_scale=5.0, num_inference_steps=20,
                 cap_mask=None, callback=None):
        """latents [B, H/8, W/8, C] fp32, enc [2, B, Lt, caption_dim]
        (uncond, cond branch-major, like DenoiseRunner).  ``cap_mask``
        [n_br, B, Lt] (1 = real token) masks padded caption tokens out of
        cross-attention; None attends to all.  Returns the final latent,
        full on every device."""
        if callback is not None:
            raise ValueError(
                "per-step callbacks are not available under PipeFusion: a "
                "denoising step is smeared across the pipeline's token "
                "ticks inside the scan, so there is no per-step boundary "
                "to fire from — use parallelism='patch' "
                "(DiTDenoiseRunner fires callbacks in every mode)"
            )
        # Re-pin the scheduler tables every call: a cached program can
        # re-trace later and must not read tables left by a different step
        # count (see DenoiseRunner.generate).
        self.scheduler.set_timesteps(num_inference_steps)
        gs = jnp.asarray(guidance_scale, jnp.float32)
        if cap_mask is None:
            cap_mask = jnp.ones(enc.shape[:3], jnp.float32)
        cap_mask = jnp.asarray(cap_mask, jnp.float32)
        if self._hybrid_dispatch(num_inference_steps):
            warm, steady = self._ensure_hybrid(num_inference_steps)
            x, sstate, kv, aux = warm(self.params, latents, enc, cap_mask,
                                      gs)
            return steady(self.params, x, sstate, kv, aux, enc, cap_mask,
                          gs)
        if num_inference_steps not in self._compiled:
            self._compiled[num_inference_steps] = self._build(num_inference_steps)
        return self._compiled[num_inference_steps](
            self.params, latents, enc, cap_mask, gs
        )

    def _hybrid_dispatch(self, num_steps: int) -> bool:
        cfg = self.cfg
        return (cfg.hybrid_loop and cfg.mode != "full_sync"
                and self.stages > 1
                and min(cfg.warmup_steps + 1, num_steps) < num_steps)

    def _ensure_hybrid(self, num_steps: int):
        key = ("hybrid", num_steps)
        if key not in self._compiled:
            self._compiled[key] = self._build_hybrid(num_steps)
        return self._compiled[key]

    def prepare(self, num_steps: int) -> None:
        """Pre-build exactly the program(s) generate() will dispatch to."""
        self.scheduler.set_timesteps(num_steps)
        if self._hybrid_dispatch(num_steps):
            self._ensure_hybrid(num_steps)
            return
        if num_steps not in self._compiled:
            self._compiled[num_steps] = self._build(num_steps)

"""Batch-row packing for the explicit stepwise carries (fused cohort step).

`PipelineExecutor.step_run` advances each resident request's explicit
denoise carry (runner.stepwise_carry_init/...step) padded to the compiled
batch width with copies of its single real row.  Batch rows are independent
end to end (the PR-1 coalescing invariant), so N cohort members whose next
step compiles to the SAME per-step program — same (phase, state, shallow)
signature — can legally share ONE dispatch: member r's real row rides batch
row r, the per-row inputs (step index, guidance scale, scheduler scalars)
become [B] vectors, and every row's numerics are byte-identical to its solo
run.  This module is the carry-layout half of that contract:

* **axis discovery** (`axes_from_shapes`): given the carry's leaf shapes at
  two batch widths (w and 2w), the batch axis of each leaf is the unique
  axis whose dim doubled.  No per-family layout table — the displaced-patch
  state, gather/ring KV, step-cache deep features, and scheduler state all
  reveal their batch axis the same way.  Leaves that don't scale are either
  per-run scalars (scheduler state: packed as a stacked [B] vector — the
  schedulers accept per-row state, schedulers/scheduling.py `_per_row`) or
  batch-less shared placeholders (the ulysses/usp KV stub) that pass
  through untouched.  An ambiguous leaf (two axes doubled) raises — the
  executor falls back to sequential dispatch, never guesses.

* **fold-aware row indexing**: a batch-bearing axis holds ``f * width``
  entries with the request row MINOR — CFG folding concatenates the batch
  block per branch (``concat([x, x])``), and the stepwise shard_map layouts
  stack per-device blocks on axis 0 — so row ``r`` of a width-``w`` carry
  occupies positions ``{r, w + r, 2w + r, ...}``.  Pack/extract reshape the
  axis to ``(f, width)`` and index the minor factor, which is exact for
  every layout the runners emit.

* **pack/extract** (`pack_rows` / `extract_row`): pack slices each member's
  real row into consecutive packed rows (padding by repeating the last
  member — the `_pad_batch` convention); extract slices one row back out
  and tiles it across the width, reproducing the solo layout exactly
  (a solo carry's rows are identical by construction, so ``extract(pack)``
  is byte-equal to never having packed — the bit-identity contract pinned
  in tests/test_stepbatch.py).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class AmbiguousPackAxisError(ValueError):
    """A carry leaf's batch axis could not be identified uniquely —
    packing would be a guess, so the caller must fall back to sequential
    per-slot dispatch (correctness-first)."""


class LeafAxes:
    """Per-leaf packing plan: ``axis`` is the batch-bearing axis (None for
    per-run scalars and batch-less shared leaves), ``ndim`` the leaf rank
    at the SOLO width (distinguishes a scalar scheduler leaf, which packs
    to a stacked [B] vector, from a shared placeholder)."""

    __slots__ = ("axis", "ndim")

    def __init__(self, axis, ndim):
        self.axis = axis
        self.ndim = ndim

    def __repr__(self):  # debugging aid only
        return f"LeafAxes(axis={self.axis}, ndim={self.ndim})"


def _leaf_axes(small: Sequence[int], big: Sequence[int]) -> LeafAxes:
    small, big = tuple(small), tuple(big)
    if len(small) != len(big):
        raise AmbiguousPackAxisError(
            f"carry leaf rank changed with batch width: {small} vs {big}"
        )
    doubled = [a for a, (s, b) in enumerate(zip(small, big))
               if s > 0 and b == 2 * s]
    if not doubled:
        return LeafAxes(None, len(small))
    if len(doubled) > 1:
        raise AmbiguousPackAxisError(
            f"carry leaf {small} has multiple batch-scaled axes {doubled}"
        )
    return LeafAxes(doubled[0], len(small))


def axes_from_shapes(small_tree: Any, big_tree: Any) -> List[LeafAxes]:
    """Per-leaf packing plan from the carry's shapes at width w
    (``small_tree``) and width 2w (``big_tree``) — trees of arrays or
    ShapeDtypeStructs with identical structure.  Returns a flat list in
    ``tree_leaves`` order (a parallel list, NOT a pytree: LeafAxes must
    not be flattened into)."""
    small_leaves = jax.tree_util.tree_leaves(small_tree)
    big_leaves = jax.tree_util.tree_leaves(big_tree)
    if len(small_leaves) != len(big_leaves):
        raise AmbiguousPackAxisError(
            "carry structure changed with batch width: "
            f"{len(small_leaves)} vs {len(big_leaves)} leaves"
        )
    return [_leaf_axes(jnp.shape(s), jnp.shape(b))
            for s, b in zip(small_leaves, big_leaves)]


def _row_block(leaf, row: int, axis: int, width: int):
    """Slice row ``row`` (keepdims) out of a fold-major/batch-minor axis:
    reshape dim ``f * width`` to ``(f, width)``, index the minor factor."""
    d = leaf.shape[axis]
    if d % width:
        raise AmbiguousPackAxisError(
            f"batch axis dim {d} is not a multiple of width {width}"
        )
    f = d // width
    shaped = leaf.reshape(leaf.shape[:axis] + (f, width)
                          + leaf.shape[axis + 1:])
    return lax.index_in_dim(shaped, row, axis=axis + 1, keepdims=True)


def pack_rows(carries: Sequence[Any], rows: Sequence[int],
              axes: List[LeafAxes], width: int) -> Any:
    """One packed carry whose row ``r`` is ``carries[r]``'s row
    ``rows[r]``, padded to ``width`` rows by repeating the last member.
    Members may be solo OR previously-packed carries — the row index
    always addresses the member's own layout."""
    if not carries or len(carries) > width:
        raise ValueError(
            f"pack_rows wants 1..{width} members, got {len(carries)}"
        )
    flats = [jax.tree_util.tree_flatten(c) for c in carries]
    treedef = flats[0][1]
    for leaves, td in flats[1:]:
        if td != treedef:
            raise AmbiguousPackAxisError(
                "pack group members carry different tree structures"
            )
    pad = width - len(carries)
    out = []
    for li, ax in enumerate(axes):
        leaves = [f[0][li] for f in flats]
        if ax.axis is None:
            if ax.ndim == 0:
                # per-run scheduler scalar -> stacked [width] vector (the
                # schedulers take per-row state); an already-packed member
                # contributes its own row
                vals = [l[r] if jnp.ndim(l) > 0 else jnp.asarray(l)
                        for l, r in zip(leaves, rows)]
                vals = vals + [vals[-1]] * pad
                out.append(jnp.stack(vals))
            else:
                # batch-less shared leaf (ulysses/usp KV placeholder):
                # identical across members by construction.  COPY — the
                # per-step programs donate carry leaves, and an aliased
                # buffer would invalidate the source carry (still
                # referenced by members outside this pack)
                out.append(jnp.copy(leaves[0]))
            continue
        blocks = [_row_block(l, r, ax.axis, width)
                  for l, r in zip(leaves, rows)]
        blocks = blocks + [blocks[-1]] * pad
        stacked = lax.concatenate(blocks, dimension=ax.axis + 1)
        out.append(stacked.reshape(leaves[0].shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def extract_row(carry: Any, row: int, axes: List[LeafAxes],
                width: int) -> Any:
    """The solo-layout carry of packed row ``row``: every batch-bearing
    axis gets that row tiled across the full width (a solo carry's rows
    are identical by construction, so this reproduces the exact layout a
    never-packed run carries), scalar-stacked leaves index back down to
    their per-run scalar, shared leaves pass through."""
    leaves, treedef = jax.tree_util.tree_flatten(carry)
    out = []
    for leaf, ax in zip(leaves, axes):
        if ax.axis is None:
            if ax.ndim == 0 and jnp.ndim(leaf) > 0:
                out.append(leaf[row])
            else:
                # copy shared leaves for the same donation-aliasing
                # reason as pack_rows (scalars are cheap either way)
                out.append(jnp.copy(leaf))
            continue
        block = _row_block(leaf, row, ax.axis, width)
        reps = [1] * block.ndim
        reps[ax.axis + 1] = width
        out.append(jnp.tile(block, reps).reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, out)

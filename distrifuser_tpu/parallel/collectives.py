"""Named-axis collective helpers over the ICI mesh.

TPU-native replacements for the reference's NCCL collective surface
(SURVEY.md §2.2; /root/reference/distrifuser/utils.py:170-179 and the module
files): sync/async `dist.all_gather` -> `lax.all_gather` over a named mesh
axis, `dist.all_reduce(SUM)` -> `lax.psum`, and — new here, because ICI makes
neighbor exchange first-class — the conv halo exchange uses `lax.ppermute`
with a *non-wrapping* permutation instead of gathering every peer's boundary
to every device (the reference allocates an n-peer buffer per conv,
pp/conv2d.py:58-67, but only ever reads the two neighbors' rows,
pp/conv2d.py:72-88).

All helpers must be called inside `shard_map` with the axis bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.config import SP_AXIS


def all_gather(x, axis: str = SP_AXIS):
    """Gather per-device blocks along `axis` into a new leading dim [n, ...]."""
    return lax.all_gather(x, axis)


def all_gather_seq(x, axis: str = SP_AXIS):
    """Gather sequence-sharded [B, L_local, C] into full [B, n*L_local, C]."""
    return lax.all_gather(x, axis, axis=1, tiled=True)


def psum_mean(x, axis: str = SP_AXIS):
    """Average over the axis (reference all_reduce(SUM)/n, pp/groupnorm.py:79-80).
    `lax.pmean` reads the peer count off the bound mesh axis itself."""
    return lax.pmean(x, axis)


def psum(x, axis: str = SP_AXIS):
    """Sum over the axis (reference all_reduce(SUM), tp/attention.py:159).
    The tensor-parallel partial-sum reduce: every TP matmul/conv shard
    contributes its local partial and reads back the full activation —
    per-layer, synchronous, the defining cost of the TP layout (the
    reason displaced patches win at small world sizes, SURVEY.md §2.6).
    Routed through here so distrilint's collective-containment checker
    keeps every raw `lax` collective inside the accounted helper
    surface."""
    return lax.psum(x, axis)


def ring_perm(n: int):
    """Wrapping next-neighbor permutation along a ring axis: device i
    sends to i+1 mod n.  Single source of truth for the ring-attention
    chunk rotation (ops/ring_attention.py) and its software-pipelined
    decomposition: hop h delivers device ``r-h mod n``'s chunk to rank
    ``r``, so n-1 hops cover every peer exactly once."""
    return [(i, (i + 1) % n) for i in range(n)]


def ring_shift(x, n: int, axis: str = SP_AXIS):
    """One ring hop: every device hands ``x`` to its next neighbor and
    receives the previous neighbor's.  The unit the pipelined ring
    attention overlaps — each hop's ppermute is issued BEFORE the compute
    that consumes the previous hop's arrival, so its wire time hides
    behind that chunk's matmuls (FastUSP-style kernel-level
    compute/communication overlap, arXiv 2602.10940)."""
    return lax.ppermute(x, axis, perm=ring_perm(n))


def neighbor_perms(n: int):
    """Non-wrapping neighbor permutations along the patch axis:
    ``(down, up)`` = (send to next device, send to previous device).  Edge
    devices have no source and receive zeros from ppermute — the image-border
    zero padding of a global conv.  Single source of truth for the halo edge
    convention (used by halo_exchange and the batched flush in
    parallel/context.py)."""
    down = [(i, i + 1) for i in range(n - 1)]
    up = [(i + 1, i) for i in range(n - 1)]
    return down, up


def exchange_boundary_rows(bottom, top, n: int, axis: str = SP_AXIS):
    """ppermute already-extracted boundary tensors to spatial neighbors:
    ``(from_prev, from_next)`` = (previous device's ``bottom``, next
    device's ``top``).  Edge devices receive zeros.  Factored out of
    ``halo_exchange`` so the compressed refresh path (parallel/compress.py
    payload + fp32 scale pairs) rides the exact same edge convention."""
    down, up = neighbor_perms(n)
    from_prev = lax.ppermute(bottom, axis, perm=down)
    from_next = lax.ppermute(top, axis, perm=up)
    return from_prev, from_next


def halo_exchange(x, halo: int, n: int, axis: str = SP_AXIS):
    """Exchange boundary rows with spatial neighbors along the patch axis.

    ``x`` is the local row-patch [B, h, W, C] (NHWC).  Returns
    ``(from_prev, from_next)``: the previous device's *bottom* `halo` rows and
    the next device's *top* `halo` rows, each [B, halo, W, C].  Edge devices
    receive zeros, which coincides exactly with the zero row-padding a global
    conv would apply at the image border — the reference reproduces this with
    explicit F.pad at ranks 0 / n-1 (pp/conv2d.py:73-78).
    """
    if halo == 0 or n == 1:
        zeros = jnp.zeros(x.shape[:1] + (halo,) + x.shape[2:], x.dtype)
        return zeros, zeros
    return exchange_boundary_rows(x[:, -halo:], x[:, :halo], n, axis)


def gather_rows(patch, axis: str = SP_AXIS):
    """Reassemble row-sharded [B, h, W, C] patches into the full [B, H, W, C].

    The per-step output gather of the reference models
    (distri_sdxl_unet_pp.py:162-169: world all_gather + torch.cat on dim 2).
    """
    return lax.all_gather(patch, axis, axis=1, tiled=True)


def gather_cols(patch, axis: str = SP_AXIS):
    """Column-split variant used by naive patch parallelism (split_scheme='col',
    naive_patch_sdxl.py:119-122)."""
    return lax.all_gather(patch, axis, axis=2, tiled=True)

"""Displaced patch parallelism for the MMDiT (SD3-class joint transformer).

DistriFusion's method applied to the joint-attention architecture.  The
token-major layout makes this the same shape as parallel/dit_sp.py: the
image-token sequence shards over the ``sp`` axis, and JOINT attention is
the only op that crosses patch boundaries — but here the attended keys are
``concat(context, image)``, which splits the problem cleanly in two:

* the **context stream** is short (77-333 tokens) and must stay exact (its
  activations feed every later block's modulation of the image stream), so
  every device computes the FULL context stream, replicated.  Its K/V need
  no assembly, no staleness, no collective.
* the **image stream**'s K/V are the only cross-device exchange:
  - sync phase (steps <= warmup, reference counter semantics §2.3): each
    block's fresh local image K/V are all-gathered — exact joint attention;
  - stale phase: each block attends over the previous step's gathered
    image K/V with its own slot overwritten fresh (the reference's
    pp/attn.py:135-140 displaced semantics), then all-gathers fresh K/V
    into the scan carry — consumed only next step, so XLA overlaps the
    collective with the remaining blocks' compute.

The replicated context stream does duplicate its (small) compute per
device; at SD3 scale that is ~¼ of one stream's tokens at n=8 vs a 4096-
token image sequence — noise next to the image-side saving.

Two layouts, selected by ``attn_impl`` (the same pair the UNet offers):
"gather" carries the full gathered stale image KV (reference buffer
layout, O(L) state); "ring" carries only the own chunk (O(L/n)) and
streams peers through the shared online-softmax ring, with the replicated
context KV merged as a NON-rotating static block (ring_pass kv_static) —
no refresh collective at all.  The head-sharding ulysses/usp layouts are
undefined for joint attention's two-origin queries and are rejected
loudly in __init__ rather than silently falling back.

Every device returns the full latent and steps the scheduler replicated —
the DenoiseRunner/DiTDenoiseRunner contract, so pipelines treat all three
interchangeably.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ..models import dit as dit_mod
from ..models import mmdit as mm
from ..models.mmdit import MMDiTConfig
from ..ops.linear import linear
from ..schedulers import BaseScheduler
from ..utils.config import CFG_AXIS, DP_AXIS, SP_AXIS, DistriConfig
from .collectives import all_gather_seq
from .compress import refresh_gather_seq, refresh_period, wire_nbytes
from .guidance import branch_select, combine_guidance
from .stepcache import is_shallow_at, run_cadence


class MMDiTDenoiseRunner:
    """Compiled displaced-patch generation loop for an MMDiT.

    API mirrors DiTDenoiseRunner.generate, with SD3 conditioning inputs:
    ``enc`` [n_br, B, Lc, joint_attention_dim] sequence embeddings and
    ``pooled`` [n_br, B, pooled_projection_dim] pooled text embeddings.
    """

    def __init__(
        self,
        distri_config: DistriConfig,
        mmdit_config: MMDiTConfig,
        params,
        scheduler: BaseScheduler,
    ):
        self.cfg = distri_config
        self.mcfg = mmdit_config
        self.params = params
        self.scheduler = scheduler
        if distri_config.attn_impl not in ("gather", "ring"):
            raise ValueError(
                f"attn_impl={distri_config.attn_impl!r}: the MMDiT runner "
                "implements 'gather' (reference-style full stale KV) and "
                "'ring' (O(L/n) state; the replicated context KV rides the "
                "ring as a non-rotating static block) — the head-sharding "
                "ulysses/usp layouts are not defined for joint attention's "
                "two-origin queries"
            )
        if distri_config.comm_batch:
            raise ValueError(
                "comm_batch applies to the UNet's per-layer halo/moment "
                "exchanges; the MMDiT path has one collective kind already"
            )
        if (distri_config.comm_compress != "none"
                and distri_config.attn_impl != "gather"):
            raise ValueError(
                "comm_compress compresses the displaced image-KV refresh "
                "gathers of attn_impl='gather'; 'ring' carries only the "
                "local chunk and has no refresh collective to compress"
            )
        if (distri_config.refresh_fraction < 1.0
                and distri_config.attn_impl != "gather"):
            raise ValueError(
                "refresh_fraction < 1 (PCPP) thins the displaced image-KV "
                "refresh gathers of attn_impl='gather'; 'ring' carries only "
                "the local chunk and has no refresh collective to thin"
            )
        n = distri_config.n_device_per_batch
        _rk = refresh_period(distri_config.refresh_fraction)
        if (_rk > 1 and mmdit_config.num_tokens % n == 0
                and (mmdit_config.num_tokens // n) % _rk != 0):
            raise ValueError(
                f"refresh_fraction=1/{_rk} needs the per-device token chunk "
                f"({mmdit_config.num_tokens // n}) divisible by {_rk} — "
                "each stale step gathers exactly one strided row group"
            )
        if mmdit_config.num_tokens % n != 0:
            raise ValueError(
                f"token count {mmdit_config.num_tokens} must be divisible "
                f"by the sp degree {n}"
            )
        if distri_config.step_cache_enabled:
            k_cache = distri_config.step_cache_depth
            max_k = mmdit_config.depth - max(
                mmdit_config.dual_attention_blocks, 1
            )
            if not 1 <= k_cache <= max_k:
                raise ValueError(
                    f"step_cache_depth={k_cache} must be in [1, {max_k}] for "
                    f"this {mmdit_config.depth}-block MMDiT: the cut must "
                    "stay below the dual-attention prefix "
                    f"({mmdit_config.dual_attention_blocks} blocks) and "
                    "leave at least one shallow block"
                )
        if (distri_config.height // 8 != mmdit_config.sample_size) or (
            distri_config.width // 8 != mmdit_config.sample_size
        ):
            raise ValueError(
                f"DistriConfig {distri_config.height}x{distri_config.width} "
                f"implies latent {distri_config.latent_height}, but "
                f"MMDiTConfig.sample_size is {mmdit_config.sample_size}"
            )
        self._compiled: Dict[int, Any] = {}
        # compiled-loop per-step callback target (_build_fused_callback)
        self._active_callback = None

    # ------------------------------------------------------------------

    def _eval_model(self, params, x_full, s, kv_state, phase_sync,
                    ctx0, vec_all, pos, shallow=False):
        """One MMDiT evaluation on this device's token rows.

        Returns (full guided-input velocity [Bl, N, D_out], new kv_state).
        ``kv_state``: gathered [depth, 2, Bl, N, hidden] stale image K/V —
        or, with dual-attention blocks (SD3.5-medium), a dict
        ``{"j": [depth, ...] joint-image KV, "d": [k_dual, ...] attn2 KV}``
        (attn2 is image-only self-attention over the same sharded rows, so
        its displaced state has the same per-block layout).  With the step
        cache enabled the whole thing wraps to ``{"kv": <that state>,
        "deep": [Bl, N/n, hidden]}``; ``shallow`` runs only the first
        ``depth - step_cache_depth`` blocks on the image stream and adds the
        carried deep residual (the skipped blocks' displaced KV rides
        through untouched — the cut always sits past the dual prefix).
        ``ctx0``: [Bl, Lc, hidden] projected context entering block 0 —
        recomputed per step is unnecessary (it is timestep-independent),
        but the stream EVOLVES through the blocks, so it restarts from
        ctx0 each step (unlike dit_sp's per-block constant caption KV).
        """
        cfg, mcfg = self.cfg, self.mcfg
        sched = self.scheduler
        n = cfg.n_device_per_batch
        chunk = mcfg.num_tokens // n
        sp_idx = lax.axis_index(SP_AXIS)
        offset = sp_idx * chunk
        compute_dtype = params["proj_in"]["kernel"].dtype

        x_in = sched.scale_model_input(x_full, s)
        rows = lax.dynamic_slice(
            x_in, (0, offset, 0), (x_in.shape[0], chunk, x_in.shape[2])
        ).astype(compute_dtype)
        if not cfg.cfg_split and cfg.do_classifier_free_guidance:
            rows = jnp.concatenate([rows, rows], axis=0)
        pos_rows = lax.dynamic_slice(pos, (offset, 0), (chunk, pos.shape[1]))
        h = linear(params["proj_in"], rows) + pos_rows[None]
        if jnp.ndim(s) == 0:
            vec = vec_all[s]  # [Bl, hidden] — one timestep for every row
        else:
            # per-row step indices (packed cohort dispatch): vec_all is
            # [S, Bl, hidden]; pick row b's own step on the diagonal, with
            # the step vector fold-doubled when the CFG branches ride the
            # batch dim (branch-major, same layout as ``rows`` above)
            sb = (jnp.concatenate([s, s])
                  if vec_all.shape[1] == 2 * s.shape[0] else s)
            vec = vec_all[sb, jnp.arange(vec_all.shape[1])]

        no_refresh = cfg.mode == "no_sync"  # keep warmup KV forever (§2.3)

        def _gather_assemble(kv_blk, box):
            """Displaced-KV assembly closure for one attention's image KV:
            sync -> all-gather fresh (exact); stale -> carried gathered KV
            with this device's slot overwritten fresh (reference
            pp/attn.py:135-140 semantics)."""

            def assemble(k_fresh, v_fresh):
                if phase_sync:
                    kv = (all_gather_seq(k_fresh), all_gather_seq(v_fresh))
                else:
                    kv = (
                        lax.dynamic_update_slice(
                            kv_blk[0], k_fresh, (0, offset, 0)
                        ),
                        lax.dynamic_update_slice(
                            kv_blk[1], v_fresh, (0, offset, 0)
                        ),
                    )
                box["kv"] = kv
                return kv

            return assemble

        def _gather_refresh(box, kv_blk, k, v):
            # refresh for the NEXT step: deferred consumption lets XLA
            # overlap the gather with the remaining blocks' compute.  Stale
            # refreshes route through the compression layer
            # (parallel/compress.py): a plain tiled gather at
            # comm_compress="none", int8/fp8 payload + fp32 scales otherwise
            if phase_sync:
                return jnp.stack(list(box["kv"]))
            if no_refresh:
                return kv_blk
            return refresh_gather_seq(
                jnp.stack([k, v]), kv_blk, cfg.comm_compress, offset,
                fraction=cfg.refresh_fraction, step=s,
            )

        def block_body_gather(carry, xs):
            hx, hc = carry
            bp, kv_blk = xs  # kv_blk [2, Bl, N, hid] stale gathered image KV
            box = {}
            hx, hc, (k, v) = mm.mmdit_block(
                bp, mcfg, hx, hc, vec, kv_assemble=_gather_assemble(kv_blk, box)
            )
            return (hx, hc), _gather_refresh(box, kv_blk, k, v)

        def dual_body_gather(carry, xs):
            hx, hc = carry
            bp, dp, kv_blk, kv2_blk = xs
            box, box2 = {}, {}
            hx, hc, (k, v), (k2, v2) = mm.mmdit_block(
                bp, mcfg, hx, hc, vec,
                kv_assemble=_gather_assemble(kv_blk, box),
                dual_p=dp, kv2_assemble=_gather_assemble(kv2_blk, box2),
            )
            return (hx, hc), (
                _gather_refresh(box, kv_blk, k, v),
                _gather_refresh(box2, kv2_blk, k2, v2),
            )

        from ..ops.ring_attention import ring_pass

        def _ring_joint_core(kv_blk, box):
            def core(cq, xq, ckv, xkv):
                ck, cv = ckv
                xk, xv = xkv
                kv_own = jnp.concatenate([xk, xv], axis=-1)
                box["kv"] = kv_own
                static = jnp.concatenate([ck, cv], axis=-1)
                # sync phase rotates fresh peer chunks (exact); stale phase
                # rotates each peer's previous-step chunk from the carry.
                # The replicated context KV never moves: it merges as a
                # static block into every device's online softmax.
                rotating = kv_own if phase_sync else kv_blk
                q = jnp.concatenate([cq, xq], axis=1)
                out = ring_pass(q, kv_own, rotating, n, SP_AXIS,
                                heads=mcfg.num_heads, kv_static=static)
                b_, lq_ = q.shape[0], q.shape[1]
                out = out.astype(xq.dtype).transpose(0, 2, 1, 3)
                return out.reshape(b_, lq_, mcfg.hidden_size)

            return core

        def _ring_dual_core(kv2_blk, box2):
            def core2(q2, xkv2):
                k2, v2 = xkv2
                kv_own = jnp.concatenate([k2, v2], axis=-1)
                box2["kv"] = kv_own
                rotating = kv_own if phase_sync else kv2_blk
                out = ring_pass(q2, kv_own, rotating, n, SP_AXIS,
                                heads=mcfg.num_heads)
                b_, lq_ = q2.shape[0], q2.shape[1]
                out = out.astype(q2.dtype).transpose(0, 2, 1, 3)
                return out.reshape(b_, lq_, mcfg.hidden_size)

            return core2

        def _ring_refresh(box, kv_blk):
            # next step's stale state is this step's own fresh chunk — no
            # refresh collective at all (ring_attention.py semantics)
            if phase_sync or not no_refresh:
                return box["kv"]
            return kv_blk

        def block_body_ring(carry, xs):
            hx, hc = carry
            bp, kv_blk = xs  # kv_blk [Bl, chunk, 2*hid] own stale chunk
            box = {}
            hx, hc, _ = mm.mmdit_block(
                bp, mcfg, hx, hc, vec, attn_core=_ring_joint_core(kv_blk, box)
            )
            return (hx, hc), _ring_refresh(box, kv_blk)

        def dual_body_ring(carry, xs):
            hx, hc = carry
            bp, dp, kv_blk, kv2_blk = xs
            box, box2 = {}, {}
            hx, hc, _, _ = mm.mmdit_block(
                bp, mcfg, hx, hc, vec,
                attn_core=_ring_joint_core(kv_blk, box),
                dual_p=dp, attn2_core=_ring_dual_core(kv2_blk, box2),
            )
            return (hx, hc), (
                _ring_refresh(box, kv_blk), _ring_refresh(box2, kv2_blk)
            )

        ring = cfg.attn_impl == "ring"
        block_body = block_body_ring if ring else block_body_gather
        k_dual = mcfg.dual_attention_blocks
        sc = cfg.step_cache_enabled
        inner = kv_state["kv"] if sc else kv_state
        d_keep = mcfg.depth - cfg.step_cache_depth if sc else mcfg.depth

        def capture_body(carry, xs):
            # block_body wrapped to record the image stream at the cut, so
            # a full step can refresh the deep residual (h_final - h_mid)
            streams, h_mid = carry
            streams, fresh = block_body(streams, xs[1:])
            h_mid = jnp.where(xs[0] == d_keep - 1, streams[0], h_mid)
            return (streams, h_mid), fresh

        if k_dual:
            dual_body = dual_body_ring if ring else dual_body_gather
            kv_j, kv_d = inner["j"], inner["d"]
            bp_pre = jax.tree.map(lambda l: l[:k_dual], params["blocks"])
            (h, hc), (kvj_pre, kvd_new) = lax.scan(
                dual_body, (h, ctx0),
                (bp_pre, params["blocks_dual"], kv_j[:k_dual], kv_d),
            )
            if sc and shallow:
                bp_mid = jax.tree.map(
                    lambda l: l[k_dual:d_keep], params["blocks"]
                )
                (h, _), kvj_mid = lax.scan(
                    block_body, (h, hc), (bp_mid, kv_j[k_dual:d_keep])
                )
                h = h + kv_state["deep"]
                kv_new = {
                    "kv": {"j": jnp.concatenate(
                        [kvj_pre, kvj_mid, kv_j[d_keep:]], axis=0),
                        "d": kvd_new},
                    "deep": kv_state["deep"],
                }
            elif sc:
                bp_suf = jax.tree.map(lambda l: l[k_dual:], params["blocks"])
                ((h, _), h_mid), kvj_suf = lax.scan(
                    capture_body, ((h, hc), h),
                    (jnp.arange(k_dual, mcfg.depth), bp_suf, kv_j[k_dual:]),
                )
                kv_new = {
                    "kv": {"j": jnp.concatenate([kvj_pre, kvj_suf], axis=0),
                           "d": kvd_new},
                    "deep": h - h_mid,
                }
            else:
                bp_suf = jax.tree.map(lambda l: l[k_dual:], params["blocks"])
                (h, _), kvj_suf = lax.scan(
                    block_body, (h, hc), (bp_suf, kv_j[k_dual:])
                )
                kv_new = {"j": jnp.concatenate([kvj_pre, kvj_suf], axis=0),
                          "d": kvd_new}
        elif sc and shallow:
            head = jax.tree.map(
                lambda l: l[:d_keep], (params["blocks"], inner)
            )
            (h, _), kv_head = lax.scan(block_body, (h, ctx0), head)
            h = h + kv_state["deep"]
            kv_new = {
                "kv": jnp.concatenate([kv_head, inner[d_keep:]], axis=0),
                "deep": kv_state["deep"],
            }
        elif sc:
            ((h, _), h_mid), kv_all = lax.scan(
                capture_body, ((h, ctx0), h),
                (jnp.arange(mcfg.depth), params["blocks"], inner),
            )
            kv_new = {"kv": kv_all, "deep": h - h_mid}
        else:
            (h, _), kv_new = lax.scan(
                block_body, (h, ctx0), (params["blocks"], kv_state)
            )
        out_rows = mm.final_layer(params, mcfg, h, vec)
        out_full = all_gather_seq(out_rows)
        return out_full, kv_new

    def _make_step(self, params, enc, pooled, gs, batch):
        """Per-device step closure + local branch count and dtype."""
        cfg, mcfg = self.cfg, self.mcfg
        sched = self.scheduler
        my_enc, _, _ = branch_select(cfg, enc)
        my_pooled, _, _ = branch_select(cfg, pooled)
        compute_dtype = params["proj_in"]["kernel"].dtype
        pos = mm.pos_embed_cropped(mcfg, compute_dtype)
        ctx0 = linear(params["ctx_in"], my_enc.astype(compute_dtype))
        ts = sched.timesteps()
        # [S, Bl, hidden] — the conditioning vec varies per step (timestep
        # features) AND per batch row (pooled text), unlike the DiT's
        # scalar-timestep adaLN table
        vec_all = jax.vmap(
            lambda t: mm.cond_vec(params, mcfg, t, my_pooled)
        )(ts)

        def step(x, sstate, kv, s, phase_sync, shallow=False):
            out, kv = self._eval_model(
                params, x, s, kv, phase_sync, ctx0, vec_all, pos,
                shallow=shallow,
            )
            guided = combine_guidance(cfg, out, gs, batch)
            x, sstate = sched.step(x, guided.astype(jnp.float32), s, sstate)
            return x, sstate, kv

        return step, my_enc.shape[0], compute_dtype

    def _kv0(self, bloc, compute_dtype):
        """Per-device zero stale-KV state: a bare [depth, ...] array, or —
        with dual-attention blocks — ``{"j": [depth, ...], "d": [k, ...]}``
        (every consumer treats the state as a pytree)."""
        mcfg = self.mcfg
        if self.cfg.attn_impl == "ring":
            chunk = mcfg.num_tokens // self.cfg.n_device_per_batch

            def mk(d):
                return jnp.zeros(
                    (d, bloc, chunk, 2 * mcfg.hidden_size), compute_dtype
                )
        else:
            def mk(d):
                return jnp.zeros(
                    (d, 2, bloc, mcfg.num_tokens, mcfg.hidden_size),
                    compute_dtype,
                )

        if mcfg.dual_attention_blocks:
            kv = {"j": mk(mcfg.depth), "d": mk(mcfg.dual_attention_blocks)}
        else:
            kv = mk(mcfg.depth)
        if self.cfg.step_cache_enabled:
            chunk = mcfg.num_tokens // self.cfg.n_device_per_batch
            return {"kv": kv, "deep": jnp.zeros(
                (bloc, chunk, mcfg.hidden_size), compute_dtype)}
        return kv

    def _device_loop(self, params, latents, enc, pooled, gs, num_steps,
                     start_step=0, end_step=None):
        # end_step: exclusive stop index; start_step > 0 is the img2img
        # entry (latents already noised to that schedule point via
        # scheduler.add_noise) — warmup counts from the first step actually
        # executed, the same convention as runner._device_loop
        cfg, mcfg = self.cfg, self.mcfg
        num_steps, n_sync = self._exec_window(num_steps, start_step, end_step)
        batch = latents.shape[0]
        step, bloc, compute_dtype = self._make_step(
            params, enc, pooled, gs, batch
        )
        x = dit_mod.patchify(mcfg, latents.astype(jnp.float32))
        sstate = self.scheduler.init_state(x.shape)
        kv0 = self._kv0(bloc, compute_dtype)

        def sync_body(i, carry):
            x, ss, kv = carry
            return step(x, ss, kv, i, True)

        x, sstate, kv = lax.fori_loop(
            start_step, start_step + n_sync, sync_body, (x, sstate, kv0)
        )

        if cfg.step_cache_enabled:
            # temporal step-cache cadence (parallel/stepcache.py): super-
            # steps of (interval-1) shallow + 1 full after the warmup —
            # the same two-bodies-in-a-scan shape as the other runners
            steady_sync = cfg.mode == "full_sync" or not cfg.is_sp
            s0 = start_step + n_sync

            def run_step(carry, i, shallow):
                x, ss, kv = carry
                return step(x, ss, kv, i, steady_sync, shallow)

            x, _, _ = run_cadence(
                (x, sstate, kv), s0, num_steps - s0,
                cfg.step_cache_interval, run_step,
            )
            return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)

        if start_step + n_sync < num_steps:
            def stale_body(carry, i):
                x, ss, kv = carry
                return step(x, ss, kv, i, False), None

            (x, _, _), _ = lax.scan(
                stale_body, (x, sstate, kv),
                jnp.arange(start_step + n_sync, num_steps)
            )
        return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)

    # ------------------------------------------------------------------

    def _build(self, num_steps: int, start_step: int = 0,
               end_step: int = None):
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)
        device_loop = partial(self._device_loop, num_steps=num_steps,
                              start_step=start_step, end_step=end_step)
        lat_spec = P(DP_AXIS)
        enc_spec = P(None, DP_AXIS)

        def loop(params, latents, enc, pooled, gs):
            return shard_map(
                device_loop,
                mesh=cfg.mesh,
                in_specs=(P(), lat_spec, enc_spec, enc_spec, P()),
                out_specs=lat_spec,
                check_vma=False,
            )(params, latents, enc, pooled, gs)

        return jax.jit(loop)

    # ------------------------------------------------------------------
    # per-step (uncompiled-loop) mode + compiled-loop callbacks
    # ------------------------------------------------------------------

    def _token_specs(self):
        """(x_spec, kv_spec, ss_spec, enc_spec) for the stepwise boundary:
        patchified tokens shard over dp on batch; the stale KV varies per
        device and stacks on a fresh leading (dp, cfg, sp) axis; scheduler
        state shards x-shaped leaves over dp, scalars replicate."""
        lat_spec = P(DP_AXIS)
        kv_spec = P((DP_AXIS, CFG_AXIS, SP_AXIS))
        mcfg = self.mcfg
        ss_shapes = self.scheduler.init_state(
            (1, mcfg.num_tokens, mcfg.token_dim)
        )
        ss_spec = jax.tree.map(
            lambda l: P(DP_AXIS) if jnp.ndim(l) >= 3 else P(), ss_shapes
        )
        return lat_spec, kv_spec, ss_spec, P(None, DP_AXIS)

    def _make_stepper(self, phase_sync: bool, shallow: bool = False):
        """Un-jitted shard_map'd single step over PATCHIFIED tokens
        [B, N, token_dim] (global-array signature): the host loop and the
        compiled-callback loop both drive it."""
        cfg = self.cfg
        x_spec, kv_spec, ss_spec, enc_spec = self._token_specs()

        def device_step(params, s, x, kv, sstate, enc, pooled, gs):
            step, _, _ = self._make_step(params, enc, pooled, gs, x.shape[0])
            kv_local = jax.tree.map(lambda l: l[0], kv)
            x, sstate, kv_new = step(x, sstate, kv_local, s, phase_sync,
                                     shallow)
            return x, sstate, jax.tree.map(lambda l: l[None], kv_new)

        def stepper(params, s, x, kv, sstate, enc, pooled, gs):
            return shard_map(
                device_step,
                mesh=cfg.mesh,
                in_specs=(P(), P(), x_spec, kv_spec, ss_spec, enc_spec,
                          enc_spec, P()),
                out_specs=(x_spec, ss_spec, kv_spec),
                check_vma=False,
            )(params, s, x, kv, sstate, enc, pooled, gs)

        return stepper

    def _kv0_global(self, batch):
        """Global stepwise-layout zeros: per-device _kv0 stacked over every
        mesh device on a fresh leading axis."""
        cfg = self.cfg
        n_total = cfg.mesh.devices.size
        bloc = (1 if cfg.cfg_split or not cfg.do_classifier_free_guidance
                else 2) * (batch // cfg.dp_degree)
        per_dev = self._kv0(bloc, self.params["proj_in"]["kernel"].dtype)
        return jax.tree.map(
            lambda l: jnp.zeros((n_total,) + l.shape, l.dtype), per_dev
        )

    def _exec_window(self, num_steps, start_step, end_step):
        num_exec_end = num_steps if end_step is None else end_step
        full_sync = self.cfg.mode == "full_sync" or not self.cfg.is_sp
        n_exec = num_exec_end - start_step
        n_sync = (n_exec if full_sync and not self.cfg.step_cache_enabled
                  else min(self.cfg.warmup_steps + 1, n_exec))
        return num_exec_end, n_sync

    def _ensure_stepper(self, num_steps: int, sync: bool,
                        shallow: bool = False):
        """Jitted per-step program, cached by (num_steps, phase, shallow):
        _make_step bakes the scheduler tables at trace time, so a different
        step count MUST get a fresh program (same convention as
        DenoiseRunner's ("stepwise", num_steps))."""
        fns = self._compiled.setdefault(("stepwise", num_steps), {})
        fkey = (sync, shallow)
        if fkey not in fns:
            fns[fkey] = jax.jit(self._make_stepper(sync, shallow),
                                donate_argnums=(3,))
        return fns[fkey]

    def _ensure_stale_scan(self, num_steps: int):
        """Hybrid mode's fused stale-only program for the default execution
        window (mirrors DenoiseRunner._ensure_stale_scan)."""
        n_sync = min(self.cfg.warmup_steps + 1, num_steps)
        skey = ("stale_scan", num_steps, n_sync)
        if skey not in self._compiled:
            self._compiled[skey] = self._build_stale_scan(num_steps, n_sync)
        return self._compiled[skey], n_sync

    def _generate_stepwise(self, latents, enc, pooled, gs, num_steps,
                           start_step=0, end_step=None, callback=None):
        """Python loop over per-step compiled calls (use_cuda_graph=False
        parity, same contract as DenoiseRunner._generate_stepwise):
        identical numerics to the fused loop, per-step latency visible
        from the host, diffusers legacy ``callback(i, t, latents)``."""
        cfg, mcfg = self.cfg, self.mcfg
        sched = self.scheduler
        sched.set_timesteps(num_steps)
        num_exec_end, n_sync = self._exec_window(num_steps, start_step,
                                                 end_step)
        x = dit_mod.patchify(mcfg, jnp.asarray(latents, jnp.float32))
        sstate = sched.init_state(x.shape)
        kv = self._kv0_global(latents.shape[0])
        pooled = jnp.asarray(pooled)
        sc = cfg.step_cache_enabled
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        for i in range(start_step, num_exec_end):
            sync = one_phase or i < start_step + n_sync
            shallow = sc and is_shallow_at(
                i, start_step + n_sync, cfg.step_cache_interval
            )
            x, sstate, kv = self._ensure_stepper(num_steps, sync, shallow)(
                self.params, jnp.asarray(i), x, kv, sstate, enc, pooled, gs,
            )
            if callback is not None:
                callback(i, sched.timesteps()[i],
                         dit_mod.unpatchify(mcfg, x, mcfg.out_channels))
        return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)

    # -- explicit-carry stepwise API (step-granular serve substrate) -------

    def stepwise_carry_init(self, latents, num_steps: int):
        """Start a host-driven denoise with the carry held EXTERNALLY:
        ``(x, sstate, kv)`` — the state one `_generate_stepwise`
        iteration threads, so the step-granular serve layer
        (serve/stepbatch.py) can park/resume/interleave requests between
        steps while each carry replays the identical per-step programs."""
        self.scheduler.set_timesteps(num_steps)
        x = dit_mod.patchify(self.mcfg, jnp.asarray(latents, jnp.float32))
        return (x, self.scheduler.init_state(x.shape),
                self._kv0_global(latents.shape[0]))

    def stepwise_carry_step(self, carry, i: int, enc, pooled, gs,
                            num_steps: int):
        """Advance one explicit carry by exactly step ``i`` — the SAME
        compiled stepper `_generate_stepwise` dispatches for this
        (phase, shallow) signature, so solo and interleaved executions
        are byte-identical."""
        cfg = self.cfg
        x, sstate, kv = carry
        _, n_sync = self._exec_window(num_steps, 0, None)
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        sync = one_phase or i < n_sync
        shallow = cfg.step_cache_enabled and is_shallow_at(
            i, n_sync, cfg.step_cache_interval)
        return self._ensure_stepper(num_steps, sync, shallow)(
            self.params, jnp.asarray(i), x, kv, sstate, enc, pooled, gs)

    def stepwise_carry_latent(self, carry):
        """The carry's current GLOBAL latent [B, H/8, W/8, C] (preview +
        decode input) — does not consume the carry."""
        return dit_mod.unpatchify(self.mcfg, carry[0],
                                  self.mcfg.out_channels)

    # -- packed cohort rows (serve/executors.py step_run; parallel/rowpack) --

    def stepwise_rows_supported(self) -> bool:
        """Whether packed multi-row dispatch preserves bit-identity on this
        config.  DP-split batches can't carry a replicated per-row step
        vector; the PCPP partial-refresh rotation (`refresh_gather_seq`
        step=s) and per-tensor compression scales couple rows."""
        cfg = self.cfg
        return (cfg.dp_degree == 1 and cfg.refresh_fraction >= 1
                and cfg.comm_compress == "none")

    def stepwise_carry_signature(self, carry, i: int, num_steps: int):
        """Compiled-program key of step ``i`` — two carries whose next
        steps share this tuple run the SAME jitted stepper and may pack
        into one dispatch."""
        cfg = self.cfg
        _, n_sync = self._exec_window(num_steps, 0, None)
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        sync = one_phase or i < n_sync
        shallow = cfg.step_cache_enabled and is_shallow_at(
            i, n_sync, cfg.step_cache_interval)
        return ("mmdit", sync, shallow, num_steps)

    def stepwise_carry_rows_axes(self, carry, num_steps: int):
        """Per-leaf rowpack plan for this runner's carry layout, found by
        comparing the carry's abstract shapes at batch widths w and 2w
        (rowpack.axes_from_shapes) — no hand-maintained layout table."""
        from . import rowpack

        x = carry[0]
        w = x.shape[0]

        def shapes(k):
            return jax.eval_shape(lambda: (
                jnp.zeros((w * k,) + x.shape[1:], x.dtype),
                self.scheduler.init_state((w * k,) + x.shape[1:]),
                self._kv0_global(w * k),
            ))

        return rowpack.axes_from_shapes(shapes(1), shapes(2))

    def stepwise_carry_step_rows(self, carry, i_rows, enc, pooled,
                                 gs_rows, num_steps: int):
        """Advance ``len(i_rows)`` packed rows in ONE dispatch of the same
        jitted stepper the solo path uses: row r steps by its own index
        ``i_rows[r]`` under its own scale ``gs_rows[r]``.  All rows must
        share one (phase, shallow) signature — callers group by
        `stepwise_carry_signature` first."""
        x, sstate, kv = carry
        sigs = {self.stepwise_carry_signature(carry, int(i), num_steps)
                for i in i_rows}
        if len(sigs) != 1:
            raise ValueError(
                f"packed rows span {len(sigs)} step signatures: {sigs}"
            )
        _, sync, shallow, _ = next(iter(sigs))
        return self._ensure_stepper(num_steps, sync, shallow)(
            self.params, jnp.asarray(list(i_rows)), x, kv, sstate, enc,
            pooled, jnp.asarray(list(gs_rows), jnp.float32))

    def _build_stale_scan(self, num_steps: int, n_start: int):
        """Fused stale steady-state ONLY (cfg.hybrid_loop; the MMDiT analog
        of DenoiseRunner._build_stale_scan): the sync warmup runs through
        the per-step programs, their KV state enters here across the
        shard_map boundary in the stepwise layout, and this ONE-body
        program scans the remaining stale steps — roughly half the fully
        fused program's (remote) compile at identical numerics."""
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)
        x_spec, kv_spec, ss_spec, enc_spec = self._token_specs()

        def device_scan(params, x, kv, sstate, enc, pooled, gs):
            step, _, _ = self._make_step(params, enc, pooled, gs, x.shape[0])

            def body(carry, i):
                x, ss, kv = carry
                return step(x, ss, kv, i, False), None

            (x, _, _), _ = lax.scan(
                body, (x, sstate, jax.tree.map(lambda l: l[0], kv)),
                jnp.arange(n_start, num_steps)
            )
            return x

        def loop(params, x, kv, sstate, enc, pooled, gs):
            return shard_map(
                device_scan,
                mesh=cfg.mesh,
                in_specs=(P(), x_spec, kv_spec, ss_spec, enc_spec, enc_spec,
                          P()),
                out_specs=x_spec,
                check_vma=False,
            )(params, x, kv, sstate, enc, pooled, gs)

        # x and the incoming state (KV AND scheduler state — its x-shaped
        # leaves are latent-sized) die at this call; let XLA reuse the HBM
        return jax.jit(loop, donate_argnums=(1, 2, 3))

    def _hybrid_dispatch(self, num_steps: int) -> bool:
        cfg = self.cfg
        return (cfg.hybrid_loop and cfg.is_sp and cfg.mode != "full_sync"
                and min(cfg.warmup_steps + 1, num_steps) < num_steps)

    def _generate_hybrid(self, latents, enc, pooled, gs, num_steps):
        """Sync warmup via per-step programs + one fused stale-only scan."""
        cfg, mcfg = self.cfg, self.mcfg
        sched = self.scheduler
        sched.set_timesteps(num_steps)
        stale_scan, n_sync = self._ensure_stale_scan(num_steps)
        x = dit_mod.patchify(mcfg, jnp.asarray(latents, jnp.float32))
        sstate = sched.init_state(x.shape)
        kv = self._kv0_global(latents.shape[0])
        pooled = jnp.asarray(pooled)
        for i in range(n_sync):
            x, sstate, kv = self._ensure_stepper(num_steps, True)(
                self.params, jnp.asarray(i), x, kv, sstate, enc, pooled, gs,
            )
        out = stale_scan(self.params, x, kv, sstate, enc, pooled, gs)
        return dit_mod.unpatchify(mcfg, out, mcfg.out_channels)

    def _fire_callback(self, i, t, x):
        """Host trampoline for the compiled-loop callback (io_callback)."""
        cb = self._active_callback
        if cb is not None:
            cb(int(i), t, x)

    def _build_fused_callback(self, num_steps: int, start_step: int = 0,
                              end_step: int = None):
        """Compiled loop that fires per-step host callbacks — the MMDiT
        analog of DenoiseRunner._build_fused_callback: lax.scan over the
        shard_map'd stepwise step with ordered io_callback shipping the
        GLOBAL unpatchified latents after each step (scan for both
        segments; ordered effects are unsupported in fori bodies)."""
        from jax.experimental import io_callback

        cfg, mcfg = self.cfg, self.mcfg
        sched = self.scheduler
        sched.set_timesteps(num_steps)
        num_exec_end, n_sync = self._exec_window(num_steps, start_step,
                                                 end_step)
        sync_step = self._make_stepper(True)
        stale_step = self._make_stepper(False)

        def loop(params, latents, enc, pooled, gs):
            x = dit_mod.patchify(mcfg, latents.astype(jnp.float32))
            sstate = sched.init_state(x.shape)
            kv = self._kv0_global(latents.shape[0])
            tsteps = sched.timesteps()

            def body_for(step_fn):
                def body(carry, i):
                    x, kv, ss = carry
                    x, ss, kv = step_fn(params, i, x, kv, ss, enc, pooled,
                                        gs)
                    io_callback(
                        self._fire_callback, None, i, tsteps[i],
                        dit_mod.unpatchify(mcfg, x, mcfg.out_channels),
                        ordered=True,
                    )
                    return (x, kv, ss), None
                return body

            (x, kv, sstate), _ = lax.scan(
                body_for(sync_step), (x, kv, sstate),
                jnp.arange(start_step, start_step + n_sync),
            )
            if start_step + n_sync < num_exec_end:
                (x, kv, sstate), _ = lax.scan(
                    body_for(stale_step), (x, kv, sstate),
                    jnp.arange(start_step + n_sync, num_exec_end),
                )
            return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)

        return jax.jit(loop)

    def comm_report(self, batch_size: int = 1) -> Dict[str, Any]:
        """Per-device stale-state and per-step collective volumes (elements)
        for the configured joint layout — closed-form, no tracing."""
        cfg, mcfg = self.cfg, self.mcfg
        n = cfg.n_device_per_batch
        layout = cfg.attn_impl
        if not cfg.is_sp:
            report = {"layout": layout, "kv_state_elems": 0,
                      "per_step_collective_elems": 0,
                      # byte model: a single-device group has no sp
                      # traffic — zero is the truth, not a guess
                      # (pipelines.comm_plan raises on runners that
                      # lack these keys)
                      "per_step_collective_bytes": 0,
                      "sync_step_collective_bytes": 0}
            if cfg.step_cache_enabled:
                report["step_cache"] = {
                    "interval": cfg.step_cache_interval,
                    "depth": cfg.step_cache_depth,
                    "shallow_per_step_collective_elems": 0,
                }
            return report
        n_br_local = (
            1 if cfg.cfg_split or not cfg.do_classifier_free_guidance else 2
        )
        b = batch_size * n_br_local
        n_tok, hid, depth = mcfg.num_tokens, mcfg.hidden_size, mcfg.depth
        # dual-attention blocks (SD3.5-medium) carry and exchange a second
        # image KV each, so they count double
        n_attn = depth + mcfg.dual_attention_blocks
        chunk = n_tok // n
        out_gather = b * n_tok * mcfg.patch_size**2 * mcfg.out_channels
        if layout == "ring":
            state = n_attn * b * chunk * 2 * hid
            # (n-1) ppermute hops of the local 2C chunk per block, in-step;
            # no refresh collective (next state = own fresh chunk)
            per_step = n_attn * (n - 1) * b * chunk * 2 * hid + out_gather
        else:
            state = n_attn * 2 * b * n_tok * hid
            per_step = n_attn * 2 * b * n_tok * hid + out_gather
        report = {"layout": layout, "kv_state_elems": int(state),
                  "per_step_collective_elems": int(per_step)}
        # wire bytes: sync full-precision always; stale compressed when
        # comm_compress is on, thinned to 1/k of the KV rows when
        # refresh_fraction = 1/k (gather layout only — ring rejects both
        # knobs).  full_refresh_* pins the fraction-1 closed form so the
        # PCPP reduction is a checked ratio.
        itemsize = jnp.dtype(cfg.dtype).itemsize
        kk = refresh_period(cfg.refresh_fraction)
        report["comm_compress"] = cfg.comm_compress
        report["refresh_fraction"] = cfg.refresh_fraction
        report["sync_step_collective_bytes"] = int(per_step) * itemsize
        if layout == "gather":
            full_refresh = n_attn * n * wire_nbytes(
                (2, b, chunk, hid), itemsize, cfg.comm_compress
            )
            part_refresh = n_attn * n * wire_nbytes(
                (2, b, chunk // kk, hid), itemsize, cfg.comm_compress
            )
            report["per_step_collective_bytes"] = int(
                part_refresh + out_gather * itemsize
            )
            report["full_refresh_per_step_collective_bytes"] = int(
                full_refresh + out_gather * itemsize
            )
        else:
            report["per_step_collective_bytes"] = int(per_step) * itemsize
            report["full_refresh_per_step_collective_bytes"] = (
                int(per_step) * itemsize
            )
        if cfg.step_cache_enabled:
            # shallow steps run d_keep of depth joint blocks (the dual
            # prefix always runs — the cut sits past it); the output gather
            # always runs
            d_keep = mcfg.depth - cfg.step_cache_depth
            n_attn_sh = d_keep + mcfg.dual_attention_blocks
            shallow = ((per_step - out_gather) * n_attn_sh // n_attn
                       + out_gather)
            report["step_cache"] = {
                "interval": cfg.step_cache_interval,
                "depth": cfg.step_cache_depth,
                "shallow_per_step_collective_elems": int(shallow),
            }
        return report

    def generate(self, latents, enc, pooled, guidance_scale=5.0,
                 num_inference_steps=20, start_step=0, end_step=None,
                 callback=None):
        """``latents`` [B, H/8, W/8, C] noise already scaled by
        init_noise_sigma — or, with ``start_step > 0`` (img2img), a clean
        latent noised to that schedule point via ``scheduler.add_noise``;
        ``enc`` [n_br, B, Lc, joint_dim]; ``pooled`` [n_br, B, pooled_dim].
        ``callback(i, t, latents)`` (diffusers legacy signature) fires
        after every step in every mode — from the host loop with
        use_cuda_graph=False, via ordered io_callback inside the compiled
        loop otherwise.  Returns the denoised latent NHWC."""
        assert 0 <= start_step < num_inference_steps, (start_step,
                                                       num_inference_steps)
        assert end_step is None or start_step < end_step <= num_inference_steps, (
            start_step, end_step, num_inference_steps)
        self.scheduler.set_timesteps(num_inference_steps)
        gs = jnp.asarray(guidance_scale, jnp.float32)
        if not self.cfg.use_compiled_step:
            return self._generate_stepwise(
                jnp.asarray(latents), enc, pooled, gs, num_inference_steps,
                start_step, end_step, callback,
            )
        if callback is not None:
            from ..utils.compat import SUPPORTS_FUSED_CALLBACK

            if not SUPPORTS_FUSED_CALLBACK or self.cfg.step_cache_enabled:
                # this jaxlib aborts compiling the ordered-io_callback
                # program (utils/compat.py) — host-driven loop instead.
                # Step-cache callbacks also take the host loop: the
                # stepwise steppers replay the exact cadence.
                return self._generate_stepwise(
                    jnp.asarray(latents), enc, pooled, gs,
                    num_inference_steps, start_step, end_step, callback,
                )
            key = ("fused_cb", num_inference_steps, start_step, end_step)
            if key not in self._compiled:
                self._compiled[key] = self._build_fused_callback(
                    num_inference_steps, start_step, end_step
                )
            self._active_callback = callback
            try:
                out = self._compiled[key](
                    self.params, jnp.asarray(latents), enc,
                    jnp.asarray(pooled), gs,
                )
                jax.effects_barrier()  # host callbacks drain before return
                jax.block_until_ready(out)
                return out
            finally:
                self._active_callback = None
        if (self._hybrid_dispatch(num_inference_steps)
                and start_step == 0 and end_step is None):
            return self._generate_hybrid(
                jnp.asarray(latents), enc, pooled, gs, num_inference_steps
            )
        key = (num_inference_steps if start_step == 0 and end_step is None
               else (num_inference_steps, start_step, end_step))
        if key not in self._compiled:
            self._compiled[key] = self._build(num_inference_steps,
                                              start_step, end_step)
        return self._compiled[key](
            self.params, latents, enc, jnp.asarray(pooled), gs
        )

    def prepare(self, num_steps: int) -> None:
        """Pre-build exactly the program generate() will dispatch to
        (per-step programs build lazily, like DenoiseRunner.prepare;
        hybrid mode pre-builds the big stale-scan program)."""
        if not self.cfg.use_compiled_step:
            return
        self.scheduler.set_timesteps(num_steps)
        if self._hybrid_dispatch(num_steps):
            self._ensure_stale_scan(num_steps)
            return
        if num_steps not in self._compiled:
            self._compiled[num_steps] = self._build(num_steps)

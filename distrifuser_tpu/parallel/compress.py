"""Lossy compression for the stale-refresh exchanges (comm_compress).

DistriFusion's displaced-patch protocol is communication-bound at scale:
every stale step ships full-precision halo rows and KV slabs whose *only*
consumer is the next step's already-approximate stale read (tolerance-tested
at 2e-4 across the repo).  The async overlap hides that volume but does not
shrink it — so this module shrinks it: refresh payloads are quantized to 8
bits before they touch the wire and dequantized right after the collective,
with one fp32 scale per tile (the last axis: a channel vector of a halo row,
a token row of a KV slab).  The carry pytree keeps full-precision leaves —
the quantize -> collective -> dequantize round trip lives entirely on the
deferred (latency-hidden) refresh path, so the full/shallow/sync step bodies
keep identical carry structures and the step-cache / fused-scan composition
in parallel/{runner,stepcache}.py is untouched.

Modes (DistriConfig.comm_compress):

* ``"none"``          — full-precision exchange (default; bit-identical).
* ``"int8"``          — symmetric per-tile int8: ``q = round(x / s)`` with
  ``s = amax(|x|) / 127`` per tile.  Error is bounded by ``s / 2``.
* ``"fp8"``           — float8_e4m3fn payload with per-tile scaling to the
  e4m3 dynamic range (amax -> 448).  Relative error ~2^-3 of the value;
  better than int8 for heavy-tailed tiles.  Requires a jax/ml_dtypes with
  ``float8_e4m3fn`` (``fp8_supported()``).
* ``"int8_residual"`` — int8 over the *delta* against the previous stale
  value already carried in the patch state.  Adjacent denoising steps are
  near-identical, so the residual's dynamic range (and thus the per-tile
  scale, and thus the absolute error) is far smaller than the activation's.
  Closed-loop (DPCM) coding: the delta is taken against the *reconstructed*
  previous value, so quantization error does not accumulate across steps.

The same per-tile machinery also generalizes from the wires to the
*weights* (ROADMAP item 5): `QuantizedTensor` + `quantize_weight` hold
matmul/conv kernels as int8/fp8 payloads with one fp32 scale per
output-channel tile, dequantized lazily at the consuming dot/conv
(models/weights.py quantize_params owns the tree-level policy;
DistriConfig.weight_quant the knob).

Only stale-phase refresh traffic compresses; warmup/sync collectives stay
full-precision and bit-exact (reference-faithful).  GroupNorm moment
exchanges are never compressed: they are O(groups) — noise against the KV
slabs — and the ``var = E[x^2] - E[x]^2`` cancellation amplifies payload
error catastrophically.  Wire accounting for all of this lives in
``wire_nbytes`` + context.WIRE_REGISTRY, surfaced by
``DenoiseRunner.comm_volume_report(per_phase=True)["bytes"]``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.config import SP_AXIS

COMPRESS_MODES = ("none", "int8", "fp8", "int8_residual")

# Weight-tree quantization modes (DistriConfig.weight_quant /
# weight_quant_aux; models/weights.py quantize_params).  "int8_residual" is
# wire-only: weights have no previous-step value to delta-code against.
WEIGHT_QUANT_MODES = ("none", "int8", "fp8")

# Quantized-COMPUTE policies (DistriConfig.quant_compute / ExecKey): how a
# QuantizedTensor kernel executes at its consuming matmul.  "off" is PR-6
# semantics — dequantize to the compute dtype and run a dense matmul
# (quantization buys HBM bytes, zero FLOPs).  "auto" resolves per shape
# through ops/gemm_routing.py (env override -> measured table -> analytic
# default); "dot" forces the low-precision dot_general path (activations
# dynamically quantized per token, int8/fp8 MACs, fused per-channel-tile
# scale after the accumulate); "pallas" forces the tiled Pallas kernel.
QUANT_COMPUTE_MODES = ("off", "auto", "dot", "pallas")

# Layer kinds (context.KIND_REGISTRY) whose stale refresh compresses.  "gn"
# is deliberately absent (see module docstring); "stepcache" is a local
# carry with no collective.
COMPRESS_KINDS = ("attn", "conv2d")

# int8 symmetric range and float8_e4m3fn max normal.
_INT8_MAX = 127.0
_FP8_MAX = 448.0
# Floor on per-tile scales: an all-zero tile (edge halos) must dequantize to
# exact zeros, not NaNs from a 0/0.
_SCALE_FLOOR = 1e-12


def fp8_dtype():
    """The fp8 payload dtype, or None when this jax build lacks it."""
    return getattr(jnp, "float8_e4m3fn", None)


def fp8_supported() -> bool:
    return fp8_dtype() is not None


def validate_mode(mode: str) -> None:
    """Config-time validation shared by DistriConfig and ServeConfig."""
    if mode not in COMPRESS_MODES:
        raise ValueError(
            f"comm_compress must be one of {COMPRESS_MODES}, got {mode!r}"
        )
    if mode == "fp8" and not fp8_supported():
        raise ValueError(
            "comm_compress='fp8' needs jax.numpy.float8_e4m3fn, which this "
            "jax build lacks — use 'int8' or 'int8_residual'"
        )


def quantize(x, mode: str, axis: int = -1):
    """Per-tile symmetric quantization over one reduction axis.

    Returns ``(payload, scale)``: payload is int8 (or float8_e4m3fn for
    "fp8") with x's shape; scale is fp32 with shape ``x.shape`` minus
    ``axis`` — one scale per tile.  The default ``axis=-1`` is the wire
    granularity (one scale per halo-row / KV-row); weight kernels use
    ``axis=-2`` (one scale per output-channel tile — the reduction axis of
    the consuming dot/conv, so dequantization error stays per-output-
    channel-bounded).  Exact zeros map to exact zeros (edge-device halo
    semantics depend on it).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis)
    if mode in ("int8", "int8_residual"):
        scale = jnp.maximum(amax, _SCALE_FLOOR) / _INT8_MAX
        q = jnp.clip(
            jnp.round(xf / jnp.expand_dims(scale, axis)), -_INT8_MAX,
            _INT8_MAX
        ).astype(jnp.int8)
    elif mode == "fp8":
        dt = fp8_dtype()
        if dt is None:
            raise ValueError("fp8 payloads unsupported by this jax build")
        scale = jnp.maximum(amax, _SCALE_FLOOR) / _FP8_MAX
        q = (xf / jnp.expand_dims(scale, axis)).astype(dt)
    else:
        raise ValueError(f"not a quantizing mode: {mode!r}")
    return q, scale


def dequantize(payload, scale, dtype, axis: int = -1):
    """Inverse of ``quantize`` (up to the per-tile rounding error)."""
    return (payload.astype(jnp.float32)
            * jnp.expand_dims(scale, axis)).astype(dtype)


def validate_weight_mode(mode: str) -> None:
    """Config-time validation of a weight-quantization mode, shared by
    DistriConfig (``weight_quant``/``weight_quant_aux``) and ServeConfig."""
    if mode not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"weight_quant must be one of {WEIGHT_QUANT_MODES}, got {mode!r}"
        )
    if mode == "fp8" and not fp8_supported():
        raise ValueError(
            "weight_quant='fp8' needs jax.numpy.float8_e4m3fn, which this "
            "jax build lacks — use 'int8'"
        )


def validate_quant_compute(policy: str, weight_quant: str = "int8") -> None:
    """Config-time validation of a quantized-compute policy, shared by
    DistriConfig, ServeConfig, and ExecKey.  Forcing a low-precision
    execution path ("dot"/"pallas") on a full-precision key is a config
    contradiction — there is no quantized kernel to execute — and refuses
    loudly rather than silently running dense."""
    if policy not in QUANT_COMPUTE_MODES:
        raise ValueError(
            f"quant_compute must be one of {QUANT_COMPUTE_MODES}, got "
            f"{policy!r}"
        )
    if policy in ("dot", "pallas") and weight_quant == "none":
        raise ValueError(
            f"quant_compute={policy!r} forces a low-precision matmul path "
            "but weight_quant='none' holds no quantized kernels — set "
            "weight_quant to int8/fp8 or keep quant_compute 'auto'/'off'"
        )


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """A quantized weight kernel: 1-byte payload + one fp32 scale per
    output-channel tile, dequantized lazily where it is consumed.

    The payload keeps the kernel's layout (linear ``[..., in, out]``, conv
    HWIO ``[kh, kw, I, O]``); the scale reduces away the second-to-last
    (input/reduction) axis, so a stacked block tree ``[depth, in, out]``
    keeps per-(block, out-channel) scales and slices along ``depth``
    exactly like a dense leaf (``jax.tree.map(lambda l: l[:k], ...)``
    maps into payload and scale, both depth-leading).

    Registered as a pytree node, so quantized trees flow through jit /
    shard_map / scan unchanged; ``__jax_array__`` makes any jnp consumer
    (``x @ kernel``, einsum, vmap'd linears) dequantize on the fly —
    inside a traced program XLA fuses the convert+multiply into the
    consuming dot, so HBM holds (and streams) the 1-byte payload.  lax
    primitives don't take the protocol: explicit call sites (the conv
    paths in ops/conv.py) densify via ``asdense``.

    ``compute`` is the EXECUTION policy (QUANT_COMPUTE_MODES minus "off",
    which maps to the leaf-level "dequant"): ops/linear.py dispatches a
    QuantizedTensor kernel to the low-precision dot_general / Pallas path
    per this policy and the ops/gemm_routing.py table.  It lives in the
    pytree AUX data (not a traced leaf), so two trees differing only in
    policy have distinct treedefs — jit retraces instead of silently
    reusing the other policy's program.  ``channel_tile`` groups output
    channels per scale (1 = per-channel, the default and the PR-6
    layout); the scale's last axis then has ``ceil(out/channel_tile)``
    entries, with a partial last tile when out %% channel_tile != 0.
    """

    __slots__ = ("payload", "scale", "_dtype", "compute", "channel_tile")

    def __init__(self, payload, scale, dtype, compute: str = "dequant",
                 channel_tile: int = 1):
        self.payload = payload
        self.scale = scale
        self._dtype = jnp.dtype(dtype)
        if compute not in ("dequant", "auto", "dot", "pallas"):
            raise ValueError(
                f"QuantizedTensor compute policy must be 'dequant', "
                f"'auto', 'dot', or 'pallas', got {compute!r}"
            )
        self.compute = compute
        ct = int(channel_tile)
        if ct < 1:
            raise ValueError(f"channel_tile must be >= 1, got {channel_tile}")
        n = payload.shape[-1] if getattr(payload, "ndim", 0) else 1
        tiles = -(-n // ct)
        sl = scale.shape[-1] if getattr(scale, "ndim", 0) else 1
        if sl != tiles:
            raise ValueError(
                f"scale/payload tile misalignment: payload has {n} output "
                f"channels at channel_tile={ct} -> {tiles} scale tiles, "
                f"but the scale's last axis has {sl} — a round-trip that "
                "dropped the tile size would dequantize with the wrong "
                "per-channel scales"
            )
        self.channel_tile = ct

    @property
    def shape(self):
        return self.payload.shape

    @property
    def ndim(self) -> int:
        return self.payload.ndim

    @property
    def size(self) -> int:
        return self.payload.size

    @property
    def dtype(self):
        """The dequantized (compute) dtype — what the dense leaf had."""
        return self._dtype

    @property
    def nbytes(self) -> int:
        """HBM residency: payload plus scales (what the fleet's weight
        reports sum)."""
        return int(self.payload.size * jnp.dtype(self.payload.dtype).itemsize
                   + self.scale.size * 4)

    def channel_scale(self):
        """The fp32 scale EXPANDED to one entry per output channel
        ([..., out]), regardless of ``channel_tile`` — what the fused
        scale application after a low-precision accumulate multiplies by
        (and what ``__jax_array__`` dequantizes with)."""
        if self.channel_tile == 1:
            return self.scale
        n = self.payload.shape[-1]
        return jnp.repeat(self.scale, self.channel_tile, axis=-1)[..., :n]

    def __jax_array__(self):
        return dequantize(self.payload, self.channel_scale(), self._dtype,
                          axis=-2)

    def __repr__(self) -> str:
        return (f"QuantizedTensor(shape={tuple(self.shape)}, "
                f"payload={jnp.dtype(self.payload.dtype).name}, "
                f"dtype={self._dtype.name}, compute={self.compute!r}, "
                f"channel_tile={self.channel_tile})")

    def tree_flatten(self):
        return ((self.payload, self.scale),
                (self._dtype, self.compute, self.channel_tile))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


def quantize_weight(w, mode: str, *, compute: str = "dequant",
                    channel_tile: int = 1) -> QuantizedTensor:
    """Quantize one kernel leaf with per-output-channel-tile fp32 scales
    (the output axis is last in both the linear and HWIO conv layouts, so
    the reduction axis is always ``-2``).  ``channel_tile > 1`` groups
    that many output channels per scale (each tile's scale is the max of
    its channels' amax, so the per-element error bound still holds — just
    against the tile amax, which is why per-channel stays the default);
    the last tile is partial when the channel count does not divide.
    ``compute`` tags the execution policy (see QuantizedTensor)."""
    if mode not in ("int8", "fp8"):
        raise ValueError(f"not a weight-quantizing mode: {mode!r}")
    ct = int(channel_tile)
    if ct <= 1:
        q, scale = quantize(w, mode, axis=-2)
        return QuantizedTensor(q, scale, w.dtype, compute, 1)
    xf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-2)  # [..., out] per-channel amax
    n = amax.shape[-1]
    tiles = -(-n // ct)
    pad = tiles * ct - n
    if pad:
        # pad with 0 so a partial last tile's scale is the max of its REAL
        # channels only
        amax = jnp.pad(amax, [(0, 0)] * (amax.ndim - 1) + [(0, pad)])
    tile_amax = amax.reshape(*amax.shape[:-1], tiles, ct).max(axis=-1)
    limit = _INT8_MAX if mode == "int8" else _FP8_MAX
    scale = jnp.maximum(tile_amax, _SCALE_FLOOR) / limit
    per_ch = jnp.repeat(scale, ct, axis=-1)[..., :n]
    div = xf / jnp.expand_dims(per_ch, -2)
    if mode == "int8":
        q = jnp.clip(jnp.round(div), -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    else:
        q = div.astype(fp8_dtype())
    return QuantizedTensor(q, scale, w.dtype, compute, ct)


def asdense(x):
    """Dequantize a `QuantizedTensor` (identity on anything else) — for
    call sites that feed lax primitives directly, which don't take the
    ``__jax_array__`` protocol."""
    return x.__jax_array__() if isinstance(x, QuantizedTensor) else x


def refresh_period(fraction: float) -> int:
    """``1 / fraction`` as the exact integer rotation period of the
    partial-refresh schedule (1 when the fraction is 1.0 — full refresh).
    ``validate_refresh_fraction`` guarantees the division is exact."""
    return int(round(1.0 / float(fraction)))


def validate_refresh_fraction(fraction: float) -> None:
    """Config-time validation of a PCPP partial-refresh fraction, shared
    by DistriConfig, ServeConfig, ExecKey, and the controller tier table.

    The fraction must be ``1/k`` for an integer ``k >= 1``: each stale
    step refreshes exactly one of ``k`` disjoint strided row groups, so
    the per-step wire bytes are exactly ``fraction`` of the full refresh
    and every row is at most ``k`` steps stale — both closed forms the
    byte accounting and the staleness bound depend on being exact."""
    f = float(fraction)
    if not (0.0 < f <= 1.0):
        raise ValueError(
            f"refresh_fraction must be in (0, 1], got {fraction!r}"
        )
    k = round(1.0 / f)
    if k < 1 or abs(k * f - 1.0) > 1e-6:
        raise ValueError(
            "refresh_fraction must be 1/k for an integer k (1, 0.5, 0.25, "
            f"...): each stale step refreshes one of k strided row groups "
            f"exactly — got {fraction!r}"
        )


def take_every_kth(x, k: int, r, *, groups: int = 1):
    """Strided row subset along axis ``-2``: rows ``{r, r+k, r+2k, ...}``
    of each of ``groups`` equal contiguous segments (static output shape
    ``[..., L/k, C]``; ``r`` may be a traced index).

    ``groups > 1`` handles a tiled-all-gather layout where axis ``-2``
    concatenates per-device chunks: the stride applies within each
    device's chunk, not across the concatenation boundary."""
    lead, L, C = x.shape[:-2], x.shape[-2], x.shape[-1]
    if L % (groups * k):
        raise ValueError(
            f"partial refresh needs the row count ({L}) divisible by "
            f"groups*k ({groups}*{k}) — pick a refresh_fraction whose "
            "period divides every refreshed row dimension"
        )
    xg = x.reshape(*lead, groups, L // (groups * k), k, C)
    sub = lax.dynamic_index_in_dim(xg, r, axis=xg.ndim - 2, keepdims=False)
    return sub.reshape(*lead, L // k, C)


def scatter_every_kth(prev, rows, k: int, r, *, groups: int = 1):
    """Inverse of `take_every_kth`: write ``rows`` [..., L/k, C] back into
    the strided positions of ``prev`` [..., L, C] (same ``groups``
    convention), returning the updated full buffer in prev's dtype."""
    lead, L, C = prev.shape[:-2], prev.shape[-2], prev.shape[-1]
    pg = prev.reshape(*lead, groups, L // (groups * k), k, C)
    up = rows.reshape(*lead, groups, L // (groups * k), 1, C)
    pg = lax.dynamic_update_slice_in_dim(
        pg, up.astype(prev.dtype), r, axis=pg.ndim - 2
    )
    return pg.reshape(prev.shape)


def wire_nbytes(shape: Sequence[int], itemsize: int, mode: str) -> int:
    """Bytes one exchange of a ``shape``-shaped tensor puts on the wire.

    ``"none"`` moves the raw payload; the quantizing modes move a 1-byte
    payload per element plus one fp32 scale per tile (last-axis vector).
    The comm accounting's single source of truth — context.WIRE_REGISTRY
    entries and the closed-form DiT/MMDiT reports both come from here.
    """
    n = int(math.prod(shape))
    if mode == "none":
        return n * itemsize
    tiles = int(math.prod(shape[:-1])) if len(shape) else 1
    return n + tiles * 4


def refresh_gather_seq(
    local,
    prev,
    mode: str,
    offset,
    axis: str = SP_AXIS,
    *,
    fraction: float = 1.0,
    step=None,
):
    """Compressed sequence-sharded refresh all-gather (DiT/MMDiT KV path).

    ``local`` is this device's fresh stacked KV rows ``[2, B, chunk, hid]``;
    ``prev`` the previous step's gathered state ``[2, B, N, hid]`` (the scan
    carry).  Returns the refreshed full ``[2, B, N, hid]`` in prev's dtype:
    a plain tiled all-gather for "none", a quantized payload + per-row fp32
    scale pair of gathers otherwise, with "int8_residual" delta-coding
    against this device's own slice of ``prev`` at token offset ``offset``.
    The result is consumed only next step, so every op here stays on the
    deferred path.

    ``fraction < 1`` is the PCPP partial-refresh path (arXiv 2412.02962):
    with period ``k = 1/fraction``, step ``step`` refreshes only rows
    ``{r, r+k, ...}`` (``r = step % k``) of each device's chunk — the
    all-gather moves ``chunk/k`` rows per device, the rest of ``prev``
    carries, and every row is at most ``k`` steps stale.  The rotation
    index is shared by every device (``step`` is replicated), so the
    refreshed gathered buffer stays replicated-consistent, and in
    residual mode the delta base is the row's own ``k``-step-old
    reconstruction — still closed-loop DPCM, just at stride ``k``."""
    tok = local.ndim - 2  # token axis of the [..., chunk, hid] layout
    k = refresh_period(fraction)
    if k <= 1:
        if mode == "none":
            return lax.all_gather(local, axis, axis=tok, tiled=True)
        src = local.astype(jnp.float32)
        if mode == "int8_residual":
            start = (0,) * tok + (offset, 0)
            my_prev = lax.dynamic_slice(prev, start, local.shape)
            src = src - my_prev.astype(jnp.float32)
        q, s = quantize(src, mode)
        gq = lax.all_gather(q, axis, axis=tok, tiled=True)
        gs = lax.all_gather(s, axis, axis=tok, tiled=True)
        new = gq.astype(jnp.float32) * gs[..., None]
        if mode == "int8_residual":
            new = prev.astype(jnp.float32) + new
        return new.astype(prev.dtype)
    if step is None:
        raise ValueError(
            "partial refresh (fraction < 1) needs the traced step index "
            "for the rotation schedule"
        )
    n = prev.shape[tok] // local.shape[tok]  # sp peers in the gathered axis
    r = jnp.mod(jnp.asarray(step, jnp.int32), k)
    sub = take_every_kth(local, k, r)  # [2, B, chunk/k, hid]
    if mode == "none":
        g = lax.all_gather(sub, axis, axis=tok, tiled=True)
        return scatter_every_kth(prev, g, k, r, groups=n)
    src = sub.astype(jnp.float32)
    if mode == "int8_residual":
        start = (0,) * tok + (offset, 0)
        my_prev = lax.dynamic_slice(prev, start, local.shape)
        src = src - take_every_kth(my_prev, k, r).astype(jnp.float32)
    q, s = quantize(src, mode)
    gq = lax.all_gather(q, axis, axis=tok, tiled=True)
    gs = lax.all_gather(s, axis, axis=tok, tiled=True)
    new = gq.astype(jnp.float32) * gs[..., None]
    if mode == "int8_residual":
        new = take_every_kth(prev, k, r, groups=n).astype(jnp.float32) + new
    return scatter_every_kth(prev, new, k, r, groups=n)

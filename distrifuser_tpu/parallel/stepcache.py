"""Temporal step-cache cadence: shared bookkeeping for the full/shallow loop.

DistriFusion exploits *spatial* redundancy (stale patch context); the step
cache exploits the matching *temporal* redundancy: adjacent denoising steps
produce near-identical deep activations (PipeFusion, arXiv 2405.14430;
partially conditioned patch parallelism, arXiv 2412.02962 shows partial /
stale context replaces full recomputation with negligible quality loss).
With ``step_cache_interval = I`` and ``step_cache_depth = K`` (DistriConfig),
the post-warmup denoise loop runs a static cadence of **super-steps**:

    [ shallow x (I-1), full x 1 ] [ shallow x (I-1), full x 1 ] ... tail

* a **full** step runs every block of the network and stashes the deep
  subtree's output (UNet: the feature entering the first shallow up block;
  DiT/MMDiT: the residual delta added by the deepest K transformer blocks)
  into the functional carry state, alongside the displaced-patch buffers;
* a **shallow** step executes only the shallow layers and substitutes the
  carried deep feature — and, because a skipped layer emits nothing, its
  stale-refresh halo/KV collectives vanish from the shallow body too
  (verifiable with utils/overlap.py on the compiled HLO).

The cadence is *shallow-first* within each super-step: every warmup (sync)
step is itself a full run that refreshes the deep cache, so the first
post-warmup step may already reuse it.  The tail (``rest % I`` steps) stays
shallow — its staleness is bounded by the same interval.

The cadence is static per compilation: the compiled program carries exactly
two step bodies (full + shallow) composed into the scan the same way the
sync/stale pair already is in parallel/runner.py, dit_sp.py and mmdit_sp.py.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax.numpy as jnp
from jax import lax

# Name of the deep-feature entry in the UNet's patch-state carry.  Lives in
# the same pytree as the displaced halo/KV/moment buffers (parallel/context
# semantics); the emitting runner tags it kind="stepcache" in KIND_REGISTRY.
STEPCACHE_KEY = "stepcache.deep"


def cadence_split(rest: int, interval: int) -> Tuple[int, int]:
    """(n_super, tail) for ``rest`` post-warmup steps: ``n_super`` complete
    super-steps of ``interval`` steps each, then ``tail`` (< interval)
    trailing shallow steps."""
    if interval < 2:
        raise ValueError(f"step-cache interval must be >= 2, got {interval}")
    return divmod(rest, interval)


def is_shallow_step(k: int, interval: int) -> bool:
    """Is post-warmup step ``k`` (0-based) a shallow step?  Shallow-first:
    positions 0..interval-2 of each super-step are shallow, the last is the
    full refresh.  The single source of truth shared by the fused loop
    (which unrolls one super-step per scan iteration) and the host-driven
    stepwise loop (which classifies step by step)."""
    return (k % interval) < interval - 1


def is_shallow_at(i: int, cadence_start: int, interval: int) -> bool:
    """Is absolute step index ``i`` shallow, with the cadence starting at
    ``cadence_start`` (the first post-warmup step)?  False during warmup and
    with the cache off — the host-driven stepwise loops classify each step
    through this so they replay exactly what run_cadence compiles."""
    return (interval > 1 and i >= cadence_start
            and is_shallow_step(i - cadence_start, interval))


def run_cadence(
    carry: Any,
    s0: int,
    n_rest: int,
    interval: int,
    run_step: Callable[[Any, Any, bool], Any],
):
    """Execute the post-warmup cadence over ``n_rest`` steps starting at
    absolute index ``s0``: one ``lax.scan`` over the complete super-steps —
    each (interval-1) shallow steps in a nested ``fori_loop`` + 1 full step,
    so the compiled program carries ONE shallow body and ONE full body
    regardless of interval (XLA inlines the trip-count-1 inner loop at
    interval 2) — then the (< interval) trailing shallow steps as another
    fori.  ``run_step(carry, i, shallow) -> carry`` is the runner's step
    closure; the one home for the loop shape shared by the UNet/DiT/MMDiT
    fused loops."""
    n_super, tail = cadence_split(n_rest, interval)

    def shallow_loop(carry, start, stop):
        return lax.fori_loop(
            start, stop, lambda i, c: run_step(c, i, True), carry
        )

    def super_body(carry, i0):
        carry = shallow_loop(carry, i0, i0 + interval - 1)
        return run_step(carry, i0 + interval - 1, False), None

    if n_super:
        carry, _ = lax.scan(
            super_body, carry, s0 + interval * jnp.arange(n_super)
        )
    if tail:
        t0 = s0 + n_super * interval
        carry = shallow_loop(carry, t0, t0 + tail)
    return carry


def phase_step_counts(num_steps: int, warmup_steps: int, interval: int):
    """How a run of ``num_steps`` splits across the static phases:
    ``{"sync": warmup steps, "stale": full steady steps, "shallow":
    shallow steady steps}``.  With the cache off (interval <= 1) every
    post-warmup step is a full stale step.  The bridge between
    ``comm_volume_report(per_phase=True)``'s per-STEP numbers and a whole
    run's traffic — scripts/bench_compress.py multiplies the two."""
    if num_steps <= 0:
        return {"sync": 0, "stale": 0, "shallow": 0}
    n_sync = min(warmup_steps + 1, num_steps)
    rest = num_steps - n_sync
    shallow = (rest - rest // interval) if interval > 1 else 0
    return {"sync": n_sync, "stale": rest - shallow, "shallow": shallow}


def shallow_step_count(num_steps: int, warmup_steps: int, interval: int) -> int:
    """How many of ``num_steps`` denoise steps run shallow under the cadence
    (0 when the cache is off, i.e. interval <= 1).

    Steps 0..min(warmup_steps, num_steps-1) are synchronous full runs; the
    remaining ``rest`` follow the shallow-first cadence, so
    ``rest - rest // interval`` of them are shallow.  Used by the serve
    layer's shallow-step-share metrics and the bench report.  Delegates to
    ``phase_step_counts`` so the cadence arithmetic has one home."""
    return phase_step_counts(num_steps, warmup_steps, interval)["shallow"]

"""Functional replacement for the reference's comm-manager / stale-buffer protocol.

The reference (/root/reference/distrifuser/utils.py:112-199,
`PatchParallelismCommManager`) keeps mutable per-layer flat buffers: each
wrapped module registers a tensor slot, the host allocates one flat buffer per
peer, and modules `enqueue` fresh activations which an async NCCL all-gather
refreshes while the next layers compute; consumers `wait()` their handle one
step later.  JAX is functional, so the same displaced-patch mechanism becomes
*explicit carry state*:

* ``state_in``  — pytree ``{layer_name: gathered buffer}`` produced by the
  previous denoising step (one step stale, exactly like the reference's
  buffers after the async all-gather completes).
* ``state_out`` — dict the ops write their freshly-exchanged activations into
  during the trace; it is returned as the next step's ``state_in``.

Because the exchanged result is only *consumed* by the next compiled step,
XLA's latency-hiding scheduler is free to overlap each collective with the
remaining layers' compute inside the same step — the role NCCL async
all-gather + CUDA-graph capture plays in the reference.  There is no
registration pass: a synchronous (warmup) step simply *returns* the full state
pytree, which seeds the stale steps.  Buffer shape/dtype bookkeeping
(`register_tensor`/`create_buffer`, utils.py:130-164) disappears — pytree
structure is the registry.

Layer identity: the reference keys buffers by registration order; we key by
the module path string (e.g. ``"down_blocks.1.attentions.0.transformer_blocks.
0.attn1"``), which is stable across traces and readable in dumps.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax

from ..utils.config import SP_AXIS

# Static phases of the denoising loop. ``SYNC`` is the warmup / full_sync
# path (all collectives blocking-fresh, reference counter <= warmup_steps,
# e.g. pp/conv2d.py:92); ``STALE`` is the displaced-patch steady state.
PHASE_SYNC = "sync"
PHASE_STALE = "stale"


@dataclasses.dataclass
class PatchContext:
    """Per-trace context threaded through every patch-parallel op.

    Mirrors what the reference's `BaseModule` reads from `DistriConfig` +
    `PatchParallelismCommManager` (modules/base_module.py:6-29): the peer
    count, the sync mode, whether we are in warmup, and the stale buffers.
    """

    n: int  # devices on the patch axis (n_device_per_batch)
    mode: str  # one of SYNC_MODES
    phase: str  # PHASE_SYNC | PHASE_STALE (static per compilation)
    axis: str = SP_AXIS
    attn_impl: str = "gather"  # "gather" | "ring" (ops/ring_attention.py)
    state_in: Optional[Dict[str, Any]] = None
    state_out: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Precomputed text-encoder KV per cross-attention layer. The reference
    # caches these at counter==0 (modules/pp/attn.py:56,73-77); we compute
    # them once before the denoise loop.
    text_kv: Optional[Dict[str, Any]] = None

    @property
    def is_sync(self) -> bool:
        """Blocking-fresh collectives? (reference: mode=='full_sync' or warmup)."""
        return self.phase == PHASE_SYNC or self.mode == "full_sync"

    @property
    def refresh(self) -> bool:
        """Should ops exchange fresh activations for the next step?

        False only for ``no_sync`` steady state (reference pp/conv2d.py:111,
        pp/attn.py:139: enqueue skipped), where buffers stay warmup-stale
        forever.
        """
        return not (self.phase == PHASE_STALE and self.mode == "no_sync")

    def split_idx(self):
        """This device's patch index along the sp axis (traced)."""
        return jax.lax.axis_index(self.axis)

    def stale(self, name: str):
        buf = None if self.state_in is None else self.state_in.get(name)
        if buf is None:
            raise KeyError(
                f"no stale buffer for layer {name!r}: stale-phase steps must be "
                f"seeded by a sync-phase step's returned state"
            )
        return buf

    def emit(self, name: str, value: Any) -> None:
        if name in self.state_out:
            raise ValueError(f"duplicate state emission for layer {name!r}")
        self.state_out[name] = value

"""Functional replacement for the reference's comm-manager / stale-buffer protocol.

The reference (/root/reference/distrifuser/utils.py:112-199,
`PatchParallelismCommManager`) keeps mutable per-layer flat buffers: each
wrapped module registers a tensor slot, the host allocates one flat buffer per
peer, and modules `enqueue` fresh activations which an async NCCL all-gather
refreshes while the next layers compute; consumers `wait()` their handle one
step later.  JAX is functional, so the same displaced-patch mechanism becomes
*explicit carry state*:

* ``state_in``  — pytree ``{layer_name: gathered buffer}`` produced by the
  previous denoising step (one step stale, exactly like the reference's
  buffers after the async all-gather completes).
* ``state_out`` — dict the ops write their freshly-exchanged activations into
  during the trace; it is returned as the next step's ``state_in``.

Because the exchanged result is only *consumed* by the next compiled step,
XLA's latency-hiding scheduler is free to overlap each collective with the
remaining layers' compute inside the same step — the role NCCL async
all-gather + CUDA-graph capture plays in the reference.  There is no
registration pass: a synchronous (warmup) step simply *returns* the full state
pytree, which seeds the stale steps.  Buffer shape/dtype bookkeeping
(`register_tensor`/`create_buffer`, utils.py:130-164) disappears — pytree
structure is the registry.

Layer identity: the reference keys buffers by registration order; we key by
the module path string (e.g. ``"down_blocks.1.attentions.0.transformer_blocks.
0.attn1"``), which is stable across traces and readable in dumps.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.config import SP_AXIS

# Trace-time registry of state-name -> layer kind ("attn" | "gn" | "conv2d"
# | "stepcache"), filled by the emitting op itself (the only party that KNOWS
# its kind) so reports never classify by name heuristics.  Populated as a
# Python side effect during tracing; names are unique per architecture, so a
# flat map is safe across models.
KIND_REGISTRY: Dict[str, str] = {}

# Names carried through UNTOUCHED (not freshly exchanged) by the most recent
# carry_unconsumed() trace — how comm_volume_report distinguishes a shallow
# step's fresh refresh traffic from the deep state it merely passes along.
# Same trace-time side-effect convention as KIND_REGISTRY; callers that need
# it clear it before tracing one step.
CARRIED_REGISTRY: set = set()

# Static phases of the denoising loop. ``SYNC`` is the warmup / full_sync
# path (all collectives blocking-fresh, reference counter <= warmup_steps,
# e.g. pp/conv2d.py:92); ``STALE`` is the displaced-patch steady state.
PHASE_SYNC = "sync"
PHASE_STALE = "stale"


@dataclasses.dataclass
class PatchContext:
    """Per-trace context threaded through every patch-parallel op.

    Mirrors what the reference's `BaseModule` reads from `DistriConfig` +
    `PatchParallelismCommManager` (modules/base_module.py:6-29): the peer
    count, the sync mode, whether we are in warmup, and the stale buffers.
    """

    n: int  # devices on the patch axis (n_device_per_batch)
    mode: str  # one of SYNC_MODES
    phase: str  # PHASE_SYNC | PHASE_STALE (static per compilation)
    axis: str = SP_AXIS
    attn_impl: str = "gather"  # "gather" | "ring" (ops/ring_attention.py)
    # Batch the stale-phase refresh collectives: defer every layer's fresh
    # halo/KV/moment emission and run ONE flat ppermute pair + one all-gather
    # per dtype at the end of the step (`flush()`), instead of ~60 small
    # per-layer collectives.  The functional analog of the reference's
    # `comm_checkpoint` buffer batching (utils.py:181-190).  Trade-off: fewer
    # collective launches on ICI vs a narrower overlap window (the batched
    # exchange can only start once the last layer has produced its rows).
    batch_comm: bool = False
    state_in: Optional[Dict[str, Any]] = None
    state_out: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # deferred refresh emissions (batch_comm): name -> local tensor / rows
    _def_gather: Dict[str, Any] = dataclasses.field(default_factory=dict)
    _def_halo: Dict[str, Tuple[Any, Any]] = dataclasses.field(default_factory=dict)
    # Precomputed text-encoder KV per cross-attention layer. The reference
    # caches these at counter==0 (modules/pp/attn.py:56,73-77); we compute
    # them once before the denoise loop.
    text_kv: Optional[Dict[str, Any]] = None

    @property
    def is_sync(self) -> bool:
        """Blocking-fresh collectives? (reference: mode=='full_sync' or warmup)."""
        return self.phase == PHASE_SYNC or self.mode == "full_sync"

    @property
    def refresh(self) -> bool:
        """Should ops exchange fresh activations for the next step?

        False only for ``no_sync`` steady state (reference pp/conv2d.py:111,
        pp/attn.py:139: enqueue skipped), where buffers stay warmup-stale
        forever.
        """
        return not (self.phase == PHASE_STALE and self.mode == "no_sync")

    def split_idx(self):
        """This device's patch index along the sp axis (traced)."""
        return jax.lax.axis_index(self.axis)

    def stale(self, name: str):
        buf = None if self.state_in is None else self.state_in.get(name)
        if buf is None:
            raise KeyError(
                f"no stale buffer for layer {name!r}: stale-phase steps must be "
                f"seeded by a sync-phase step's returned state"
            )
        return buf

    def emit(self, name: str, value: Any, kind: str = None) -> None:
        if name in self.state_out:
            raise ValueError(f"duplicate state emission for layer {name!r}")
        if kind is not None:
            KIND_REGISTRY[name] = kind
        self.state_out[name] = value

    # ------------------------------------------------------------------
    # refresh emissions (stale phase): immediate or deferred-batched
    # ------------------------------------------------------------------

    def emit_refresh_gather(self, name: str, local: Any, kind: str = None) -> None:
        """Record `local` as this layer's next-step gathered state
        ([n, *local.shape] after the all-gather) — immediately, or deferred
        into the step-end batched exchange under ``batch_comm``."""
        if kind is not None:
            KIND_REGISTRY[name] = kind
        if self.batch_comm:
            if name in self._def_gather or name in self.state_out:
                raise ValueError(f"duplicate state emission for layer {name!r}")
            self._def_gather[name] = local
        else:
            self.emit(name, lax.all_gather(local, self.axis))

    def emit_refresh_halos(self, name: str, x: Any, halo: int) -> None:
        """Record the fresh boundary rows of ``x`` [B, h, W, C] as this
        layer's next-step halo state [2, B, halo, W, C] (stacked
        from-prev/from-next, matching the sync-phase emission in
        ops/conv.py)."""
        KIND_REGISTRY[name] = "conv2d"
        if self.batch_comm:
            if name in self._def_halo or name in self.state_out:
                raise ValueError(f"duplicate state emission for layer {name!r}")
            # x.shape[1]-halo (not -halo) so halo == 0 defers zero rows, the
            # same empty halos halo_exchange returns on the unbatched path
            self._def_halo[name] = (x[:, :halo], x[:, x.shape[1] - halo :])
        else:
            from .collectives import halo_exchange

            top, bottom = halo_exchange(x, halo, self.n, self.axis)
            self.emit(name, jnp.stack([top, bottom]))

    def carry_unconsumed(self) -> None:
        """Pass every ``state_in`` entry this step did not re-emit through to
        ``state_out`` unchanged.

        The temporal step-cache (parallel/stepcache.py) skips whole layers on
        shallow steps, so their displaced buffers — and the deep-feature
        cache itself — must ride the carry untouched to keep the pytree
        structure identical across the full/shallow pair of loop bodies (a
        lax.scan carry cannot change structure).  Also covers full steps in
        ``no_sync`` mode, where no layer refreshes but the step-cache entry
        still does.  Call after ``flush()``; records the carried names in
        ``CARRIED_REGISTRY`` for the comm report."""
        assert not self._def_gather and not self._def_halo, (
            "carry_unconsumed must run after flush()"
        )
        if self.state_in is None:
            return
        for name, value in self.state_in.items():
            if name not in self.state_out:
                self.state_out[name] = value
                CARRIED_REGISTRY.add(name)

    def flush(self) -> None:
        """Run the batched refresh exchanges deferred by ``batch_comm``.

        One `lax.all_gather` per participating dtype carries every layer's
        flattened KV/moment tensor; one non-wrapping `lax.ppermute` pair
        carries every conv's boundary rows.  Results are split back to the
        per-layer shapes the unbatched path would have produced, so the carry
        pytree (and therefore numerics) is identical either way.  No-op when
        nothing was deferred.
        """
        if self._def_gather:
            by_dtype: Dict[Any, list] = {}
            for name, t in self._def_gather.items():
                by_dtype.setdefault(jnp.dtype(t.dtype), []).append((name, t))
            for items in by_dtype.values():
                flat = jnp.concatenate([t.reshape(-1) for _, t in items])
                gathered = lax.all_gather(flat, self.axis)  # [n, total]
                off = 0
                for name, t in items:
                    size = t.size
                    self.state_out[name] = gathered[:, off : off + size].reshape(
                        (gathered.shape[0],) + t.shape
                    )
                    off += size
            self._def_gather.clear()
        if self._def_halo:
            from .collectives import neighbor_perms

            down, up = neighbor_perms(self.n)
            by_dtype = {}
            for name, (top_rows, bottom_rows) in self._def_halo.items():
                by_dtype.setdefault(jnp.dtype(top_rows.dtype), []).append(
                    (name, top_rows, bottom_rows)
                )
            for items in by_dtype.values():
                # my bottom rows -> next device's from-prev (top) halo;
                # my top rows -> previous device's from-next (bottom) halo.
                bottoms = jnp.concatenate([b.reshape(-1) for _, _, b in items])
                tops = jnp.concatenate([t.reshape(-1) for _, t, _ in items])
                from_prev = lax.ppermute(bottoms, self.axis, perm=down)
                from_next = lax.ppermute(tops, self.axis, perm=up)
                off = 0
                for name, top_rows, _ in items:
                    size = top_rows.size
                    shape = top_rows.shape
                    self.state_out[name] = jnp.stack(
                        [
                            from_prev[off : off + size].reshape(shape),
                            from_next[off : off + size].reshape(shape),
                        ]
                    )
                    off += size
            self._def_halo.clear()

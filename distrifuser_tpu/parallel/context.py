"""Functional replacement for the reference's comm-manager / stale-buffer protocol.

The reference (/root/reference/distrifuser/utils.py:112-199,
`PatchParallelismCommManager`) keeps mutable per-layer flat buffers: each
wrapped module registers a tensor slot, the host allocates one flat buffer per
peer, and modules `enqueue` fresh activations which an async NCCL all-gather
refreshes while the next layers compute; consumers `wait()` their handle one
step later.  JAX is functional, so the same displaced-patch mechanism becomes
*explicit carry state*:

* ``state_in``  — pytree ``{layer_name: gathered buffer}`` produced by the
  previous denoising step (one step stale, exactly like the reference's
  buffers after the async all-gather completes).
* ``state_out`` — dict the ops write their freshly-exchanged activations into
  during the trace; it is returned as the next step's ``state_in``.

Because the exchanged result is only *consumed* by the next compiled step,
XLA's latency-hiding scheduler is free to overlap each collective with the
remaining layers' compute inside the same step — the role NCCL async
all-gather + CUDA-graph capture plays in the reference.  There is no
registration pass: a synchronous (warmup) step simply *returns* the full state
pytree, which seeds the stale steps.  Buffer shape/dtype bookkeeping
(`register_tensor`/`create_buffer`, utils.py:130-164) disappears — pytree
structure is the registry.

Layer identity: the reference keys buffers by registration order; we key by
the module path string (e.g. ``"down_blocks.1.attentions.0.transformer_blocks.
0.attn1"``), which is stable across traces and readable in dumps.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.config import SP_AXIS

# Trace-time registry of state-name -> layer kind ("attn" | "gn" | "conv2d"
# | "stepcache" | "local"), filled by the emitting op itself (the only party
# that KNOWS its kind) so reports never classify by name heuristics.  Populated as a
# Python side effect during tracing; names are unique per architecture, so a
# flat map is safe across models.
KIND_REGISTRY: Dict[str, str] = {}

# Names carried through UNTOUCHED (not freshly exchanged) by the most recent
# carry_unconsumed() trace — how comm_volume_report distinguishes a shallow
# step's fresh refresh traffic from the deep state it merely passes along.
# Same trace-time side-effect convention as KIND_REGISTRY; callers that need
# it clear it before tracing one step.
CARRIED_REGISTRY: set = set()

# Trace-time wire accounting: state-name -> bytes the emitting exchange put
# on the wire (per device, gathered-buffer convention — the byte analog of
# the element counts comm_volume_report derives from the carry shapes).
# Only EXCEPTIONS register here: compressed refresh payloads (int8/fp8 +
# fp32 scales, parallel/compress.py) and wire-free local carries (own-rows
# residual seeds).  Entries absent from the registry default to the carried
# buffer's full elements x itemsize.  Cleared per trace, like
# CARRIED_REGISTRY.
WIRE_REGISTRY: Dict[str, int] = {}

# Suffix for the sender-side own-boundary-rows carry that "int8_residual"
# halos delta-code against: the receiver's stale halos hold the NEIGHBORS'
# previous rows, so the sender must carry its own (wire-free, kind "local").
OWN_SUFFIX = "#own"

# Static phases of the denoising loop. ``SYNC`` is the warmup / full_sync
# path (all collectives blocking-fresh, reference counter <= warmup_steps,
# e.g. pp/conv2d.py:92); ``STALE`` is the displaced-patch steady state.
PHASE_SYNC = "sync"
PHASE_STALE = "stale"


@dataclasses.dataclass
class PatchContext:
    """Per-trace context threaded through every patch-parallel op.

    Mirrors what the reference's `BaseModule` reads from `DistriConfig` +
    `PatchParallelismCommManager` (modules/base_module.py:6-29): the peer
    count, the sync mode, whether we are in warmup, and the stale buffers.
    """

    n: int  # devices on the patch axis (n_device_per_batch)
    mode: str  # one of SYNC_MODES
    phase: str  # PHASE_SYNC | PHASE_STALE (static per compilation)
    axis: str = SP_AXIS
    attn_impl: str = "gather"  # "gather" | "ring" (ops/ring_attention.py)
    # Batch the stale-phase refresh collectives: defer every layer's fresh
    # halo/KV/moment emission and run ONE flat ppermute pair + one all-gather
    # per dtype at the end of the step (`flush()`), instead of ~60 small
    # per-layer collectives.  The functional analog of the reference's
    # `comm_checkpoint` buffer batching (utils.py:181-190).  Trade-off: fewer
    # collective launches on ICI vs a narrower overlap window (the batched
    # exchange can only start once the last layer has produced its rows).
    batch_comm: bool = False
    # Stale-refresh payload compression (parallel/compress.py): "none",
    # "int8", "fp8", or "int8_residual".  Applies ONLY to the refresh
    # emissions below — sync-phase exchanges (ctx.emit paths) stay
    # full-precision and bit-exact.
    compress: str = "none"
    # PCPP partial refresh (arXiv 2412.02962; DistriConfig.refresh_fraction):
    # with fraction 1/k, each stale step refreshes only rows {r, r+k, ...}
    # (r = step % k) of every refreshable payload — KV token rows on the
    # gather path, halo columns on the conv path — and the rest of the
    # carried buffer stays as-is, so per-step refresh bytes are exactly
    # fraction x full and every row is at most k steps stale.  Applies to
    # the same kinds compression does (attn/conv2d — GroupNorm moments are
    # cancellation-sensitive and tiny, so they always refresh whole); sync
    # exchanges always move everything.  ``step`` is the traced absolute
    # step index driving the rotation (required when fraction < 1).
    refresh_fraction: float = 1.0
    step: Any = None
    state_in: Optional[Dict[str, Any]] = None
    state_out: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # deferred refresh emissions (batch_comm): name -> record dict with
    # either {"raw": <tensor(s)>} or the quantized parts
    # {"q": ..., "s": ..., "prev": ..., "dtype": ...}
    _def_gather: Dict[str, Any] = dataclasses.field(default_factory=dict)
    _def_halo: Dict[str, Tuple[Any, Any]] = dataclasses.field(default_factory=dict)
    # Precomputed text-encoder KV per cross-attention layer. The reference
    # caches these at counter==0 (modules/pp/attn.py:56,73-77); we compute
    # them once before the denoise loop.
    text_kv: Optional[Dict[str, Any]] = None

    @property
    def is_sync(self) -> bool:
        """Blocking-fresh collectives? (reference: mode=='full_sync' or warmup)."""
        return self.phase == PHASE_SYNC or self.mode == "full_sync"

    @property
    def refresh(self) -> bool:
        """Should ops exchange fresh activations for the next step?

        False only for ``no_sync`` steady state (reference pp/conv2d.py:111,
        pp/attn.py:139: enqueue skipped), where buffers stay warmup-stale
        forever.
        """
        return not (self.phase == PHASE_STALE and self.mode == "no_sync")

    def split_idx(self):
        """This device's patch index along the sp axis (traced)."""
        return jax.lax.axis_index(self.axis)

    def stale(self, name: str):
        buf = None if self.state_in is None else self.state_in.get(name)
        if buf is None:
            raise KeyError(
                f"no stale buffer for layer {name!r}: stale-phase steps must be "
                f"seeded by a sync-phase step's returned state"
            )
        return buf

    def emit(self, name: str, value: Any, kind: str = None) -> None:
        if name in self.state_out:
            raise ValueError(f"duplicate state emission for layer {name!r}")
        if kind is not None:
            KIND_REGISTRY[name] = kind
        self.state_out[name] = value

    # ------------------------------------------------------------------
    # refresh emissions (stale phase): immediate or deferred-batched
    # ------------------------------------------------------------------

    def _compress_for(self, kind: Optional[str]) -> Optional[str]:
        """Active compression mode for a refresh emission of this kind, or
        None when the payload goes out full-precision."""
        from .compress import COMPRESS_KINDS

        if self.compress == "none" or kind not in COMPRESS_KINDS:
            return None
        return self.compress

    def _partial_for(self, kind: Optional[str]):
        """Partial-refresh (period, rotation-index) for a refresh emission
        of this kind, or None for a full refresh.  Eligibility tracks
        COMPRESS_KINDS — the same payloads that tolerate lossy wires
        tolerate a strided refresh; GroupNorm moments do neither."""
        from .compress import COMPRESS_KINDS, refresh_period

        k = refresh_period(self.refresh_fraction)
        if k <= 1 or kind not in COMPRESS_KINDS:
            return None
        if self.step is None:
            raise ValueError(
                "partial refresh (refresh_fraction < 1) needs the traced "
                "step index on PatchContext.step for the rotation schedule"
            )
        return k, jnp.mod(jnp.asarray(self.step, jnp.int32), k)

    def emit_refresh_gather(self, name: str, local: Any, kind: str = None) -> None:
        """Record `local` as this layer's next-step gathered state
        ([n, *local.shape] after the all-gather) — immediately, or deferred
        into the step-end batched exchange under ``batch_comm``.  With
        ``compress`` active for this kind, the wire carries an int8/fp8
        payload plus per-tile fp32 scales instead of the raw tensor
        (residual mode delta-codes against this device's own slot of the
        stale buffer); the emitted carry value is the dequantized
        full-precision gather either way, so the carry pytree structure is
        mode-independent."""
        if kind is not None:
            KIND_REGISTRY[name] = kind
        mode = self._compress_for(kind or KIND_REGISTRY.get(name))
        if self.batch_comm:
            # DistriConfig rejects batch_comm x refresh_fraction < 1, so
            # the deferred records never carry a partial subset
            if name in self._def_gather or name in self.state_out:
                raise ValueError(f"duplicate state emission for layer {name!r}")
            self._def_gather[name] = self._gather_record(name, local, mode)
            return
        partial = self._partial_for(kind or KIND_REGISTRY.get(name))
        if partial is not None:
            self._partial_refresh_gather(name, local, mode, partial)
            return
        if mode is None:
            self.emit(name, lax.all_gather(local, self.axis))
            return
        from .compress import dequantize

        rec = self._gather_record(name, local, mode)
        gq = lax.all_gather(rec["q"], self.axis)
        gs = lax.all_gather(rec["s"], self.axis)
        new = dequantize(gq, gs, jnp.float32)
        if rec["prev"] is not None:
            new = rec["prev"].astype(jnp.float32) + new
        self.emit(name, new.astype(rec["dtype"]))

    def _partial_refresh_gather(self, name: str, local: Any,
                                mode: Optional[str], partial) -> None:
        """PCPP gather refresh: all-gather only this step's strided row
        group (``local`` rows {r, r+k, ...}) and scatter it into the
        carried gathered buffer — the other rows stay as the previous
        reconstruction, at most k steps stale.  Composes with the
        compression modes exactly like the full path; residual mode
        delta-codes each row against its own k-step-old slot, which every
        peer holds identically (closed-loop at stride k)."""
        from .compress import (
            dequantize,
            quantize,
            scatter_every_kth,
            take_every_kth,
            wire_nbytes,
        )

        k, r = partial
        prev = self.stale(name)  # [n, B, L, C] gathered carry
        sub = take_every_kth(local, k, r)
        itemsize = jnp.dtype(local.dtype).itemsize
        WIRE_REGISTRY[name] = self.n * wire_nbytes(
            sub.shape, itemsize, mode or "none"
        )
        if mode is None:
            g = lax.all_gather(sub, self.axis)  # [n, B, L/k, C]
            self.emit(name, scatter_every_kth(prev, g, k, r))
            return
        src = sub.astype(jnp.float32)
        if mode == "int8_residual":
            own = jnp.take(prev, self.split_idx(), axis=0)
            src = src - take_every_kth(own, k, r).astype(jnp.float32)
        q, s = quantize(src, mode)
        gq = lax.all_gather(q, self.axis)
        gs = lax.all_gather(s, self.axis)
        new = dequantize(gq, gs, jnp.float32)
        if mode == "int8_residual":
            new = take_every_kth(prev, k, r).astype(jnp.float32) + new
        self.emit(
            name, scatter_every_kth(prev, new.astype(local.dtype), k, r)
        )

    def _gather_record(self, name: str, local: Any, mode: Optional[str]):
        """Build the deferred-emission record for one gather refresh and
        register its wire bytes (gathered-buffer convention: n x the local
        payload, matching the element counts)."""
        from .compress import quantize, wire_nbytes

        itemsize = jnp.dtype(local.dtype).itemsize
        WIRE_REGISTRY[name] = self.n * wire_nbytes(
            local.shape, itemsize, mode or "none"
        )
        if mode is None:
            return {"raw": local}
        src = local.astype(jnp.float32)
        prev = None
        if mode == "int8_residual":
            # delta against this device's own previous emission — its slot
            # in the stale gathered buffer (identical content on every peer,
            # so the reconstruction below is replicated-consistent)
            prev = self.stale(name)
            src = src - jnp.take(prev, self.split_idx(), axis=0).astype(
                jnp.float32
            )
        q, s = quantize(src, mode)
        return {"q": q, "s": s, "prev": prev, "dtype": local.dtype}

    def emit_refresh_halos(self, name: str, x: Any, halo: int) -> None:
        """Record the fresh boundary rows of ``x`` [B, h, W, C] as this
        layer's next-step halo state [2, B, halo, W, C] (stacked
        from-prev/from-next, matching the sync-phase emission via
        ``emit_sync_halos``).  With ``compress`` active the neighbor
        permutes move int8/fp8 rows + fp32 scales; residual mode
        delta-codes against the sender's own previous rows (the
        ``OWN_SUFFIX`` carry this method also refreshes)."""
        KIND_REGISTRY[name] = "conv2d"
        mode = self._compress_for("conv2d")
        partial = self._partial_for("conv2d")
        if halo == 0 or self.n == 1:
            mode = None  # nothing real moves; keep the zero-halo semantics
            partial = None
        top, bottom = x[:, :halo], x[:, x.shape[1] - halo :]
        if self.batch_comm:
            if name in self._def_halo or name in self.state_out:
                raise ValueError(f"duplicate state emission for layer {name!r}")
            # halo == 0 defers zero rows, the same empty halos halo_exchange
            # returns on the unbatched path
            self._def_halo[name] = self._halo_record(name, top, bottom, mode)
            return
        if partial is not None:
            self._partial_refresh_halos(name, top, bottom, mode, partial)
            return
        if mode is None:
            from .collectives import halo_exchange

            t, b = halo_exchange(x, halo, self.n, self.axis)
            self.emit(name, jnp.stack([t, b]))
            return
        from .collectives import exchange_boundary_rows
        from .compress import dequantize

        rec = self._halo_record(name, top, bottom, mode)
        q_prev, q_next = exchange_boundary_rows(
            rec["q"][1], rec["q"][0], self.n, self.axis
        )
        s_prev, s_next = exchange_boundary_rows(
            rec["s"][1], rec["s"][0], self.n, self.axis
        )
        from_prev = dequantize(q_prev, s_prev, jnp.float32)
        from_next = dequantize(q_next, s_next, jnp.float32)
        if rec["prev"] is not None:
            from_prev = rec["prev"][0].astype(jnp.float32) + from_prev
            from_next = rec["prev"][1].astype(jnp.float32) + from_next
        self.emit(
            name, jnp.stack([from_prev, from_next]).astype(rec["dtype"])
        )

    def _halo_record(self, name: str, top: Any, bottom: Any,
                     mode: Optional[str]):
        """Deferred-emission record for one halo refresh + wire accounting
        (both boundary rows move).  In residual mode this also refreshes
        the own-rows predictor carry — with the RECONSTRUCTION (previous
        own + dequantized delta), never the raw rows: the predictor must
        equal the base each receiver accumulates onto, or the coding goes
        open-loop and quantization error grows with step count instead of
        cancelling (the closed-loop DPCM invariant; the gather path gets
        the same property from delta-coding against the stale buffer)."""
        from .compress import dequantize, quantize, wire_nbytes

        itemsize = jnp.dtype(top.dtype).itemsize
        WIRE_REGISTRY[name] = 2 * wire_nbytes(
            top.shape, itemsize, mode or "none"
        )
        if mode is None:
            return {"raw": (top, bottom)}
        t, b = top.astype(jnp.float32), bottom.astype(jnp.float32)
        prev = None
        if mode == "int8_residual":
            own = self.stale(name + OWN_SUFFIX)  # my previous [top, bottom]
            t = t - own[0].astype(jnp.float32)
            b = b - own[1].astype(jnp.float32)
            prev = self.stale(name)  # receiver-side base [from_prev, from_next]
        qt, st = quantize(t, mode)
        qb, sb = quantize(b, mode)
        if mode == "int8_residual":
            self._emit_own_halos(
                name,
                (own[0].astype(jnp.float32)
                 + dequantize(qt, st, jnp.float32)).astype(top.dtype),
                (own[1].astype(jnp.float32)
                 + dequantize(qb, sb, jnp.float32)).astype(top.dtype),
            )
        return {"q": (qt, qb), "s": (st, sb), "prev": prev,
                "dtype": top.dtype}

    def _partial_refresh_halos(self, name: str, top: Any, bottom: Any,
                               mode: Optional[str], partial) -> None:
        """PCPP halo refresh: exchange only this step's strided COLUMN
        group of the boundary rows (axis -2 of the [B, halo, W, C] layout
        is W) and scatter it into the carried halo state; the other
        columns keep their previous reconstruction, at most k steps
        stale.  Residual mode keeps the own-rows predictor carry in
        lockstep by scattering the same reconstructed subset into it."""
        from .collectives import exchange_boundary_rows
        from .compress import (
            dequantize,
            quantize,
            scatter_every_kth,
            take_every_kth,
            wire_nbytes,
        )

        k, r = partial
        prev = self.stale(name)  # [2, B, halo, W, C] from-prev/from-next
        sub_t = take_every_kth(top, k, r)
        sub_b = take_every_kth(bottom, k, r)
        itemsize = jnp.dtype(top.dtype).itemsize
        WIRE_REGISTRY[name] = 2 * wire_nbytes(
            sub_t.shape, itemsize, mode or "none"
        )
        if mode is None:
            from_prev, from_next = exchange_boundary_rows(
                sub_b, sub_t, self.n, self.axis
            )
            self.emit(name, jnp.stack([
                scatter_every_kth(prev[0], from_prev, k, r),
                scatter_every_kth(prev[1], from_next, k, r),
            ]))
            return
        t = sub_t.astype(jnp.float32)
        b = sub_b.astype(jnp.float32)
        own = None
        if mode == "int8_residual":
            own = self.stale(name + OWN_SUFFIX)  # my previous [top, bottom]
            t = t - take_every_kth(own[0], k, r).astype(jnp.float32)
            b = b - take_every_kth(own[1], k, r).astype(jnp.float32)
        qt, st = quantize(t, mode)
        qb, sb = quantize(b, mode)
        if mode == "int8_residual":
            # own-rows predictor: scatter the RECONSTRUCTED subset (prev
            # own + dequantized delta) so sender and receivers keep the
            # identical base — the closed-loop invariant at stride k
            rec_t = (take_every_kth(own[0], k, r).astype(jnp.float32)
                     + dequantize(qt, st, jnp.float32))
            rec_b = (take_every_kth(own[1], k, r).astype(jnp.float32)
                     + dequantize(qb, sb, jnp.float32))
            self._emit_own_halos(
                name,
                scatter_every_kth(own[0], rec_t.astype(top.dtype), k, r),
                scatter_every_kth(own[1], rec_b.astype(top.dtype), k, r),
            )
        q_prev, q_next = exchange_boundary_rows(qb, qt, self.n, self.axis)
        s_prev, s_next = exchange_boundary_rows(sb, st, self.n, self.axis)
        from_prev = dequantize(q_prev, s_prev, jnp.float32)
        from_next = dequantize(q_next, s_next, jnp.float32)
        if mode == "int8_residual":
            from_prev = (take_every_kth(prev[0], k, r).astype(jnp.float32)
                         + from_prev)
            from_next = (take_every_kth(prev[1], k, r).astype(jnp.float32)
                         + from_next)
        self.emit(name, jnp.stack([
            scatter_every_kth(prev[0], from_prev.astype(top.dtype), k, r),
            scatter_every_kth(prev[1], from_next.astype(top.dtype), k, r),
        ]))

    def _emit_own_halos(self, name: str, top: Any, bottom: Any) -> None:
        """Refresh the sender-side own-rows predictor carry for residual
        halo coding.  Wire-free (kind "local", 0 registered bytes); no-op
        outside ``int8_residual``.  Stale steps pass the RECONSTRUCTED rows
        (see ``_halo_record``); the sync seed is the exact fresh rows,
        which equal what receivers hold after an exact exchange."""
        if self.compress != "int8_residual":
            return
        own = name + OWN_SUFFIX
        KIND_REGISTRY[own] = "local"
        WIRE_REGISTRY[own] = 0
        self.emit(own, jnp.stack([top, bottom]))

    def emit_sync_halos(self, name: str, x: Any, halo: int):
        """Sync-phase halo exchange + emission (ops/conv.py's warmup path):
        exchanges FRESH halos (blocking, full-precision — the reference
        warmup all_gather), emits them as the stale phase's seed state, and
        in residual mode also seeds the own-rows carry the stale deltas
        code against.  Returns ``(from_prev, from_next)`` for the conv."""
        from .collectives import halo_exchange

        top, bottom = halo_exchange(x, halo, self.n, self.axis)
        self.emit(name, jnp.stack([top, bottom]), kind="conv2d")
        if self._compress_for("conv2d") is not None and halo and self.n > 1:
            self._emit_own_halos(name, x[:, :halo], x[:, x.shape[1] - halo:])
        return top, bottom

    def carry_unconsumed(self) -> None:
        """Pass every ``state_in`` entry this step did not re-emit through to
        ``state_out`` unchanged.

        The temporal step-cache (parallel/stepcache.py) skips whole layers on
        shallow steps, so their displaced buffers — and the deep-feature
        cache itself — must ride the carry untouched to keep the pytree
        structure identical across the full/shallow pair of loop bodies (a
        lax.scan carry cannot change structure).  Also covers full steps in
        ``no_sync`` mode, where no layer refreshes but the step-cache entry
        still does.  Call after ``flush()``; records the carried names in
        ``CARRIED_REGISTRY`` for the comm report."""
        assert not self._def_gather and not self._def_halo, (
            "carry_unconsumed must run after flush()"
        )
        if self.state_in is None:
            return
        for name, value in self.state_in.items():
            if name not in self.state_out:
                self.state_out[name] = value
                CARRIED_REGISTRY.add(name)

    def flush(self) -> None:
        """Run the batched refresh exchanges deferred by ``batch_comm``.

        One `lax.all_gather` per participating dtype carries every layer's
        flattened KV/moment tensor; one non-wrapping `lax.ppermute` pair
        carries every conv's boundary rows.  Compressed layers contribute
        their int8/fp8 payload to the payload-dtype batch and their fp32
        scales to the fp32 batch (scales share a flat gather with any raw
        fp32 traffic), and dequantize after the split.  Results match the
        per-layer shapes and values the unbatched path would have produced,
        so the carry pytree is identical either way.  No-op when nothing
        was deferred.
        """
        from .compress import dequantize

        if self._def_gather:
            parts = []  # (name, part key, tensor)
            for name, rec in self._def_gather.items():
                if "raw" in rec:
                    parts.append((name, "raw", rec["raw"]))
                else:
                    parts.append((name, "q", rec["q"]))
                    parts.append((name, "s", rec["s"]))
            gathered = self._batched_gather(parts)
            for name, rec in self._def_gather.items():
                if "raw" in rec:
                    self.state_out[name] = gathered[(name, "raw")]
                    continue
                new = dequantize(
                    gathered[(name, "q")], gathered[(name, "s")], jnp.float32
                )
                if rec["prev"] is not None:
                    new = rec["prev"].astype(jnp.float32) + new
                self.state_out[name] = new.astype(rec["dtype"])
            self._def_gather.clear()
        if self._def_halo:
            parts = []  # (name, part key, (top, bottom))
            for name, rec in self._def_halo.items():
                if "raw" in rec:
                    parts.append((name, "raw", rec["raw"]))
                else:
                    parts.append((name, "q", rec["q"]))
                    parts.append((name, "s", rec["s"]))
            exchanged = self._batched_halo_exchange(parts)
            for name, rec in self._def_halo.items():
                if "raw" in rec:
                    self.state_out[name] = jnp.stack(exchanged[(name, "raw")])
                    continue
                q_prev, q_next = exchanged[(name, "q")]
                s_prev, s_next = exchanged[(name, "s")]
                from_prev = dequantize(q_prev, s_prev, jnp.float32)
                from_next = dequantize(q_next, s_next, jnp.float32)
                if rec["prev"] is not None:
                    from_prev = rec["prev"][0].astype(jnp.float32) + from_prev
                    from_next = rec["prev"][1].astype(jnp.float32) + from_next
                self.state_out[name] = jnp.stack(
                    [from_prev, from_next]
                ).astype(rec["dtype"])
            self._def_halo.clear()

    def _batched_gather(self, parts) -> Dict[Tuple[str, str], Any]:
        """One flat all_gather per dtype over ``(name, part, tensor)``
        entries; returns {(name, part): [n, *tensor.shape]}."""
        by_dtype: Dict[Any, list] = {}
        for name, part, t in parts:
            by_dtype.setdefault(jnp.dtype(t.dtype), []).append((name, part, t))
        out: Dict[Tuple[str, str], Any] = {}
        for items in by_dtype.values():
            flat = jnp.concatenate([t.reshape(-1) for _, _, t in items])
            gathered = lax.all_gather(flat, self.axis)  # [n, total]
            off = 0
            for name, part, t in items:
                out[(name, part)] = gathered[:, off : off + t.size].reshape(
                    (gathered.shape[0],) + t.shape
                )
                off += t.size
        return out

    def _batched_halo_exchange(self, parts) -> Dict[Tuple[str, str], Any]:
        """One flat non-wrapping ppermute pair per dtype over
        ``(name, part, (top, bottom))`` entries; returns
        {(name, part): (from_prev, from_next)}.  My bottom rows become the
        next device's from-prev halo; my top rows the previous device's
        from-next halo."""
        from .collectives import exchange_boundary_rows

        by_dtype: Dict[Any, list] = {}
        for name, part, (top, bottom) in parts:
            by_dtype.setdefault(jnp.dtype(top.dtype), []).append(
                (name, part, top, bottom)
            )
        out: Dict[Tuple[str, str], Any] = {}
        for items in by_dtype.values():
            bottoms = jnp.concatenate([b.reshape(-1) for _, _, _, b in items])
            tops = jnp.concatenate([t.reshape(-1) for _, _, t, _ in items])
            from_prev, from_next = exchange_boundary_rows(
                bottoms, tops, self.n, self.axis
            )
            off = 0
            for name, part, top, _ in items:
                size, shape = top.size, top.shape
                out[(name, part)] = (
                    from_prev[off : off + size].reshape(shape),
                    from_next[off : off + size].reshape(shape),
                )
                off += size
        return out

"""Classifier-free-guidance branch handling shared by all runners.

Three CFG modes exist framework-wide (reference semantics, utils.py:68-96 +
the world_size==1 batch-fold path in the model forwards):

* ``cfg_split``   — the ``cfg`` mesh axis holds one branch per device group;
* folded          — no split axis, both branches ride the batch dim (2B);
* none            — guidance off, single branch.

`DenoiseRunner` (displaced patch / tensor) and `PipeFusionRunner` (DiT
pipeline) must agree on branch order (0 = unconditional, reference rank
layout utils.py:98-104) and on the combine formula, so the logic lives here
once.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..utils.config import CFG_AXIS, DistriConfig
from .collectives import all_gather


def branch_select(cfg: DistriConfig, enc, added=None):
    """Pick this device's CFG branch of branch-major inputs ``[2, B, ...]``
    (cfg_split), fold branches into the batch dim (single-group CFG), or
    drop the conditional branch (guidance off).

    Returns (my_enc, my_added, batch_mult): ``batch_mult`` is how many
    branch-copies of the latent batch ride the model's batch dim.
    """
    if cfg.cfg_split:
        br = lax.axis_index(CFG_AXIS)
        my_enc = jnp.take(enc, br, axis=0)
        my_added = (
            {k: jnp.take(v, br, axis=0) for k, v in added.items()}
            if added is not None
            else None
        )
        return my_enc, my_added, 1
    if cfg.do_classifier_free_guidance:
        my_enc = enc.reshape(-1, *enc.shape[2:])
        my_added = (
            {k: v.reshape(-1, *v.shape[2:]) for k, v in added.items()}
            if added is not None
            else None
        )
        return my_enc, my_added, enc.shape[0]
    my_added = {k: v[0] for k, v in added.items()} if added is not None else None
    return enc[0], my_added, 1


def _per_row_gs(gs, ref):
    """A [B]-shaped guidance vector (packed cohort rows, each request its
    own scale) broadcasts over the per-sample trailing dims; the scalar
    path is untouched — byte-identical programs for solo dispatch."""
    gs = jnp.asarray(gs)
    if gs.ndim == 0:
        return gs
    return gs.reshape(gs.shape + (1,) * (jnp.ndim(ref) - 1))


def combine_guidance(cfg: DistriConfig, out, gs, batch):
    """Guided output from per-branch model output (full latent or chunk):
    ``u + gs * (c - u)`` with branches gathered over the cfg axis
    (cfg_split), unfolded from the batch dim (folded), or passed through.
    ``gs`` is a scalar, or [B] for packed cohort rows (one scale per
    batch row)."""
    if cfg.cfg_split:
        both = all_gather(out, CFG_AXIS)  # [2, B, ...]
        u, c = both[0], both[1]
        return u + _per_row_gs(gs, u) * (c - u)
    if cfg.do_classifier_free_guidance:
        u, c = out[:batch], out[batch:]
        return u + _per_row_gs(gs, u) * (c - u)
    return out

"""Displaced patch parallelism for the DiT — DistriFusion's method on the
transformer model family.

The reference implements displaced patches for the UNet only (its whole
module zoo exists to make convs/GroupNorm/attention patch-aware,
modules/pp/*).  A DiT needs none of that machinery: LayerNorm, the MLP, and
text cross-attention are strictly per-token, so **self-attention is the only
op that crosses patch boundaries**.  Sharding the token sequence over the
``sp`` axis therefore reduces DistriFusion to exactly one exchange:

* sync phase (steps <= warmup, reference counter semantics §2.3): each
  block's fresh local K/V are all-gathered — exact full attention;
* stale phase: each block attends over the *previous step's* gathered K/V
  with this device's own slot overwritten fresh (pp/attn.py:135-140
  semantics), and all-gathers its fresh K/V into the scan carry — consumed
  only next step, so XLA's latency-hiding scheduler overlaps the collective
  with the remaining blocks' compute, the role of the reference's async
  NCCL gathers (utils.py:170-190).

Per-block stale state depends on ``attn_impl``: "gather" carries the full
gathered [depth, 2, B, N, hidden] K/V (O(L), the reference's buffer
layout); "ring" carries only the own [depth, B, N/n, 2*hidden] chunk and
streams peers through the shared ``ring_pass`` online softmax — O(L/n)
state and no refresh collective at all.  Two exact (stateless) layouts
complete the menu: "ulysses" (head-sharding all_to_all over the whole sp
axis) and "usp" (the xDiT-style 2-level composition — sp factored into
``ulysses_degree`` x ring sub-axes, one all_to_all per block over the
inner axis and a fresh-KV ring over the outer one).  The pipeline runner
(pipefusion.py) and this runner are complementary points on the
memory/traffic trade (weights/depth-sharded + O(N/M) ring hops vs
weights-replicated + KV exchange).

Every device returns the full latent and steps the scheduler replicated —
the same contract as DenoiseRunner, so pipelines can treat both
interchangeably.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ..models import dit as dit_mod
from ..models.dit import DiTConfig
from ..ops.attention import sdpa
from ..schedulers import BaseScheduler
from ..utils.config import (
    CFG_AXIS,
    DP_AXIS,
    SP_AXIS,
    SP_R_AXIS,
    SP_U_AXIS,
    DistriConfig,
)
from .collectives import all_gather_seq
from .compress import refresh_gather_seq, refresh_period, wire_nbytes
from .guidance import branch_select, combine_guidance
from .stepcache import is_shallow_at, run_cadence


class DiTDenoiseRunner:
    """Compiled displaced-patch generation loop for a DiT.

    API mirrors DenoiseRunner/PipeFusionRunner.generate.
    """

    def __init__(
        self,
        distri_config: DistriConfig,
        dit_config: DiTConfig,
        params,
        scheduler: BaseScheduler,
    ):
        self.cfg = distri_config
        self.dcfg = dit_config
        self.params = params
        self.scheduler = scheduler
        # attn_impl="gather" carries full gathered KV per block (reference
        # layout); "ring" carries only the local chunk and streams peers
        # through the online-softmax ring (O(L/n) state, no refresh
        # collective) — the same pair of layouts the UNet offers.
        if distri_config.comm_batch:
            raise ValueError(
                "comm_batch applies to the UNet's per-layer halo/moment "
                "exchanges; the DiT path has one collective kind already"
            )
        if (distri_config.comm_compress != "none"
                and distri_config.attn_impl != "gather"):
            raise ValueError(
                f"comm_compress compresses the displaced KV refresh gathers "
                f"of attn_impl='gather'; {distri_config.attn_impl!r} has no "
                "refresh collective to compress (ring carries the local "
                "chunk; ulysses/usp are exact and stateless)"
            )
        if (distri_config.refresh_fraction < 1.0
                and distri_config.attn_impl != "gather"):
            raise ValueError(
                "refresh_fraction < 1 (PCPP) thins the displaced KV refresh "
                f"gathers of attn_impl='gather'; {distri_config.attn_impl!r} "
                "has no refresh collective to thin"
            )
        n = distri_config.n_device_per_batch
        if (
            distri_config.attn_impl == "ulysses"
            and dit_config.num_heads % n != 0
        ):
            raise ValueError(
                f"ulysses needs num_heads ({dit_config.num_heads}) divisible "
                f"by the sp degree ({n})"
            )
        if (
            distri_config.attn_impl == "usp"
            and dit_config.num_heads % distri_config.ulysses_degree != 0
        ):
            raise ValueError(
                f"usp needs num_heads ({dit_config.num_heads}) divisible by "
                f"ulysses_degree ({distri_config.ulysses_degree})"
            )
        # USP runs on the 4-axis factored view of the same device grid;
        # sequence-sharding ops address the composite (sp_u, sp_r) axis pair.
        self._usp = distri_config.attn_impl == "usp"
        self.mesh = distri_config.usp_mesh() if self._usp else distri_config.mesh
        self.seq_axes = (SP_U_AXIS, SP_R_AXIS) if self._usp else SP_AXIS
        if dit_config.num_tokens % n != 0:
            raise ValueError(
                f"token count {dit_config.num_tokens} must be divisible by "
                f"the sp degree {n}"
            )
        _rk = refresh_period(distri_config.refresh_fraction)
        if _rk > 1 and (dit_config.num_tokens // n) % _rk != 0:
            raise ValueError(
                f"refresh_fraction=1/{_rk} needs the per-device token chunk "
                f"({dit_config.num_tokens // n}) divisible by {_rk} — each "
                "stale step gathers exactly one strided row group"
            )
        if distri_config.step_cache_enabled and not (
            1 <= distri_config.step_cache_depth < dit_config.depth
        ):
            raise ValueError(
                f"step_cache_depth={distri_config.step_cache_depth} must be "
                f"in [1, {dit_config.depth - 1}] for this {dit_config.depth}-"
                "block DiT (at least one block must stay shallow)"
            )
        if (distri_config.height // 8 != dit_config.sample_size) or (
            distri_config.width // 8 != dit_config.sample_size
        ):
            raise ValueError(
                f"DistriConfig {distri_config.height}x{distri_config.width} "
                f"implies latent {distri_config.latent_height}, but "
                f"DiTConfig.sample_size is {dit_config.sample_size}"
            )
        self._compiled: Dict[int, Any] = {}
        # compiled-loop per-step callback target (_build_fused_callback)
        self._active_callback = None

    # ------------------------------------------------------------------

    def _eval_model(self, params, x_full, s, kv_state, phase_sync,
                    cap_kv, c6_all, temb_all, pos, cap_bias, shallow=False):
        """One DiT evaluation on this device's token rows.

        Returns (full guided-input epsilon [Bl, N, D_out], new kv_state).
        ``kv_state``: gathered [depth, 2, Bl, N, hidden] stale K/V
        (attn_impl="gather") or the own [depth, Bl, N/n, 2*hidden] chunk
        (attn_impl="ring") — or, with the step cache enabled,
        ``{"kv": <that state>, "deep": [Bl, N/n, hidden]}`` where ``deep``
        is the residual the deepest ``step_cache_depth`` blocks added on
        the last full step.  ``shallow`` runs only the first
        ``depth - step_cache_depth`` blocks and adds the carried residual
        (the skipped blocks' displaced KV rides through untouched).
        """
        cfg, dcfg = self.cfg, self.dcfg
        sched = self.scheduler
        n = cfg.n_device_per_batch
        n_tok = dcfg.num_tokens
        chunk = n_tok // n
        sp_idx = lax.axis_index(self.seq_axes)
        offset = sp_idx * chunk
        compute_dtype = params["proj_in"]["kernel"].dtype

        x_in = sched.scale_model_input(x_full, s)
        rows = lax.dynamic_slice(
            x_in, (0, offset, 0), (x_in.shape[0], chunk, x_in.shape[2])
        ).astype(compute_dtype)
        folded = not cfg.cfg_split and cfg.do_classifier_free_guidance
        if folded:
            rows = jnp.concatenate([rows, rows], axis=0)
        pos_rows = lax.dynamic_slice(pos, (offset, 0), (chunk, pos.shape[1]))
        h = dit_mod.embed_tokens(params, dcfg, rows, pos_rows)
        c6 = c6_all[s]
        temb = temb_all[s]
        if jnp.ndim(s) and folded:
            # per-row step indices (packed cohort dispatch): the [B, ...]
            # conditioning tables fold branch-major exactly like the rows
            c6 = jnp.concatenate([c6, c6], axis=0)
            temb = jnp.concatenate([temb, temb], axis=0)

        no_refresh = cfg.mode == "no_sync"  # keep warmup KV forever (§2.3)
        ring = cfg.attn_impl == "ring"
        ulysses = cfg.attn_impl == "ulysses"
        usp = self._usp

        def block_body_ulysses(carry, xs):
            """Ulysses SP (exact, stateless): all_to_all re-shards the
            sequence-sharded q/k/v to head-sharded full sequences, runs full
            attention on H/n heads, and re-shards back — the DeepSpeed-
            Ulysses layout (SURVEY §2.1 lists it absent in the reference).
            No staleness, so sync and stale phases are identical and the
            carry passes through untouched."""
            hcur = carry
            bp, ckv, kv_blk = xs
            heads = dcfg.num_heads
            d = dcfg.hidden_size // heads

            def core(q, k, v):
                b_, lq_ = q.shape[0], q.shape[1]

                def to_headshard(t):
                    th = t.reshape(b_, lq_, heads, d)
                    # split heads over sp, concat tokens -> [B, N, H/n, D]
                    return lax.all_to_all(
                        th, SP_AXIS, split_axis=2, concat_axis=1, tiled=True
                    )

                qg, kg, vg = to_headshard(q), to_headshard(k), to_headshard(v)
                n_full = qg.shape[1]
                h_loc = heads // n
                att = sdpa(
                    qg.reshape(b_, n_full, h_loc * d),
                    kg.reshape(b_, n_full, h_loc * d),
                    vg.reshape(b_, n_full, h_loc * d),
                    heads=h_loc,
                )
                att = att.reshape(b_, n_full, h_loc, d)
                back = lax.all_to_all(
                    att, SP_AXIS, split_axis=1, concat_axis=2, tiled=True
                )  # [B, chunk, H, D]
                return back.reshape(b_, lq_, dcfg.hidden_size)

            h_out, _ = dit_mod.dit_block(
                bp, dcfg, hcur, c6, ckv, attn_core=core, cap_bias=cap_bias
            )
            return h_out, kv_blk

        def block_body_usp(carry, xs):
            """USP (exact, stateless): the xDiT-style 2-level composition.
            The sp axis is factored (sp_u x sp_r); one all_to_all over sp_u
            turns [B, N/n, H, D] token shards into [B, N/r, H/u, D]
            head-sharded assemblies, the exact KV ring over sp_r streams the
            other r-1 assemblies through the online softmax (every chunk
            fresh — unlike the displaced "ring" layout there is no
            staleness), and the inverse all_to_all restores the token shard.
            Per block this moves 1/u of pure-ring bytes over the ring and
            1/r of pure-ulysses bytes through the all_to_alls — the knob
            (ulysses_degree) picks the point between them that fits the
            mesh."""
            from ..ops.ring_attention import ring_pass

            hcur = carry
            bp, ckv, kv_blk = xs
            heads = dcfg.num_heads
            d = dcfg.hidden_size // heads
            u = cfg.ulysses_degree
            r = n // u

            def core(q, k, v):
                b_, lq_ = q.shape[0], q.shape[1]

                def to_headshard(t):
                    th = t.reshape(b_, lq_, heads, d)
                    if u == 1:
                        return th
                    # split heads over sp_u, concat this u-group's tokens
                    return lax.all_to_all(
                        th, SP_U_AXIS, split_axis=2, concat_axis=1, tiled=True
                    )  # [B, N/r, H/u, D]

                qg, kg, vg = to_headshard(q), to_headshard(k), to_headshard(v)
                l_loc, h_loc = qg.shape[1], heads // u
                q2 = qg.reshape(b_, l_loc, h_loc * d)
                kv_local = jnp.concatenate(
                    [kg.reshape(b_, l_loc, h_loc * d),
                     vg.reshape(b_, l_loc, h_loc * d)], axis=-1
                )
                out = ring_pass(q2, kv_local, kv_local, r, SP_R_AXIS,
                                heads=h_loc)  # [B, H/u, N/r, D] fp32
                out = out.astype(q.dtype).transpose(0, 2, 1, 3)
                if u > 1:
                    out = lax.all_to_all(
                        out, SP_U_AXIS, split_axis=1, concat_axis=2, tiled=True
                    )  # [B, N/n, H, D]
                return out.reshape(b_, lq_, dcfg.hidden_size)

            h_out, _ = dit_mod.dit_block(
                bp, dcfg, hcur, c6, ckv, attn_core=core, cap_bias=cap_bias
            )
            return h_out, kv_blk

        def block_body_gather(carry, xs):
            hcur = carry
            bp, ckv, kv_blk = xs  # kv_blk [2, Bl, N, hid] stale gathered
            assembled = {}

            def assemble(k_fresh, v_fresh):
                if phase_sync:
                    kv = (all_gather_seq(k_fresh), all_gather_seq(v_fresh))
                else:
                    kv = (
                        lax.dynamic_update_slice(kv_blk[0], k_fresh, (0, offset, 0)),
                        lax.dynamic_update_slice(kv_blk[1], v_fresh, (0, offset, 0)),
                    )
                assembled["kv"] = kv
                return kv

            h_out, (k, v) = dit_mod.dit_block(
                bp, dcfg, hcur, c6, ckv, kv_assemble=assemble, cap_bias=cap_bias
            )
            # refresh for the NEXT step: fresh gathered K/V flow only into
            # the carry (deferred consumption = overlappable collective).
            # Sync phase reuses the already-assembled gather; no_sync keeps
            # the carried state untouched after warmup.  Stale refreshes
            # route through the compression layer (parallel/compress.py) —
            # a plain tiled gather at comm_compress="none", an int8/fp8
            # payload + fp32-scale pair of gathers otherwise.
            if phase_sync:
                fresh = jnp.stack(list(assembled["kv"]))
            elif no_refresh:
                fresh = kv_blk
            else:
                fresh = refresh_gather_seq(
                    jnp.stack([k, v]), kv_blk, cfg.comm_compress, offset,
                    fraction=cfg.refresh_fraction, step=s,
                )
            return h_out, fresh

        def block_body_ring(carry, xs):
            from ..ops.ring_attention import ring_pass

            hcur = carry
            bp, ckv, kv_blk = xs  # kv_blk [Bl, chunk, 2*hid] own stale chunk

            def core(q, k, v):
                # with no kv_assemble/self_kv, dit_block hands the fresh
                # local (k, v) straight through — exactly the own chunk
                kv_local = jnp.concatenate([k, v], axis=-1)
                # sync phase rotates fresh chunks (exact); stale phase
                # rotates each peer's previous-step chunk from the carry
                rotating = kv_local if phase_sync else kv_blk
                out = ring_pass(q, kv_local, rotating, n, SP_AXIS,
                                heads=dcfg.num_heads)
                b_, lq_ = q.shape[0], q.shape[1]
                out = out.astype(q.dtype).transpose(0, 2, 1, 3)
                return out.reshape(b_, lq_, dcfg.hidden_size)

            h_out, (k, v) = dit_mod.dit_block(
                bp, dcfg, hcur, c6, ckv, attn_core=core, cap_bias=cap_bias
            )
            # next step's stale state is just this step's own fresh chunk —
            # no collective at all (ring_attention.py semantics).  Sync steps
            # always commit (that snapshot IS what no_sync freezes).
            if phase_sync or not no_refresh:
                fresh = jnp.concatenate([k, v], axis=-1)
            else:
                fresh = kv_blk
            return h_out, fresh

        if usp:
            block_body = block_body_usp
        elif ulysses:
            block_body = block_body_ulysses
        else:
            block_body = block_body_ring if ring else block_body_gather

        if cfg.step_cache_enabled:
            kv_blocks, deep = kv_state["kv"], kv_state["deep"]
            d_keep = dcfg.depth - cfg.step_cache_depth
            if shallow:
                # shallow body: only the first d_keep blocks execute; the
                # deepest blocks' contribution is the carried residual, and
                # their displaced KV (and the residual) pass through — so
                # their refresh collectives never appear in this body.
                head_xs = jax.tree.map(
                    lambda l: l[:d_keep],
                    (params["blocks"], cap_kv, kv_blocks),
                )
                h, kv_head = lax.scan(block_body, h, head_xs)
                h = h + deep
                kv_new = {
                    "kv": jax.tree.map(
                        lambda fresh, old: jnp.concatenate(
                            [fresh, old[d_keep:]], axis=0
                        ),
                        kv_head, kv_blocks,
                    ),
                    "deep": deep,
                }
            else:
                # full body: run everything, capturing h at the cut so the
                # deep residual (h_final - h_mid) refreshes the carry
                def full_body(carry, xs):
                    hcur, h_mid = carry
                    h2, fresh = block_body(hcur, xs[1:])
                    h_mid = jnp.where(xs[0] == d_keep - 1, h2, h_mid)
                    return (h2, h_mid), fresh

                (h, h_mid), kv_all = lax.scan(
                    full_body, (h, h),
                    (jnp.arange(dcfg.depth), params["blocks"], cap_kv,
                     kv_blocks),
                )
                kv_new = {"kv": kv_all, "deep": h - h_mid}
        else:
            h, kv_new = lax.scan(
                block_body, h, (params["blocks"], cap_kv, kv_state)
            )
        eps_rows = dit_mod.final_layer(params, dcfg, h, temb)
        eps_full = all_gather_seq(eps_rows, self.seq_axes)
        return eps_full, kv_new

    def _make_step(self, params, enc, cap_mask, gs, batch):
        """Per-device step closure + the local branch count and dtype —
        shared by the fused loop and the hybrid pair of programs."""
        cfg, dcfg = self.cfg, self.dcfg
        sched = self.scheduler
        my_enc, _, _ = branch_select(cfg, enc)
        my_mask, _, _ = branch_select(cfg, cap_mask)
        cap_bias = dit_mod.caption_mask_bias(my_mask)
        compute_dtype = params["proj_in"]["kernel"].dtype
        pos = dit_mod.pos_embed_table(dcfg, compute_dtype)
        cap_kv = dit_mod.precompute_caption_kv(params, dcfg, my_enc)
        ts = sched.timesteps()
        temb_all = jax.vmap(lambda t: dit_mod.t_embed(params, dcfg, t))(ts)
        c6_all = jax.vmap(lambda e: dit_mod.adaln_table(params, dcfg, e))(temb_all)

        def step(x, sstate, kv, s, phase_sync, shallow=False):
            eps, kv = self._eval_model(
                params, x, s, kv, phase_sync, cap_kv, c6_all, temb_all, pos,
                cap_bias, shallow=shallow,
            )
            guided = combine_guidance(cfg, eps, gs, batch)
            x, sstate = sched.step(x, guided.astype(jnp.float32), s, sstate)
            return x, sstate, kv

        return step, my_enc.shape[0], compute_dtype

    def _kv0(self, bloc, compute_dtype):
        cfg, dcfg = self.cfg, self.dcfg
        if cfg.attn_impl in ("ulysses", "usp"):
            # exact and stateless: a minimal placeholder keeps the block
            # scan's xs structure uniform
            kv = jnp.zeros((dcfg.depth, 1), compute_dtype)
        elif cfg.attn_impl == "ring":
            chunk = dcfg.num_tokens // cfg.n_device_per_batch
            kv = jnp.zeros(
                (dcfg.depth, bloc, chunk, 2 * dcfg.hidden_size), compute_dtype
            )
        else:
            kv = jnp.zeros(
                (dcfg.depth, 2, bloc, dcfg.num_tokens, dcfg.hidden_size),
                compute_dtype,
            )
        if cfg.step_cache_enabled:
            chunk = dcfg.num_tokens // cfg.n_device_per_batch
            return {"kv": kv, "deep": jnp.zeros(
                (bloc, chunk, dcfg.hidden_size), compute_dtype)}
        return kv

    def _device_loop(self, params, latents, enc, cap_mask, gs, num_steps):
        cfg, dcfg = self.cfg, self.dcfg
        batch = latents.shape[0]
        step, bloc, compute_dtype = self._make_step(
            params, enc, cap_mask, gs, batch
        )
        x = dit_mod.patchify(dcfg, latents.astype(jnp.float32))
        sstate = self.scheduler.init_state(x.shape)
        kv0 = self._kv0(bloc, compute_dtype)

        full_sync = cfg.mode == "full_sync" or not cfg.is_sp

        def sync_body(i, carry):
            x, ss, kv = carry
            return step(x, ss, kv, i, True)

        if cfg.step_cache_enabled:
            # temporal step-cache cadence (parallel/stepcache.py): full
            # warmup, then super-steps of (interval-1) shallow + 1 full —
            # the same two-bodies-in-a-scan shape as the UNet runner's
            n_sync = min(cfg.warmup_steps + 1, num_steps)
            x, sstate, kv = lax.fori_loop(
                0, n_sync, sync_body, (x, sstate, kv0)
            )

            def run_step(carry, i, shallow):
                x, ss, kv = carry
                return step(x, ss, kv, i, full_sync, shallow)

            x, _, _ = run_cadence(
                (x, sstate, kv), n_sync, num_steps - n_sync,
                cfg.step_cache_interval, run_step,
            )
            return dit_mod.unpatchify(dcfg, x, dcfg.in_channels)

        n_sync = num_steps if full_sync else min(cfg.warmup_steps + 1, num_steps)

        x, sstate, kv = lax.fori_loop(0, n_sync, sync_body, (x, sstate, kv0))

        if n_sync < num_steps:
            def stale_body(carry, i):
                x, ss, kv = carry
                return step(x, ss, kv, i, False), None

            (x, _, _), _ = lax.scan(
                stale_body, (x, sstate, kv), jnp.arange(n_sync, num_steps)
            )
        return dit_mod.unpatchify(dcfg, x, dcfg.in_channels)

    # ------------------------------------------------------------------

    def _build(self, num_steps: int):
        cfg = self.cfg
        self.scheduler.set_timesteps(num_steps)
        device_loop = partial(self._device_loop, num_steps=num_steps)
        lat_spec = P(DP_AXIS)
        enc_spec = P(None, DP_AXIS)

        def loop(params, latents, enc, cap_mask, gs):
            return shard_map(
                device_loop,
                mesh=self.mesh,
                in_specs=(P(), lat_spec, enc_spec, enc_spec, P()),
                out_specs=lat_spec,
                check_vma=False,
            )(params, latents, enc, cap_mask, gs)

        return jax.jit(loop)

    def _build_hybrid(self, num_steps: int):
        """Two ONE-body programs instead of one two-body program
        (cfg.hybrid_loop; the DiT analog of runner._build_stale_scan): the
        sync warmup fori and the stale scan each carry a single transformer
        body, roughly halving the big program's (remote) compile at
        identical numerics.  The carry crosses the jit boundary: tokens and
        scheduler state are replicated within a dp group (the CFG-combined
        scheduler step makes them identical on every device of the group),
        while the stale KV state varies per device and is laid out along
        (dp, cfg, sp) on a fresh leading axis."""
        cfg, dcfg = self.cfg, self.dcfg
        self.scheduler.set_timesteps(num_steps)
        n_sync = min(cfg.warmup_steps + 1, num_steps)
        lat_spec, kv_spec, ss_spec, enc_spec = self._token_specs()

        def device_sync(params, latents, enc, cap_mask, gs):
            batch = latents.shape[0]
            step, bloc, compute_dtype = self._make_step(
                params, enc, cap_mask, gs, batch
            )
            x = dit_mod.patchify(dcfg, latents.astype(jnp.float32))
            sstate = self.scheduler.init_state(x.shape)

            def sync_body(i, carry):
                x, ss, kv = carry
                return step(x, ss, kv, i, True)

            x, sstate, kv = lax.fori_loop(
                0, n_sync, sync_body,
                (x, sstate, self._kv0(bloc, compute_dtype)),
            )
            return x, sstate, kv[None]

        def device_stale(params, x, sstate, kv, enc, cap_mask, gs):
            batch = x.shape[0]
            step, _, _ = self._make_step(params, enc, cap_mask, gs, batch)

            def stale_body(carry, i):
                x, ss, kv = carry
                return step(x, ss, kv, i, False), None

            (x, _, _), _ = lax.scan(
                stale_body, (x, sstate, kv[0]),
                jnp.arange(n_sync, num_steps),
            )
            return dit_mod.unpatchify(dcfg, x, dcfg.in_channels)

        sync = jax.jit(lambda p, l, e, m, g: shard_map(
            device_sync, mesh=self.mesh,
            in_specs=(P(), lat_spec, enc_spec, enc_spec, P()),
            out_specs=(lat_spec, ss_spec, kv_spec),
            check_vma=False,
        )(p, l, e, m, g))
        stale = jax.jit(lambda p, x, ss, kv, e, m, g: shard_map(
            device_stale, mesh=self.mesh,
            in_specs=(P(), lat_spec, ss_spec, kv_spec, enc_spec, enc_spec,
                      P()),
            out_specs=lat_spec,
            check_vma=False,
        )(p, x, ss, kv, e, m, g), donate_argnums=(1, 2, 3))
        return sync, stale

    # ------------------------------------------------------------------
    # per-step (uncompiled-loop) mode + compiled-loop callbacks
    # ------------------------------------------------------------------

    def _token_specs(self):
        """(x_spec, kv_spec, ss_spec, enc_spec) for the stepwise boundary —
        the same layout _build_hybrid documents: tokens/scheduler state
        replicated within a dp group, the per-device stale KV stacked on a
        fresh leading (dp, cfg, sp...) axis."""
        seq = (self.seq_axes if isinstance(self.seq_axes, tuple)
               else (self.seq_axes,))
        kv_spec = P((DP_AXIS, CFG_AXIS) + seq)
        ss_shapes = self.scheduler.init_state(
            (1, self.dcfg.num_tokens, self.dcfg.token_dim)
        )
        ss_spec = jax.tree.map(
            lambda l: P(DP_AXIS) if jnp.ndim(l) >= 3 else P(), ss_shapes
        )
        return P(DP_AXIS), kv_spec, ss_spec, P(None, DP_AXIS)

    def _make_stepper(self, phase_sync: bool, shallow: bool = False):
        """Un-jitted shard_map'd single step over PATCHIFIED tokens
        [B, N, token_dim] (global-array signature)."""
        x_spec, kv_spec, ss_spec, enc_spec = self._token_specs()

        def device_step(params, s, x, kv, sstate, enc, cap_mask, gs):
            step, _, _ = self._make_step(params, enc, cap_mask, gs,
                                         x.shape[0])
            kv_local = jax.tree.map(lambda l: l[0], kv)
            x, sstate, kv_new = step(x, sstate, kv_local, s, phase_sync,
                                     shallow)
            return x, sstate, jax.tree.map(lambda l: l[None], kv_new)

        def stepper(params, s, x, kv, sstate, enc, cap_mask, gs):
            return shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(P(), P(), x_spec, kv_spec, ss_spec, enc_spec,
                          enc_spec, P()),
                out_specs=(x_spec, ss_spec, kv_spec),
                check_vma=False,
            )(params, s, x, kv, sstate, enc, cap_mask, gs)

        return stepper

    def _ensure_stepper(self, num_steps: int, sync: bool,
                        shallow: bool = False):
        """Jitted per-step program cached by (num_steps, phase, shallow) —
        the scheduler tables bake at trace time (same convention as the
        UNet and MMDiT runners)."""
        fns = self._compiled.setdefault(("stepwise", num_steps), {})
        fkey = (sync, shallow)
        if fkey not in fns:
            fns[fkey] = jax.jit(self._make_stepper(sync, shallow),
                                donate_argnums=(3,))
        return fns[fkey]

    def _kv0_global(self, batch):
        """Global stepwise-layout zeros: per-device _kv0 stacked over every
        mesh device on a fresh leading axis."""
        cfg = self.cfg
        n_total = self.mesh.devices.size
        bloc = (1 if cfg.cfg_split or not cfg.do_classifier_free_guidance
                else 2) * (batch // cfg.dp_degree)
        per_dev = self._kv0(bloc, self.params["proj_in"]["kernel"].dtype)
        return jax.tree.map(
            lambda l: jnp.zeros((n_total,) + l.shape, l.dtype), per_dev
        )

    def _exec_phases(self, num_steps: int):
        full_sync = self.cfg.mode == "full_sync" or not self.cfg.is_sp
        if full_sync and not self.cfg.step_cache_enabled:
            return num_steps
        return min(self.cfg.warmup_steps + 1, num_steps)

    def _generate_stepwise(self, latents, enc, cap_mask, gs, num_steps,
                           callback=None):
        """Python loop over per-step compiled calls (use_cuda_graph=False
        parity): same numerics as the fused loop, per-step latency visible
        from the host, diffusers legacy ``callback(i, t, latents)``."""
        cfg, dcfg = self.cfg, self.dcfg
        sched = self.scheduler
        sched.set_timesteps(num_steps)
        n_sync = self._exec_phases(num_steps)
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        sc = cfg.step_cache_enabled
        x = dit_mod.patchify(dcfg, jnp.asarray(latents, jnp.float32))
        sstate = sched.init_state(x.shape)
        kv = self._kv0_global(latents.shape[0])
        for i in range(num_steps):
            shallow = sc and is_shallow_at(i, n_sync,
                                           cfg.step_cache_interval)
            x, sstate, kv = self._ensure_stepper(
                num_steps, one_phase or i < n_sync, shallow
            )(
                self.params, jnp.asarray(i), x, kv, sstate, enc, cap_mask,
                gs,
            )
            if callback is not None:
                callback(i, sched.timesteps()[i],
                         dit_mod.unpatchify(dcfg, x, dcfg.in_channels))
        return dit_mod.unpatchify(dcfg, x, dcfg.in_channels)

    # -- explicit-carry stepwise API (step-granular serve substrate) -------

    def stepwise_carry_init(self, latents, num_steps: int):
        """Start a host-driven denoise with the carry held EXTERNALLY:
        ``(x, sstate, kv)`` — the state one `_generate_stepwise`
        iteration threads, so the step-granular serve layer
        (serve/stepbatch.py) can park/resume/interleave requests between
        steps while each carry replays the identical per-step programs."""
        self.scheduler.set_timesteps(num_steps)
        x = dit_mod.patchify(self.dcfg, jnp.asarray(latents, jnp.float32))
        return (x, self.scheduler.init_state(x.shape),
                self._kv0_global(latents.shape[0]))

    def stepwise_carry_step(self, carry, i: int, enc, cap_mask, gs,
                            num_steps: int):
        """Advance one explicit carry by exactly step ``i`` — the SAME
        compiled stepper `_generate_stepwise` dispatches for this
        (phase, shallow) signature, so solo and interleaved executions
        are byte-identical."""
        cfg = self.cfg
        x, sstate, kv = carry
        n_sync = self._exec_phases(num_steps)
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        shallow = cfg.step_cache_enabled and is_shallow_at(
            i, n_sync, cfg.step_cache_interval)
        return self._ensure_stepper(
            num_steps, one_phase or i < n_sync, shallow
        )(self.params, jnp.asarray(i), x, kv, sstate, enc, cap_mask, gs)

    def stepwise_carry_latent(self, carry):
        """The carry's current GLOBAL latent [B, H/8, W/8, C] (preview +
        decode input) — does not consume the carry."""
        return dit_mod.unpatchify(self.dcfg, carry[0],
                                  self.dcfg.in_channels)

    # -- packed cohort rows (serve/executors.py step_run; parallel/rowpack) --

    def stepwise_rows_supported(self) -> bool:
        """Whether packed multi-row dispatch preserves bit-identity on this
        config.  DP-split batches can't carry a replicated per-row step
        vector; the PCPP partial-refresh rotation (`refresh_gather_seq`
        step=s) and per-tensor compression scales couple rows."""
        cfg = self.cfg
        return (cfg.dp_degree == 1 and cfg.refresh_fraction >= 1
                and cfg.comm_compress == "none")

    def stepwise_carry_signature(self, carry, i: int, num_steps: int):
        """Compiled-program key of step ``i`` — two carries whose next
        steps share this tuple run the SAME jitted stepper and may pack
        into one dispatch."""
        cfg = self.cfg
        n_sync = self._exec_phases(num_steps)
        one_phase = cfg.mode == "full_sync" or not cfg.is_sp
        sync = one_phase or i < n_sync
        shallow = cfg.step_cache_enabled and is_shallow_at(
            i, n_sync, cfg.step_cache_interval)
        return ("dit", sync, shallow, num_steps)

    def stepwise_carry_rows_axes(self, carry, num_steps: int):
        """Per-leaf rowpack plan for this runner's carry layout, found by
        comparing the carry's abstract shapes at batch widths w and 2w
        (rowpack.axes_from_shapes) — no hand-maintained layout table."""
        from . import rowpack

        x = carry[0]
        w = x.shape[0]

        def shapes(k):
            return jax.eval_shape(lambda: (
                jnp.zeros((w * k,) + x.shape[1:], x.dtype),
                self.scheduler.init_state((w * k,) + x.shape[1:]),
                self._kv0_global(w * k),
            ))

        return rowpack.axes_from_shapes(shapes(1), shapes(2))

    def stepwise_carry_step_rows(self, carry, i_rows, enc, cap_mask,
                                 gs_rows, num_steps: int):
        """Advance ``len(i_rows)`` packed rows in ONE dispatch of the same
        jitted stepper the solo path uses: row r steps by its own index
        ``i_rows[r]`` under its own scale ``gs_rows[r]``.  All rows must
        share one (phase, shallow) signature — callers group by
        `stepwise_carry_signature` first."""
        x, sstate, kv = carry
        sigs = {self.stepwise_carry_signature(carry, int(i), num_steps)
                for i in i_rows}
        if len(sigs) != 1:
            raise ValueError(
                f"packed rows span {len(sigs)} step signatures: {sigs}"
            )
        _, sync, shallow, _ = next(iter(sigs))
        return self._ensure_stepper(num_steps, sync, shallow)(
            self.params, jnp.asarray(list(i_rows)), x, kv, sstate, enc,
            cap_mask, jnp.asarray(list(gs_rows), jnp.float32))

    def _fire_callback(self, i, t, x):
        """Host trampoline for the compiled-loop callback (io_callback)."""
        cb = self._active_callback
        if cb is not None:
            cb(int(i), t, x)

    def _build_fused_callback(self, num_steps: int):
        """Compiled loop that fires per-step host callbacks: lax.scan over
        the shard_map'd stepwise step with ordered io_callback shipping the
        GLOBAL unpatchified latents after each step (scan for both
        segments; ordered effects are unsupported in fori bodies)."""
        from jax.experimental import io_callback

        cfg, dcfg = self.cfg, self.dcfg
        sched = self.scheduler
        sched.set_timesteps(num_steps)
        n_sync = self._exec_phases(num_steps)
        sync_step = self._make_stepper(True)
        stale_step = self._make_stepper(False)

        def loop(params, latents, enc, cap_mask, gs):
            x = dit_mod.patchify(dcfg, latents.astype(jnp.float32))
            sstate = sched.init_state(x.shape)
            kv = self._kv0_global(latents.shape[0])
            tsteps = sched.timesteps()

            def body_for(step_fn):
                def body(carry, i):
                    x, kv, ss = carry
                    x, ss, kv = step_fn(params, i, x, kv, ss, enc, cap_mask,
                                        gs)
                    io_callback(
                        self._fire_callback, None, i, tsteps[i],
                        dit_mod.unpatchify(dcfg, x, dcfg.in_channels),
                        ordered=True,
                    )
                    return (x, kv, ss), None
                return body

            (x, kv, sstate), _ = lax.scan(
                body_for(sync_step), (x, kv, sstate), jnp.arange(n_sync)
            )
            if n_sync < num_steps:
                (x, kv, sstate), _ = lax.scan(
                    body_for(stale_step), (x, kv, sstate),
                    jnp.arange(n_sync, num_steps),
                )
            return dit_mod.unpatchify(dcfg, x, dcfg.in_channels)

        return jax.jit(loop)

    def comm_report(self, batch_size: int = 1) -> Dict[str, Any]:
        """Per-device stale-state and per-step collective volumes (elements)
        for the configured attention layout — the DiT analog of
        DenoiseRunner.comm_volume_report / PipeFusionRunner.comm_report
        (reference verbose buffer stats, utils.py:152-158).  Closed-form from
        the architecture; no tracing."""
        cfg, dcfg = self.cfg, self.dcfg
        n = cfg.n_device_per_batch
        if not cfg.is_sp:
            report = {"layout": cfg.attn_impl, "kv_state_elems": 0,
                      "per_step_collective_elems": 0,
                      # byte model: a single-device group has no sp
                      # traffic — zero is the truth, not a guess
                      # (pipelines.comm_plan raises on runners that
                      # lack these keys)
                      "per_step_collective_bytes": 0,
                      "sync_step_collective_bytes": 0}
            if cfg.step_cache_enabled:
                report["step_cache"] = {
                    "interval": cfg.step_cache_interval,
                    "depth": cfg.step_cache_depth,
                    "shallow_per_step_collective_elems": 0,
                }
            return report
        # Per-device folded batch (guidance.branch_select): cfg_split keeps
        # one branch locally; otherwise CFG rides the batch dim as 2B.
        n_br_local = (
            1 if cfg.cfg_split or not cfg.do_classifier_free_guidance else 2
        )
        b = batch_size * n_br_local
        n_tok, hid, depth = dcfg.num_tokens, dcfg.hidden_size, dcfg.depth
        chunk = n_tok // n
        # the final-layer epsilon gather runs in every layout; eps-only head
        # (out_channels), not diffusers' 2x (eps, sigma) head — ADVICE r3
        eps_gather = b * n_tok * dcfg.patch_size**2 * dcfg.out_channels
        if cfg.attn_impl == "gather":
            state = depth * 2 * b * n_tok * hid
            per_step = depth * 2 * b * n_tok * hid + eps_gather
        elif cfg.attn_impl == "ring":
            state = depth * b * chunk * 2 * hid
            # (n-1) ppermute hops of the local 2C chunk per block, in-step
            per_step = depth * (n - 1) * b * chunk * 2 * hid + eps_gather
        elif cfg.attn_impl == "ulysses":
            state = 0
            # 2 all_to_alls (qkv out + attn back) moving ~the local tokens
            per_step = depth * b * chunk * hid * 4 + eps_gather
        else:  # usp
            u = cfg.ulysses_degree
            r = n // u
            state = 0
            a2a = depth * b * chunk * hid * 4 if u > 1 else 0
            ring_hops = depth * (r - 1) * b * (chunk * u) * 2 * hid // u
            per_step = a2a + ring_hops + eps_gather
        report = {"layout": cfg.attn_impl, "kv_state_elems": int(state),
                  "per_step_collective_elems": int(per_step)}
        # wire bytes: sync steps always move full precision; stale steps
        # move the compressed payload + fp32 scales when comm_compress is
        # on, and only 1/k of the KV rows when refresh_fraction = 1/k
        # (gather layout only — the other layouts reject both knobs).
        # full_refresh_* is the same closed form at fraction 1, so the
        # PCPP reduction is a checked ratio, not a recomputation.
        itemsize = jnp.dtype(cfg.dtype).itemsize
        kk = refresh_period(cfg.refresh_fraction)
        report["comm_compress"] = cfg.comm_compress
        report["refresh_fraction"] = cfg.refresh_fraction
        report["sync_step_collective_bytes"] = int(per_step) * itemsize
        if cfg.attn_impl == "gather":
            full_refresh = depth * n * wire_nbytes(
                (2, b, chunk, hid), itemsize, cfg.comm_compress
            )
            part_refresh = depth * n * wire_nbytes(
                (2, b, chunk // kk, hid), itemsize, cfg.comm_compress
            )
            report["per_step_collective_bytes"] = int(
                part_refresh + eps_gather * itemsize
            )
            report["full_refresh_per_step_collective_bytes"] = int(
                full_refresh + eps_gather * itemsize
            )
        else:
            report["per_step_collective_bytes"] = int(per_step) * itemsize
            report["full_refresh_per_step_collective_bytes"] = (
                int(per_step) * itemsize
            )
        if cfg.step_cache_enabled:
            # shallow steps run only d_keep of depth blocks, so the
            # per-block exchange volume scales down proportionally; the
            # final epsilon gather always runs
            d_keep = depth - cfg.step_cache_depth
            shallow = (per_step - eps_gather) * d_keep // depth + eps_gather
            report["step_cache"] = {
                "interval": cfg.step_cache_interval,
                "depth": cfg.step_cache_depth,
                "shallow_per_step_collective_elems": int(shallow),
            }
        return report

    def generate(self, latents, enc, guidance_scale=5.0, num_inference_steps=20,
                 cap_mask=None, callback=None):
        """Same contract as PipeFusionRunner.generate.  ``cap_mask``
        [n_br, B, Lt] (1 = real caption token) masks padded text tokens out
        of cross-attention (PixArt semantics); None attends to all.
        ``callback(i, t, latents)`` (diffusers legacy signature) fires
        after every step in every mode — from the host loop with
        use_cuda_graph=False, via ordered io_callback inside the compiled
        loop otherwise."""
        self.scheduler.set_timesteps(num_inference_steps)
        gs = jnp.asarray(guidance_scale, jnp.float32)
        if cap_mask is None:
            cap_mask = jnp.ones(enc.shape[:3], jnp.float32)
        cap_mask = jnp.asarray(cap_mask, jnp.float32)
        if not self.cfg.use_compiled_step:
            return self._generate_stepwise(
                latents, enc, cap_mask, gs, num_inference_steps, callback,
            )
        if callback is not None:
            from ..utils.compat import SUPPORTS_FUSED_CALLBACK

            if not SUPPORTS_FUSED_CALLBACK or self.cfg.step_cache_enabled:
                # this jaxlib aborts compiling the ordered-io_callback
                # program (utils/compat.py) — host-driven loop instead.
                # Step-cache callbacks also take the host loop: the
                # stepwise steppers replay the exact cadence.
                return self._generate_stepwise(
                    latents, enc, cap_mask, gs, num_inference_steps, callback,
                )
            key = ("fused_cb", num_inference_steps)
            if key not in self._compiled:
                self._compiled[key] = self._build_fused_callback(
                    num_inference_steps
                )
            self._active_callback = callback
            try:
                out = self._compiled[key](
                    self.params, jnp.asarray(latents), enc, cap_mask, gs
                )
                jax.effects_barrier()  # host callbacks drain before return
                jax.block_until_ready(out)
                return out
            finally:
                self._active_callback = None
        if self._hybrid_dispatch(num_inference_steps):
            sync, stale = self._ensure_hybrid(num_inference_steps)
            x, sstate, kv = sync(self.params, latents, enc, cap_mask, gs)
            return stale(self.params, x, sstate, kv, enc, cap_mask, gs)
        if num_inference_steps not in self._compiled:
            self._compiled[num_inference_steps] = self._build(num_inference_steps)
        return self._compiled[num_inference_steps](
            self.params, latents, enc, cap_mask, gs
        )

    def _hybrid_dispatch(self, num_steps: int) -> bool:
        cfg = self.cfg
        return (cfg.hybrid_loop and cfg.is_sp and cfg.mode != "full_sync"
                and min(cfg.warmup_steps + 1, num_steps) < num_steps)

    def _ensure_hybrid(self, num_steps: int):
        key = ("hybrid", num_steps)
        if key not in self._compiled:
            self._compiled[key] = self._build_hybrid(num_steps)
        return self._compiled[key]

    def prepare(self, num_steps: int) -> None:
        """Pre-build exactly the program(s) generate() will dispatch to
        (per-step programs build lazily, like the other runners)."""
        if not self.cfg.use_compiled_step:
            return
        self.scheduler.set_timesteps(num_steps)
        if self._hybrid_dispatch(num_steps):
            self._ensure_hybrid(num_steps)
            return
        if num_steps not in self._compiled:
            self._compiled[num_steps] = self._build(num_steps)

"""distrifuser_tpu: TPU-native displaced patch parallelism for diffusion models.

A from-scratch JAX/XLA/Pallas re-design of DistriFusion (mit-han-lab/distrifuser,
CVPR 2024): training-free distributed inference for SDXL / SD that splits the
latent image into spatial patches across TPU chips and hides cross-patch
communication behind compute by reusing one-step-stale activations.
"""

from .__version__ import __version__
from .utils.config import DistriConfig, init_multihost


def __getattr__(name):
    # Lazy pipeline exports keep `import distrifuser_tpu` light.
    if name in ("DistriSDXLPipeline", "DistriSDPipeline",
                "DistriPixArtPipeline", "DistriSD3Pipeline"):
        from . import pipelines

        return getattr(pipelines, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Closed-loop SLO controller: load-driven tier selection over the
quality/cost lattice.

The repo's serving stack has accumulated a ladder of quality/cost knobs —
denoise step count, the temporal step cache (PR 2), stale-refresh wire
compression (PR 4), and PCPP partial refresh (this PR) — but until now
the only thing that moved along it was the *failure*-driven degradation
ladder (serve/resilience.py): under heavy load every request paid full
price until something broke.  This module closes the loop on the *load*
side, steering on the signals PR 8 built (`server.slo_snapshot()`:
per-slo_class rolling p50/p99 plus queue-depth/inflight gauges):

* a validated, ordered **tier table** (`TierSpec`) walks the lattice from
  full quality to progressively cheaper compiled programs — step cache →
  wire compression → PCPP partial refresh → reduced steps — with
  **admission control** past the last tier;
* per SLO class, `SLOController` holds the current tier and, on every
  scheduler tick, compares each tier's PREDICTED latency (calibrated
  per-batch service time x the tier's cost multiplier x the queue-depth
  load factor) against the class's p99 target, walking one rung per
  cooldown toward the cheapest tier that holds the SLO — and back toward
  full quality, with margin, when load subsides;
* the scheduler maps each batch's key through the winning tier
  (`apply_tier`) — a different `ExecKey`, so full-quality and degraded
  executables coexist in the `ExecutorCache` like every other key family;
* every decision is traced (PR-8 spans, track "controller") and counted
  (MetricsRegistry: per-class tier gauges, per-tier dispatch counters,
  transition counters).

**Precedence vs the failure ladder**: the controller picks the tier and
maps the key FIRST; the resilience engine then tracks breakers and sticky
degradation rungs per *tier key* and applies its rungs on top
(`ResilienceEngine.degraded_key`).  Ladder rungs therefore always win —
a tier requesting the step cache on a key whose ladder learned
``step_cache_off`` still dispatches with the cache off (the controller's
knob is retracted by construction), and a tier key whose circuit opened
sheds exactly like any other key.

Determinism: every decision is a pure function of (injected clock,
`slo_snapshot`, the calibration ring) — replayed load on the same clock
produces the identical tier walk, which is what the load-replay tests
pin.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import sync
from ..utils.config import validate_step_cache_knobs
from .cache import ExecKey

# The virtual rung past the last tier: reject at admission instead of
# dispatching work that cannot hold its SLO (serve/errors.py
# AdmissionRejectedError).  Not a TierSpec — nothing executes there.
ADMISSION = "admission_control"


@dataclasses.dataclass(frozen=True)
class TierSpec:
    """One rung of the quality/cost lattice.

    ``cost`` is the tier's predicted service-time multiplier relative to
    the full tier (1.0) — the controller's forward model, calibrated
    against measured completions via cost-normalized observations.  The
    knob fields are ``None`` = leave the key's value alone; set = override
    on `apply_tier`.  ``steps_scale`` multiplies the request's step count
    (floor 1).  Knob overrides other than ``steps_scale`` apply to
    displaced-patch keys only — a pipefusion bucket still benefits from
    the step scaling, but the patch-protocol knobs don't exist there."""

    name: str
    cost: float
    step_cache: Optional[Tuple[int, int]] = None
    comm_compress: Optional[str] = None
    refresh_fraction: Optional[float] = None
    steps_scale: float = 1.0

    def validate(self) -> None:
        if not self.name or self.name == ADMISSION:
            raise ValueError(f"invalid tier name {self.name!r}")
        if not (0.0 < self.cost <= 1.0):
            raise ValueError(
                f"tier {self.name!r}: cost must be in (0, 1], got {self.cost}"
            )
        if self.step_cache is not None:
            validate_step_cache_knobs(*self.step_cache)
        if self.comm_compress is not None:
            from ..parallel.compress import validate_mode

            validate_mode(self.comm_compress)
        if self.refresh_fraction is not None:
            from ..parallel.compress import validate_refresh_fraction

            validate_refresh_fraction(self.refresh_fraction)
        if not (0.0 < self.steps_scale <= 1.0):
            raise ValueError(
                f"tier {self.name!r}: steps_scale must be in (0, 1], got "
                f"{self.steps_scale}"
            )


# The default walk down the lattice (ISSUE 10 tier table): full → step
# cache → wire compression → PCPP partial refresh → reduced steps →
# admission control at the extreme.  Costs are the forward-model priors —
# the closed loop corrects for a mesh where they are off, since tier
# escalation keys off MEASURED windows too.
DEFAULT_TIERS: Tuple[TierSpec, ...] = (
    TierSpec("full", 1.0),
    TierSpec("step_cache", 0.75, step_cache=(2, 1)),
    TierSpec("comm_compress", 0.65, step_cache=(2, 1), comm_compress="int8"),
    TierSpec("partial_refresh", 0.55, step_cache=(2, 1),
             comm_compress="int8", refresh_fraction=0.5),
    TierSpec("reduced_steps", 0.3, step_cache=(2, 1), comm_compress="int8",
             refresh_fraction=0.5, steps_scale=0.5),
)


def normalize_tier_table(tiers: Sequence[Any]) -> Tuple[TierSpec, ...]:
    """Validate a tier table (ControllerConfig.tiers): TierSpec instances
    or mapping entries, unique names, the first tier the cost-1.0
    identity, costs strictly decreasing (the walk must actually get
    cheaper — equal-cost rungs would make the controller burn a cooldown
    for nothing).  () resolves to `DEFAULT_TIERS`."""
    if not tiers:
        return DEFAULT_TIERS
    specs: List[TierSpec] = []
    for entry in tiers:
        if isinstance(entry, TierSpec):
            spec = entry
        elif isinstance(entry, dict):
            kw = dict(entry)
            if kw.get("step_cache") is not None:
                kw["step_cache"] = tuple(int(x) for x in kw["step_cache"])
            spec = TierSpec(**kw)
        else:
            raise ValueError(
                f"tier table entries must be TierSpec or dict, got "
                f"{type(entry).__name__}"
            )
        spec.validate()
        specs.append(spec)
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"tier names must be unique, got {names}")
    if specs[0].cost != 1.0:
        raise ValueError(
            "the first tier is the full-quality identity and must have "
            f"cost 1.0, got {specs[0].cost} ({specs[0].name!r})"
        )
    for a, b in zip(specs, specs[1:]):
        if b.cost >= a.cost:
            raise ValueError(
                f"tier costs must strictly decrease along the table: "
                f"{a.name!r} ({a.cost}) -> {b.name!r} ({b.cost})"
            )
    return tuple(specs)


def apply_tier(key: ExecKey, tier: TierSpec) -> ExecKey:
    """Map a bucket's base `ExecKey` through one tier's knob overrides.

    Patch-protocol knobs (step cache, comm_compress, refresh_fraction)
    apply to displaced-patch keys only; ``steps_scale`` applies to every
    key.  The ladder's sticky rungs compose ON TOP of the returned key
    (`ResilienceEngine.degraded_key`), so a rung like ``step_cache_off``
    overrides the tier's cadence — ladder wins, controller retracts."""
    repl: Dict[str, Any] = {}
    if tier.steps_scale != 1.0:
        repl["steps"] = max(1, int(round(key.steps * tier.steps_scale)))
    if key.parallelism == "patch":
        if tier.step_cache is not None:
            repl["step_cache_interval"] = int(tier.step_cache[0])
            repl["step_cache_depth"] = int(tier.step_cache[1])
        if tier.comm_compress is not None:
            repl["comm_compress"] = tier.comm_compress
        if tier.refresh_fraction is not None:
            repl["refresh_fraction"] = float(tier.refresh_fraction)
    return dataclasses.replace(key, **repl) if repl else key


@dataclasses.dataclass
class _ClassState:
    """Per-SLO-class controller state (scheduler-thread mutations; the
    ``tier`` int is read racily by `admit` — a torn read is impossible
    for a GIL-word int, and admission staleness is bounded by one poll)."""

    tier: int = 0
    last_change: float = 0.0
    transitions: int = 0


class SLOController:
    """Per-class tier selection on the injected server clock.

    ``decide``/``poll`` run on the scheduler thread only; ``admit`` and
    ``observe_batch`` are any-thread (lock-guarded where it matters).
    ``snapshot_fn`` is `InferenceServer.slo_snapshot` (or any callable
    with its schema) — the ONE signal surface the controller steers on.
    """

    def __init__(
        self,
        config,
        *,
        clock: Callable[[], float],
        batch_hint: int,
        registry=None,
        tracer=None,
        prompt_cache=None,
    ):
        self.config = config
        self.tiers: Tuple[TierSpec, ...] = tuple(config.tiers)
        self.clock = clock
        self.batch_hint = max(1, int(batch_hint))
        self.tracer = tracer
        self.registry = registry
        self.prompt_cache = prompt_cache
        self._lock = sync.Lock()
        self._classes: Dict[str, _ClassState] = {}
        # cost-normalized per-batch service observations (ring): a batch
        # completing in t seconds at tier i contributes t / cost_i — the
        # full-tier-equivalent service time the predictions scale from
        self._service: List[float] = []
        self._service_sum = 0.0
        # step-granular calibration (step-level continuous batching,
        # serve/stepbatch.py): cost-normalized per-STEP service ring —
        # one cohort step completing in t at mean member cost c
        # contributes t / c.  Feeds both the EDF slack clock and the
        # step-mode occupancy prediction below.
        self._step_service: List[float] = []
        self._step_service_sum = 0.0
        self._dispatches = (registry.counter("serve_controller_dispatches")
                            if registry is not None else None)
        self._transitions = (
            registry.counter("serve_controller_transitions")
            if registry is not None else None)

    # -- shared state helpers -----------------------------------------------

    def _state(self, slo_class: str) -> _ClassState:
        with self._lock:
            st = self._classes.get(slo_class)
            if st is None:
                st = _ClassState(last_change=self.clock())
                self._classes[slo_class] = st
                if self.registry is not None:
                    self.registry.gauge(
                        "serve_controller_tier",
                        labels={"slo_class": slo_class},
                    ).set(0.0)
            return st

    def target(self, slo_class: str) -> float:
        slo = self.config.slo_p99_s
        return float(slo.get(slo_class, slo["default"]))

    def service_estimate(self) -> float:
        """Calibrated full-tier-equivalent per-batch service seconds
        (config.service_prior_s until completions arrive)."""
        with self._lock:
            if not self._service:
                return float(self.config.service_prior_s)
            return self._service_sum / len(self._service)

    def observe_batch(self, tier_idx: Optional[int], exec_s: float) -> None:
        """Record one completed batch's execute seconds, normalized by the
        tier it ran at (any thread — staged decode workers complete
        concurrently with the scheduler)."""
        if tier_idx is None:
            tier_idx = 0
        cost = self.tiers[min(int(tier_idx), len(self.tiers) - 1)].cost
        v = float(exec_s) / cost
        with self._lock:
            self._service.append(v)
            self._service_sum += v
            if len(self._service) > self.config.service_window:
                self._service_sum -= self._service.pop(0)

    def observe_step(self, mean_cost: float, step_s: float,
                     requests: int = 1,
                     dispatches: Optional[int] = None) -> None:
        """Record one cohort denoise step's wall seconds at the cohort's
        mean tier cost (scheduler thread; step-granular servers call this
        instead of per-batch observations — occupancy there is per-step,
        not per-batch).

        ``requests``/``dispatches`` normalize for packed dispatch
        (serve/executors.py step_run): a round that advances R requests
        in D compiled calls records the per-REQUEST service ``step_s x
        D/R``, so the step-granular occupancy model and EDF slack don't
        over-predict by exactly the pack factor.  Omitted (or equal, the
        sequential executors), the observation is the raw round time —
        the pre-pack behavior."""
        if dispatches is None:
            dispatches = requests
        v = (float(step_s) / max(float(mean_cost), 1e-9)
             * (float(dispatches) / max(float(requests), 1.0)))
        with self._lock:
            self._step_service.append(v)
            self._step_service_sum += v
            if len(self._step_service) > self.config.service_window:
                self._step_service_sum -= self._step_service.pop(0)

    def step_service_estimate(self) -> Optional[float]:
        """Calibrated full-tier-equivalent per-STEP service seconds, or
        None before any step completed (the step batcher then falls back
        to its own prior/EWMA)."""
        with self._lock:
            if not self._step_service:
                return None
            return self._step_service_sum / len(self._step_service)

    # -- the decision loop (scheduler thread) -------------------------------

    def _predicted(self, idx: int, s_full: float, load_batches: float) -> float:
        """Forward model: a request dispatched now at tier ``idx`` waits
        out the backlog and then its own batch — (1 + backlog-in-batches)
        batch services at the tier's cost."""
        return s_full * self.tiers[idx].cost * (1.0 + load_batches)

    def _effective_service(self) -> float:
        s = self.service_estimate()
        share = self.config.encode_share
        if share and self.prompt_cache is not None:
            s *= 1.0 - share * self.prompt_cache.hit_rate()
        return s

    def _step_predictor(self, snapshot: Dict[str, Any]):
        """Step-granular occupancy forward model (step-level continuous
        batching; the satellite accounting fix): a whole-batch server's
        request waits out (1 + backlog-in-batches) BATCH services, but a
        slot-pool request only waits its own steps plus the backlog's
        steps amortized over the slot width — predicting whole-batch
        completion there over-escalates tiers by roughly the pool width.
        Returns predicted(idx) over ``cost x per_step x (own_steps +
        backlog_steps / slots)``, or None when the snapshot carries no
        step block (whole-batch server)."""
        step = snapshot.get("step")
        if not step:
            return None
        calibrated = self.step_service_estimate()
        per_step = (calibrated if calibrated is not None
                    else float(step.get("per_step_s", 0.0))
                    or self.config.service_prior_s / self.batch_hint)
        own_steps = float(step.get("steps_hint", 1))
        backlog = (snapshot.get("queue_depth", 0) * own_steps
                   + float(step.get("remaining_steps_total", 0)))
        slots = max(1, int(step.get("slots", 1)))

        def predicted(idx: int) -> float:
            return (self.tiers[idx].cost * per_step
                    * (own_steps + backlog / slots))

        return predicted

    def poll(self, snapshot: Dict[str, Any]) -> None:
        """One decision tick over every known SLO class (scheduler
        thread): walk each class one rung toward the least-degraded tier
        whose predicted latency holds its target, under the hysteresis
        cooldowns.  ``snapshot`` is `slo_snapshot()` — when it carries a
        ``"step"`` occupancy block the step-granular forward model
        replaces the whole-batch one (see `_step_predictor`)."""
        now = self.clock()
        cfgc = self.config
        s_full = self._effective_service()
        load_batches = (
            snapshot.get("queue_depth", 0) + snapshot.get(
                "inflight_requests", 0)
        ) / self.batch_hint
        predicted = self._step_predictor(snapshot)
        if predicted is None:
            def predicted(idx: int) -> float:  # noqa: E306 — whole-batch
                return self._predicted(idx, s_full, load_batches)
        with self._lock:
            classes = set(self._classes)
        classes.update(snapshot.get("classes", {}))
        for cls in sorted(classes):
            st = self._state(cls)
            target = self.target(cls)
            # least-degraded tier whose prediction holds the target
            desired = len(self.tiers)
            for idx in range(len(self.tiers)):
                if predicted(idx) <= target:
                    desired = idx
                    break
            # measured breach forces at least one rung down: the forward
            # model may flatter a mesh whose real service is slower.
            # Only under live load — an idle server's window still holds
            # the burst's latencies (until slo_max_age_s ages them out),
            # and escalating on ghosts would wedge every class at
            # admission with nothing running.
            window = snapshot.get("classes", {}).get(cls, {})
            if (load_batches > 0
                    and window.get("window", 0) >= cfgc.min_samples
                    and window.get("p99", 0.0) > target):
                desired = max(desired, st.tier + 1)
            desired = min(desired, len(self.tiers))  # admission is the cap
            if desired > st.tier:
                if now - st.last_change >= cfgc.escalate_cooldown_s:
                    self._move(cls, st, st.tier + 1, now, "escalate")
            elif desired < st.tier:
                if (now - st.last_change >= cfgc.retract_cooldown_s
                        and predicted(min(st.tier - 1, len(self.tiers) - 1))
                        <= cfgc.retract_margin * target):
                    self._move(cls, st, st.tier - 1, now, "retract")

    def _tier_name(self, idx: int) -> str:
        return ADMISSION if idx >= len(self.tiers) else self.tiers[idx].name

    def _move(self, cls: str, st: _ClassState, to: int, now: float,
              kind: str) -> None:
        frm = st.tier
        st.tier = to
        st.last_change = now
        st.transitions += 1
        name = self._tier_name(to)
        if self._transitions is not None:
            self._transitions.inc(f"{kind}:{cls}:{name}")
        if self.registry is not None:
            self.registry.gauge(
                "serve_controller_tier", labels={"slo_class": cls}
            ).set(float(to))
        if self.tracer is not None:
            self.tracer.event(
                f"tier_{kind}", track="controller",
                args={"slo_class": cls, "from": self._tier_name(frm),
                      "to": name})

    # -- scheduler-side reads ------------------------------------------------

    def admit(self, slo_class: str) -> bool:
        """Admission control (any thread, submit path): False when the
        class currently sits past the last tier — even the cheapest
        program cannot hold its SLO, so the request is rejected with the
        typed 429 instead of queued into certain lateness."""
        return self._state(str(slo_class)).tier < len(self.tiers)

    def tier_for_batch(self, slo_classes: Sequence[str]) -> Tuple[int, TierSpec]:
        """The tier one coalesced batch dispatches at: the CHEAPEST tier
        any member class currently needs (a cheaper tier is faster for
        everyone in the batch; a richer one would blow the tight class's
        SLO).  Admission-parked classes clamp to the last real tier —
        their queued survivors still execute, as cheaply as possible."""
        idx = 0
        for cls in slo_classes:
            idx = max(idx, self._state(str(cls)).tier)
        idx = min(idx, len(self.tiers) - 1)
        return idx, self.tiers[idx]

    def count_dispatch(self, tier_idx: int, n_requests: int) -> None:
        if self._dispatches is not None:
            self._dispatches.inc(self.tiers[tier_idx].name, n_requests)

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON state for `metrics_snapshot()["controller"]`."""
        with self._lock:
            classes = {
                cls: {
                    "tier": st.tier,
                    "tier_name": self._tier_name(st.tier),
                    "transitions": st.transitions,
                }
                for cls, st in sorted(self._classes.items())
            }
        return {
            "tiers": [t.name for t in self.tiers] + [ADMISSION],
            "service_estimate_s": self.service_estimate(),
            # step-granular calibration (None until a step-mode server
            # observed its first cohort step)
            "step_service_estimate_s": self.step_service_estimate(),
            "classes": classes,
        }

"""Deterministic weightless fakes for the serve layer.

Everything the scheduler does — admission, bucketing, coalescing,
deadlines, cache hits/evictions, metrics — is independent of what the
executor computes, so tests, the ``--demo`` entry point, and
``scripts/serve_bench.py --dry-run`` all drive the real scheduler with
these fakes: no weights, no devices, milliseconds per "generation", and
outputs that are a pure function of (prompt, seed, bucket, steps) so any
reordering or cross-request mixup is detectable.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, List

import numpy as np

from ..utils import sync
from .cache import ExecKey


def fake_image(prompt: str, seed: int, key: ExecKey) -> np.ndarray:
    """Deterministic tiny "image" for (prompt, seed, bucket, steps): an
    8x8x3 float array seeded from a crc32 of the identifying tuple."""
    h = zlib.crc32(
        f"{prompt}|{seed}|{key.height}x{key.width}|{key.steps}|{key.cfg}"
        .encode()
    )
    rng = np.random.RandomState(h % (2**31))
    return rng.rand(8, 8, 3).astype(np.float32)


class FakeExecutor:
    """Serve-executor fake: optional simulated step time, call log.

    ``batch_sizes`` records the *real* (unpadded) size of every invocation
    — what tests assert coalescing against.

    The simulated service time honors the key's quality/cost knobs
    (`effective_service_s`) so controller-driven tiers are measurably
    cheaper on fakes, with the knob-free key costing EXACTLY
    ``step_time_s * steps`` as before: shallow cadence steps cost a 0.35
    FLOP fraction (the PR-2 measured ratio), wire compression and PCPP
    partial refresh model a comm-bound mesh with multiplicative discounts.
    Deterministic — the SLO-bench goodput numbers reproduce.
    """

    # cost-model constants, shared with the docs' tier-table discussion
    SHALLOW_FRACTION = 0.35
    COMPRESS_DISCOUNT = 0.85

    def __init__(self, key: ExecKey, batch_size: int = 8,
                 step_time_s: float = 0.0):
        self.key = key
        self.batch_size = batch_size
        self.step_time_s = step_time_s
        self.batch_sizes: List[int] = []
        # mirror PipelineExecutor's shallow-step accounting from the key's
        # cadence so fake-backed servers exercise the share metrics too
        from ..parallel.stepcache import shallow_step_count

        self.shallow_steps = shallow_step_count(
            key.steps, warmup_steps=0, interval=key.step_cache_interval
        )

    def effective_service_s(self) -> float:
        """Key-aware simulated batch service time (see class docstring)."""
        key = self.key
        full = key.steps - self.shallow_steps
        eff = full + self.SHALLOW_FRACTION * self.shallow_steps
        m = 1.0
        if key.comm_compress != "none":
            m *= self.COMPRESS_DISCOUNT
        if key.refresh_fraction < 1.0:
            # refresh bytes scale with the fraction; comm is a ~40% share
            # of the modeled stale step, so half the refresh ≈ 0.8x
            m *= 0.6 + 0.4 * key.refresh_fraction
        return self.step_time_s * eff * m

    def __call__(self, prompts: List[str], negative_prompts: List[str],
                 guidance_scale: float, seeds: List[int]) -> List[Any]:
        assert len(prompts) == len(negative_prompts) == len(seeds)
        self.batch_sizes.append(len(prompts))
        if self.step_time_s:
            # batched invocation costs one pass regardless of batch size —
            # the whole point of coalescing
            time.sleep(self.effective_service_s())
        return [fake_image(p, s, self.key) for p, s in zip(prompts, seeds)]


class FakeExecutorFactory:
    """Counts builds and keeps every built executor inspectable.

    ``build_delay_s`` simulates the compile cost a cache miss pays, so
    load-generator runs show the warm/cold latency split without XLA.
    The simulated compile honors the AOT-store contract the real runner
    follows (`utils/aot.py`): when a build runs inside a store
    activation, a persisted entry for the key skips the build delay
    entirely (the fake's "program" is its key string, round-tripped
    through the store's real envelope/faults/eviction machinery), and a
    miss pays the delay then persists — so warm-vs-cold replica-start
    benches measure the genuine store path without XLA.  ``aot_warmed``
    counts the builds a persisted entry made instant.
    """

    def __init__(self, batch_size: int = 8, build_delay_s: float = 0.0,
                 step_time_s: float = 0.0):
        self.batch_size = batch_size
        self.build_delay_s = build_delay_s
        self.step_time_s = step_time_s
        self.built: List[ExecKey] = []
        self.executors: List[FakeExecutor] = []
        self.aot_warmed = 0

    def _new_executor(self, key: ExecKey) -> FakeExecutor:
        """Construction hook: subclasses override THIS (not __call__) so
        the build-delay simulation and built/executors bookkeeping live
        in exactly one place."""
        return FakeExecutor(key, batch_size=self.batch_size,
                            step_time_s=self.step_time_s)

    def __call__(self, key: ExecKey) -> FakeExecutor:
        from ..utils.aot import active_aot_scope

        act = active_aot_scope()
        store = fp = None
        warmed = False
        if act is not None:
            store, scope = act
            fp = store.fingerprint(scope, mesh_shape="fake",
                                   layout="fake")
            payload = store.get(fp)
            if payload == f"fake-program:{key.short()}".encode():
                # a validated persisted entry stands in for the
                # deserialized executable: no simulated compile
                warmed = True
                self.aot_warmed += 1
        if self.build_delay_s and not warmed:
            time.sleep(self.build_delay_s)
        if store is not None and not warmed:
            store.put(fp, f"fake-program:{key.short()}".encode())
        self.built.append(key)
        ex = self._new_executor(key)
        self.executors.append(ex)
        return ex

    def batch_sizes(self) -> List[int]:
        """Every invocation's real batch size, across all executors."""
        return [n for ex in self.executors for n in ex.batch_sizes]


def fake_preview(prompt: str, seed: int, key: ExecKey,
                 step: int) -> np.ndarray:
    """Deterministic tiny preview for (prompt, seed, key, step): a 4x4x3
    float array — a pure function, so preview streams replay exactly."""
    h = zlib.crc32(
        f"preview|{prompt}|{seed}|{key.height}x{key.width}|{key.steps}|"
        f"{step}".encode()
    )
    rng = np.random.RandomState(h % (2**31))
    return rng.rand(4, 4, 3).astype(np.float32)


class StepFakeExecutor(FakeExecutor):
    """Serve-executor fake implementing the step-granular contract
    (serve/stepbatch.py) alongside the monolithic ``__call__``.

    One `step_run` call advances its whole cohort one denoise step and
    sleeps ONE key-aware step time (``effective_service_s() / steps``)
    regardless of cohort size — a batched step costs one pass, the same
    coalescing premise `FakeExecutor.__call__` models for whole batches.
    That is what makes continuous mode measurably request-shaped on the
    fakes: a joiner rides the next cohort step instead of waiting out a
    whole batch.  The real `PipelineExecutor.step_run` now matches this
    cost model: same-signature cohort members pack into ONE compiled
    dispatch (parallel/rowpack.py), so fake-measured ratios track the
    real executor's dispatch shape.  Outputs are `fake_image` either
    way, so solo, joined, preempted-and-resumed, and monolithic runs are
    byte-identical by construction — the scheduler behavior is what the
    tests interrogate.

    ``step_calls`` records every cohort step's size; ``park_calls`` /
    ``resume_calls`` count the preemption hand-offs; ``step_pack_stats``
    mirrors the real executor's pack-efficiency tallies (the whole fake
    cohort is one "dispatch"), so the server's stepbatch_* counters and
    fill gauge exercise on fakes.
    """

    def __init__(self, key: ExecKey, batch_size: int = 8,
                 step_time_s: float = 0.0):
        super().__init__(key, batch_size=batch_size,
                         step_time_s=step_time_s)
        self.step_calls: List[int] = []
        self.park_calls = 0
        self.resume_calls = 0
        self.step_pack_stats = {"dispatches": 0, "packed_rows": 0,
                                "rows_capacity": 0}

    def step_signature(self, work: dict):
        """Every fake work at the same step count packs together — the
        fake's cohort step IS one dispatch (`StepBatcher.cohort`'s
        pack_align source)."""
        return (id(self), self.key.steps)

    def step_time_per_step_s(self) -> float:
        return (self.effective_service_s() / self.key.steps
                if self.key.steps else 0.0)

    def step_begin(self, prompt: str, negative_prompt: str, seed: int,
                   guidance_scale: float) -> dict:
        return {"prompt": prompt, "seed": int(seed), "i": 0}

    def step_run(self, works: List[dict]) -> None:
        self.step_calls.append(len(works))
        self.step_pack_stats = {"dispatches": 1,
                                "packed_rows": len(works),
                                "rows_capacity": max(self.batch_size,
                                                     len(works))}
        if self.step_time_s:
            time.sleep(self.step_time_per_step_s())
        for w in works:
            w["i"] += 1

    def step_done(self, work: dict) -> bool:
        return work["i"] >= self.key.steps

    def step_finish(self, work: dict):
        return fake_image(work["prompt"], work["seed"], self.key)

    def step_abort(self, work: dict) -> None:
        pass  # no device buffers to release

    def step_park(self, work: dict) -> None:
        self.park_calls += 1

    def step_resume(self, work: dict) -> None:
        self.resume_calls += 1

    def step_preview(self, work: dict, max_size: int = 64) -> np.ndarray:
        return fake_preview(work["prompt"], work["seed"], self.key,
                            work["i"])

    # -- carry migration (serve/migration.py) ------------------------------
    #
    # The fake's "carry" is its step index plus the (prompt, seed)
    # identity, exported as one int32 leaf so the envelope's leaf
    # machinery (shape/dtype descriptors, checksum over raw bytes) is
    # exercised end to end even on fakes.

    def step_export(self, work: dict):
        extra = {"family": type(self).__name__, "step": int(work["i"])}
        return extra, [np.asarray([work["i"]], dtype=np.int32)]

    def step_import(self, meta: dict, leaves, prompt: str,
                    negative_prompt: str, seed: int,
                    guidance_scale: float) -> dict:
        from .errors import MigrationRejectedError

        family = type(self).__name__
        if meta.get("family") != family:
            raise MigrationRejectedError(
                f"carry snapshot family {meta.get('family')!r} cannot "
                f"import into a {family} executor"
            )
        step = int(meta["step"])
        if not (0 <= step <= self.key.steps):
            raise MigrationRejectedError(
                f"carry snapshot step {step} out of range for a "
                f"{self.key.steps}-step executor"
            )
        if (len(leaves) != 1 or tuple(leaves[0].shape) != (1,)
                or leaves[0].dtype != np.int32
                or int(leaves[0][0]) != step):
            raise MigrationRejectedError(
                "carry snapshot leaves do not match the fake step "
                "executor's carry structure"
            )
        return {"prompt": prompt, "seed": int(seed), "i": step}


class StepFakeExecutorFactory(FakeExecutorFactory):
    """FakeExecutorFactory building step-granular fakes."""

    def _new_executor(self, key: ExecKey) -> StepFakeExecutor:
        return StepFakeExecutor(key, batch_size=self.batch_size,
                                step_time_s=self.step_time_s)

    def step_calls(self) -> List[int]:
        """Every cohort step's size, across all executors."""
        return [n for ex in self.executors
                for n in getattr(ex, "step_calls", ())]


class ExecutionLedger:
    """Fleet-wide completed-execution counter keyed by (prompt, seed).

    The fleet failover invariant — a request is re-dispatched only after
    its prior replica's outcome is terminal, so a dispatch that failed
    before completing never runs twice — is asserted by sharing one
    ledger across every replica's `LedgerFakeExecutorFactory`: each
    successful executor return records its requests, and
    ``max_count() <= 1`` proves no double execution (a dispatch
    killed/failed before returning never records).  Caveat: a
    watchdog-ABANDONED dispatch (``hang`` faults) may still finish in
    the background and record — its result is discarded by the watchdog,
    but the ledger honestly counts the physical execution, so assert
    ``max_count() == 1`` only under fault kinds that fail before
    completion (kill / errors / oom).  Thread-safe: replicas execute
    concurrently."""

    def __init__(self):

        self._lock = sync.Lock()
        self._counts: dict = {}
        self._steps: dict = {}

    def record(self, prompt: str, seed: int, replica: str = "") -> None:
        with self._lock:
            key = (prompt, int(seed))
            entry = self._counts.setdefault(key, [])
            entry.append(replica)

    def count(self, prompt: str, seed: int) -> int:
        with self._lock:
            return len(self._counts.get((prompt, int(seed)), []))

    def max_count(self) -> int:
        with self._lock:
            return max((len(v) for v in self._counts.values()), default=0)

    def snapshot(self) -> dict:
        """{(prompt, seed): [replica, ...]} of completed executions."""
        with self._lock:
            return {k: list(v) for k, v in self._counts.items()}

    # -- step-granular records (carry migration) ---------------------------
    #
    # The migration invariant is STEP-scoped: a salvaged step is never
    # re-executed, so across the whole fleet every (request, step index)
    # pair runs exactly once.  `StepLedgerFakeExecutor` records each
    # completed denoise step here; ``max_step_count() <= 1`` proves zero
    # double-executed steps the same way ``max_count()`` proves it for
    # whole requests.

    def record_step(self, prompt: str, seed: int, step: int,
                    replica: str = "") -> None:
        with self._lock:
            per_req = self._steps.setdefault((prompt, int(seed)), {})
            per_req.setdefault(int(step), []).append(replica)

    def step_counts(self, prompt: str, seed: int) -> dict:
        """{step_index: [replica, ...]} of one request's executed steps."""
        with self._lock:
            return {i: list(v) for i, v in
                    self._steps.get((prompt, int(seed)), {}).items()}

    def max_step_count(self) -> int:
        """Max executions of any single (request, step) pair — the
        exactly-once gate asserts this == 1 (0 with no steps)."""
        with self._lock:
            return max(
                (len(v) for per in self._steps.values()
                 for v in per.values()),
                default=0,
            )

    def steps_snapshot(self) -> dict:
        """{(prompt, seed): {step_index: [replica, ...]}}."""
        with self._lock:
            return {k: {i: list(v) for i, v in per.items()}
                    for k, per in self._steps.items()}


class LedgerFakeExecutor(FakeExecutor):
    """`FakeExecutor` recording every COMPLETED execution in a shared
    `ExecutionLedger` (faults injected before/at the call never record —
    exactly the semantics of work that died before producing output)."""

    def __init__(self, key: ExecKey, ledger: ExecutionLedger,
                 replica: str = "", batch_size: int = 8,
                 step_time_s: float = 0.0):
        super().__init__(key, batch_size=batch_size, step_time_s=step_time_s)
        self.ledger = ledger
        self.replica = replica

    def __call__(self, prompts: List[str], negative_prompts: List[str],
                 guidance_scale: float, seeds: List[int]) -> List[Any]:
        out = super().__call__(prompts, negative_prompts, guidance_scale,
                               seeds)
        for p, s in zip(prompts, seeds):
            self.ledger.record(p, s, self.replica)
        return out


class LedgerFakeExecutorFactory(FakeExecutorFactory):
    """Per-replica factory building `LedgerFakeExecutor`s against one
    shared ledger; ``replica`` tags which replica executed what."""

    def __init__(self, ledger: ExecutionLedger, replica: str = "",
                 batch_size: int = 8, build_delay_s: float = 0.0,
                 step_time_s: float = 0.0):
        super().__init__(batch_size=batch_size, build_delay_s=build_delay_s,
                         step_time_s=step_time_s)
        self.ledger = ledger
        self.replica = replica

    def _new_executor(self, key: ExecKey) -> LedgerFakeExecutor:
        return LedgerFakeExecutor(key, self.ledger, replica=self.replica,
                                  batch_size=self.batch_size,
                                  step_time_s=self.step_time_s)


class StepLedgerFakeExecutor(StepFakeExecutor):
    """`StepFakeExecutor` recording every COMPLETED denoise step (and
    every completed request) in a shared `ExecutionLedger` — the
    step-granular evidence behind the carry-migration exactly-once gate:
    replica A records steps 0..k-1, the kill fires before step k
    records, and the importing replica B records k..N-1, so
    ``max_step_count() == 1`` proves salvaged steps never re-ran."""

    def __init__(self, key: ExecKey, ledger: ExecutionLedger,
                 replica: str = "", batch_size: int = 8,
                 step_time_s: float = 0.0):
        super().__init__(key, batch_size=batch_size,
                         step_time_s=step_time_s)
        self.ledger = ledger
        self.replica = replica

    def step_run(self, works: List[dict]) -> None:
        pending = [(w["prompt"], w["seed"], w["i"]) for w in works]
        super().step_run(works)
        # record AFTER the step completed — a step killed mid-dispatch
        # never records, exactly like work that died before output
        for prompt, seed, step in pending:
            self.ledger.record_step(prompt, seed, step, self.replica)

    def step_finish(self, work: dict):
        image = super().step_finish(work)
        self.ledger.record(work["prompt"], work["seed"], self.replica)
        return image


class StepLedgerFakeExecutorFactory(FakeExecutorFactory):
    """Per-replica factory building `StepLedgerFakeExecutor`s against
    one shared ledger; ``replica`` tags which replica executed what."""

    def __init__(self, ledger: ExecutionLedger, replica: str = "",
                 batch_size: int = 8, build_delay_s: float = 0.0,
                 step_time_s: float = 0.0):
        super().__init__(batch_size=batch_size, build_delay_s=build_delay_s,
                         step_time_s=step_time_s)
        self.ledger = ledger
        self.replica = replica

    def _new_executor(self, key: ExecKey) -> StepLedgerFakeExecutor:
        return StepLedgerFakeExecutor(
            key, self.ledger, replica=self.replica,
            batch_size=self.batch_size, step_time_s=self.step_time_s)

    def step_calls(self) -> List[int]:
        """Every cohort step's size, across all executors."""
        return [n for ex in self.executors
                for n in getattr(ex, "step_calls", ())]


class StageTracker:
    """Concurrent-residency probe shared by staged fakes: counts batches
    between encode-stage entry and decode-stage exit (the window in which
    a real batch holds device buffers) and records the peak — what tests
    assert the ``max_inflight_batches`` HBM cap against, independently of
    the pipeline's own semaphore accounting."""

    def __init__(self):

        self._lock = sync.Lock()
        self.current = 0
        self.peak = 0

    def enter(self) -> None:
        with self._lock:
            self.current += 1
            self.peak = max(self.peak, self.current)

    def exit(self) -> None:
        with self._lock:
            self.current -= 1


class StagedFakeExecutor(FakeExecutor):
    """Serve-executor fake implementing the three-stage contract
    (serve/staging.py) alongside the monolithic ``__call__``.

    Per-stage simulated times make overlap measurable without XLA:
    sleeping stages do not compete for CPU, so a pipelined run's
    steady-state throughput approaches 1/max(stage) while the monolithic
    run pays 1/sum(stage) — the scheduler behavior under test, isolated
    from compute.  ``denoise_s`` defaults to ``step_time_s * steps`` so
    the monolithic path (which sleeps exactly that in ``__call__``) costs
    the same mesh time as the staged path.  Outputs are `fake_image`
    either way: staged and monolithic dispatch are bit-identical.

    ``fail_stage``/``fail_times`` inject ``fail_exc`` (default RuntimeError)
    into the first N invocations of one stage; ``stage_calls`` counts every
    stage entry for assertions.
    """

    def __init__(self, key: ExecKey, batch_size: int = 8,
                 step_time_s: float = 0.0, encode_s: float = 0.0,
                 denoise_s: float = None, decode_s: float = 0.0,
                 tracker: StageTracker = None, fail_stage: str = None,
                 fail_times: int = 0, fail_exc: Exception = None):
        super().__init__(key, batch_size=batch_size, step_time_s=step_time_s)
        self.encode_s = encode_s
        self.denoise_s = (step_time_s * key.steps if denoise_s is None
                          else denoise_s)
        self.decode_s = decode_s
        self.tracker = tracker
        self.fail_stage = fail_stage
        self.fail_times = fail_times
        self.fail_exc = fail_exc
        self.stage_calls = {"encode": 0, "denoise": 0, "decode": 0}
        # serve/promptcache.py contract (the server attaches its cache to
        # any executor exposing attach_prompt_cache): a hit skips the
        # simulated encode sleep, mirroring the real executor's skipped
        # tokenize + text-encode
        self.prompt_cache = None

    def attach_prompt_cache(self, cache):
        self.prompt_cache = cache
        return cache

    def _stage(self, name: str, sleep_s: float) -> None:
        self.stage_calls[name] += 1
        if self.fail_stage == name and self.fail_times > 0:
            self.fail_times -= 1
            if self.tracker is not None:
                # a failed batch leaves the pipeline here: balance the
                # encode-entry so residency probes stay correct under
                # fault injection.  (Batches DROPPED between stages —
                # cancel/stop — never re-enter the executor, so the
                # tracker is only meaningful for runs without drops.)
                self.tracker.exit()
            raise (self.fail_exc if self.fail_exc is not None
                   else RuntimeError(f"injected {name} stage failure"))
        if sleep_s:
            time.sleep(sleep_s)

    def __call__(self, prompts: List[str], negative_prompts: List[str],
                 guidance_scale: float, seeds: List[int]) -> List[Any]:
        # the monolithic dispatch runs every stage serially: its simulated
        # cost is the SUM of the stage times, so staged-vs-monolithic
        # benchmark ratios measure real overlap, not a handicapped baseline
        assert len(prompts) == len(negative_prompts) == len(seeds)
        self.batch_sizes.append(len(prompts))
        total = self.encode_s + self.denoise_s + self.decode_s
        if total:
            time.sleep(total)
        return [fake_image(p, s, self.key) for p, s in zip(prompts, seeds)]

    def encode_stage(self, prompts: List[str], negative_prompts: List[str],
                     seeds: List[int]):
        if self.tracker is not None:
            self.tracker.enter()
        if self.prompt_cache is not None:
            key = (("fake", self.key.model_id), tuple(prompts),
                   tuple(negative_prompts))
            self.prompt_cache.get_or_encode(
                key, lambda: self._stage("encode", self.encode_s) or True)
        else:
            self._stage("encode", self.encode_s)
        return {"prompts": list(prompts), "seeds": list(seeds)}

    def denoise_stage(self, work, guidance_scale: float):
        self._stage("denoise", self.denoise_s)
        return work

    def decode_stage(self, work) -> List[Any]:
        self._stage("decode", self.decode_s)
        out = [fake_image(p, s, self.key)
               for p, s in zip(work["prompts"], work["seeds"])]
        if self.tracker is not None:
            self.tracker.exit()
        return out


class StagedFakeExecutorFactory(FakeExecutorFactory):
    """FakeExecutorFactory building staged fakes; one shared `StageTracker`
    across every executor measures whole-service residency."""

    def __init__(self, batch_size: int = 8, build_delay_s: float = 0.0,
                 step_time_s: float = 0.0, encode_s: float = 0.0,
                 denoise_s: float = None, decode_s: float = 0.0,
                 fail_stage: str = None, fail_times: int = 0,
                 fail_exc: Exception = None):
        super().__init__(batch_size=batch_size, build_delay_s=build_delay_s,
                         step_time_s=step_time_s)
        self.encode_s = encode_s
        self.denoise_s = denoise_s
        self.decode_s = decode_s
        self.fail_stage = fail_stage
        self.fail_times = fail_times
        self.fail_exc = fail_exc
        self.tracker = StageTracker()

    def _new_executor(self, key: ExecKey) -> StagedFakeExecutor:
        return StagedFakeExecutor(
            key, batch_size=self.batch_size, step_time_s=self.step_time_s,
            encode_s=self.encode_s, denoise_s=self.denoise_s,
            decode_s=self.decode_s, tracker=self.tracker,
            fail_stage=self.fail_stage, fail_times=self.fail_times,
            fail_exc=self.fail_exc,
        )

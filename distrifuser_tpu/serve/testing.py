"""Deterministic weightless fakes for the serve layer.

Everything the scheduler does — admission, bucketing, coalescing,
deadlines, cache hits/evictions, metrics — is independent of what the
executor computes, so tests, the ``--demo`` entry point, and
``scripts/serve_bench.py --dry-run`` all drive the real scheduler with
these fakes: no weights, no devices, milliseconds per "generation", and
outputs that are a pure function of (prompt, seed, bucket, steps) so any
reordering or cross-request mixup is detectable.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, List

import numpy as np

from .cache import ExecKey


def fake_image(prompt: str, seed: int, key: ExecKey) -> np.ndarray:
    """Deterministic tiny "image" for (prompt, seed, bucket, steps): an
    8x8x3 float array seeded from a crc32 of the identifying tuple."""
    h = zlib.crc32(
        f"{prompt}|{seed}|{key.height}x{key.width}|{key.steps}|{key.cfg}"
        .encode()
    )
    rng = np.random.RandomState(h % (2**31))
    return rng.rand(8, 8, 3).astype(np.float32)


class FakeExecutor:
    """Serve-executor fake: optional simulated step time, call log.

    ``batch_sizes`` records the *real* (unpadded) size of every invocation
    — what tests assert coalescing against.
    """

    def __init__(self, key: ExecKey, batch_size: int = 8,
                 step_time_s: float = 0.0):
        self.key = key
        self.batch_size = batch_size
        self.step_time_s = step_time_s
        self.batch_sizes: List[int] = []
        # mirror PipelineExecutor's shallow-step accounting from the key's
        # cadence so fake-backed servers exercise the share metrics too
        from ..parallel.stepcache import shallow_step_count

        self.shallow_steps = shallow_step_count(
            key.steps, warmup_steps=0, interval=key.step_cache_interval
        )

    def __call__(self, prompts: List[str], negative_prompts: List[str],
                 guidance_scale: float, seeds: List[int]) -> List[Any]:
        assert len(prompts) == len(negative_prompts) == len(seeds)
        self.batch_sizes.append(len(prompts))
        if self.step_time_s:
            # batched invocation costs one pass regardless of batch size —
            # the whole point of coalescing
            time.sleep(self.step_time_s * self.key.steps)
        return [fake_image(p, s, self.key) for p, s in zip(prompts, seeds)]


class FakeExecutorFactory:
    """Counts builds and keeps every built executor inspectable.

    ``build_delay_s`` simulates the compile cost a cache miss pays, so
    load-generator runs show the warm/cold latency split without XLA.
    """

    def __init__(self, batch_size: int = 8, build_delay_s: float = 0.0,
                 step_time_s: float = 0.0):
        self.batch_size = batch_size
        self.build_delay_s = build_delay_s
        self.step_time_s = step_time_s
        self.built: List[ExecKey] = []
        self.executors: List[FakeExecutor] = []

    def __call__(self, key: ExecKey) -> FakeExecutor:
        if self.build_delay_s:
            time.sleep(self.build_delay_s)
        self.built.append(key)
        ex = FakeExecutor(key, batch_size=self.batch_size,
                          step_time_s=self.step_time_s)
        self.executors.append(ex)
        return ex

    def batch_sizes(self) -> List[int]:
        """Every invocation's real batch size, across all executors."""
        return [n for ex in self.executors for n in ex.batch_sizes]

"""Seedable, deterministic fault injection for the serve + runner stack.

Real deployments see failed compiles, transient execute errors, hung
devices, and OOMs (preempted/slow devices are the premise of STADI,
arXiv 2509.04719); nothing in a clean CPU test run does.  A `FaultPlan`
makes those events *reproducible*: named injection sites consult the plan,
and each matching `FaultRule` decides — from its own seeded RNG stream —
whether to raise, sleep, or pass.  The same plan + the same call sequence
at a site fires the same faults, so every resilience behavior (retry,
circuit breaking, watchdog, degradation ladder) is testable on the 2-core
CPU runner.

Injection sites (the convention — sites are plain strings):

* ``"build"`` — `InferenceServer` around `executor_factory(key)` (covers
  fake and real factories alike);
* ``"execute"`` — `InferenceServer` inside the watchdog-wrapped batched
  dispatch (so a ``hang`` here is what the watchdog exists to bound);
* ``"executor.build"`` / ``"executor.execute"`` — `pipeline_executor_factory`
  / `PipelineExecutor.__call__` for direct (server-less) executor use;
* ``"runner.compile"`` — `DenoiseRunner.compiled_handle` before building a
  fused-loop program (reads the process-global plan, see
  `install_fault_plan`, because the runner has no serve-layer plumbing);
* ``"replica"`` — `serve.replica.Replica` at the top of every monolithic
  executor dispatch AND every step-granular cohort step (``step_run``).
  The site's key stringifies to the REPLICA NAME, so ``key_substr``
  targets a named replica; combined with ``after_calls`` a rule kills /
  hangs / degrades that replica deterministically mid-load — under step
  batching, after an exact number of denoise steps (fleet failover and
  carry migration are what the site exists to exercise);
* ``"migrate.export"`` / ``"migrate.import"`` — the carry-migration wire
  (serve/migration.py): MUTATION sites consulted through
  `FaultPlan.mutate` on the encoded snapshot bytes as they leave the
  dying replica / arrive at the adopting one.  Only the
  ``snapshot_truncate`` / ``snapshot_corrupt`` kinds apply here;
* ``"aotcache.save"`` / ``"aotcache.load"`` — the persistent AOT
  executable store's disk wire (serve/aotcache.py): MUTATION sites on
  the encoded envelope bytes on their way to disk / read back, keyed by
  the entry's scope.  Same two mutation kinds; every mangling must be
  caught by the store's checksum/envelope validation
  (`AotCacheRejectedError`) and fall back to a fresh compile — never a
  wrong program.

Fault kinds:

* ``compile_error`` — raises `InjectedCompileError`;
* ``execute_error`` — raises `InjectedExecuteError`;
* ``oom`` — raises `InjectedResourceExhausted`, whose message is
  RESOURCE_EXHAUSTED-shaped so `errors.is_oom` (and any code matching real
  jaxlib OOMs) classifies it identically;
* ``hang`` — sleeps ``hang_s`` then returns normally, modelling a stalled
  device that eventually recovers.  Under a watchdog the call is abandoned
  at the timeout; the sleeping thread finishes in the background and its
  result is discarded;
* ``kill`` — raises `InjectedReplicaKilled`, modelling a replica process
  dying mid-dispatch.  Only meaningful at the ``"replica"`` site: the
  `Replica` catches it in its executor wrapper, transitions to STOPPED,
  SYNCHRONOUSLY signals the server's shutdown (queued futures fail with
  `ServerClosedError`; the blocking scheduler join runs in the
  background), and re-raises so the in-flight batch fails terminally —
  the fleet router then fails the whole replica's load over;
* ``snapshot_truncate`` — `FaultPlan.mutate` cuts the snapshot bytes in
  half, modelling a connection dropped mid-transfer;
* ``snapshot_corrupt`` — `FaultPlan.mutate` flips one byte at a
  deterministic offset, modelling silent wire/storage corruption.  Both
  mutation kinds must be caught by the importer's checksum/envelope
  validation (`MigrationRejectedError`) — never by a wrong image.

Only the ``execute`` sites run under the watchdog.  A ``hang`` injected
at a build/compile site blocks its caller for the full ``hang_s`` —
which is the faithful simulation: executor builds are synchronous in the
scheduler thread (a slow compile service stalls admission-to-dispatch
exactly like this), and the watchdog deliberately does not bound them
because legitimate cold compiles take minutes.  Size ``hang_s``
accordingly when targeting a build site.
"""

from __future__ import annotations

import dataclasses
import random
import time
import zlib
from typing import Dict, Optional, Sequence, Tuple

from ..utils import sync

FAULT_KINDS = ("compile_error", "execute_error", "oom", "hang", "kill",
               "snapshot_truncate", "snapshot_corrupt")

# Data-mutation kinds: they never raise at a ``check`` site — they
# corrupt bytes passing through a ``mutate`` site (the carry-migration
# wire, serve/migration.py), and the RECEIVER's typed validation is what
# the chaos run interrogates.
MUTATE_KINDS = ("snapshot_truncate", "snapshot_corrupt")


class InjectedFault(Exception):
    """Marker base for injected faults (mixed into concrete kinds) so
    tests and metrics can tell injected failures from organic ones."""


class InjectedCompileError(RuntimeError, InjectedFault):
    pass


class InjectedExecuteError(RuntimeError, InjectedFault):
    pass


class InjectedResourceExhausted(RuntimeError, InjectedFault):
    """Message deliberately RESOURCE_EXHAUSTED-shaped (jaxlib's OOM
    surface) so OOM classification has one code path for injected and
    real faults."""


class InjectedReplicaKilled(RuntimeError, InjectedFault):
    """The ``kill`` kind at the ``"replica"`` site: the replica process
    "died" — its in-flight dispatch fails with this, and the `Replica`
    handle shuts its server down (see serve/replica.py)."""


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One injection rule: WHERE (site + filters), WHAT (kind), WHEN
    (probability per call, or exact 0-based call indices at the site).

    Filters are checked before the rule's RNG is consulted, so a rule's
    random stream advances only on calls it could have fired on — the
    firing pattern is a pure function of (seed, the site's filtered call
    sequence).
    """

    site: str
    kind: str
    p: float = 0.0  # per-eligible-call probability
    at_calls: Tuple[int, ...] = ()  # exact site-call indices (0-based)
    after_calls: int = 0  # eligible only once the site saw >= this many calls
    min_batch: int = 0  # only fire when batch_size >= min_batch
    key_substr: str = ""  # only fire when ExecKey.short() contains this
    max_fires: int = -1  # -1 = unbounded
    hang_s: float = 10.0  # sleep length for kind == "hang"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"fault kind must be one of {FAULT_KINDS}, got {self.kind!r}"
            )
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"fault probability must be in [0, 1], got {self.p}")
        if self.p == 0.0 and not self.at_calls:
            raise ValueError(
                f"rule {self.site}/{self.kind}: give a probability p > 0 or "
                "explicit at_calls indices — a rule that can never fire is a "
                "misconfigured plan, not a no-op"
            )
        if self.after_calls < 0:
            raise ValueError(
                f"after_calls must be >= 0, got {self.after_calls}"
            )


def _raise_fault(rule: FaultRule, site: str) -> None:
    msg = f"injected {rule.kind} at site {site!r}"
    if rule.kind == "compile_error":
        raise InjectedCompileError(msg)
    if rule.kind == "execute_error":
        raise InjectedExecuteError(msg)
    if rule.kind == "oom":
        raise InjectedResourceExhausted(
            f"RESOURCE_EXHAUSTED: {msg} (simulated out-of-memory while "
            "allocating device buffers)"
        )
    if rule.kind == "kill":
        raise InjectedReplicaKilled(msg)
    raise AssertionError(rule.kind)  # hang handled by the caller


class FaultPlan:
    """A seeded set of `FaultRule`s plus per-site call counters.

    ``check(site, key=..., batch_size=...)`` is the single entry point a
    site calls; it either returns (no fault), sleeps then returns
    (``hang``), or raises the injected exception.  At most one rule fires
    per call (first matching rule in declaration order wins).

    Thread-safe: the scheduler thread and watchdog worker threads consult
    the same plan.  ``fired()`` snapshots ``{(site, kind): count}`` so
    benches can report exactly what chaos was applied.
    """

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0):
        self.rules = tuple(rules)
        self.seed = int(seed)
        self._lock = sync.Lock()
        self._site_calls: Dict[str, int] = {}
        self._fires: Dict[Tuple[str, str], int] = {}
        self._rule_fires = [0] * len(self.rules)
        # one independent deterministic stream per rule: interleaving of
        # *different* sites can never perturb a rule's pattern
        self._rngs = [
            random.Random(
                zlib.crc32(f"{self.seed}|{i}|{r.site}|{r.kind}".encode())
            )
            for i, r in enumerate(self.rules)
        ]

    # -- internals ----------------------------------------------------------

    def _eligible(self, rule: FaultRule, key, batch_size: Optional[int]) -> bool:
        if rule.min_batch and (batch_size is None or batch_size < rule.min_batch):
            return False
        if rule.key_substr:
            short = key.short() if hasattr(key, "short") else str(key)
            if key is None or rule.key_substr not in short:
                return False
        return True

    def _pick(self, site: str, key, batch_size: Optional[int],
              mutate: bool = False) -> Optional[FaultRule]:
        with self._lock:
            call_idx = self._site_calls.get(site, 0)
            self._site_calls[site] = call_idx + 1
            for i, rule in enumerate(self.rules):
                if rule.site != site:
                    continue
                if (rule.kind in MUTATE_KINDS) != mutate:
                    # raise-kinds fire from check(), mutate-kinds from
                    # mutate() — a rule can never cross the two APIs
                    continue
                if call_idx < rule.after_calls:
                    # index-gated like at_calls: the rule's RNG stream
                    # does not advance on calls before its window opens
                    continue
                if not self._eligible(rule, key, batch_size):
                    continue
                if 0 <= rule.max_fires <= self._rule_fires[i]:
                    continue
                fire = call_idx in rule.at_calls
                if not fire and rule.p > 0.0:
                    fire = self._rngs[i].random() < rule.p
                if fire:
                    self._rule_fires[i] += 1
                    k = (site, rule.kind)
                    self._fires[k] = self._fires.get(k, 0) + 1
                    return rule
            return None

    # -- the site API -------------------------------------------------------

    def check(self, site: str, key=None, batch_size: Optional[int] = None) -> None:
        """Consult the plan at ``site``; raise/sleep if a rule fires."""
        rule = self._pick(site, key, batch_size)
        if rule is None:
            return
        if rule.kind == "hang":
            time.sleep(rule.hang_s)
            return
        _raise_fault(rule, site)

    def mutate(self, site: str, data: bytes, key=None) -> bytes:
        """Consult the plan at a MUTATION ``site``: returns ``data``
        unchanged (no rule fired) or a deterministically corrupted copy
        (``snapshot_truncate`` halves it; ``snapshot_corrupt`` flips one
        mid-payload byte).  Never raises — the corruption's *detection*
        belongs to the receiver's validation, which is the code path
        under test."""
        rule = self._pick(site, key, None, mutate=True)
        if rule is None or not data:
            return data
        if rule.kind == "snapshot_truncate":
            return data[: len(data) // 2]
        # snapshot_corrupt: one flipped byte, deterministic position
        pos = len(data) // 2
        corrupted = bytearray(data)
        corrupted[pos] ^= 0xFF
        return bytes(corrupted)

    # -- observability ------------------------------------------------------

    def fired(self) -> Dict[str, int]:
        """``{"site/kind": count}`` of every fault fired so far."""
        with self._lock:
            return {f"{s}/{k}": n for (s, k), n in sorted(self._fires.items())}

    def site_calls(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._site_calls.items()))


# The process-global plan — the hook for sites with no serve-layer
# plumbing (DenoiseRunner.compiled_handle) — lives in the stdlib-only
# leaf utils/chaos.py so the parallel layer can consult it WITHOUT
# importing this package; re-exported here so chaos tools keep one
# import surface.  Chaos tools install a plan for a run and clear it
# after; production code never sets it.
from ..utils.chaos import (  # noqa: E402, F401  (re-exports)
    active_fault_plan,
    clear_fault_plan,
    install_fault_plan,
)

"""Staged serving pipeline: overlap text-encode, denoise, and VAE-decode
across micro-batches.

DistriFusion's whole thesis is hiding latency by overlapping work — the
paper overlaps stale-activation communication with compute inside one
step; this module applies the same displacement argument one level up,
across the *stages* of the request path.  The monolithic dispatch runs
text-encode, the N-step denoise, VAE decode, and the device->host copy
serially on one thread, so the denoiser mesh idles through every encode,
decode, and transfer.  Here three stage workers connected by hand-off
queues form a software pipeline over coalesced batches:

    encode worker  : tokenize + text-encode + draw the seeded latents
    denoise worker : the compiled denoise-loop program (the mesh)
    decode worker  : chunked VAE decode + host conversion + future
                     resolution

While batch k denoises, batch k+1 encodes and batch k-1 decodes — the
steady-state throughput ceiling moves from 1/sum(stage times) to
1/max(stage times), with the denoise stage the bottleneck resource by
construction.  (PipeFusion, arXiv 2405.14430, pipelines *within* the
denoiser across devices; STADI, arXiv 2509.04719, schedules step/patch
work across heterogeneous compute — this is the same argument applied to
the request path.)

Invariants:

* **HBM cap** — at most ``max_inflight_batches`` batches hold device
  buffers at once, enforced by a semaphore acquired at submission and
  released when the batch leaves the pipeline by ANY path (success,
  failure, cancel, stop).  Submission blocks the scheduler thread while
  the pipeline is full — backpressure that deepens the request queue and
  widens the next coalesced batch rather than growing residency.
* **Stage isolation** — each stage invocation runs under its own
  watchdog (`ResilienceConfig.watchdog_timeout_s`); a hung stage fails
  its batch, never the workers.  Executors are *pinned* in the
  `ExecutorCache` for the batch's whole trip, so LRU eviction or
  `invalidate` can never free a program a stage worker is about to run.
* **One terminal failure** — a failure in any stage fails the whole
  batch once (typed, serve/errors.py) and surfaces to the scheduler
  thread through `drain_outcomes()` as ONE terminal dispatch failure for
  the circuit breaker; there is no intra-stage retry loop (the
  resilience layer's sticky degradations — including forcing staging off
  via the ``staging_off`` rung — handle repeat offenders).
* **Cancel/deadline propagation** — a batch whose every future was
  cancelled is dropped at the next stage boundary; a batch whose every
  request deadline lapsed before its denoise stage begins is failed with
  `DeadlineExceededError` instead of burning mesh time (deadlines gate
  scheduling — and the denoise dispatch is a scheduling point — but
  never abandon mesh work already started).
* **Deterministic stop** — `stop()` drains every stage queue: batches
  not yet through decode fail with `ServerClosedError`, the stage
  invocation in progress is allowed to finish (bounded by its watchdog),
  and every submitted future is resolved before `stop()` returns.

Observability: per-stage queue-wait and service-time histograms plus the
**denoise-gap fraction** (`utils.metrics.GapTracker`) — the share of the
denoise stage's busy envelope the mesh sat idle, i.e. the latency the
overlap failed to hide.  The overlap is measured, not asserted.
"""

from __future__ import annotations

import queue as queue_mod
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import sync
from ..utils.metrics import Counter, GapTracker, LatencyHistogram
from .cache import ExecKey
from .errors import (
    DeadlineExceededError,
    ExecuteFailedError,
    ResourceExhaustedError,
    ServeError,
    ServerClosedError,
    WatchdogTimeoutError,
    is_oom,
)
from .resilience import Watchdog

STAGES = ("encode", "denoise", "decode")

_SENTINEL = object()


class StagedBatch:
    """One coalesced batch's trip through the stage pipeline: the requests
    and their executor (pinned in the cache for the whole trip), plus the
    in-flight product handed from stage to stage."""

    __slots__ = ("batch_key", "base_key", "ekey", "requests",
                 "guidance_scale", "executor", "compile_hit", "dispatch_ts",
                 "started_ts", "stage_ready_ts", "work", "tier")

    def __init__(self, *, batch_key, base_key: ExecKey, ekey: ExecKey,
                 requests, executor, compile_hit: bool, dispatch_ts: float,
                 tier: Optional[int] = None):
        self.batch_key = batch_key
        self.base_key = base_key
        self.ekey = ekey
        self.requests = list(requests)
        self.guidance_scale = batch_key.guidance_scale
        self.executor = executor
        self.compile_hit = compile_hit
        self.dispatch_ts = dispatch_ts
        self.started_ts: Optional[float] = None  # encode-stage entry
        self.stage_ready_ts = dispatch_ts  # when the next stage could start
        self.work: Any = None
        # SLO-controller tier index this batch dispatched at (None when
        # the controller is off) — rides to _complete_batch's calibration
        self.tier = tier

    @property
    def prompts(self) -> List[str]:
        return [r.prompt for r in self.requests]

    @property
    def negative_prompts(self) -> List[str]:
        return [r.negative_prompt for r in self.requests]

    @property
    def seeds(self) -> List[int]:
        return [r.seed for r in self.requests]

    def cancelled(self) -> bool:
        return all(r.future.cancelled() for r in self.requests)

    def expired(self, now: float) -> bool:
        return all(r.expired(now) for r in self.requests)


class StagePipeline:
    """The three-stage worker pipeline (module docstring).

    Callbacks (all may run on stage-worker threads — they must only touch
    thread-safe state; breaker/ladder bookkeeping instead rides the
    `drain_outcomes()` queue back to the scheduler thread):

    * ``on_success(sb, outputs, t_start, t_end)`` — decode finished;
      resolve futures and record request metrics;
    * ``on_failure(sb, exc)`` — the batch failed (stage error, watchdog,
      deadline, stop); fail futures and count by type;
    * ``on_release(sb)`` — the batch left the pipeline by any path;
      unpin its executor.
    """

    def __init__(
        self,
        *,
        max_inflight: int = 2,
        watchdog_timeout_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
        counters: Optional[Counter] = None,
        on_success: Optional[Callable[..., None]] = None,
        on_failure: Optional[Callable[..., None]] = None,
        on_release: Optional[Callable[..., None]] = None,
        fault_plan=None,
        registry=None,
        tracer=None,
    ):
        assert max_inflight >= 1, max_inflight
        self.max_inflight = max_inflight
        self.clock = clock
        self.counters = counters if counters is not None else Counter()
        # optional utils.trace.Tracer: each stage invocation lands as a
        # span on its stage's track ("stage/encode" etc.) tagged with the
        # member trace ids, so the Perfetto view shows the overlap — the
        # measured form of "batch k+1 encodes under batch k's denoise"
        self.tracer = tracer
        # chaos composition: the server's "execute"-site faults fire at
        # the denoise stage (the staged analog of the monolithic
        # watchdog-bounded dispatch), so a chaos run against a staged
        # server exercises the staged failure machinery too
        self.fault_plan = fault_plan
        self.on_success = on_success
        self.on_failure = on_failure
        self.on_release = on_release
        self._slots = sync.Semaphore(max_inflight)
        self._stop = sync.Event()
        self._lock = sync.Lock()
        # serializes submit()'s stop-check-then-enqueue against stop()'s
        # flag-set: without it a submit racing stop() could enqueue AFTER
        # the worker consumed its sentinel and exited, orphaning the
        # batch's futures forever
        self._submit_lock = sync.Lock()
        self._inflight = 0
        self.peak_inflight = 0
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        # metric primitives live in the unified MetricsRegistry when the
        # owning server passes one (hierarchical names + stage labels,
        # rendered by /metrics); standalone pipelines (direct tests) keep
        # private instances — the objects and snapshots are identical
        if registry is not None:
            self.hist_wait = {
                s: registry.histogram("serve_stage_wait_seconds",
                                      labels={"stage": s})
                for s in STAGES
            }
            self.hist_service = {
                s: registry.histogram("serve_stage_service_seconds",
                                      labels={"stage": s})
                for s in STAGES
            }
            self.denoise_gap = registry.gap("serve_denoise_gap")
        else:
            self.hist_wait = {s: LatencyHistogram() for s in STAGES}
            self.hist_service = {s: LatencyHistogram() for s in STAGES}
            self.denoise_gap = GapTracker()
        self._queues = {s: sync.Queue() for s in STAGES}
        self._watchdogs = {s: Watchdog(watchdog_timeout_s) for s in STAGES}
        self._outcomes: "deque[Tuple[ExecKey, ExecKey, Optional[Exception]]]" = deque()
        self._threads = [
            sync.Thread(target=self._worker, args=(s,),
                             name=f"serve-stage-{s}", daemon=True)
            for s in STAGES
        ]
        for t in self._threads:
            t.start()

    # -- scheduler-thread surface ------------------------------------------

    def submit(self, sb: StagedBatch) -> bool:
        """Enter the pipeline, blocking while ``max_inflight`` batches are
        resident (the HBM cap doubling as backpressure).  Returns False
        when the pipeline is stopping — the caller fails the batch."""
        while not self._stop.is_set():
            if self._slots.acquire(timeout=0.05):
                with self._submit_lock:
                    if self._stop.is_set():
                        # stop() holds/held the submit lock when setting
                        # the flag, so a put that reaches the queue is
                        # always BEFORE the sentinel — the worker aborts
                        # it deterministically before exiting
                        self._slots.release()
                        return False
                    with self._lock:
                        self._inflight += 1
                        self.peak_inflight = max(self.peak_inflight,
                                                 self._inflight)
                        self.submitted += 1
                    sb.stage_ready_ts = self.clock()
                    self._queues["encode"].put(sb)
                return True
        return False

    def drain_outcomes(self) -> List[Tuple[ExecKey, ExecKey, Optional[Exception]]]:
        """(base_key, executed ekey, exc-or-None) per finished batch, for
        the scheduler thread's breaker/ladder bookkeeping — stage workers
        never mutate resilience state directly (the breaker's mutating
        methods are scheduler-thread-only by contract)."""
        out = []
        while True:
            try:
                out.append(self._outcomes.popleft())
            except IndexError:
                return out

    # -- internals ----------------------------------------------------------

    def _release(self, sb: StagedBatch, after=None) -> None:
        """Give back the batch's inflight slot now; run ``on_release``
        (the executor unpin) immediately, or — when ``after`` is the done
        event of a watchdog-abandoned worker still executing this batch's
        stage — only once that worker drains, so the unpin can never free
        a program the abandoned thread is still running against."""
        with self._lock:
            self._inflight -= 1
        self._slots.release()
        if self.on_release is None:
            return
        if after is None:
            self.on_release(sb)
            return

        def waiter():
            after.wait()
            self.on_release(sb)

        sync.Thread(target=waiter, name="serve-stage-deferred-unpin",
                         daemon=True).start()

    def _fail(self, sb: StagedBatch, exc: Exception, *,
              record: bool = True, release_after=None) -> None:
        with self._lock:
            self.failed += 1
        if record:
            self._outcomes.append((sb.base_key, sb.ekey, exc))
        try:
            if self.on_failure is not None:
                self.on_failure(sb, exc)
        except Exception:  # noqa: BLE001 — a callback bug must not kill
            # the stage worker (the pipeline would stall forever); loud
            # in counters + stderr, like the server's scheduler guard
            import traceback

            self.counters.inc("staged_callback_errors")
            traceback.print_exc()
        finally:
            self._release(sb, after=release_after)

    def _wrap(self, stage: str, sb: StagedBatch,
              exc: BaseException) -> Exception:
        if isinstance(exc, ServeError):
            return exc  # watchdog timeouts etc. arrive already typed
        if is_oom(exc):
            wrapped: Exception = ResourceExhaustedError(
                f"staged {stage} OOM for {sb.ekey.short()} at batch "
                f"{len(sb.requests)}: {exc}"
            )
        else:
            wrapped = ExecuteFailedError(
                f"staged {stage} failed for {sb.ekey.short()}: "
                f"{type(exc).__name__}: {exc}"
            )
        wrapped.__cause__ = exc
        return wrapped

    def _stage_call(self, stage: str, sb: StagedBatch) -> Any:
        ex = sb.executor
        if stage == "encode":
            return ex.encode_stage(sb.prompts, sb.negative_prompts, sb.seeds)
        if stage == "denoise":
            if self.fault_plan is not None:
                self.fault_plan.check("execute", key=sb.ekey,
                                      batch_size=len(sb.requests))
            return ex.denoise_stage(sb.work, sb.guidance_scale)
        return ex.decode_stage(sb.work)

    def _worker(self, stage: str) -> None:
        q = self._queues[stage]
        idx = STAGES.index(stage)
        nxt = STAGES[idx + 1] if idx + 1 < len(STAGES) else None
        wd = self._watchdogs[stage]
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            sb: StagedBatch = item
            now = self.clock()
            if self._stop.is_set():
                # stop() drains deterministically: work not yet through
                # decode fails; no breaker event (the service stopped, the
                # key did nothing wrong)
                self._fail(sb, ServerClosedError("server stopped"),
                           record=False)
                continue
            if sb.cancelled():
                # every rider gave up: drop at the stage boundary, spend
                # no further stage time on it
                self.counters.inc("staged_cancelled")
                self._release(sb)
                continue
            if stage == "denoise" and sb.expired(now):
                # every rider's deadline lapsed before mesh work began;
                # the denoise dispatch is a scheduling point, so this is
                # a rejection, not an abandonment
                self.counters.inc("staged_expired")
                self._fail(sb, DeadlineExceededError(
                    f"all {len(sb.requests)} requests expired before the "
                    "denoise stage"
                ), record=False)
                continue
            self.hist_wait[stage].observe(now - sb.stage_ready_ts)
            t0 = self.clock()
            if stage == "denoise":
                self.denoise_gap.begin(t0)
            prev_abandoned = wd.abandoned_event
            try:
                out = wd.run(lambda: self._stage_call(stage, sb))
            except Exception as exc:  # noqa: BLE001 — typed + reported
                if stage == "denoise":
                    self.denoise_gap.end(self.clock())
                # a FRESH abandonment means the watchdog's orphaned thread
                # is still executing THIS batch's stage: its executor
                # unpin must wait for that thread (a stale abandonment
                # belongs to an earlier batch — this one never started)
                abandoned = wd.abandoned_event
                fresh = (isinstance(exc, WatchdogTimeoutError)
                         and abandoned is not None
                         and abandoned is not prev_abandoned)
                if self.tracer is not None:
                    self.tracer.event(
                        f"{stage}_failed", track=f"stage/{stage}",
                        args={"key": sb.ekey.short(),
                              "error": type(exc).__name__})
                self._fail(sb, self._wrap(stage, sb, exc),
                           release_after=abandoned if fresh else None)
                continue
            t1 = self.clock()
            if stage == "denoise":
                self.denoise_gap.end(t1)
            self.hist_service[stage].observe(t1 - t0)
            if self.tracer is not None:
                self.tracer.complete(
                    stage, t0, t1, track=f"stage/{stage}",
                    args={"n": len(sb.requests), "key": sb.ekey.short(),
                          "traces": [r.trace.trace_id for r in sb.requests
                                     if r.trace is not None]},
                )
            if stage == "encode":
                sb.started_ts = t0
            if nxt is not None:
                sb.work = out
                sb.stage_ready_ts = t1
                self._queues[nxt].put(sb)
                continue
            # decode finished: resolve
            if len(out) != len(sb.requests):
                # executor contract violation — terminal, typed like the
                # monolithic path's RuntimeError (feeds the breaker)
                self._fail(sb, RuntimeError(
                    f"staged executor returned {len(out)} outputs for a "
                    f"batch of {len(sb.requests)}"
                ))
                continue
            with self._lock:
                self.completed += 1
            self._outcomes.append((sb.base_key, sb.ekey, None))
            started = sb.started_ts if sb.started_ts is not None else t0
            try:
                if self.on_success is not None:
                    self.on_success(sb, out, started, t1)
            except Exception:  # noqa: BLE001 — see _fail: worker survives
                import traceback

                self.counters.inc("staged_callback_errors")
                traceback.print_exc()
            finally:
                self._release(sb)

    # -- lifecycle -----------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        """Deterministic drain (module docstring): every batch inside the
        pipeline resolves before return — ``ServerClosedError`` for work
        that had not completed decode.  Joins stage-by-stage in pipeline
        order so an upstream worker can no longer feed a downstream queue
        after the downstream drain."""
        with self._submit_lock:
            # under the submit lock: every racing submit either enqueued
            # BEFORE this (its batch precedes the sentinel and is aborted
            # by the worker) or sees the flag and refuses
            self._stop.set()
        deadline = time.monotonic() + timeout
        for stage, t in zip(STAGES, self._threads):
            self._queues[stage].put(_SENTINEL)
            t.join(max(0.05, deadline - time.monotonic()))
            if t.is_alive():
                # a stage invocation is still running past its watchdog
                # bound: drain its queue here so no future is left pending,
                # and leave another sentinel for whenever it unsticks
                self.counters.inc("staged_stop_join_timeouts")
                while True:
                    try:
                        item = self._queues[stage].get_nowait()
                    except queue_mod.Empty:
                        break
                    if item is not _SENTINEL:
                        self._fail(item, ServerClosedError("server stopped"),
                                   record=False)
                self._queues[stage].put(_SENTINEL)

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly staged-pipeline metrics (docs/SERVING.md schema):
        per-stage queue-wait/service histograms, the denoise-gap fraction,
        and residency accounting."""
        with self._lock:
            inflight = self._inflight
            peak = self.peak_inflight
            submitted = self.submitted
            completed = self.completed
            failed = self.failed
        return {
            "max_inflight_batches": self.max_inflight,
            "inflight": inflight,
            "peak_inflight": peak,
            "submitted": submitted,
            "completed": completed,
            "failed": failed,
            "stages": {
                s: {
                    "queue_wait": self.hist_wait[s].snapshot(),
                    "service": self.hist_service[s].snapshot(),
                }
                for s in STAGES
            },
            "denoise_gap": self.denoise_gap.snapshot(),
            "watchdog_timeouts": sum(w.timeouts
                                     for w in self._watchdogs.values()),
        }

"""The long-lived inference server: admission -> micro-batch -> execute.

`InferenceServer` ties the serve pieces together around ONE mesh:

* `submit()` (any thread) runs admission control and returns a
  `concurrent.futures.Future` resolving to a `ServeResult`;
* a single scheduler thread drains the queue through the `MicroBatcher`,
  fetches the bucket's executor from the `ExecutorCache` (warm = hit, cold
  = compile), runs the coalesced batch through it, and resolves the
  futures (the executor pads to its compiled batch width and strips);
* every request's lifecycle (queue wait, batch size, compile hit/miss,
  execute and end-to-end latency) lands in streaming histograms
  (utils/metrics.py) exported as one JSON artifact — the serving analog of
  `bench.py`'s one-JSON-line contract.

One scheduler thread is deliberate: the service owns one device mesh, and
the mesh runs one program at a time — extra dispatch threads would only
interleave compiles with execution.  Concurrency lives in the *queue*
(callers block on futures, not on the mesh) and in the batcher that turns
queue depth into batch width.

The executor contract (what `executor_factory(key)` must return):
  * ``batch_size`` attribute — the compiled batch width to pad to;
  * ``__call__(prompts, negative_prompts, guidance_scale, seeds) -> list``
    of per-request outputs, ``len == len(prompts)`` (already unpadded).
`serve/executors.py` adapts the real pipelines; `serve/testing.py` has the
deterministic weightless fake used by tests, the demo, and
``scripts/serve_bench.py --dry-run``.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from ..utils.config import ServeConfig
from ..utils.metrics import Counter, LatencyHistogram
from .batcher import BatchKey, BucketTable, MicroBatcher, NoBucketError
from .cache import ExecKey, ExecutorCache
from .queue import (
    DeadlineExceededError,
    QueueFullError,
    Request,
    RequestQueue,
    ServeResult,
    ServerClosedError,
)


class InferenceServer:
    """Async request scheduler with continuous micro-batching over one mesh.

    ``executor_factory(key: ExecKey)`` builds (and compiles) the executor
    for a bucket; ``model_id``/``scheduler``/``mesh_plan`` identify the
    served model in cache keys — pass ``distri_config.mesh_plan`` when
    wrapping real pipelines so a mesh change invalidates the cache keys.
    """

    def __init__(
        self,
        executor_factory: Callable[[ExecKey], Any],
        config: Optional[ServeConfig] = None,
        *,
        model_id: str = "model",
        scheduler: str = "ddim",
        mesh_plan: str = "dp1.cfg1.sp1",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or ServeConfig()
        self.model_id = model_id
        self.scheduler = scheduler
        self.mesh_plan = mesh_plan
        self.clock = clock
        self.queue = RequestQueue(self.config.max_queue_depth)
        self.cache = ExecutorCache(
            executor_factory, capacity=self.config.cache_capacity
        )
        self.counters = Counter()
        self.hist_queue_wait = LatencyHistogram()
        self.hist_execute = LatencyHistogram()
        self.hist_e2e = LatencyHistogram()
        self._batch_sizes = Counter()
        self.batcher = MicroBatcher(
            self.queue,
            BucketTable(self.config.buckets),
            model_id=model_id,
            scheduler=scheduler,
            max_batch_size=self.config.max_batch_size,
            batch_window_s=self.config.batch_window_s,
            on_reject=self._reject,
            clock=clock,
        )
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self, warmup: bool = True) -> "InferenceServer":
        """Spin up the scheduler thread; with ``warmup``, first prefetch
        the configured hot buckets so their compiles happen before the
        first request is admitted."""
        assert self._thread is None, "server already started"
        if warmup and self.config.warmup_buckets:
            self.cache.warmup(self._warmup_keys())
        self._stop.clear()
        self._started = True
        self._thread = threading.Thread(
            target=self._loop, name="distrifuser-serve", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: stop admitting, finish nothing further, fail
        still-queued futures with `ServerClosedError`."""
        self._stop.set()
        for req in self.queue.close():
            self.counters.inc("rejected_server_closed")
            self._resolve(req.future, exc=ServerClosedError("server stopped"))
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _warmup_keys(self) -> List[ExecKey]:
        keys = []
        table = self.batcher.table
        for entry in self.config.warmup_buckets:
            h, w = entry[0], entry[1]
            steps = entry[2] if len(entry) > 2 else self.config.default_steps
            bh, bw = table.snap(h, w)
            keys.append(self._exec_key_for(bh, bw, steps,
                                           cfg=self.config.warmup_cfg))
        return keys

    def _exec_key_for(self, h: int, w: int, steps: int, cfg: bool) -> ExecKey:
        return ExecKey(
            model_id=self.model_id,
            scheduler=self.scheduler,
            height=h,
            width=w,
            steps=steps,
            cfg=cfg,
            mesh_plan=self.mesh_plan,
            step_cache_interval=self.config.step_cache_interval,
            step_cache_depth=self.config.step_cache_depth,
        )

    # -- submission (any thread) ------------------------------------------

    def submit(
        self,
        prompt: str,
        *,
        height: int,
        width: int,
        negative_prompt: str = "",
        num_inference_steps: Optional[int] = None,
        guidance_scale: float = 5.0,
        seed: int = 0,
        ttl_s: Optional[float] = None,
    ) -> Future:
        """Admit one request; returns a Future of `ServeResult`.

        Raises `QueueFullError` (backpressure — retry against another
        replica or later) or `ServerClosedError` immediately; deadline and
        bucket rejections fail the *future* instead, since they are decided
        at scheduling time."""
        if not self._started or self._stop.is_set():
            raise ServerClosedError("server is not running")
        steps = (self.config.default_steps if num_inference_steps is None
                 else num_inference_steps)
        ttl = self.config.default_ttl_s if ttl_s is None else ttl_s
        req = Request(
            prompt=prompt,
            negative_prompt=negative_prompt,
            height=height,
            width=width,
            num_inference_steps=steps,
            guidance_scale=guidance_scale,
            seed=seed,
            deadline=self.clock() + ttl,
            enqueue_ts=self.clock(),
        )
        self.counters.inc("submitted")
        try:
            self.queue.put(req)
        except QueueFullError:
            self.counters.inc("rejected_queue_full")
            raise
        return req.future

    # -- scheduling loop (single thread) ----------------------------------

    @staticmethod
    def _resolve(future, *, result=None, exc: Optional[Exception] = None) -> None:
        """set_result/set_exception tolerating an already-resolved future
        (a caller may cancel() while the request is queued — that must not
        take down the scheduler thread)."""
        try:
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(result)
        except Exception:
            pass  # cancelled/raced future: the caller gave up on it

    def _reject(self, req: Request, exc: Exception) -> None:
        if isinstance(exc, DeadlineExceededError):
            self.counters.inc("rejected_deadline")
        elif isinstance(exc, NoBucketError):
            self.counters.inc("rejected_no_bucket")
        else:
            self.counters.inc("rejected_other")
        self._resolve(req.future, exc=exc)

    def _loop(self) -> None:
        # The scheduler thread IS the service: an unexpected error
        # (contract-violating executor, future-callback bug) must fail
        # loudly in metrics and keep serving, never die silently.
        import traceback

        while not self._stop.is_set():
            try:
                got = self.batcher.next_batch(timeout=0.05)
            except Exception:  # noqa: BLE001
                self.counters.inc("scheduler_errors")
                traceback.print_exc()
                continue
            if got is None:
                continue
            key, batch = got
            try:
                self._execute(key, batch)
            except Exception as exc:  # noqa: BLE001
                self.counters.inc("scheduler_errors")
                traceback.print_exc()
                for req in batch:
                    self._resolve(req.future, exc=exc)

    def _execute(self, key: BatchKey, batch: List[Request]) -> None:
        dispatch_ts = self.clock()
        ekey = self._exec_key_for(key.height, key.width, key.steps, key.cfg)
        try:
            executor, hit = self.cache.get(ekey)
        except Exception as exc:  # build failed: fail the batch, keep serving
            self.counters.inc("failed_build", len(batch))
            for req in batch:
                self._resolve(req.future, exc=exc)
            return
        self.counters.inc("batches")
        self.counters.inc("requests_compile_hit" if hit
                          else "requests_compile_miss", len(batch))
        self._batch_sizes.inc(f"size_{len(batch)}")

        prompts = [r.prompt for r in batch]
        negs = [r.negative_prompt for r in batch]
        seeds = [r.seed for r in batch]
        t0 = self.clock()
        try:
            outputs = executor(prompts, negs, key.guidance_scale, seeds)
        except Exception as exc:
            self.counters.inc("failed_execute", len(batch))
            for req in batch:
                self._resolve(req.future, exc=exc)
            return
        t1 = self.clock()
        if len(outputs) != len(batch):
            # contract violation; surfaces via the _loop guard, which fails
            # the batch's futures and counts a scheduler_error
            raise RuntimeError(
                f"executor returned {len(outputs)} outputs for a batch of "
                f"{len(batch)}"
            )
        exec_s = t1 - t0
        # shallow-step share: how much of the mesh time the step cache
        # saved from full network evaluations (0 when the cache is off)
        self.counters.inc("denoise_steps_total", key.steps * len(batch))
        shallow = int(getattr(executor, "shallow_steps", 0))
        if shallow:
            self.counters.inc("denoise_steps_shallow", shallow * len(batch))
        for req, out in zip(batch, outputs):
            queue_wait = dispatch_ts - req.enqueue_ts
            e2e = t1 - req.enqueue_ts
            self.hist_queue_wait.observe(queue_wait)
            self.hist_execute.observe(exec_s)
            self.hist_e2e.observe(e2e)
            self.counters.inc("completed")
            self._resolve(req.future, result=ServeResult(
                request_id=req.request_id,
                output=out,
                bucket=(key.height, key.width),
                requested_size=(req.height, req.width),
                queue_wait_s=queue_wait,
                execute_s=exec_s,
                e2e_s=e2e,
                batch_size=len(batch),
                compile_hit=hit,
            ))

    # -- observability -----------------------------------------------------

    def metrics_snapshot(self) -> Dict[str, Any]:
        """JSON-friendly service metrics — the serve artifact schema
        (docs/SERVING.md) consumed by scripts/serve_bench.py."""
        sizes = self._batch_sizes.snapshot()
        n_batches = sum(sizes.values())
        n_reqs = sum(int(k.split("_")[1]) * v for k, v in sizes.items())
        reqs = self.counters.snapshot()
        steps_total = reqs.get("denoise_steps_total", 0)
        steps_shallow = reqs.get("denoise_steps_shallow", 0)
        return {
            "model_id": self.model_id,
            "scheduler": self.scheduler,
            "mesh_plan": self.mesh_plan,
            "config": {
                "max_queue_depth": self.config.max_queue_depth,
                "max_batch_size": self.config.max_batch_size,
                "batch_window_s": self.config.batch_window_s,
                "cache_capacity": self.config.cache_capacity,
                "buckets": [list(b) for b in self.batcher.table.buckets],
            },
            "requests": reqs,
            "step_cache": {
                "interval": self.config.step_cache_interval,
                "depth": self.config.step_cache_depth,
                "steps_total": steps_total,
                "steps_shallow": steps_shallow,
                "shallow_share": (steps_shallow / steps_total
                                  if steps_total else 0.0),
            },
            "latency_s": {
                "queue_wait": self.hist_queue_wait.snapshot(),
                "execute": self.hist_execute.snapshot(),
                "e2e": self.hist_e2e.snapshot(),
            },
            "batch_size": {
                "hist": sizes,
                "mean": (n_reqs / n_batches) if n_batches else 0.0,
            },
            "cache": self.cache.stats(),
        }

    def export_metrics(self, path: str) -> Dict[str, Any]:
        snap = self.metrics_snapshot()
        with open(path, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
            f.write("\n")
        return snap

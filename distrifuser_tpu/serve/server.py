"""The long-lived inference server: admission -> micro-batch -> execute.

`InferenceServer` ties the serve pieces together around ONE mesh:

* `submit()` (any thread) runs admission control and returns a
  `concurrent.futures.Future` resolving to a `ServeResult`;
* a single scheduler thread drains the queue through the `MicroBatcher`,
  fetches the bucket's executor from the `ExecutorCache` (warm = hit, cold
  = compile), runs the coalesced batch through it, and resolves the
  futures (the executor pads to its compiled batch width and strips);
* every request's lifecycle (queue wait, batch size, compile hit/miss,
  execute and end-to-end latency) lands in streaming histograms
  (utils/metrics.py) exported as one JSON artifact — the serving analog of
  `bench.py`'s one-JSON-line contract.

One scheduler thread is deliberate: the service owns one device mesh, and
the mesh runs one program at a time — extra dispatch threads would only
interleave compiles with execution.  Concurrency lives in the *queue*
(callers block on futures, not on the mesh) and in the batcher that turns
queue depth into batch width.

Failures are policy, not luck (serve/resilience.py, configured by
`ServeConfig.resilience`): build/execute errors are typed
(serve/errors.py), retried with exponential backoff under a global retry
budget; a hung batch is bounded by the watchdog and fails without killing
the scheduler; a key that keeps failing trips its circuit breaker and
sheds fast with `CircuitOpenError`; OOM/compile failures walk the
graceful-degradation ladder (split the coalesced batch — bit-identical
outputs, per-request seeds — then recompile without the step cache, then
the stepwise loop, then a smaller bucket).  `health()` snapshots the
whole picture.  A `FaultPlan` (serve/faults.py) can inject any of these
failures deterministically at the named sites ``"build"``/``"execute"``.

The executor contract (what `executor_factory(key)` must return):
  * ``batch_size`` attribute — the compiled batch width to pad to;
  * ``__call__(prompts, negative_prompts, guidance_scale, seeds) -> list``
    of per-request outputs, ``len == len(prompts)`` (already unpadded).
`serve/executors.py` adapts the real pipelines; `serve/testing.py` has the
deterministic weightless fake used by tests, the demo, and
``scripts/serve_bench.py --dry-run``.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from ..utils import sync
from ..utils.config import ServeConfig
from ..utils.metrics import Counter, MetricsRegistry
from ..utils.trace import RequestTrace, Tracer
from .batcher import BatchKey, BucketTable, MicroBatcher
from .cache import ExecKey, ExecutorCache
from .errors import (
    AdmissionRejectedError,
    BuildFailedError,
    CarryExportedError,
    CircuitOpenError,
    DeadlineExceededError,
    DegradationInapplicableError,
    ExecuteFailedError,
    ExecutorContractError,
    FatalError,
    MigrationRejectedError,
    NoBucketError,
    QueueFullError,
    ResourceExhaustedError,
    RetryableError,
    ServeError,
    ServerClosedError,
    TenantQuotaError,
    WatchdogTimeoutError,
    is_oom,
)
from .faults import FaultPlan
from .migration import (
    check_identity,
    check_key_compatible,
    decode_snapshot,
    encode_snapshot,
)
from .queue import Request, RequestQueue, ServeResult
from .resilience import (
    RUNG_SPLIT,
    RUNG_STAGING_OFF,
    ResilienceEngine,
    failure_kind,
)


class InferenceServer:
    """Async request scheduler with continuous micro-batching over one mesh.

    ``executor_factory(key: ExecKey)`` builds (and compiles) the executor
    for a bucket; ``model_id``/``scheduler``/``mesh_plan`` identify the
    served model in cache keys — pass ``distri_config.mesh_plan`` when
    wrapping real pipelines so a mesh change invalidates the cache keys.
    ``fault_plan`` (chaos/testing) injects failures at sites ``"build"``
    (around the factory) and ``"execute"`` (inside the watchdog-bounded
    dispatch).
    """

    def __init__(
        self,
        executor_factory: Callable[[ExecKey], Any],
        config: Optional[ServeConfig] = None,
        *,
        model_id: str = "model",
        scheduler: str = "ddim",
        mesh_plan: str = "dp1.cfg1.sp1",
        clock: Callable[[], float] = time.monotonic,
        fault_plan: Optional[FaultPlan] = None,
        registry: Optional[MetricsRegistry] = None,
        replica_name: Optional[str] = None,
    ):
        self.config = config or ServeConfig()
        self.model_id = model_id
        self.scheduler = scheduler
        self.mesh_plan = mesh_plan
        self.clock = clock
        self.fault_plan = fault_plan
        self.replica_name = replica_name
        self.queue = RequestQueue(self.config.max_queue_depth)
        # Per-tenant fair queuing (serve/tenancy.py): a non-empty tenant
        # table in ServeConfig.gateway turns the queue tenant-aware —
        # token-bucket quotas at put(), weighted DRR feeding peek_best().
        # None when unconfigured: the queue stays pure EDF and the
        # tenant-off request path runs zero tenancy code (the
        # tracer/controller convention).
        self.tenancy = None
        if self.config.gateway.tenants:
            from .tenancy import TenancyPolicy

            self.tenancy = TenancyPolicy(self.config.gateway, clock=clock)
            self.queue.policy = self.tenancy
        # self.prompt_cache is created below (it needs the registry); the
        # factory wrapper reads the attribute lazily at build time, which
        # always happens after __init__ completes (warmup/start/dispatch)
        self.prompt_cache = None

        def _factory(key, _inner=executor_factory):
            # the "build" site wraps WHATEVER factory was passed, so fake
            # and real executors get build faults through one code path —
            # and every built executor gets the server's prompt cache
            # attached when it knows how to use one
            if self.fault_plan is not None:
                self.fault_plan.check("build", key=key)
            ex = _inner(key)
            if (self.prompt_cache is not None
                    and hasattr(ex, "attach_prompt_cache")):
                ex.attach_prompt_cache(self.prompt_cache)
            return ex

        self.cache = ExecutorCache(
            _factory, capacity=self.config.cache_capacity
        )
        obs = self.config.observability
        # Request-scoped tracing (utils/trace.py): None when off — every
        # hook below is guarded, so the tracing-off request path runs no
        # tracing code at all (the ≤2% overhead budget is met by absence)
        self.tracer = (Tracer(clock=clock, capacity=obs.trace_capacity)
                       if obs.trace else None)
        self.cache.tracer = self.tracer
        # Persistent AOT executable store (serve/aotcache.py): None when
        # unconfigured — the store-off build path runs zero AOT code, the
        # tracer/controller convention.  When on, every executor build
        # runs inside the store's activation (see ExecutorCache.get), so
        # warmup and ladder rebuilds consult the store first and populate
        # it on miss; replicas sharing the configured dir warm from each
        # other's compiles.
        self.aot_store = None
        if self.config.aot_cache.dir:
            from .aotcache import AotExecutableCache

            self.aot_store = AotExecutableCache(
                self.config.aot_cache, fault_plan=fault_plan)
            self.cache.aot_store = self.aot_store
        # Unified metrics plane (utils/metrics.py MetricsRegistry): every
        # Counter/LatencyHistogram/GapTracker/RingLog the server and its
        # sub-pieces mutate is OWNED here under hierarchical names, so
        # /metrics (Prometheus), /metrics.json, and metrics_snapshot()
        # all render one source of truth.  A fleet (serve/fleet.py)
        # passes one SHARED registry plus a replica_name: every metric
        # this server creates then carries a {"replica": name} label, so
        # two replicas' otherwise-identical gauges are distinct label
        # sets in the shared plane instead of a registration collision.
        base_registry = registry if registry is not None else MetricsRegistry()
        self.registry = (base_registry.scoped({"replica": replica_name})
                         if replica_name is not None else base_registry)
        self.counters = self.registry.counter("serve_requests")
        self.hist_queue_wait = self.registry.histogram(
            "serve_latency_seconds", labels={"phase": "queue_wait"})
        self.hist_execute = self.registry.histogram(
            "serve_latency_seconds", labels={"phase": "execute"})
        self.hist_e2e = self.registry.histogram(
            "serve_latency_seconds", labels={"phase": "e2e"})
        self._batch_sizes = self.registry.counter("serve_batch_size")
        # SLO signal plumbing (ROADMAP item 3's controller interface):
        # rolling-window p50/p99 per SLO class + the queue-depth and
        # inflight gauges, all readable via slo_snapshot()
        self._slo_window = obs.slo_window
        self._slo_max_age = obs.slo_max_age_s
        self._inflight_c = Counter()  # "requests": dispatched, unresolved
        self.registry.gauge("serve_queue_depth",
                            lambda: float(len(self.queue)))
        self.registry.gauge("serve_inflight_requests",
                            lambda: float(self._inflight_c.get("requests")))
        self.registry.gauge("serve_cache_entries",
                            lambda: float(len(self.cache)))
        self.registry.gauge("serve_cache_hits",
                            lambda: float(self.cache.hits))
        self.registry.gauge("serve_cache_misses",
                            lambda: float(self.cache.misses))
        if self.aot_store is not None:
            # warm-start observability (docs/OBSERVABILITY.md): how much
            # of this replica's warmup deserialized vs compiled, how
            # many persisted entries were rejected (corrupt/version-skew
            # entries that fell back to a fresh compile), and the bytes
            # resident in the shared on-disk store
            self.registry.gauge("aot_cache_hits",
                                lambda: float(self.aot_store.hits))
            self.registry.gauge("aot_cache_misses",
                                lambda: float(self.aot_store.misses))
            self.registry.gauge("aot_cache_rejects",
                                lambda: float(self.aot_store.rejects))
            self.registry.gauge(
                "aot_cache_bytes",
                lambda: float(self.aot_store.stats()["total_bytes"]))
        self.registry.gauge(
            "serve_retry_budget_remaining",
            lambda: float(self.resilience.budget.remaining))
        # per-tenant metrics plane (tenancy on only): admission counters
        # keyed admitted/rejected_quota/completed, a rolling queue-wait
        # window per tenant (the fairness number the gateway bench
        # gates), and a live token/deficit gauge pair read from the
        # policy snapshot.  Tenant tables are static config, so the
        # label sets are bounded by construction.
        self._tenant_counters: Dict[str, Counter] = {}
        self._tenant_wait = {}
        if self.tenancy is not None:
            for tname in self.tenancy.tenant_names:
                self._tenant_counters[tname] = self.registry.counter(
                    "serve_tenant_requests", labels={"tenant": tname})
                self._tenant_wait[tname] = self.registry.rolling(
                    "serve_tenant_queue_wait_s",
                    window=obs.slo_window, labels={"tenant": tname},
                    clock=clock, max_age_s=obs.slo_max_age_s)
                self.registry.gauge(
                    "serve_tenant_tokens",
                    (lambda t=tname: float(
                        (self.queue.tenancy_snapshot() or {})
                        .get(t, {}).get("tokens", 0.0))),
                    labels={"tenant": tname})
        self.metrics_endpoint = None
        self.gateway_endpoint = None
        self.batcher = MicroBatcher(
            self.queue,
            BucketTable(self.config.buckets),
            model_id=model_id,
            scheduler=scheduler,
            max_batch_size=self.config.max_batch_size,
            batch_window_s=self.config.batch_window_s,
            on_reject=self._reject,
            clock=clock,
            batch_cap=self._batch_cap_for,
        )
        self._stop = sync.Event()
        self.resilience = ResilienceEngine(
            self.config.resilience,
            buckets=self.batcher.table.buckets,
            clock=clock,
            # backoff sleeps become stop-interruptible waits: stop() never
            # waits out a backoff schedule
            sleep=self._stop.wait,
            staging=self.config.pipeline_stages,
            tracer=self.tracer,
        )
        # Prompt/embedding LRU cache (serve/promptcache.py): repeated
        # prompts skip text-encode; hit rate rides the registry and feeds
        # the controller's predicted service time
        if self.config.prompt_cache_capacity > 0:
            from .promptcache import PromptCache

            self.prompt_cache = PromptCache(
                self.config.prompt_cache_capacity,
                counter=self.registry.counter("serve_prompt_cache"),
            )
            self.registry.register("serve_prompt_cache_state",
                                   self.prompt_cache)
        # Closed-loop SLO controller (serve/controller.py): per-slo_class
        # tier selection over the quality/cost lattice, admission control
        # at the extreme.  None when off — the controller-off dispatch
        # path runs zero controller code, same convention as the tracer.
        self.controller = None
        if self.config.controller.enabled:
            from .controller import SLOController

            self.controller = SLOController(
                self.config.controller,
                clock=clock,
                batch_hint=self.config.max_batch_size,
                registry=self.registry,
                tracer=self.tracer,
                prompt_cache=self.prompt_cache,
            )
        # the resilience ring log joins the unified registry (JSON render;
        # the Prometheus exposition skips free-text rings by design)
        self.registry.register("serve_last_errors",
                               self.resilience.last_errors)
        self.registry.gauge(
            "serve_watchdog_timeouts",
            lambda: float(self.resilience.watchdog.timeouts))
        # Step-level continuous batching (serve/stepbatch.py): the denoise
        # loop becomes a slot pool of per-request carries — requests join
        # and leave BETWEEN STEPS, EDF reorders the cohort, low-slack
        # arrivals preempt the slackest slot, and occupied slots stream
        # progressive previews.  None when off — the whole-batch dispatch
        # path runs zero step-pool code, the tracer/controller convention.
        self.stepbatch = None
        if self.config.step_batching.enabled:
            from .stepbatch import StepBatcher

            self.stepbatch = StepBatcher(
                self.config.step_batching,
                clock=clock,
                # calibrated per-step service from the PR-9 controller
                # when it is on (EDF's clock unit); the batcher's own
                # EWMA otherwise
                step_estimate=(self.controller.step_service_estimate
                               if self.controller is not None else None),
                # pack-compatibility key source for width-truncated
                # cohorts (StepBatchConfig.pack_align)
                pack_signature=self._step_pack_signature,
            )
            # pack-efficiency: real request rows per dispatched row
            # capacity across the server lifetime (1.0 = every packed
            # dispatch full; sequential dispatches drag it toward 1/width)
            self._pack_rows_total = 0
            self._pack_capacity_total = 0
            self.registry.gauge(
                "serve_stepbatch_pack_fill",
                lambda: (self._pack_rows_total / self._pack_capacity_total
                         if self._pack_capacity_total else 0.0))
            self.hist_first_preview = self.registry.histogram(
                "serve_latency_seconds", labels={"phase": "first_preview"})
            self.registry.gauge(
                "serve_slot_occupied",
                lambda: float(len(self.stepbatch.occupied())))
            self.registry.gauge(
                "serve_slot_parked",
                lambda: float(len(self.stepbatch.parked)))
            self.registry.gauge(
                "serve_slot_capacity",
                lambda: float(self.config.step_batching.slots))
            if self.tenancy is not None:
                # per-tenant slot occupancy: the live fairness picture
                # (rides the blessed snapshot-read policy, like every
                # other slot gauge)
                for tname in self.tenancy.tenant_names:
                    self.registry.gauge(
                        "serve_tenant_slot_occupied",
                        (lambda t=tname: float(
                            self.stepbatch.occupied_by_tenant()
                            .get(t, 0))),
                        labels={"tenant": tname})
        # Staged pipelining (serve/staging.py): three stage workers overlap
        # text-encode, denoise, and VAE-decode across micro-batches.  The
        # scheduler thread submits and drains outcome events; futures
        # resolve from the decode worker.
        self.staging = None
        if self.config.pipeline_stages:
            from .staging import StagePipeline

            self.staging = StagePipeline(
                max_inflight=self.config.max_inflight_batches,
                watchdog_timeout_s=self.config.resilience.watchdog_timeout_s,
                clock=clock,
                counters=self.counters,
                on_success=self._staged_success,
                on_failure=self._staged_failure,
                on_release=self._staged_release,
                fault_plan=fault_plan,
                registry=self.registry,
                tracer=self.tracer,
            )
        self._thread: Optional[threading.Thread] = None
        self._started = False
        # guards the two lifecycle cells concurrent stop()/start() callers
        # mutate: stop() is documented idempotent-from-any-thread, and
        # distrisched pinned the unlocked handle/flag writes as races
        # (a concurrent stop pair could even None the handle between
        # another stopper's check and join).  Reads stay unlocked under
        # the blessed snapshot-read policy.
        self._lifecycle_lock = sync.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self, warmup: bool = True) -> "InferenceServer":
        """Spin up the scheduler thread; with ``warmup``, first prefetch
        the configured hot buckets so their compiles happen before the
        first request is admitted."""
        assert self._thread is None, "server already started"
        if self.queue.closed:
            # stop() closed the queue for good: a "restarted" server
            # would be a zombie — scheduler alive, every submit rejected
            # by the closed queue.  Refuse loudly instead.
            raise ServerClosedError(
                "this server was stopped (its queue is closed); build a "
                "new InferenceServer to serve again"
            )
        if warmup and self.config.warmup_buckets:
            self._warmup()
        if (self.config.observability.metrics_port is not None
                and self.metrics_endpoint is None):
            self.start_metrics_endpoint()
        if (self.config.gateway.port is not None
                and self.gateway_endpoint is None):
            self.start_gateway()
        self._stop.clear()
        t = sync.Thread(
            target=self._loop, name="distrifuser-serve", daemon=True
        )
        with self._lifecycle_lock:
            self._started = True
            self._thread = t
            # started inside the lock: a concurrent stop() reads the
            # handle under the same lock and joins it — publishing an
            # unstarted thread would hand it a join that raises
            t.start()
        return self

    def request_stop(self) -> None:
        """Non-blocking shutdown signal, safe from ANY thread — including
        from inside a dispatch (the replica kill path), where a full
        `stop()` would deadlock on the scheduler join.  Stops admitting,
        fails every still-queued future with `ServerClosedError`, and
        marks the scheduler so the in-flight retry loop fails its batch
        terminally at the next check.  A later `stop()` completes the
        shutdown (join, staging drain, endpoint teardown)."""
        self._stop.set()
        for req in self.queue.close():
            self.counters.inc("rejected_server_closed")
            self._trace_finish(req, "server_closed")
            self._resolve(req.future, exc=ServerClosedError("server stopped"))

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful, deterministic shutdown: stop admitting, fail EVERY
        still-queued future with `ServerClosedError` (including batches
        the batcher pops after the stop flag is set), interrupt any
        backoff sleep, and join the scheduler.  The one batch possibly
        in flight on the mesh completes normally (its wall-time is
        bounded by the watchdog), so `stop()` returns within roughly
        ``max(timeout, one batch)`` with no future left unresolved."""
        if self.gateway_endpoint is not None:
            # first: stop accepting HTTP and resolve every open SSE
            # stream (closed-mark + wake), so no client socket outlives
            # the scheduler it was streaming from
            self.gateway_endpoint.stop()
            self.gateway_endpoint = None
        self.request_stop()
        if self.staging is not None:
            # drain the stage queues deterministically: every staged batch
            # not yet through decode fails with ServerClosedError (the
            # stage invocation in progress finishes, bounded by its
            # watchdog), so no staged future is left unresolved either
            self.staging.stop(timeout)
        with self._lifecycle_lock:
            t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # still draining a long dispatch: KEEP the handle —
                # health() must keep reporting scheduler_alive truthfully,
                # and start()'s assert must refuse to spawn a second
                # scheduler over the one still owning the mesh
                self.counters.inc("stop_join_timeouts")
            else:
                with self._lifecycle_lock:
                    if self._thread is t:
                        self._thread = None
        if self.metrics_endpoint is not None:
            self.metrics_endpoint.stop()
            self.metrics_endpoint = None
        with self._lifecycle_lock:
            self._started = False

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _warmup(self) -> None:
        """Best-effort warmup prefetch: a failed warmup build must not
        abort startup ("failures are policy, not luck" applies to minute
        zero too).  The failure is recorded in metrics and the key's
        resilience state — the first request for the bucket rebuilds
        through the full retry/degradation machinery — and the remaining
        warmup keys still prefetch."""
        for key in self._warmup_keys():
            try:
                self.cache.get(key)
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                self.counters.inc("warmup_build_failures")
                self.resilience.on_failure(key, BuildFailedError(
                    f"warmup build failed for {key.short()}: "
                    f"{type(exc).__name__}: {exc}"
                ))

    def _warmup_keys(self) -> List[ExecKey]:
        keys = []
        table = self.batcher.table
        for entry in self.config.warmup_buckets:
            h, w = entry[0], entry[1]
            steps = entry[2] if len(entry) > 2 else self.config.default_steps
            bh, bw = table.snap(h, w)
            keys.append(self._exec_key_for(bh, bw, steps,
                                           cfg=self.config.warmup_cfg))
        return keys

    def _exec_key_for(self, h: int, w: int, steps: int, cfg: bool) -> ExecKey:
        # per-bucket strategy map (ServeConfig.bucket_parallelism, keyed
        # by post-snap bucket): lets one fleet hold patch-parallel and
        # pipeline-parallel executors for different resolution buckets
        # simultaneously — PipeFusion wins at high resolution / deep
        # meshes, displaced patches below the crossover (docs/PERF.md)
        parallelism = self.config.bucket_parallelism.get(
            (h, w), self.config.parallelism)
        pipe_patches = (int(self.config.pipe_patches or 0)
                        if parallelism == "pipefusion" else 0)
        return ExecKey(
            model_id=self.model_id,
            scheduler=self.scheduler,
            height=h,
            width=w,
            steps=steps,
            cfg=cfg,
            mesh_plan=self.mesh_plan,
            step_cache_interval=self.config.step_cache_interval,
            step_cache_depth=self.config.step_cache_depth,
            comm_compress=self.config.comm_compress,
            # the PCPP knob is a patch-protocol field: pipefusion buckets
            # key at 1.0 (ExecKey validation would reject anything else)
            refresh_fraction=(self.config.refresh_fraction
                              if parallelism == "patch" else 1.0),
            weight_quant=self.config.weight_quant,
            quant_compute=self.config.quant_compute,
            # the step-granular dispatch discipline is compile-distinct:
            # a slot-pool server's executors run the per-step programs
            # with an explicit external carry, never the fused scan
            exec_mode=("step" if self.config.step_batching.enabled
                       else "fused"),
            parallelism=parallelism,
            pipe_patches=pipe_patches,
        )

    def _batch_cap_for(self, key: BatchKey) -> Optional[int]:
        """Batcher hook: the sticky batch-size ceiling the split_batch
        degradation learned for this key (None = no cap)."""
        return self.resilience.batch_cap(
            self._exec_key_for(key.height, key.width, key.steps, key.cfg)
        )

    # -- submission (any thread) ------------------------------------------

    def submit(
        self,
        prompt: str,
        *,
        height: int,
        width: int,
        negative_prompt: str = "",
        num_inference_steps: Optional[int] = None,
        guidance_scale: float = 5.0,
        seed: int = 0,
        ttl_s: Optional[float] = None,
        slo_class: str = "default",
        tenant: str = "default",
        on_progress: Optional[Callable[..., Any]] = None,
        carry_snapshot: Optional[bytes] = None,
    ) -> Future:
        """Admit one request; returns a Future of `ServeResult`.

        Raises `QueueFullError` (backpressure — retry against another
        replica or later), `TenantQuotaError` (the submitting tenant's
        token bucket is empty — per-tenant 429, tenancy on only) or
        `ServerClosedError` immediately; deadline, bucket,
        circuit-breaker, and execution failures fail the *future*
        instead, since they are decided at scheduling time.  Every error
        is a `ServeError`: `RetryableError` means the same request may
        succeed later/elsewhere, `FatalError` means it cannot.

        ``slo_class`` tags the request for the per-class rolling-latency
        windows (`slo_snapshot`) — the signal the SLO controller steers
        on; it does NOT affect scheduling today.

        ``tenant`` is the fairness identity (serve/tenancy.py): with a
        tenant table configured it must name a known tenant (or the
        implicit default), and the request is held to that tenant's
        quota and DRR share.  Ignored when tenancy is off.

        ``on_progress(step, total_steps, preview)`` — progressive
        previews (step-level continuous batching only): fires on the
        scheduler thread every ``step_batching.preview_interval`` steps
        with a cheap downsampled-latent image.  Keep it fast; ignored on
        whole-batch servers.

        ``carry_snapshot`` — carry migration (serve/migration.py): the
        encoded bytes a dying replica exported for this same request
        (`CarryExportedError.snapshot`).  Decoded and identity-checked
        HERE, synchronously — `MigrationRejectedError` (retryable) means
        the caller must strip the snapshot and resubmit from step 0;
        ExecKey compatibility is checked later at step admission, where
        the executing key is known.  Step-batching servers only."""
        if not self._started or self._stop.is_set():
            raise ServerClosedError("server is not running")
        snap = None
        if carry_snapshot is not None:
            if self.stepbatch is None:
                raise MigrationRejectedError(
                    "carry import needs step-level continuous batching "
                    "(ServeConfig.step_batching.enabled) on the "
                    "importing replica"
                )
            data = carry_snapshot
            if self.fault_plan is not None:
                # chaos site: corruption in flight between replicas
                data = self.fault_plan.mutate("migrate.import", data)
            try:
                snap = decode_snapshot(data)
                check_identity(snap, prompt=prompt, seed=seed)
            except MigrationRejectedError:
                self.counters.inc("migrations_rejected")
                raise
        if self.controller is not None and not self.controller.admit(
                str(slo_class)):
            # the controller's extreme rung: even the cheapest tier cannot
            # hold this class's SLO under the current load — reject at
            # admission (typed 429) instead of queueing certain lateness
            self.counters.inc("rejected_admission")
            raise AdmissionRejectedError(
                f"slo_class {slo_class!r} is admission-controlled: the "
                "cheapest quality tier cannot hold its p99 target at the "
                "current load; retry later or against another replica"
            )
        steps = (self.config.default_steps if num_inference_steps is None
                 else num_inference_steps)
        ttl = self.config.default_ttl_s if ttl_s is None else ttl_s
        req = Request(
            prompt=prompt,
            negative_prompt=negative_prompt,
            height=height,
            width=width,
            num_inference_steps=steps,
            guidance_scale=guidance_scale,
            seed=seed,
            slo_class=str(slo_class),
            tenant=str(tenant),
            deadline=self.clock() + ttl,
            enqueue_ts=self.clock(),
            on_progress=on_progress,
            carry_snapshot=snap,
        )
        if self.tracer is not None:
            self._trace_submit(req, steps)
        self.counters.inc("submitted")
        try:
            self.queue.put(req)
        except QueueFullError:
            self.counters.inc("rejected_queue_full")
            self._trace_finish(req, "queue_full")
            raise
        except TenantQuotaError:
            self.counters.inc("rejected_tenant_quota")
            tc = self._tenant_counters.get(req.tenant)
            if tc is not None:
                tc.inc("rejected_quota")
            self._trace_finish(req, "tenant_quota")
            raise
        tc = self._tenant_counters.get(req.tenant)
        if tc is not None:
            tc.inc("admitted")
        return req.future

    # -- tracing hooks (all no-ops when config.observability.trace is off) --

    def _trace_submit(self, req: Request, steps: int) -> None:
        """Open the request's root + queue-wait spans (its whole track)."""
        tr = self.tracer
        tid = tr.new_trace()
        track = f"req/{tid}"
        root = tr.begin("request", track=track, trace=tid, args={
            "requested": f"{req.height}x{req.width}",
            "steps": steps,
            "slo_class": req.slo_class,
        })
        tr.event("enqueue", track=track, trace=tid)
        qspan = tr.begin("queue_wait", track=track, trace=tid, parent=root)
        req.trace = RequestTrace(trace_id=tid, track=track, root=root,
                                 queue_span=qspan)

    def _trace_dequeue(self, req: Request, batch_span: int,
                       batch_size: int) -> None:
        """Close the queue-wait span at the batcher's pop time and mark
        the coalesce, flow-linking the member to the batch span."""
        rt = req.trace
        if rt is None or rt.done:
            return
        tr = self.tracer
        ts = req.dequeue_ts if req.dequeue_ts is not None else self.clock()
        tr.end(rt.queue_span, t=ts, args={"batch_span": batch_span})
        rt.queue_span = None
        tr.event("coalesce", track=rt.track, trace=rt.trace_id, t=ts,
                 args={"batch_span": batch_span, "batch_size": batch_size})
        rt.flow_id = tr.new_flow()
        tr.flow(rt.flow_id, "s", track="scheduler", name="member")

    def _trace_finish(self, req: Request, outcome: str,
                      args: Optional[dict] = None) -> None:
        """Terminal mark for one request: close any still-open queue span
        and the root span with the outcome.  Idempotent — races between
        cancel, deadline, and stop() must not double-close."""
        rt = req.trace
        if rt is None or rt.done or self.tracer is None:
            return
        rt.done = True
        tr = self.tracer
        if rt.queue_span is not None:
            tr.end(rt.queue_span, args={"outcome": outcome})
            rt.queue_span = None
        a = {"outcome": outcome}
        if args:
            a.update(args)
        tr.event("complete" if outcome == "completed" else outcome,
                 track=rt.track, trace=rt.trace_id)
        tr.end(rt.root, args=a)

    # -- scheduling loop (single thread) ----------------------------------

    @staticmethod
    def _resolve(future, *, result=None, exc: Optional[Exception] = None) -> None:
        """set_result/set_exception tolerating an already-resolved future
        (a caller may cancel() while the request is queued — that must not
        take down the scheduler thread)."""
        try:
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(result)
        except Exception:
            pass  # cancelled/raced future: the caller gave up on it

    _OUTCOMES = {
        "CarryExportedError": "carry_exported",
        "MigrationRejectedError": "migration_rejected",
        "ServerClosedError": "server_closed",
        "DeadlineExceededError": "deadline_exceeded",
        "CircuitOpenError": "shed_circuit_open",
        "NoBucketError": "no_bucket",
        "WatchdogTimeoutError": "watchdog_timeout",
    }

    def _fail_batch(self, batch: List[Request], exc: Exception) -> None:
        outcome = self._OUTCOMES.get(type(exc).__name__,
                                     type(exc).__name__)
        for req in batch:
            self._trace_finish(req, outcome)
            self._resolve(req.future, exc=exc)

    def _reject(self, req: Request, exc: Exception) -> None:
        if isinstance(exc, DeadlineExceededError):
            self.counters.inc("rejected_deadline")
        elif isinstance(exc, NoBucketError):
            self.counters.inc("rejected_no_bucket")
        else:
            self.counters.inc("rejected_other")
        self._trace_finish(
            req, self._OUTCOMES.get(type(exc).__name__,
                                    type(exc).__name__))
        self._resolve(req.future, exc=exc)

    def _loop(self) -> None:
        # The scheduler thread IS the service: an unexpected error
        # (contract-violating executor, future-callback bug) must fail
        # loudly in metrics and keep serving, never die silently.
        import traceback

        if self.stepbatch is not None:
            # step-level continuous batching: the slot-pool round loop
            # replaces whole-batch dispatch entirely for this server
            try:
                while not self._stop.is_set():
                    try:
                        if self.controller is not None:
                            self.controller.poll(self.slo_snapshot())
                        busy = self._step_round()
                    except Exception:  # noqa: BLE001
                        self.counters.inc("scheduler_errors")
                        traceback.print_exc()
                        continue
                    if not busy:
                        # idle: sleep until an arrival (or the stop flag's
                        # next check) instead of spinning the pool
                        self.queue.wait_nonempty(0.05)
            finally:
                # deterministic drain on the owner thread: every resident
                # carry (occupied AND parked) resolves its future — the
                # step-mode analog of close() draining the queue
                self._step_drain()
            return

        while not self._stop.is_set():
            try:
                # staged outcomes ride an event queue back here: the
                # breaker/ladder mutating methods are scheduler-thread-only
                self._drain_staged_outcomes()
                if self.controller is not None:
                    # one decision tick per scheduler round — also while
                    # idle, so an admission-parked class can retract when
                    # the load that parked it drains away
                    self.controller.poll(self.slo_snapshot())
                got = self.batcher.next_batch(timeout=0.05)
            except Exception:  # noqa: BLE001
                self.counters.inc("scheduler_errors")
                traceback.print_exc()
                continue
            if got is None:
                continue
            key, batch = got
            if self._stop.is_set():
                # popped concurrently with stop(): fail deterministically,
                # exactly like the still-queued futures close() drained
                self.counters.inc("rejected_server_closed", len(batch))
                self._fail_batch(batch, ServerClosedError("server stopped"))
                continue
            try:
                self._execute(key, batch)
            except Exception as exc:  # noqa: BLE001
                self.counters.inc("scheduler_errors")
                traceback.print_exc()
                self._fail_batch(batch, exc)

    # -- the step-granular scheduling round (serve/stepbatch.py) -----------
    #
    # One round = reap -> fill -> preempt -> advance one step -> previews
    # -> retire.  Everything here runs on the single scheduler thread; the
    # slot pool is its private state (lock-discipline registry entry).

    def _step_round(self) -> bool:
        """One slot-pool scheduling round; returns whether any work
        happened (False lets the loop sleep on the queue condition)."""
        sb = self.stepbatch
        now = self.clock()
        busy = False
        for req in self.queue.pop_expired(now):
            self._reject(req, DeadlineExceededError(
                f"request {req.request_id} expired after "
                f"{now - req.enqueue_ts:.3f}s in queue"
            ))
            busy = True
        busy = self._step_reap(now) or busy
        busy = self._step_fill(now) or busy
        busy = self._step_preempt(now) or busy
        cohort = sb.cohort(self.clock())
        if cohort:
            sb.rounds += 1
            stepped = self._step_advance(cohort)
            if stepped:
                self._step_previews(stepped)
            self._step_retire_finished()
            busy = True
        return busy

    def _step_slack_score(self, now: float):
        sb = self.stepbatch

        def score(req: Request) -> float:
            return sb.request_slack(req, now)

        return score

    def _step_release(self, state, *, abort: bool) -> None:
        """Common teardown for one slot state leaving the pool: buffers,
        pin, pool membership, inflight gauge."""
        self.stepbatch.remove(state)
        self._inflight_c.inc("requests", -1)
        if abort:
            try:
                state.executor.step_abort(state.work)
            except Exception:  # noqa: BLE001 — release is best-effort
                pass
        self.cache.unpin(state.executor)

    def _step_fail_state(self, state, exc: Exception) -> None:
        outcome = self._OUTCOMES.get(type(exc).__name__,
                                     type(exc).__name__)
        self._step_release(state, abort=True)
        self._trace_finish(state.request, outcome)
        self._resolve(state.request.future, exc=exc)

    def _step_fail_group_deferred(self, members, exc: Exception,
                                  after) -> None:
        """Fail a watchdog-ABANDONED cohort group: resolve the futures
        and free the slots NOW, but defer every member's buffer release
        and executor unpin behind the orphaned worker's done-event with
        ONE waiter thread — the staged pipeline's deferral protocol (the
        abandoned thread still mutates the work dicts and runs the
        compiled program; freeing either under it would be a
        use-after-free)."""
        outcome = self._OUTCOMES.get(type(exc).__name__,
                                     type(exc).__name__)
        for m in members:
            self.stepbatch.remove(m)
            self._inflight_c.inc("requests", -1)
            self._trace_finish(m.request, outcome)
            self._resolve(m.request.future, exc=exc)

        def waiter(_members=list(members), _ev=after):
            _ev.wait()
            for m in _members:
                try:
                    m.executor.step_abort(m.work)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
                self.cache.unpin(m.executor)

        sync.Thread(target=waiter, name="serve-step-deferred-release",
                    daemon=True).start()

    def _step_reap(self, now: float) -> bool:
        """Drop cancelled futures (client gave up — free the slot early)
        and fail PARKED states whose deadline lapsed: a parked request is
        not on the mesh, so the in-flight completes-late exemption does
        not apply to it."""
        sb = self.stepbatch
        busy = False
        for state in list(sb.occupied()) + list(sb.parked):
            if state.request.future.cancelled():
                self.counters.inc("step_cancelled")
                self._step_release(state, abort=True)
                self._trace_finish(state.request, "cancelled")
                busy = True
            elif state.parked and state.request.expired(now):
                self.counters.inc("rejected_deadline")
                self._step_fail_state(state, DeadlineExceededError(
                    f"request {state.request.request_id} expired while "
                    f"parked at step {state.steps_done}/"
                    f"{state.steps_total}"
                ))
                busy = True
        return busy

    def _step_fill(self, now: float) -> bool:
        """Fill free slots in ascending-slack (EDF) order from the parked
        list and the queue jointly — a resumed carry competes with fresh
        arrivals on the same deadline math."""
        sb = self.stepbatch
        busy = False
        score = self._step_slack_score(now)
        while sb.free_slots() > 0:
            parked = (min(sb.parked, key=lambda s: sb.state_slack(s, now))
                      if sb.parked else None)
            queued = self.queue.peek_best(score)
            take_parked = parked is not None and (
                queued is None
                or sb.state_slack(parked, now) <= score(queued))
            if take_parked:
                try:
                    parked.executor.step_resume(parked.work)
                except Exception as exc:  # noqa: BLE001 — typed fail
                    self.counters.inc("failed_execute")
                    self._step_fail_state(parked, ExecuteFailedError(
                        f"step resume failed for {parked.ekey.short()}: "
                        f"{type(exc).__name__}: {exc}"))
                    busy = True
                    continue
                sb.unpark(parked)
                self.counters.inc("step_resumes")
                if self.tracer is not None and parked.request.trace:
                    rt = parked.request.trace
                    self.tracer.event("resume", track=rt.track,
                                      trace=rt.trace_id,
                                      args={"step": parked.steps_done})
                busy = True
            elif queued is not None:
                if not self.queue.remove(queued):
                    break  # raced close(); the drain path owns it now
                self._step_admit(queued, now)
                busy = True
            else:
                break
        return busy

    def _step_request_key(self, req: Request):
        """The ONE derivation of a request's admission identity — bucket
        snap -> base key -> controller tier — shared by `_step_admit` and
        the preemption pre-check so the two can never drift.  Returns
        ``(bucket, base_key, tier_idx)``; raises `NoBucketError`."""
        bh, bw = self.batcher.table.snap(req.height, req.width)
        base_key = self._exec_key_for(bh, bw, req.num_inference_steps,
                                      cfg=req.guidance_scale > 1.0)
        tier_idx = None
        if self.controller is not None:
            from .controller import apply_tier

            tier_idx, tier = self.controller.tier_for_batch([req.slo_class])
            base_key = apply_tier(base_key, tier)
        return (bh, bw), base_key, tier_idx

    def _step_admit(self, req: Request, now: float) -> bool:
        """Admit one request into a free slot: snap, tier-map, breaker
        gate, pinned executor fetch, and `step_begin` (encode + seeded
        latent + carry init).  Failures are ONE terminal dispatch failure
        (no step-granular retry loop — the staged-pipeline convention),
        with the ladder advancing on OOM/compile kinds."""
        sb = self.stepbatch
        try:
            (bh, bw), base_key, tier_idx = self._step_request_key(req)
        except NoBucketError as exc:
            self._reject(req, exc)
            return False
        if not self.resilience.allow(base_key):
            self._shed(base_key, [req])
            return False
        ekey = self.resilience.degraded_key(base_key)
        try:
            executor, hit = self.cache.get(ekey, pin=True)
        except Exception as exc:  # noqa: BLE001 — typed below
            bexc = exc if isinstance(exc, ServeError) else BuildFailedError(
                f"executor build failed for {ekey.short()}: "
                f"{type(exc).__name__}: {exc}")
            self._step_admit_failure(req, base_key, ekey, bexc,
                                     invalidate=False)
            return False
        if not hasattr(executor, "step_begin"):
            self.cache.unpin(executor)
            self._step_admit_failure(req, base_key, ekey, BuildFailedError(
                f"executor for {ekey.short()} has no step-granular "
                "contract (step_begin/step_run) — step batching needs a "
                "patch-parallel pipeline or a step-capable fake"),
                invalidate=False)
            return False
        snap = req.carry_snapshot
        try:
            if snap is not None:
                # carry migration import: the snapshot's envelope and
                # request identity were validated at submit; HERE the
                # executing key is known, so compatibility is the last
                # gate before grafting the leaves into a fresh work dict
                check_key_compatible(snap, ekey)
                if not hasattr(executor, "step_import"):
                    raise MigrationRejectedError(
                        f"executor for {ekey.short()} has no step_import "
                        "— cannot adopt a migrated carry")
                work = executor.step_import(
                    snap.meta, list(snap.leaves), req.prompt,
                    req.negative_prompt, req.seed, req.guidance_scale)
            else:
                work = executor.step_begin(req.prompt, req.negative_prompt,
                                           req.seed, req.guidance_scale)
        except MigrationRejectedError as exc:
            # a bad snapshot is the SNAPSHOT's failure, not this
            # replica's: fail typed without feeding the breaker/ladder —
            # the fleet strips the snapshot and retries from step 0
            self.cache.unpin(executor)
            self.counters.inc("migrations_rejected")
            self._fail_batch([req], exc)
            return False
        except Exception as exc:  # noqa: BLE001 — typed below
            self.cache.unpin(executor)
            wexc = exc if isinstance(exc, ServeError) else (
                ResourceExhaustedError(
                    f"step admit OOM for {ekey.short()}: {exc}")
                if is_oom(exc) else ExecuteFailedError(
                    f"step admit failed for {ekey.short()}: "
                    f"{type(exc).__name__}: {exc}"))
            self._step_admit_failure(req, base_key, ekey, wexc,
                                     invalidate=True)
            return False
        from .stepbatch import SlotState

        salvaged = snap.step if snap is not None else 0
        state = SlotState(
            request=req, work=work, base_key=base_key, ekey=ekey,
            executor=executor, compile_hit=hit, steps_total=ekey.steps,
            tier_idx=tier_idx, admit_ts=self.clock(),
            steps_done=salvaged, steps_salvaged=salvaged,
            migrations=1 if snap is not None else 0,
        )
        slot = sb.admit(state)
        self._inflight_c.inc("requests", 1)
        req.bucket = (bh, bw)
        req.dequeue_ts = state.admit_ts
        self.counters.inc("step_joins")
        if snap is not None:
            self.counters.inc("carries_imported")
            self.counters.inc("steps_salvaged", salvaged)
        if tier_idx is not None:
            self.controller.count_dispatch(tier_idx, 1)
        if self.tracer is not None and req.trace is not None:
            rt = req.trace
            if rt.queue_span is not None:
                self.tracer.end(rt.queue_span, t=state.admit_ts)
                rt.queue_span = None
            self.tracer.event("join", track=rt.track, trace=rt.trace_id,
                              args={"slot": slot, "key": ekey.short(),
                                    "steps": state.steps_total})
            if snap is not None:
                self.tracer.event("migrate_in", track=rt.track,
                                  trace=rt.trace_id,
                                  args={"step": salvaged,
                                        "of": state.steps_total})
        return True

    def _step_admit_failure(self, req: Request, base_key: ExecKey,
                            ekey: ExecKey, exc: Exception,
                            invalidate: bool) -> None:
        self.resilience.on_failure(base_key, exc)
        kind = failure_kind(exc)
        if kind in ("oom", "compile"):
            rung = self.resilience.degrade(base_key, kind, 1)
            if rung is not None:
                self.counters.inc("degraded_" + rung)
                if invalidate:
                    self.cache.invalidate(ekey)
        self.counters.inc("failed_build"
                          if isinstance(exc, BuildFailedError)
                          else "failed_execute")
        self._fail_batch([req], exc)

    def _step_preempt(self, now: float) -> bool:
        """Deadline-aware preemption: when the pool is full and the
        tightest queued request would miss its deadline waiting for the
        earliest natural free slot — but still makes it if admitted now —
        park the slackest occupied slot (bit-identical resume later) and
        admit the newcomer.  At most one preemption per round."""
        sb = self.stepbatch
        if (not self.config.step_batching.allow_preemption
                or sb.free_slots() > 0):
            return False
        occupied = sb.occupied()
        if not occupied:
            return False
        # policy-blind peek: rescue must see the globally tightest
        # request even while the DRR cursor camps on another tenant's
        # backlog — fairness shapes shares, not deadline rescues
        cand = self.queue.peek_urgent(self._step_slack_score(now))
        if cand is None:
            return False
        slack_now = sb.request_slack(cand, now)
        if slack_now < 0:
            return False  # already doomed — preempting trades a second miss
        min_remaining = min(s.remaining for s in occupied)
        waits_out = sb.slack(cand.deadline,
                             cand.num_inference_steps + min_remaining, now)
        if waits_out >= 0:
            return False  # waiting is safe; don't pay the park
        # cheap admission pre-checks BEFORE touching a victim: a newcomer
        # its bucket table or circuit breaker would reject anyway must
        # not cost an innocent slot a carry round-trip and its one-time
        # no-thrash budget — SAME derivation as _step_admit, so the two
        # gates cannot drift
        try:
            _, cand_key, _ = self._step_request_key(cand)
        except NoBucketError:
            return False  # the regular fill path rejects it typed
        if not self.resilience.allow(cand_key):
            return False  # shedding would free no slot for the newcomer
        victim = sb.pick_victim(slack_now, now)
        if victim is None:
            return False
        try:
            victim.executor.step_park(victim.work)
        except Exception as exc:  # noqa: BLE001 — typed fail, no park
            self.counters.inc("failed_execute")
            self._step_fail_state(victim, ExecuteFailedError(
                f"step park failed for {victim.ekey.short()}: "
                f"{type(exc).__name__}: {exc}"))
            return True
        sb.park(victim)
        self.counters.inc("step_preempts")
        if self.tracer is not None and victim.request.trace is not None:
            rt = victim.request.trace
            self.tracer.event("preempt", track=rt.track, trace=rt.trace_id,
                              args={"step": victim.steps_done,
                                    "by": cand.request_id})
        admitted = (self.queue.remove(cand)
                    and self._step_admit(cand, now))
        if not admitted and sb.free_slots() > 0:
            # the preemption fizzled past the pre-checks (build/encode
            # failure, raced close): give the victim its slot — and its
            # no-thrash budget — straight back instead of leaving it
            # parked for a vacant pool
            sb.unpark(victim)
            sb.resumes -= 1
            sb.preempt_count -= 1
            victim.preempts -= 1
            self.counters.inc("step_preempts", -1)
            try:
                victim.executor.step_resume(victim.work)
            except Exception as exc:  # noqa: BLE001 — typed fail
                self.counters.inc("failed_execute")
                self._step_fail_state(victim, ExecuteFailedError(
                    f"step resume failed for {victim.ekey.short()}: "
                    f"{type(exc).__name__}: {exc}"))
        return True

    def _step_pack_signature(self, state):
        """Pack-compatibility key of a slot's next step for the batcher's
        width-aligned cohort (`StepBatcher.cohort`): the executor's
        `step_signature`, None when the executor has no pack support
        (fakes without the hook, sequential-only configs)."""
        fn = getattr(state.executor, "step_signature", None)
        if fn is None:
            return None
        try:
            return fn(state.work)
        except Exception:  # noqa: BLE001 — alignment is best-effort
            return None

    def _step_advance(self, cohort) -> list:
        """Advance the cohort one denoise step, grouped by executor (a
        group shares one compiled program; its step is one watchdog-
        bounded mesh dispatch).  A group failure is ONE terminal dispatch
        failure for every member — no step-granular retry.  Returns the
        states that actually stepped."""
        sb = self.stepbatch
        stepped = []
        round_dispatches = 0
        groups: Dict[int, list] = {}
        for state in cohort:
            groups.setdefault(id(state.executor), []).append(state)
        round_t0 = self.clock()
        for members in groups.values():
            executor = members[0].executor
            ekey = members[0].ekey
            base_key = members[0].base_key
            works = [m.work for m in members]

            def call(_ex=executor, _works=works, _ekey=ekey):
                if self.fault_plan is not None:
                    self.fault_plan.check("execute", key=_ekey,
                                          batch_size=len(_works))
                _ex.step_run(_works)

            wd = self.resilience.watchdog
            prev_abandoned = wd.abandoned_event
            try:
                wd.run(call)
            except Exception as exc:  # noqa: BLE001 — typed below
                # a FRESH abandonment means the watchdog's orphaned
                # thread is still executing THIS group's step: the
                # members' buffer release and executor unpin must wait
                # for it (the staged pipeline's deferral protocol)
                abandoned = wd.abandoned_event
                fresh_abandon = (isinstance(exc, WatchdogTimeoutError)
                                 and abandoned is not None
                                 and abandoned is not prev_abandoned)
                if self._stop.is_set() and not fresh_abandon:
                    # raced a stop/kill mid-round: leave every remaining
                    # member RESIDENT instead of failing it — the loop's
                    # finally-drain exports each carry for migration (the
                    # dispatch failed before any member's step advanced,
                    # so the carries are valid at their current step),
                    # and a dying server must not feed its own breaker
                    break
                if isinstance(exc, WatchdogTimeoutError):
                    self.counters.inc("watchdog_timeouts")
                    texc = exc
                elif isinstance(exc, ServeError):
                    texc = exc
                elif is_oom(exc):
                    texc = ResourceExhaustedError(
                        f"step execute OOM for {ekey.short()} at cohort "
                        f"{len(works)}: {exc}")
                else:
                    texc = ExecuteFailedError(
                        f"step execute failed for {ekey.short()}: "
                        f"{type(exc).__name__}: {exc}")
                # one terminal dispatch failure for the whole group
                # (members share base_key through the shared executor)
                self.resilience.on_failure(base_key, texc)
                kind = failure_kind(texc)
                if kind in ("oom", "compile"):
                    rung = self.resilience.degrade(base_key, kind, 1)
                    if rung is not None:
                        self.counters.inc("degraded_" + rung)
                        self.cache.invalidate(ekey)
                self.counters.inc("failed_execute", len(members))
                if fresh_abandon:
                    self._step_fail_group_deferred(members, texc,
                                                   abandoned)
                else:
                    for m in members:
                        self._step_fail_state(m, texc)
                continue
            self.resilience.on_success(base_key)
            for m in members:
                m.steps_done += 1
                stepped.append(m)
            self.counters.inc("steps_executed", len(members))
            # pack-efficiency accounting (serve/executors.py step_run):
            # how many compiled dispatches this group's step cost and how
            # many real request rows they carried
            stats = getattr(executor, "step_pack_stats", None)
            if stats:
                nd = int(stats.get("dispatches", 0))
                nr = int(stats.get("packed_rows", 0))
                round_dispatches += nd
                self.counters.inc("stepbatch_dispatches", nd)
                self.counters.inc("stepbatch_packed_rows", nr)
                self._pack_rows_total += nr
                self._pack_capacity_total += int(
                    stats.get("rows_capacity", 0))
                if (nd < len(members) and self.tracer is not None
                        and members[0].request.trace is not None):
                    rt = members[0].request.trace
                    self.tracer.event(
                        "packed-step", track=rt.track, trace=rt.trace_id,
                        args={"members": len(members), "dispatches": nd,
                              "rows": nr})
            else:
                # executors without pack accounting dispatch per member
                round_dispatches += len(members)
        if stepped:
            # calibrate on the WHOLE round, not per executor group: the
            # EDF clock unit is "one more step for this slot", and a slot
            # advances once per round — a round that serially dispatches
            # three bucket groups costs the sum, and slack math priced at
            # a single group's time would flatter every deadline
            round_dt = self.clock() - round_t0
            sb.note_round(round_dt)
            if self.controller is not None:
                costs = [self.controller.tiers[
                    min(m.tier_idx or 0, len(self.controller.tiers) - 1)
                ].cost for m in stepped]
                # per-REQUEST service: a packed dispatch advances several
                # requests for one program call, so the round time is
                # normalized by the pack factor — without this the
                # step-granular occupancy model over-predicts by exactly
                # how well the executor packs
                self.controller.observe_step(sum(costs) / len(costs),
                                             round_dt,
                                             requests=len(stepped),
                                             dispatches=round_dispatches)
        return stepped

    def _step_previews(self, stepped) -> None:
        """Emit progressive previews for stepped slots that are due: a
        cheap host-side downsampled latent through the request's
        on_progress callback, traced as its own span.  Callback errors
        are counted, never fatal — a client's slow/broken callback must
        not take down the step loop."""
        k = self.config.step_batching.preview_interval
        if not k:
            return
        for state in stepped:
            req = state.request
            if req.on_progress is None or state.steps_done % k:
                continue
            t0 = self.clock()
            try:
                img = state.executor.step_preview(
                    state.work, self.config.step_batching.preview_size)
                req.on_progress(state.steps_done, state.steps_total, img)
            except Exception:  # noqa: BLE001 — counted, never fatal
                self.counters.inc("preview_errors")
                continue
            t1 = self.clock()
            state.previews += 1
            self.counters.inc("step_previews")
            if state.first_preview_s is None:
                state.first_preview_s = t1 - req.enqueue_ts
                self.hist_first_preview.observe(state.first_preview_s)
            if self.tracer is not None and req.trace is not None:
                rt = req.trace
                self.tracer.complete("preview", t0, t1, track=rt.track,
                                     trace=rt.trace_id, parent=rt.root,
                                     args={"step": state.steps_done,
                                           "of": state.steps_total})

    def _step_retire_finished(self) -> None:
        """Decode + resolve every occupied slot whose denoise finished —
        the leave side of continuous batching, freeing slots for the next
        round's joiners."""
        for state in list(self.stepbatch.occupied()):
            if state.steps_done < state.steps_total:
                continue
            try:
                out = state.executor.step_finish(state.work)
            except Exception as exc:  # noqa: BLE001 — typed fail
                texc = exc if isinstance(exc, ServeError) else (
                    ExecuteFailedError(
                        f"step decode failed for {state.ekey.short()}: "
                        f"{type(exc).__name__}: {exc}"))
                self.resilience.on_failure(state.base_key, texc)
                self.counters.inc("failed_execute")
                self._step_fail_state(state, texc)
                continue
            self._step_complete(state, out, self.clock())

    def _step_complete(self, state, out, t1: float) -> None:
        """Success bookkeeping for one step-granular request — the
        request-shaped mirror of `_complete_batch`."""
        req = state.request
        queue_wait = state.admit_ts - req.enqueue_ts
        exec_s = t1 - state.admit_ts
        e2e = t1 - req.enqueue_ts
        self.hist_queue_wait.observe(queue_wait)
        self.hist_execute.observe(exec_s)
        self.hist_e2e.observe(e2e)
        self.slo_window(req.slo_class).observe(e2e)
        self._tenant_observe(req, queue_wait)
        self.counters.inc("completed")
        self.counters.inc("requests_compile_hit" if state.compile_hit
                          else "requests_compile_miss")
        self.counters.inc("denoise_steps_total", state.steps_total)
        if req.expired(t1):
            self.counters.inc("completed_late")
        tier_name = (self.controller.tiers[state.tier_idx].name
                     if state.tier_idx is not None
                     and self.controller is not None else None)
        degradations = tuple(
            self.resilience.key_state(state.base_key).rungs)
        if req.trace is not None and self.tracer is not None:
            rt = req.trace
            self.tracer.complete(
                "execute", state.admit_ts, t1, track=rt.track,
                trace=rt.trace_id, parent=rt.root,
                args={"bucket": f"{state.ekey.height}x{state.ekey.width}",
                      "steps": state.steps_total,
                      "preempts": state.preempts,
                      "compile_hit": state.compile_hit})
            self._trace_finish(req, "completed", args={
                "previews": state.previews,
                "preempts": state.preempts})
        result = ServeResult(
            request_id=req.request_id,
            output=out,
            bucket=(state.ekey.height, state.ekey.width),
            requested_size=(req.height, req.width),
            queue_wait_s=queue_wait,
            execute_s=exec_s,
            e2e_s=e2e,
            batch_size=1,
            compile_hit=state.compile_hit,
            retries=0,
            degradations=degradations,
            exec_key=state.ekey.short(),
            tier=tier_name,
            replica=self.replica_name,
            previews=state.previews,
            first_preview_s=state.first_preview_s,
            preempts=state.preempts,
            migrations=state.migrations,
            steps_salvaged=state.steps_salvaged,
        )
        self._step_release(state, abort=False)
        self._resolve(req.future, result=result)

    def _step_export(self, state) -> Optional[bytes]:
        """Serialize one resident carry for migration, or None when no
        snapshot can ride out: export disabled, the executor lacks the
        hook, the carry is at step 0 (nothing to salvage) or already
        finished (retire, don't migrate), or the export itself failed —
        the drain path then falls back to progress-only accounting."""
        if not self.config.step_batching.export_carries:
            return None
        if not (0 < state.steps_done < state.steps_total):
            return None
        executor = state.executor
        if not hasattr(executor, "step_export"):
            return None
        try:
            extra, leaves = executor.step_export(state.work)
            extra = dict(extra)
            family = str(extra.pop("family", ""))
            # the executor's own step index is authoritative — it and
            # steps_done advance together, but the carry is what resumes
            step = int(extra.pop("step", state.steps_done))
            data = encode_snapshot(
                ekey=state.ekey, family=family, step=step,
                steps_total=state.steps_total,
                request_id=str(state.request.request_id),
                prompt=state.request.prompt, seed=state.request.seed,
                leaves=list(leaves), extra=extra or None,
            )
        except Exception:  # noqa: BLE001 — export is best-effort
            self.counters.inc("carry_export_failed")
            return None
        if self.fault_plan is not None:
            # chaos site: truncation/corruption during the export write
            data = self.fault_plan.mutate("migrate.export", data,
                                          key=state.ekey)
        return data

    def _step_drain(self) -> None:
        """Deterministic stop: every resident carry (occupied + parked)
        resolves its future and releases its buffers — no step-mode
        future is ever left unresolved.  With ``export_carries`` on, a
        mid-denoise carry first serializes (serve/migration.py) and
        rides out on `CarryExportedError.snapshot` so the fleet's
        failover resumes it on another replica instead of re-running
        from step 0; a carry that cannot export still reports its
        ``steps_done`` so the fleet can count the steps it is about to
        re-execute."""
        sb = self.stepbatch
        for state in list(sb.occupied()) + list(sb.parked):
            self.counters.inc("rejected_server_closed")
            data = self._step_export(state)
            if data is not None:
                self.counters.inc("carries_exported")
                if (self.tracer is not None
                        and state.request.trace is not None):
                    rt = state.request.trace
                    self.tracer.event(
                        "migrate_out", track=rt.track, trace=rt.trace_id,
                        args={"step": state.steps_done,
                              "of": state.steps_total,
                              "bytes": len(data)})
                exc: ServerClosedError = CarryExportedError(
                    f"server stopped at step {state.steps_done}/"
                    f"{state.steps_total}; carry exported for migration",
                    snapshot=data, steps_done=state.steps_done)
            elif (self.config.step_batching.export_carries
                    and state.steps_done > 0):
                # export was ON but this carry could not serialize:
                # progress-only accounting still rides out so the fleet
                # can count the steps it is about to re-execute.  With
                # export OFF the operator opted out of migration — the
                # documented contract is the plain ServerClosedError path
                exc = CarryExportedError(
                    f"server stopped at step {state.steps_done}/"
                    f"{state.steps_total}; carry not exportable",
                    snapshot=None, steps_done=state.steps_done)
            else:
                exc = ServerClosedError("server stopped")
            self._step_fail_state(state, exc)

    # -- the resilient execute path ---------------------------------------

    def _execute(self, key: BatchKey, batch: List[Request]) -> None:
        dispatch_ts = self.clock()
        # staged outcomes that landed while this batch was forming must
        # reach the breaker/ladder BEFORE the allow()/routing decisions
        self._drain_staged_outcomes()
        base_key = self._exec_key_for(key.height, key.width, key.steps,
                                      key.cfg)
        # Closed-loop tier selection (serve/controller.py): map the bucket
        # key through the cheapest tier any member class needs BEFORE the
        # resilience layer sees it — breakers and sticky ladder rungs then
        # track per TIER key, and degraded_key() composes the rungs on top
        # of the tier's knobs, so ladder rungs always win.
        tier_idx = None
        if self.controller is not None:
            from .controller import apply_tier

            tier_idx, tier = self.controller.tier_for_batch(
                [r.slo_class for r in batch])
            base_key = apply_tier(base_key, tier)
        batch_span = None
        if self.tracer is not None:
            targs = {"bucket": f"{key.height}x{key.width}",
                     "n": len(batch), "key": base_key.short(),
                     "traces": [r.trace.trace_id for r in batch
                                if r.trace is not None]}
            if tier_idx is not None:
                targs["tier"] = self.controller.tiers[tier_idx].name
            batch_span = self.tracer.begin(
                "batch", track="scheduler", t=dispatch_ts, args=targs)
            for req in batch:
                self._trace_dequeue(req, batch_span, len(batch))
        if not self.resilience.allow(base_key):
            self._shed(base_key, batch)
            if self.tracer is not None:
                self.tracer.end(batch_span, args={"outcome": "shed"})
            return
        if tier_idx is not None:
            # counted only past the breaker gate: a shed batch never ran
            # at the tier, and the per-tier dispatch counters are read as
            # tier THROUGHPUT exactly when the mesh is failing
            self.controller.count_dispatch(tier_idx, len(batch))
        # inflight gauge: dispatched-but-unresolved requests (the SLO
        # controller's second queue signal).  Every exit path below must
        # balance it — staged submissions hand the decrement to
        # _staged_release, which fires exactly once per submitted batch.
        self._inflight_c.inc("requests", len(batch))
        staged = self._execute_staged(key, base_key, batch, dispatch_ts,
                                      tier_idx)
        if staged == "submitted":
            if self.tracer is not None:
                self.tracer.end(batch_span, args={"outcome": "staged"})
            return
        if staged == "failed":
            self._inflight_c.inc("requests", -len(batch))
            if self.tracer is not None:
                self.tracer.end(batch_span, args={"outcome": "failed"})
            return
        try:
            self._execute_resilient(key, base_key, batch, dispatch_ts,
                                    tier_idx)
        finally:
            # batch span first, THEN the inflight decrement: a client
            # observing inflight==0 knows the scheduler has made its
            # last tracer/clock call for this batch (the trace
            # determinism tests quiesce on exactly this)
            if self.tracer is not None:
                self.tracer.end(batch_span)
            self._inflight_c.inc("requests", -len(batch))

    # -- the staged execute path -------------------------------------------

    def _drain_staged_outcomes(self) -> None:
        """Apply finished staged batches' breaker/ladder bookkeeping on
        the scheduler thread.  A staged failure is ONE terminal dispatch
        failure (there is no intra-stage retry loop); OOM/compile kinds
        advance the sticky degradation ladder — ``staging_off`` first, so
        a key that cannot afford the overlap's residency falls back to
        the monolithic path (which still has the full retry machinery)."""
        if self.staging is None:
            return
        for base_key, ekey, exc in self.staging.drain_outcomes():
            if exc is None:
                self.resilience.on_success(base_key)
                continue
            self.resilience.on_failure(base_key, exc)
            kind = failure_kind(exc)
            if kind in ("oom", "compile"):
                # batch_size=1 deliberately skips RUNG_SPLIT: staged
                # dispatches never split (splitting is the retry loop's
                # move), so the ladder advances straight to the key rungs
                rung = self.resilience.degrade(base_key, kind, 1)
                if rung is not None:
                    self.counters.inc("degraded_" + rung)
                    if rung != RUNG_STAGING_OFF:
                        # the poisoned program must not satisfy the next
                        # dispatch (same contract as the monolithic path)
                        self.cache.invalidate(ekey)

    def _staging_routed(self, base_key: ExecKey) -> bool:
        return (self.staging is not None
                and RUNG_STAGING_OFF
                not in self.resilience.key_state(base_key).rungs)

    def _execute_staged(self, key: BatchKey, base_key: ExecKey,
                        batch: List[Request], dispatch_ts: float,
                        tier_idx: Optional[int] = None) -> str:
        """Submit the batch to the stage pipeline.  Returns
        ``"submitted"`` (the pipeline owns the batch now — its inflight
        decrement rides `_staged_release`), ``"failed"`` (consumed by a
        terminal failure here), or ``"fallthrough"`` to the monolithic
        path (staging off/degraded for this key, or an executor without
        stage programs)."""
        if not self._staging_routed(base_key):
            return "fallthrough"
        from .staging import StagedBatch

        ekey = self.resilience.degraded_key(base_key)
        try:
            # pinned for the batch's whole trip: LRU eviction or
            # invalidate() must never free a program a stage worker is
            # about to run (ExecutorCache defers the release to unpin)
            executor, hit = self.cache.get(ekey, pin=True)
        except Exception as exc:  # noqa: BLE001 — typed below
            bexc = exc if isinstance(exc, ServeError) else BuildFailedError(
                f"executor build failed for {ekey.short()}: "
                f"{type(exc).__name__}: {exc}"
            )
            # one terminal dispatch failure, like a stage failure — the
            # ladder may force staging off so the NEXT dispatch retries
            # through the monolithic machinery
            self.resilience.on_failure(base_key, bexc)
            kind = failure_kind(bexc)
            if kind in ("oom", "compile"):
                rung = self.resilience.degrade(base_key, kind, 1)
                if rung is not None:
                    self.counters.inc("degraded_" + rung)
            self.counters.inc("failed_build", len(batch))
            self._fail_batch(batch, bexc)
            return "failed"
        if not hasattr(executor, "encode_stage"):
            # executor has no stage programs (plain fakes, custom
            # adapters): unpin and run monolithically
            self.cache.unpin(executor)
            return "fallthrough"
        sb = StagedBatch(
            batch_key=key, base_key=base_key, ekey=ekey, requests=batch,
            executor=executor, compile_hit=hit, dispatch_ts=dispatch_ts,
            tier=tier_idx,
        )
        if not self.staging.submit(sb):
            # pipeline is stopping: deterministic close, like the queued
            # futures stop() drains
            self.cache.unpin(executor)
            self.counters.inc("rejected_server_closed", len(batch))
            self._fail_batch(batch, ServerClosedError("server stopped"))
            return "failed"
        return "submitted"

    def _staged_success(self, sb, outputs, t0: float, t1: float) -> None:
        """Decode-worker callback: resolve and record one completed staged
        batch (counters/histograms are thread-safe; breaker bookkeeping
        rides drain_outcomes instead)."""
        shallow = int(getattr(sb.executor, "shallow_steps", 0))
        degradations = tuple(self.resilience.key_state(sb.base_key).rungs)
        self._complete_batch(
            sb.batch_key, sb.ekey, sb.requests, outputs, sb.dispatch_ts,
            t0, t1, sb.compile_hit, retries=0, degradations=degradations,
            shallow_steps=shallow, tier=sb.tier,
        )

    def _staged_failure(self, sb, exc: Exception) -> None:
        """Stage-worker callback: fail one staged batch's futures, counted
        by failure type (mirrors the monolithic counters)."""
        n = len(sb.requests)
        if isinstance(exc, ServerClosedError):
            self.counters.inc("rejected_server_closed", n)
        elif isinstance(exc, DeadlineExceededError):
            self.counters.inc("rejected_deadline", n)
        elif isinstance(exc, WatchdogTimeoutError):
            self.counters.inc("watchdog_timeouts")
            self.counters.inc("failed_execute", n)
        elif isinstance(exc, BuildFailedError):
            self.counters.inc("failed_build", n)
        elif isinstance(exc, FatalError):
            self.counters.inc("failed_fatal", n)
        else:
            self.counters.inc("failed_execute", n)
        self._fail_batch(sb.requests, exc)

    def _staged_release(self, sb) -> None:
        # fires exactly once per submitted staged batch, on ANY exit path
        # (success, failure, cancel-drop, stop): the executor unpin and
        # the inflight decrement both belong to "the batch left the
        # pipeline"
        self._inflight_c.inc("requests", -len(sb.requests))
        self.cache.unpin(sb.executor)

    def _shed(self, ekey: ExecKey, batch: List[Request]) -> None:
        """Circuit open: fail fast with the 503-style typed error — the
        whole point is spending O(dispatch) time, not queue/retry time,
        on a key that keeps failing."""
        self.counters.inc("shed_circuit_open", len(batch))
        self._fail_batch(batch, CircuitOpenError(
            f"circuit open for {ekey.short()}: shedding fast; retry after "
            f"the {self.config.resilience.breaker_cooldown_s:.1f}s cooldown "
            "or against another replica"
        ))

    def _get_executor(self, ekey: ExecKey):
        """Cache fetch with build failures wrapped into the typed
        hierarchy (`BuildFailedError`; message keeps the OOM shape
        visible when the compile itself exhausted memory)."""
        try:
            return self.cache.get(ekey)
        except ServeError:
            raise
        except Exception as exc:
            raise BuildFailedError(
                f"executor build failed for {ekey.short()}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc

    def _dispatch(self, ekey: ExecKey, key: BatchKey, executor,
                  batch: List[Request]):
        """One watchdog-bounded batched executor invocation; execute
        failures come back typed (`ResourceExhaustedError` for OOM shapes,
        `ExecuteFailedError` otherwise, `WatchdogTimeoutError` on hang)."""
        prompts = [r.prompt for r in batch]
        negs = [r.negative_prompt for r in batch]
        seeds = [r.seed for r in batch]
        t0 = self.clock()

        def call():
            if self.fault_plan is not None:
                self.fault_plan.check("execute", key=ekey,
                                      batch_size=len(batch))
            return executor(prompts, negs, key.guidance_scale, seeds)

        try:
            outputs = self.resilience.watchdog.run(call)
        except WatchdogTimeoutError:
            self.counters.inc("watchdog_timeouts")
            raise
        except ServeError:
            raise
        except Exception as exc:
            if is_oom(exc):
                raise ResourceExhaustedError(
                    f"batched execute OOM for {ekey.short()} at batch "
                    f"{len(batch)}: {exc}"
                ) from exc
            raise ExecuteFailedError(
                f"batched execute failed for {ekey.short()}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        t1 = self.clock()
        if len(outputs) != len(batch):
            # contract violation, NOT a transient fault: the typed escape
            # (outside the ServeError hierarchy, serve/errors.py) bubbles
            # past the retry loop to the _loop guard, which fails the
            # batch and counts a scheduler_error
            raise ExecutorContractError(
                f"executor returned {len(outputs)} outputs for a batch of "
                f"{len(batch)}"
            )
        return outputs, t0, t1

    def _execute_resilient(self, key: BatchKey, base_key: ExecKey,
                           batch: List[Request], dispatch_ts: float,
                           tier_idx: Optional[int] = None) -> None:
        """Bounded retry loop around (build -> dispatch) with the
        degradation ladder on OOM/compile failures.  Splitting recurses
        with fresh attempt budgets (depth is bounded by log2(batch));
        every retry anywhere draws from the global retry budget."""
        res = self.resilience
        rcfg = self.config.resilience
        attempts = 0
        while True:
            if self._stop.is_set():
                self.counters.inc("rejected_server_closed", len(batch))
                self._fail_batch(batch, ServerClosedError("server stopped"))
                return
            ekey = res.degraded_key(base_key)
            try:
                executor, hit = self._get_executor(ekey)
                outputs, t0, t1 = self._dispatch(ekey, key, executor, batch)
            except FatalError as exc:
                res.on_failure(base_key, exc)
                self.counters.inc("failed_fatal", len(batch))
                self._fail_batch(batch, exc)
                return
            except RetryableError as exc:
                # attempt-level: observability only — the breaker counts
                # TERMINAL dispatch failures (below), so exhausting
                # max_retries and tripping the circuit stay separately
                # tuned policies
                res.note_error(base_key, exc)
                kind = failure_kind(exc)
                failed_counter = ("failed_build"
                                  if isinstance(exc, BuildFailedError)
                                  else "failed_execute")
                cause = exc.__cause__
                if (isinstance(exc, BuildFailedError)
                        and isinstance(cause, DegradationInapplicableError)
                        and res.retract_rung(base_key, cause.rung)):
                    # the rung can NEVER build for this key's builder
                    # (e.g. weight_quant_on against tensor/pipefusion):
                    # un-apply it and retry at the retracted key instead
                    # of turning a transient OOM into a permanently
                    # failing key; the pin in KeyResilience.inapplicable
                    # keeps the ladder from re-picking it
                    self.counters.inc("degradation_retracted_" + cause.rung)
                elif kind in ("oom", "compile"):
                    rung = res.degrade(base_key, kind, len(batch))
                    if rung == RUNG_SPLIT:
                        if not res.acquire_retry():
                            self.counters.inc("retry_budget_exhausted")
                            self.counters.inc(failed_counter, len(batch))
                            res.record_terminal_failure(base_key)
                            self._fail_batch(batch, exc)
                            return
                        self.counters.inc("retries")
                        self.counters.inc("degraded_split_batch")
                        if self.tracer is not None:
                            self.tracer.event(
                                "split_batch", track="scheduler",
                                args={"key": ekey.short(),
                                      "n": len(batch)})
                        mid = (len(batch) + 1) // 2
                        self._execute_resilient(key, base_key, batch[:mid],
                                                dispatch_ts, tier_idx)
                        self._execute_resilient(key, base_key, batch[mid:],
                                                dispatch_ts, tier_idx)
                        return
                    if rung is not None:
                        self.counters.inc("degraded_" + rung)
                        # the poisoned program must not satisfy the retry
                        self.cache.invalidate(ekey)
                attempts += 1
                if attempts > rcfg.max_retries:
                    self.counters.inc(failed_counter, len(batch))
                    res.record_terminal_failure(base_key)
                    self._fail_batch(batch, exc)
                    return
                if not res.acquire_retry():
                    self.counters.inc("retry_budget_exhausted")
                    self.counters.inc(failed_counter, len(batch))
                    res.record_terminal_failure(base_key)
                    self._fail_batch(batch, exc)
                    return
                self.counters.inc("retries")
                if self.tracer is not None:
                    self.tracer.event(
                        "retry", track="scheduler",
                        args={"attempt": attempts, "kind": kind,
                              "key": ekey.short(),
                              "error": type(exc).__name__})
                res.sleep(res.backoff_delay(attempts))
                continue
            except Exception as exc:
                # non-ServeError escape (executor contract violation):
                # destined for the _loop guard, but the breaker must still
                # see it — a HALF_OPEN probe that dies this way would
                # otherwise leave the probe-inflight latch set forever,
                # permanently shedding the key with no healing path
                res.on_failure(base_key, exc)
                raise
            # ---- success ------------------------------------------------
            res.on_success(base_key)
            self._complete_batch(
                key, ekey, batch, outputs, dispatch_ts, t0, t1, hit,
                retries=attempts,
                degradations=tuple(res.key_state(base_key).rungs),
                shallow_steps=int(getattr(executor, "shallow_steps", 0)),
                tier=tier_idx,
            )
            return

    def _complete_batch(self, key: BatchKey, ekey: ExecKey,
                        batch: List[Request], outputs, dispatch_ts: float,
                        t0: float, t1: float, hit: bool, *, retries: int,
                        degradations: tuple, shallow_steps: int,
                        tier: Optional[int] = None) -> None:
        """Per-request success bookkeeping shared by the monolithic and
        staged dispatch paths: counters, latency histograms, and future
        resolution.  Thread-safe (staged batches complete on the decode
        worker while the scheduler thread completes monolithic ones)."""
        self.counters.inc("batches")
        # tier pinning (ServeResult audit trail): resolve the tier index
        # to its name once per batch — None when the controller is off
        tier_name = (self.controller.tiers[tier].name
                     if tier is not None and self.controller is not None
                     else None)
        ekey_short = ekey.short()
        if self.controller is not None:
            # calibrate the controller's forward model: one cost-
            # normalized batch-service observation per completed batch
            self.controller.observe_batch(tier, t1 - t0)
        self.counters.inc("requests_compile_hit" if hit
                          else "requests_compile_miss", len(batch))
        self._batch_sizes.inc(f"size_{len(batch)}")
        exec_s = t1 - t0
        # shallow-step share: how much of the mesh time the step cache
        # saved from full network evaluations (0 when the cache is off)
        self.counters.inc("denoise_steps_total", key.steps * len(batch))
        if shallow_steps:
            self.counters.inc("denoise_steps_shallow",
                              shallow_steps * len(batch))
        for req, out in zip(batch, outputs):
            queue_wait = dispatch_ts - req.enqueue_ts
            e2e = t1 - req.enqueue_ts
            self.hist_queue_wait.observe(queue_wait)
            self.hist_execute.observe(exec_s)
            self.hist_e2e.observe(e2e)
            self.slo_window(req.slo_class).observe(e2e)
            self._tenant_observe(req, queue_wait)
            self.counters.inc("completed")
            if req.expired(t1):
                # deadline lapsed while IN FLIGHT: deadlines gate
                # scheduling, never abandon mesh work — the caller
                # still gets the result, and the lateness is counted
                self.counters.inc("completed_late")
            if req.trace is not None and self.tracer is not None:
                rt = req.trace
                self.tracer.complete(
                    "execute", t0, t1, track=rt.track, trace=rt.trace_id,
                    parent=rt.root,
                    args={"bucket": f"{ekey.height}x{ekey.width}",
                          "batch_size": len(batch), "compile_hit": hit})
                if rt.flow_id is not None:
                    # finish the batch->member flow arrow inside the
                    # execute slice
                    self.tracer.flow(rt.flow_id, "f", track=rt.track,
                                     t=t0, name="member")
                self._trace_finish(req, "completed", args={
                    "retries": retries,
                    "degradations": list(degradations),
                    "batch_size": len(batch)})
            self._resolve(req.future, result=ServeResult(
                request_id=req.request_id,
                output=out,
                bucket=(ekey.height, ekey.width),
                requested_size=(req.height, req.width),
                queue_wait_s=queue_wait,
                execute_s=exec_s,
                e2e_s=e2e,
                batch_size=len(batch),
                compile_hit=hit,
                retries=retries,
                degradations=degradations,
                exec_key=ekey_short,
                tier=tier_name,
                replica=self.replica_name,
            ))

    # -- observability -----------------------------------------------------

    def _tenant_observe(self, req: Request, queue_wait: float) -> None:
        """Per-tenant completion accounting (no-op when tenancy is off):
        the rolling queue-wait window the gateway bench gates, plus the
        completed count."""
        tc = self._tenant_counters.get(req.tenant)
        if tc is not None:
            tc.inc("completed")
        w = self._tenant_wait.get(req.tenant)
        if w is not None:
            w.observe(queue_wait)

    def slo_window(self, slo_class: str):
        """The rolling e2e-latency window for one SLO class (created on
        first use; one `RollingQuantile` per class in the registry).
        Samples age out after ``observability.slo_max_age_s`` on the
        server clock — without the bound the windows are time-blind and
        an idle server pins minutes-old p99s into the controller."""
        return self.registry.rolling(
            "serve_slo_e2e_seconds", window=self._slo_window,
            labels={"slo_class": str(slo_class)},
            clock=self.clock, max_age_s=self._slo_max_age)

    def pending(self) -> int:
        """Queued + dispatched-but-unresolved request count — the cheap
        load signal the fleet router reads per dispatch (unlike
        `slo_snapshot`, no class windows are rendered)."""
        return len(self.queue) + int(self._inflight_c.get("requests"))

    def slo_snapshot(self) -> Dict[str, Any]:
        """THE interface the closed-loop SLO controller (ROADMAP item 3)
        reads: current queue depth, dispatched-but-unresolved request
        count, and per-SLO-class rolling p50/p99 over the last
        ``observability.slo_window`` completions.  O(classes · window)
        and any-thread-safe — poll it as fast as you like."""
        classes = {}
        # one family, not the whole registry: health()/the controller
        # poll this, and a scrape must not pay for every histogram
        for lbls, window in self.registry.family("serve_slo_e2e_seconds"):
            classes[lbls.get("slo_class", "default")] = window.snapshot()
        snap = {
            "queue_depth": len(self.queue),
            "inflight_requests": self._inflight_c.get("requests"),
            "slo_window": self._slo_window,
            "classes": classes,
        }
        if self.stepbatch is not None:
            # step-granular occupancy block: the controller's forward
            # model switches to per-step accounting when this is present
            # (SLOController._step_predictor) — occupancy is per-step,
            # not per-batch, on a slot-pool server
            sb = self.stepbatch
            snap["step"] = {
                "slots": self.config.step_batching.slots,
                "occupied": len(sb.occupied()),
                "parked": len(sb.parked),
                "remaining_steps_total": sb.remaining_steps_total(),
                "per_step_s": sb.per_step_s(),
                "steps_hint": self.config.default_steps,
            }
        return snap

    def metrics_prometheus(self) -> str:
        """The unified registry in Prometheus text exposition format —
        what the ``--metrics_port`` endpoint serves at ``/metrics``."""
        return self.registry.to_prometheus()

    def start_metrics_endpoint(self, port: Optional[int] = None):
        """Serve the metrics plane over stdlib HTTP: ``/metrics``
        (Prometheus text), ``/metrics.json`` (registry JSON), and
        ``/healthz`` (the `health()` snapshot).  Auto-started by
        `start()` when ``observability.metrics_port`` is set; ``port=0``
        binds ephemerally (read ``server.metrics_endpoint.port``)."""
        from ..utils.metrics import MetricsHTTPEndpoint

        if self.metrics_endpoint is not None:
            return self.metrics_endpoint
        if port is None:
            port = self.config.observability.metrics_port or 0
        self.metrics_endpoint = MetricsHTTPEndpoint(
            prom=self.metrics_prometheus,
            json_snapshot=lambda: self.registry.snapshot(),
            health=self.health,
            port=int(port),
            host=self.config.observability.metrics_host,
        ).start()
        return self.metrics_endpoint

    def start_gateway(self, port: Optional[int] = None):
        """Serve the generation plane over stdlib HTTP/SSE
        (serve/gateway.py): ``POST /v1/generate``, SSE progress at
        ``GET /v1/requests/<id>/events``, result polling, and cancel.
        Auto-started by `start()` when ``config.gateway.port`` is set;
        ``port=0`` binds ephemerally (read
        ``server.gateway_endpoint.port``).  Stopped by `stop()` before
        the scheduler drains, so every open stream resolves."""
        from .gateway import Gateway

        if self.gateway_endpoint is not None:
            return self.gateway_endpoint
        cfg = self.config.gateway
        if port is None:
            port = cfg.port or 0
        self.gateway_endpoint = Gateway(
            self, config=cfg, registry=self.registry,
            tracer=self.tracer, clock=self.clock,
        ).start(port=int(port))
        return self.gateway_endpoint

    def dump_observability(self, directory: str) -> Dict[str, str]:
        """Write the whole observability surface as files into
        ``directory`` (created if needed): ``metrics.json`` (the serve
        artifact snapshot), ``registry.json`` (the raw registry),
        ``metrics.prom`` (Prometheus text), ``health.json``,
        ``slo.json``, and — when tracing is on — ``trace.json``
        (Perfetto-loadable).  Returns {name: path}."""
        import os

        os.makedirs(directory, exist_ok=True)
        paths: Dict[str, str] = {}

        def dump_json(name, payload):
            path = os.path.join(directory, name)
            with open(path, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
                f.write("\n")
            paths[name] = path

        dump_json("metrics.json", self.metrics_snapshot())
        dump_json("registry.json", self.registry.snapshot())
        dump_json("health.json", self.health())
        dump_json("slo.json", self.slo_snapshot())
        prom_path = os.path.join(directory, "metrics.prom")
        with open(prom_path, "w") as f:
            f.write(self.metrics_prometheus())
        paths["metrics.prom"] = prom_path
        if self.tracer is not None:
            trace_path = os.path.join(directory, "trace.json")
            self.tracer.export(trace_path)
            paths["trace.json"] = trace_path
        return paths

    def health(self) -> Dict[str, Any]:
        """Liveness/readiness snapshot (docs/SERVING.md schema): queue
        depth, scheduler liveness, per-key circuit states, active
        degradations, retry budget, and the most recent errors."""
        res = self.resilience.snapshot()
        c = self.counters.snapshot()
        degraded = bool(res["open_circuits"] or res["degradations"])
        t = self._thread  # one read: a concurrent stop may None the attr
        return {
            "status": "degraded" if degraded else "ok",
            "queue_depth": len(self.queue),
            "scheduler_alive": bool(t is not None and t.is_alive()),
            "requests": {
                k: c.get(k, 0)
                for k in ("submitted", "completed", "completed_late",
                          "retries", "shed_circuit_open",
                          "watchdog_timeouts", "failed_build",
                          "failed_execute", "scheduler_errors")
            },
            **res,
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        """JSON-friendly service metrics — the serve artifact schema
        (docs/SERVING.md) consumed by scripts/serve_bench.py."""
        sizes = self._batch_sizes.snapshot()
        n_batches = sum(sizes.values())
        n_reqs = sum(int(k.split("_")[1]) * v for k, v in sizes.items())
        reqs = self.counters.snapshot()
        steps_total = reqs.get("denoise_steps_total", 0)
        steps_shallow = reqs.get("denoise_steps_shallow", 0)
        return {
            "model_id": self.model_id,
            "scheduler": self.scheduler,
            "mesh_plan": self.mesh_plan,
            # which fleet replica this server is (None on a bare server)
            "replica": self.replica_name,
            "config": {
                "max_queue_depth": self.config.max_queue_depth,
                "max_batch_size": self.config.max_batch_size,
                "batch_window_s": self.config.batch_window_s,
                "cache_capacity": self.config.cache_capacity,
                "buckets": [list(b) for b in self.batcher.table.buckets],
                "pipeline_stages": self.config.pipeline_stages,
                "max_inflight_batches": self.config.max_inflight_batches,
            },
            "requests": reqs,
            "step_cache": {
                "interval": self.config.step_cache_interval,
                "depth": self.config.step_cache_depth,
                "steps_total": steps_total,
                "steps_shallow": steps_shallow,
                "shallow_share": (steps_shallow / steps_total
                                  if steps_total else 0.0),
            },
            "latency_s": {
                "queue_wait": self.hist_queue_wait.snapshot(),
                "execute": self.hist_execute.snapshot(),
                "e2e": self.hist_e2e.snapshot(),
            },
            "batch_size": {
                "hist": sizes,
                "mean": (n_reqs / n_batches) if n_batches else 0.0,
            },
            "cache": self.cache.stats(),
            # per-executor weight-HBM bytes (quantization-aware, None for
            # non-reporting executors) — the weight-side companion of the
            # PR-4 wire-byte accounting
            "weights": {
                "weight_quant": self.config.weight_quant,
                "quant_compute": self.config.quant_compute,
                "per_executor_nbytes": self.cache.weight_bytes(),
            },
            "resilience": self.resilience.snapshot(),
            # per-stage queue-wait/service histograms + denoise-gap
            # fraction (None on monolithic servers)
            "staging": (self.staging.snapshot()
                        if self.staging is not None else None),
            # slot-pool state + join/leave/preempt/resume lifetime
            # counters (None on whole-batch servers)
            "step_batching": (self.stepbatch.snapshot()
                              if self.stepbatch is not None else None),
            # per-tenant fair-queue accounting: token/deficit state plus
            # admit/reject/dequeue counts (None when tenancy is off)
            "tenancy": self.queue.tenancy_snapshot(),
            # the tracing + SLO plane (docs/OBSERVABILITY.md): trace ring
            # stats (None when tracing is off) and the rolling-window SLO
            # signals the closed-loop controller reads
            "observability": {
                "trace": (self.tracer.stats()
                          if self.tracer is not None else None),
                "slo": self.slo_snapshot(),
            },
            # the closed-loop SLO controller's tier state (None when off)
            "controller": (self.controller.snapshot()
                           if self.controller is not None else None),
            # prompt/embedding cache in front of text-encode (None when off)
            "prompt_cache": (self.prompt_cache.snapshot()
                             if self.prompt_cache is not None else None),
        }

    def export_metrics(self, path: str) -> Dict[str, Any]:
        snap = self.metrics_snapshot()
        with open(path, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
            f.write("\n")
        return snap
